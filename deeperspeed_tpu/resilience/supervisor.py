"""Auto-resume supervisor: restart crashed/preempted training runs.

::

    python -m deeperspeed_tpu.resilience.supervisor \
        --checkpoint-dir /ckpts/run7 --max-restarts 20 \
        -- python train.py --deepspeed_config ds.json

The supervisor owns the restart policy so the trainer stays a plain
script:

  * exit 0                          -> done, exit 0.
  * the preemption sentinel (86 by  -> restart immediately; preemptions
    default, see config.py)            are routine on TPU pools and do
                                       NOT count against the crash cap
                                       or grow the backoff.
  * anything else (crash, SIGKILL,  -> restart after exponential
    OOM, infra flake)                  backoff (base * factor^n, capped)
                                       until ``--max-restarts`` crashes.

Before each restart the supervisor discovers the newest VALID tag in
``--checkpoint-dir`` (manifest-verified; torn tags from the fatal
instant are skipped) and exports it as ``DS_TPU_RESUME_TAG`` /
``DS_TPU_RESUME_DIR`` — a trainer can simply call
``engine.load_checkpoint(os.environ["DS_TPU_RESUME_DIR"])`` at start,
and the latest-pointer fallback logic does the rest. ``DS_TPU_RESTART_
COUNT`` counts total restarts.

Elastic resume: with ``--elastic-config ds.json`` the supervisor reads
the config's ``elasticity`` block and exports the valid accelerator
counts as ``DS_TPU_ELASTIC_WORLD_SIZES`` — a restart may land on a
different chip count (the pool shrank or grew); elasticity picks the
batch geometry for whatever is available, and the orbax sharded loader
re-shards the checkpoint onto the new mesh.

The run loop is dependency-injectable (``run_fn``/``sleep_fn``) so the
backoff policy is unit-testable without subprocesses.
"""

import argparse
import json
import os
import random
import re
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence

from ..monitor.runctx import (
    INCARNATION_ENV,
    ROLE_ENV,
    RUN_ID_ENV,
    ensure_run_id,
)
from ..utils.logging import logger
from .config import PREEMPTION_EXIT_CODE_DEFAULT
from .manifest import find_latest_valid_tag, tag_step

RESUME_TAG_ENV = "DS_TPU_RESUME_TAG"
RESUME_DIR_ENV = "DS_TPU_RESUME_DIR"
RESTART_COUNT_ENV = "DS_TPU_RESTART_COUNT"
RESTART_REASON_ENV = "DS_TPU_RESTART_REASON"
ELASTIC_WORLD_SIZES_ENV = "DS_TPU_ELASTIC_WORLD_SIZES"
WORLD_SIZE_ENV = "DS_TPU_WORLD_SIZE"
# exported so the child's lifecycle re-mesh hook re-reads the SAME pool
# file the supervisor watches when the re-mesh signal arrives
POOL_FILE_ENV = "DS_TPU_POOL_FILE"


def compute_backoff(failures: int, base: float, factor: float,
                    cap: float, jitter: float = 0.0,
                    rand: Optional[Callable[[], float]] = None) -> float:
    """Delay before restart number ``failures`` (1-based): base *
    factor^(failures-1), capped. ``jitter`` adds a bounded random
    fraction (delay * U[0, jitter]) so a fleet of supervisors killed by
    the same pool event does not restart in lockstep; the jittered delay
    still respects ``cap``. Pure (given ``rand``) so the policy is
    testable; jitter defaults off."""
    if failures <= 0:
        return 0.0
    delay = min(cap, base * factor ** (failures - 1))
    if jitter > 0.0:
        u = (rand or random.random)()
        delay = min(cap, delay * (1.0 + jitter * u))
    return delay


@dataclass
class SupervisorPolicy:
    max_restarts: int = 10  # crash restarts; preemptions are free
    backoff_base: float = 1.0
    backoff_factor: float = 2.0
    backoff_max: float = 60.0
    backoff_jitter: float = 0.0  # bounded fraction; see compute_backoff
    preempt_exit_code: int = PREEMPTION_EXIT_CODE_DEFAULT
    checkpoint_dir: Optional[str] = None
    elastic_config: Optional[str] = None
    verify_checksums: bool = True
    # elastic fleet: a file holding the surviving pool's device count,
    # re-read before every (re)start; the supervisor picks the largest
    # admissible elastic world size that fits and exports it
    pool_file: Optional[str] = None
    # lifecycle live re-mesh: watch the pool file WHILE the child runs
    # and signal the running trainer (remesh_signal) instead of waiting
    # for the next relaunch — the child's lifecycle.RemeshHook flips the
    # topology in process. Writes are debounced (a pool update must hold
    # still for pool_debounce_s) so an editor's write-rename or a burst
    # of shrink events resolves to one signal.
    watch_pool: bool = False
    pool_poll_interval_s: float = 0.25
    pool_debounce_s: float = 0.5
    remesh_signal: int = signal.SIGUSR1
    restart_log: Optional[str] = None  # JSONL transition record
    # drills: also export JAX_PLATFORMS=cpu + --xla_force_host_platform_
    # device_count so the chosen world size becomes real CPU devices
    simulate_cpu_devices: bool = False


class Supervisor:
    def __init__(self, cmd: Sequence[str], policy: SupervisorPolicy,
                 run_fn: Optional[Callable[[List[str], dict], int]] = None,
                 sleep_fn: Callable[[float], None] = time.sleep):
        if not cmd:
            raise ValueError("supervisor needs a command to run")
        self.cmd = list(cmd)
        self.policy = policy
        self._run_fn = run_fn or self._run_subprocess
        self._sleep_fn = sleep_fn
        self.restarts = 0  # total child launches minus one
        self.crashes = 0  # non-preemption failures (drives backoff/cap)
        self.history: List[int] = []  # child return codes, in order
        self.world_history: List[Optional[int]] = []  # world per launch
        self.remesh_signals = 0  # live re-mesh signals sent to children
        self._last_reason: Optional[str] = None  # why the NEXT launch is one
        # run-scoped observability: every incarnation of this run shares
        # one run_id; the child's role/incarnation label its trace lane
        self.run_id = ensure_run_id()

    def _run_subprocess(self, cmd: List[str], env: dict) -> int:
        """Default run_fn: Popen (not call) so the pool watcher can
        signal the RUNNING child for a live re-mesh."""
        proc = subprocess.Popen(cmd, env=env)
        stop = watcher = None
        if self.policy.watch_pool and self.policy.pool_file:
            stop = threading.Event()
            watcher = threading.Thread(
                target=self._watch_pool, args=(proc, stop), daemon=True)
            watcher.start()
        try:
            return proc.wait()
        finally:
            if stop is not None:
                stop.set()
                watcher.join(timeout=5.0)

    def _read_pool(self) -> Optional[int]:
        try:
            with open(self.policy.pool_file) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return None

    def _watch_pool(self, proc: "subprocess.Popen", stop: threading.Event
                    ) -> None:
        """Poll the pool file while the child runs; a debounced value
        change sends the re-mesh signal and records a ``remesh``
        transition (distinct from crash/preemption relaunches) in the
        restart log."""
        pol = self.policy
        last = self._read_pool()
        pending_val: Optional[int] = None
        pending_since = 0.0
        while not stop.wait(pol.pool_poll_interval_s):
            val = self._read_pool()
            if val is None or val == last:
                pending_val = None
                continue
            now = time.time()
            if val != pending_val:
                pending_val, pending_since = val, now  # (re)start debounce
                continue
            if now - pending_since < pol.pool_debounce_s:
                continue
            last, pending_val = val, None
            try:
                proc.send_signal(pol.remesh_signal)
            except OSError:
                return  # child already gone; run() handles the exit
            self.remesh_signals += 1
            logger.info(
                "supervisor: pool file now %d — sent signal %d for a "
                "live re-mesh (no restart)", val, int(pol.remesh_signal))
            self._log_event({"event": "remesh", "reason": "pool_change",
                             "pool": val,
                             "signal": int(pol.remesh_signal)})

    # ------------------------------------------------------------------ #

    def _child_env(self) -> dict:
        env = dict(os.environ)
        env[RESTART_COUNT_ENV] = str(self.restarts)
        env[RUN_ID_ENV] = self.run_id
        env.setdefault(ROLE_ENV, "trainer")
        env[INCARNATION_ENV] = str(self.restarts)
        if self._last_reason is not None:
            env[RESTART_REASON_ENV] = self._last_reason
        pol = self.policy
        resume_tag = None
        if pol.checkpoint_dir:
            tag = find_latest_valid_tag(
                pol.checkpoint_dir, verify_checksums=pol.verify_checksums)
            if tag is not None:
                resume_tag = tag
                env[RESUME_TAG_ENV] = tag
                env[RESUME_DIR_ENV] = pol.checkpoint_dir
                step = tag_step(tag)
                logger.info(
                    "supervisor: newest valid checkpoint is %r%s",
                    tag, f" (step {step})" if step is not None else "")
            else:
                env.pop(RESUME_TAG_ENV, None)
                env.pop(RESUME_DIR_ENV, None)
                if self.restarts:
                    logger.warning(
                        "supervisor: no valid checkpoint in %s; the "
                        "restart begins from scratch", pol.checkpoint_dir)
        sizes: List[int] = []
        if pol.elastic_config:
            sizes = self._elastic_world_sizes(pol.elastic_config)
            if sizes:
                env[ELASTIC_WORLD_SIZES_ENV] = ",".join(map(str, sizes))
                logger.info("supervisor: elastic world sizes %s", sizes)
        if pol.pool_file:
            # the child's lifecycle re-mesh hook reads the same pool file
            env[POOL_FILE_ENV] = pol.pool_file
        world = self._choose_world(sizes)
        self.world_history.append(world)
        if world is not None:
            env[WORLD_SIZE_ENV] = str(world)
            if pol.simulate_cpu_devices:
                env["JAX_PLATFORMS"] = "cpu"
                flags = re.sub(
                    r"--xla_force_host_platform_device_count=\d+", "",
                    env.get("XLA_FLAGS", "")).strip()
                env["XLA_FLAGS"] = (
                    f"{flags} " if flags else ""
                ) + f"--xla_force_host_platform_device_count={world}"
        self._log_event({"event": "launch", "restart": self.restarts,
                         "reason": self._last_reason or "initial",
                         "world_size": world, "resume_tag": resume_tag})
        return env

    def _choose_world(self, sizes: List[int]) -> Optional[int]:
        """The largest admissible world size for the surviving pool:
        re-reads ``pool_file`` (an integer device count) before every
        launch, then picks ``max(s in sizes if s <= pool)``. Without a
        pool file the topology is whatever the launcher provides and the
        child self-selects via DS_TPU_ELASTIC_WORLD_SIZES."""
        pol = self.policy
        if pol.pool_file is None:
            return None
        try:
            with open(pol.pool_file) as f:
                pool = int(f.read().strip())
        except (OSError, ValueError) as e:
            logger.warning("supervisor: unreadable pool file %s (%s); "
                           "leaving world size unset", pol.pool_file, e)
            return None
        admissible = [s for s in sizes if s <= pool]
        if not admissible:
            logger.error(
                "supervisor: no admissible elastic world size fits the "
                "surviving pool of %d (valid: %s); launching without "
                "%s — the child will fail fast and the backoff retries "
                "while the pool recovers", pool, sizes, WORLD_SIZE_ENV)
            return None
        world = max(admissible)
        if pool != world:
            logger.info(
                "supervisor: pool of %d devices -> elastic world size %d",
                pool, world)
        return world

    def _log_event(self, record: dict) -> None:
        """Append one transition record to the restart JSONL log."""
        if self.policy.restart_log is None:
            return
        record = {"ts": time.time(), "run_id": self.run_id, **record}
        try:
            parent = os.path.dirname(self.policy.restart_log)
            if parent:
                os.makedirs(parent, exist_ok=True)
            with open(self.policy.restart_log, "a") as f:
                f.write(json.dumps(record) + "\n")
        except OSError as e:  # advisory — never kill the run loop
            logger.warning("supervisor: could not append to restart log "
                           "%s: %s", self.policy.restart_log, e)

    @staticmethod
    def _elastic_world_sizes(config_path: str) -> List[int]:
        try:
            with open(config_path) as f:
                cfg = json.load(f)
            from ..elasticity import elastic_world_sizes

            return elastic_world_sizes(cfg)
        except Exception as e:  # noqa: BLE001 - advisory only
            logger.warning("supervisor: could not compute elastic world "
                           "sizes from %s: %s", config_path, e)
            return []

    # ------------------------------------------------------------------ #

    def run(self) -> int:
        pol = self.policy
        while True:
            rc = self._run_fn(self.cmd, self._child_env())
            self.history.append(rc)
            if rc == 0:
                logger.info("supervisor: run finished cleanly after %d "
                            "restart(s)", self.restarts)
                self._log_event({"event": "exit", "code": 0,
                                 "reason": "done",
                                 "restarts": self.restarts})
                return 0
            preempted = rc == pol.preempt_exit_code
            if preempted:
                delay = 0.0
                self._last_reason = "preemption"
                logger.warning(
                    "supervisor: child preempted (exit %d); restarting "
                    "immediately", rc)
            else:
                self.crashes += 1
                self._last_reason = "crash"
                if self.crashes > pol.max_restarts:
                    logger.error(
                        "supervisor: giving up after %d crash(es) "
                        "(max_restarts=%d); last exit code %d",
                        self.crashes, pol.max_restarts, rc)
                    self._log_event({"event": "exit", "code": rc,
                                     "reason": "gave_up",
                                     "crashes": self.crashes})
                    return rc
                delay = compute_backoff(
                    self.crashes, pol.backoff_base, pol.backoff_factor,
                    pol.backoff_max, pol.backoff_jitter)
                logger.warning(
                    "supervisor: child crashed (exit %d, crash %d/%d); "
                    "restarting in %.1fs", rc, self.crashes,
                    pol.max_restarts, delay)
            self._log_event({"event": "exit", "code": rc,
                             "reason": self._last_reason,
                             "crashes": self.crashes, "delay": delay})
            if delay > 0:
                self._sleep_fn(delay)
            self.restarts += 1


# --------------------------------------------------------------------- #
# CLI
# --------------------------------------------------------------------- #


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m deeperspeed_tpu.resilience.supervisor",
        description="Restart a training command on crash/preemption, "
                    "resuming from the newest valid checkpoint.")
    p.add_argument("--checkpoint-dir", default=None,
                   help="where the trainer saves; scanned for the newest "
                        "valid tag before each restart")
    p.add_argument("--max-restarts", type=int, default=10,
                   help="crash-restart cap (preemptions do not count)")
    p.add_argument("--backoff-base", type=float, default=1.0)
    p.add_argument("--backoff-factor", type=float, default=2.0)
    p.add_argument("--backoff-max", type=float, default=60.0)
    p.add_argument("--backoff-jitter", type=float, default=0.0,
                   help="bounded random backoff fraction (e.g. 0.5 adds "
                        "up to +50%%) so fleets do not restart in "
                        "lockstep")
    p.add_argument("--preempt-exit-code", type=int,
                   default=PREEMPTION_EXIT_CODE_DEFAULT,
                   help="sentinel exit code the preemption guard uses")
    p.add_argument("--elastic-config", default=None, metavar="DS_JSON",
                   help="master config with an elasticity block; exports "
                        "the valid world sizes to the child")
    p.add_argument("--pool-file", default=None, metavar="PATH",
                   help="file holding the surviving pool's device count; "
                        "re-read before every launch to pick the largest "
                        "admissible elastic world size")
    p.add_argument("--watch-pool", action="store_true",
                   help="watch --pool-file while the child runs and send "
                        "--remesh-signal on a (debounced) change so the "
                        "trainer re-meshes live instead of restarting")
    p.add_argument("--pool-debounce", type=float, default=0.5,
                   metavar="S", help="pool-file writes must hold still "
                                     "this long before the signal fires")
    p.add_argument("--pool-poll-interval", type=float, default=0.25,
                   metavar="S", help="pool-file polling period")
    p.add_argument("--remesh-signal", type=int,
                   default=int(signal.SIGUSR1),
                   help="signal number sent to the running child on a "
                        "pool change (default SIGUSR1)")
    p.add_argument("--restart-log", default=None, metavar="JSONL",
                   help="append one JSON record per launch/exit "
                        "transition (reason, world size, resume tag)")
    p.add_argument("--simulate-cpu-devices", action="store_true",
                   help="drills: export JAX_PLATFORMS=cpu and "
                        "--xla_force_host_platform_device_count matching "
                        "the chosen world size")
    p.add_argument("--no-verify", action="store_true",
                   help="skip manifest checksum verification during "
                        "checkpoint discovery (size/presence only)")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="-- followed by the training command")
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        build_parser().error("no training command given (put it after --)")
    policy = SupervisorPolicy(
        max_restarts=args.max_restarts,
        backoff_base=args.backoff_base,
        backoff_factor=args.backoff_factor,
        backoff_max=args.backoff_max,
        backoff_jitter=args.backoff_jitter,
        preempt_exit_code=args.preempt_exit_code,
        checkpoint_dir=args.checkpoint_dir,
        elastic_config=args.elastic_config,
        verify_checksums=not args.no_verify,
        pool_file=args.pool_file,
        watch_pool=args.watch_pool,
        pool_poll_interval_s=args.pool_poll_interval,
        pool_debounce_s=args.pool_debounce,
        remesh_signal=args.remesh_signal,
        restart_log=args.restart_log,
        simulate_cpu_devices=args.simulate_cpu_devices,
    )
    return Supervisor(cmd, policy).run()


if __name__ == "__main__":
    sys.exit(main())
