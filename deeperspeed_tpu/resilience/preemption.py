"""Preemption guard: turn SIGTERM/SIGINT into an orderly exit.

TPU pools preempt with a signal and a short grace window. The guard's
handler does NOTHING dangerous in signal context — it sets a flag and
returns. The training loop notices the flag at the next step boundary
(``ResilienceManager.on_step_boundary``), takes an urgent checkpoint,
drains any live serving engines, and exits with a sentinel code the
auto-resume supervisor recognizes as "preempted: restart without
backoff, don't count it as a crash".

A second SIGINT while a preemption is already pending raises
``KeyboardInterrupt`` immediately — ctrl-C twice still means "now".
"""

import signal
import threading
from typing import Callable, Dict, Optional, Sequence

from ..monitor.tracer import trace_instant
from ..utils.logging import logger


class PreemptionGuard:
    def __init__(self, signals: Sequence[str] = ("SIGTERM", "SIGINT"),
                 on_request: Optional[Callable[[int], None]] = None):
        self._signal_names = tuple(signals)
        self._on_request = on_request
        self._requested = threading.Event()
        self._signum: Optional[int] = None
        self._prev: Dict[int, object] = {}
        self._installed = False

    # ---- lifecycle -------------------------------------------------- #

    def install(self) -> bool:
        """Install the handlers; returns False (with a warning) when not
        on the main thread, where CPython forbids signal.signal."""
        if self._installed:
            return True
        if threading.current_thread() is not threading.main_thread():
            logger.warning(
                "preemption guard not installed: signal handlers require "
                "the main thread")
            return False
        for name in self._signal_names:
            sig = getattr(signal, name)
            try:
                self._prev[sig] = signal.signal(sig, self._handler)
            except (ValueError, OSError) as e:  # pragma: no cover
                logger.warning("could not install handler for %s: %s",
                               name, e)
        self._installed = bool(self._prev)
        return self._installed

    def uninstall(self) -> None:
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):  # pragma: no cover
                pass
        self._prev.clear()
        self._installed = False

    # ---- signal context --------------------------------------------- #

    def _handler(self, signum, frame) -> None:
        if self._requested.is_set() and signum == signal.SIGINT:
            # second ctrl-C: the user means it
            raise KeyboardInterrupt
        self._signum = signum
        self._requested.set()
        # signal-safe work only: flag + (reentrant-safe) log; the trace
        # instant is a dict append under a non-reentrant path only if a
        # drop-note fires, which the guard tolerates (tracing is advisory)
        trace_instant("run/preempt", lane="run", signum=int(signum))
        logger.warning(
            "received %s: urgent checkpoint at the next step boundary, "
            "then exit (signal again with SIGINT to abort immediately)",
            signal.Signals(signum).name)
        if self._on_request is not None:
            self._on_request(signum)

    # ---- training-loop surface -------------------------------------- #

    @property
    def requested(self) -> bool:
        return self._requested.is_set()

    @property
    def signum(self) -> Optional[int]:
        return self._signum

    def request(self, signum: int = signal.SIGTERM) -> None:
        """Programmatic preemption (tests / external schedulers)."""
        self._signum = int(signum)
        self._requested.set()
        trace_instant("run/preempt", lane="run", signum=int(signum))

    def clear(self) -> None:
        self._requested.clear()
        self._signum = None

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._requested.wait(timeout)
