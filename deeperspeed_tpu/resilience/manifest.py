"""Checkpoint manifest + two-phase commit + valid-tag discovery.

The durability contract of the resilience subsystem lives here:

  * a save writes every file into ``<tag>.tmp/`` (the staging dir), then
    a ``MANIFEST.json`` recording per-file sizes and sha256 checksums,
    fsyncs everything, atomically renames the staging dir to ``<tag>``
    and drops a ``COMMITTED`` marker — so a reader can NEVER observe a
    half-written tag: either the rename happened (and every file inside
    was fsynced first) or the tag does not exist.
  * a load verifies the manifest (``verify_manifest``) and, when the
    requested tag is missing/partial/corrupt, falls back to the newest
    older tag that still verifies (``find_latest_valid_tag``).

Tag states (``tag_status``):

  * ``committed`` — COMMITTED marker present and, when asked, every
    manifest checksum matches. The only state the resilience writer
    produces.
  * ``legacy``    — no marker and no manifest, but the directory holds
    model states (msgpack or orbax layout). Pre-resilience checkpoints;
    accepted for backward compatibility.
  * ``partial``   — a manifest without a marker (death between manifest
    and commit) or a directory with neither states nor marker.
  * ``corrupt``   — marker present but a checksum/size mismatch.
  * ``staging`` / ``missing`` — ``*.tmp`` dirs and absent paths.

Everything here is stdlib-only (os/json/hashlib) so the supervisor can
use it without importing jax-adjacent modules.
"""

import hashlib
import json
import os
import re
from typing import Iterable, List, Optional, Set, Tuple

from ..utils.logging import logger

MANIFEST_FILE = "MANIFEST.json"
COMMITTED_MARKER = "COMMITTED"
STAGING_SUFFIX = ".tmp"
MANIFEST_VERSION = 1

# files a manifest never covers: itself, the marker, and the `latest`
# pointer (which lives in the parent dir anyway)
_UNMANIFESTED = frozenset({MANIFEST_FILE, COMMITTED_MARKER})

VALID_STATES = ("committed", "legacy")

_TAG_STEP_RE = re.compile(r"(\d+)\s*$")


class CheckpointCorruption(RuntimeError):
    """A committed checkpoint failed manifest verification."""


# --------------------------------------------------------------------- #
# fsync helpers
# --------------------------------------------------------------------- #


def fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(path: str) -> None:
    """fsync a directory so the entries inside it (renames, creates)
    survive power loss; a no-op on filesystems that refuse the open."""
    flags = os.O_RDONLY | getattr(os, "O_DIRECTORY", 0)
    try:
        fd = os.open(path, flags)
    except OSError:  # pragma: no cover - exotic filesystems
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover
        pass
    finally:
        os.close(fd)


# --------------------------------------------------------------------- #
# manifest write / verify
# --------------------------------------------------------------------- #


def file_checksum(path: str, chunk_bytes: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk_bytes)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def _walk_files(ckpt_dir: str) -> Iterable[str]:
    for root, _dirs, files in os.walk(ckpt_dir):
        for fname in sorted(files):
            rel = os.path.relpath(os.path.join(root, fname), ckpt_dir)
            if rel in _UNMANIFESTED:
                continue
            yield rel


def write_manifest(ckpt_dir: str, extra: Optional[dict] = None) -> str:
    """Record size + sha256 for every file under ``ckpt_dir`` into
    ``MANIFEST.json`` (written atomically and fsynced). Returns the
    manifest path."""
    files = {}
    for rel in _walk_files(ckpt_dir):
        full = os.path.join(ckpt_dir, rel)
        files[rel] = {
            "bytes": os.path.getsize(full),
            "sha256": file_checksum(full),
        }
    manifest = {"version": MANIFEST_VERSION, "files": files}
    if extra:
        manifest["meta"] = dict(extra)
    path = os.path.join(ckpt_dir, MANIFEST_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(ckpt_dir)
    return path


def verify_manifest(ckpt_dir: str,
                    check_checksums: bool = True) -> Tuple[bool, List[str]]:
    """Check every manifest entry against the on-disk files. Returns
    (ok, problems); a missing manifest is itself a problem."""
    path = os.path.join(ckpt_dir, MANIFEST_FILE)
    try:
        with open(path) as f:
            manifest = json.load(f)
        entries = manifest["files"]
    except (OSError, ValueError, KeyError) as e:
        return False, [f"unreadable manifest: {e}"]
    problems = []
    for rel, want in sorted(entries.items()):
        full = os.path.join(ckpt_dir, rel)
        if not os.path.isfile(full):
            problems.append(f"{rel}: missing")
            continue
        size = os.path.getsize(full)
        if size != want.get("bytes"):
            problems.append(
                f"{rel}: size {size} != manifest {want.get('bytes')}")
            continue
        if check_checksums:
            digest = file_checksum(full)
            if digest != want.get("sha256"):
                problems.append(f"{rel}: sha256 mismatch")
    return not problems, problems


# --------------------------------------------------------------------- #
# two-phase commit
# --------------------------------------------------------------------- #


def staging_dir_for(save_dir: str, tag: str) -> str:
    return os.path.join(save_dir, str(tag) + STAGING_SUFFIX)


def commit_checkpoint(staging: str, final_dir: str) -> None:
    """Atomically publish a fully-written staging dir: fsync every file
    and the dir itself, rename into place, drop the COMMITTED marker,
    fsync the parent. A crash at ANY instant leaves either the old tag,
    no tag, or the complete new tag — never a readable partial one (the
    marker is the last write, so a rename that landed without it is
    still skipped by ``tag_status``)."""
    for rel in _walk_files(staging):
        fsync_file(os.path.join(staging, rel))
    fsync_dir(staging)
    parent = os.path.dirname(final_dir) or "."
    if os.path.isdir(final_dir):
        # re-save of an existing tag: move the old copy aside first so a
        # crash mid-swap still leaves one complete directory on disk
        import shutil

        old = final_dir + ".old" + STAGING_SUFFIX
        shutil.rmtree(old, ignore_errors=True)
        os.replace(final_dir, old)
        os.rename(staging, final_dir)
        shutil.rmtree(old, ignore_errors=True)
    else:
        os.rename(staging, final_dir)
    marker = os.path.join(final_dir, COMMITTED_MARKER)
    with open(marker, "w") as f:
        f.write("ok\n")
        f.flush()
        os.fsync(f.fileno())
    fsync_dir(final_dir)
    fsync_dir(parent)


def is_committed(ckpt_dir: str) -> bool:
    return os.path.isfile(os.path.join(ckpt_dir, COMMITTED_MARKER))


# --------------------------------------------------------------------- #
# tag state + discovery
# --------------------------------------------------------------------- #


def _looks_like_checkpoint(ckpt_dir: str) -> bool:
    """Pre-resilience layouts: msgpack model-state shards or the orbax
    ``sharded_state`` directory (patterns mirrored from
    checkpoint/serialization.py, kept literal so this module stays
    stdlib-only)."""
    if os.path.isdir(os.path.join(ckpt_dir, "sharded_state")):
        return True
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return False
    return any(n.endswith("model_states.msgpack") for n in names)


def tag_status(ckpt_dir: str, verify_checksums: bool = True) -> str:
    if os.path.basename(ckpt_dir).endswith(STAGING_SUFFIX):
        return "staging"
    if not os.path.isdir(ckpt_dir):
        return "missing"
    if is_committed(ckpt_dir):
        if os.path.isfile(os.path.join(ckpt_dir, MANIFEST_FILE)):
            ok, _problems = verify_manifest(
                ckpt_dir, check_checksums=verify_checksums)
            return "committed" if ok else "corrupt"
        return "committed"
    if os.path.isfile(os.path.join(ckpt_dir, MANIFEST_FILE)):
        return "partial"  # died between manifest and commit
    if _looks_like_checkpoint(ckpt_dir):
        return "legacy"
    return "partial"


def tag_step(tag: str) -> Optional[int]:
    """Trailing integer of a tag (``global_step120`` -> 120); None for
    tags with no step suffix (ranked by mtime instead)."""
    m = _TAG_STEP_RE.search(str(tag))
    return int(m.group(1)) if m else None


def list_tags(load_dir: str) -> List[str]:
    """Candidate tag dirs under ``load_dir``, newest first (by parsed
    step number, then mtime); staging dirs excluded."""
    try:
        names = os.listdir(load_dir)
    except OSError:
        return []
    cands = []
    for name in names:
        full = os.path.join(load_dir, name)
        if not os.path.isdir(full) or name.endswith(STAGING_SUFFIX):
            continue
        step = tag_step(name)
        try:
            mtime = os.path.getmtime(full)
        except OSError:
            mtime = 0.0
        cands.append((0 if step is None else 1, step or 0, mtime, name))
    cands.sort(reverse=True)
    return [name for _, _, _, name in cands]


def find_latest_valid_tag(load_dir: str,
                          exclude: Set[str] = frozenset(),
                          verify_checksums: bool = True) -> Optional[str]:
    for tag in list_tags(load_dir):
        if tag in exclude:
            continue
        if tag_status(os.path.join(load_dir, tag), verify_checksums) \
                in VALID_STATES:
            return tag
    return None


def resolve_load_tag(load_dir: str, requested: Optional[str],
                     verify_checksums: bool = True,
                     ) -> Tuple[Optional[str], bool]:
    """Map a requested tag (explicit, or from the ``latest`` pointer) to
    a loadable one. Returns (tag, fell_back): the requested tag itself
    when it verifies, else the newest older valid tag with a warning —
    a crash mid-save must cost at most one checkpoint interval, never
    the run. (None, False) when nothing on disk is loadable."""
    if requested is None:
        return None, False
    status = tag_status(os.path.join(load_dir, str(requested)),
                        verify_checksums)
    if status in VALID_STATES:
        return str(requested), False
    fallback = find_latest_valid_tag(
        load_dir, exclude={str(requested)}, verify_checksums=verify_checksums)
    if fallback is None:
        logger.warning(
            "checkpoint tag %r in %s is not loadable (%s) and no older "
            "valid tag exists", requested, load_dir, status)
        return None, False
    logger.warning(
        "checkpoint tag %r in %s is not loadable (%s); falling back to "
        "newest valid tag %r", requested, load_dir, status, fallback)
    return fallback, True
