"""World-size resharding for elastic resume.

A checkpoint written at world size W must be loadable at any admissible
W′. Three kinds of state need help beyond what orbax already does
(arrays whose GLOBAL shape is unchanged reshard onto the new mesh for
free — params, optimizer moments, fp32 masters, and canonical-mode comm
residuals all fall in that bucket):

* **comm error-feedback residuals** in the classic (non-canonical)
  layout are ``(W, n)`` stacks — one row per device — so their global
  shape bakes in the world size. :func:`reshard_comm_residuals` rebuilds
  them for W′ from the saved :meth:`GradReducer.plan_summary` metadata:

  - ``"e"`` rows are per-device quantization errors of the SAME padded
    bucket vector: device i's error for its own full-bucket
    contribution. Error feedback only needs the SUM over devices of
    what is fed back to track the sum of true gradients, so rows are
    regrouped sum-preservingly (``new[i % W'] += old[i]``) — exact in
    aggregate, approximate per device.
  - ``"e2"`` rows (int8 flat second phase) are POSITIONAL chunks of the
    padded bucket vector (device j owns elements ``[j*L/W, (j+1)*L/W)``),
    so the global vector is reassembled, re-padded to the new plan's
    padded length, and re-sliced into W′ chunks — positionally exact
    (the pad region's residual is provably zero: packed gradients pad
    with zeros and the all-zero-block quantizer is exact on zeros).
  - hierarchical residuals (``e1``/``e2`` with ``hier_k > 0``) are
    per-GROUP chunks whose grouping does not survive a world change;
    they reset to zero with a warning.

* **datapipe cursors** (:func:`remap_data_state`): `DataState` counters
  are GLOBAL (samples/cursor count consumed samples, not per-device
  work), and under elasticity the global batch row count is invariant
  across world sizes — so the exact-stream remap is the identity. When
  the global rows DID change (config edit, not an elastic flip), the
  sample cursor still marks the exact stream position, but step-keyed
  schedules (curriculum, batch-size ramps) reinterpret their step axis
  at the new granularity — flagged with a warning.

Everything here is host-side numpy on checkpoint data; callers place
the results onto the running mesh.
"""

from typing import Dict, List, Optional

import numpy as np

from ..utils.logging import logger

#: keys of a reducer plan summary that must match (world excluded) for
#: residuals to reshard instead of reset
_PLAN_MATCH_KEYS = ("mode", "block", "error_feedback", "bucket_lengths")


def _normalize_buckets(buckets) -> Optional[List[Dict[str, np.ndarray]]]:
    """Checkpoint codecs differ on list encoding: msgpack round-trips a
    list as an index-keyed dict ({'0': ..., '1': ...}), orbax keeps the
    list. Normalize to a list of dicts of numpy arrays."""
    if buckets is None:
        return None
    if isinstance(buckets, dict):
        try:
            buckets = [buckets[k] for k in sorted(buckets, key=int)]
        except (ValueError, TypeError):
            return None
    out = []
    for b in buckets:
        if not isinstance(b, dict):
            return None
        out.append({k: np.asarray(v, dtype=np.float32)
                    for k, v in b.items()})
    return out


def _normalize_plan(plan) -> Optional[dict]:
    """Undo codec damage on a saved plan summary: msgpack round-trips
    lists as index-keyed dicts and may widen ints. Returns a clean dict
    (or None for non-dicts)."""
    if not isinstance(plan, dict):
        return None
    out = dict(plan)
    for k in ("bucket_lengths", "bucket_padded"):
        v = out.get(k)
        if isinstance(v, dict):
            try:
                v = [v[i] for i in sorted(v, key=int)]
            except (ValueError, TypeError):
                return None
        if isinstance(v, (list, tuple)):
            out[k] = [int(n) for n in v]
    for k in ("world", "block", "hier_k", "canonical"):
        if k in out and out[k] is not None:
            out[k] = int(out[k])
    if "error_feedback" in out:
        out["error_feedback"] = bool(out["error_feedback"])
    return out


def plans_reshardable(saved_plan: Optional[dict],
                      target_plan: dict) -> Optional[str]:
    """None when residuals saved under ``saved_plan`` can be resharded
    onto ``target_plan`` (same layout, only the world size differs);
    otherwise the human-readable reason they cannot."""
    saved_plan = _normalize_plan(saved_plan)
    if saved_plan is None:
        return "checkpoint predates comm_plan metadata"
    for k in _PLAN_MATCH_KEYS:
        if saved_plan.get(k) != target_plan.get(k):
            return (f"comm layout changed: {k} "
                    f"{saved_plan.get(k)!r} -> {target_plan.get(k)!r}")
    if saved_plan.get("canonical", 0) != target_plan.get("canonical", 0):
        return ("canonical_shards changed: "
                f"{saved_plan.get('canonical', 0)} -> "
                f"{target_plan.get('canonical', 0)}")
    if int(saved_plan.get("hier_k", 0) or 0):
        return "hierarchical residuals are per-group; they reset to zero"
    if int(target_plan.get("hier_k", 0) or 0):
        return "restoring onto a hierarchical schedule resets residuals"
    return None


def reshard_comm_residuals(saved_buckets, saved_plan: dict,
                           target_plan: dict
                           ) -> Optional[List[Dict[str, np.ndarray]]]:
    """Reshape (W, n)-stacked comm residuals from ``saved_plan``'s world
    size onto ``target_plan``'s. Returns the new per-bucket residual
    dicts (host numpy, shaped for the target plan), or None when the
    layouts are incompatible (caller falls back to zeros)."""
    reason = plans_reshardable(saved_plan, target_plan)
    if reason is not None:
        logger.warning("comm residuals cannot be resharded (%s)", reason)
        return None
    saved_plan = _normalize_plan(saved_plan)
    buckets = _normalize_buckets(saved_buckets)
    if buckets is None:
        logger.warning("comm residuals have an unrecognized container "
                       "layout; resetting to zero")
        return None
    w_old = int(saved_plan["world"])
    w_new = int(target_plan["world"])
    lengths = [int(n) for n in target_plan["bucket_lengths"]]
    padded_old = [int(n) for n in saved_plan["bucket_padded"]]
    padded_new = [int(n) for n in target_plan["bucket_padded"]]
    if len(buckets) != len(lengths):
        logger.warning(
            "comm residuals carry %d buckets but the plan has %d; "
            "resetting to zero", len(buckets), len(lengths))
        return None

    out: List[Dict[str, np.ndarray]] = []
    for j, res in enumerate(buckets):
        length, lo, ln = lengths[j], padded_old[j], padded_new[j]
        new_res: Dict[str, np.ndarray] = {}
        for key, arr in res.items():
            if key == "e":
                if arr.shape != (w_old, lo):
                    logger.warning(
                        "bucket %d residual 'e' has shape %s, expected "
                        "%s; resetting to zero", j, arr.shape, (w_old, lo))
                    return None
                new = np.zeros((w_new, ln), np.float32)
                for i in range(w_old):
                    # sum-preserving regroup of per-device errors; the
                    # pad region [length:] is identically zero
                    new[i % w_new, :length] += arr[i, :length]
                new_res[key] = new
            elif key == "e2":
                chunk_old, chunk_new = lo // w_old, ln // w_new
                if arr.shape != (w_old, chunk_old):
                    logger.warning(
                        "bucket %d residual 'e2' has shape %s, expected "
                        "%s; resetting to zero", j, arr.shape,
                        (w_old, chunk_old))
                    return None
                flat = arr.reshape(-1)  # the padded global vector
                if flat.shape[0] < ln:
                    flat = np.pad(flat, (0, ln - flat.shape[0]))
                new_res[key] = flat[:ln].reshape(w_new, chunk_new).astype(
                    np.float32)
            else:
                logger.warning(
                    "bucket %d carries unknown residual key %r; "
                    "resetting to zero", j, key)
                return None
        out.append(new_res)
    return out


def reshard_transform_residuals(saved_buckets, saved_plan: Optional[dict],
                                target_plan: dict
                                ) -> Optional[List[Dict[str, np.ndarray]]]:
    """Reshape the pipeline engine's transform-only residuals — per-bucket
    ``(padded,)`` vectors — onto a new plan. Residual content beyond each
    bucket's unpadded length is provably zero, and padding is the ONLY
    world-size-dependent part of the layout, so the remap is exact:
    truncate or zero-extend each vector to the target padded length. Also
    the identity when the world size did not change. None when the bucket
    layout itself differs (caller keeps zeros)."""
    saved_plan = _normalize_plan(saved_plan)
    if saved_plan is None:
        logger.warning("comm transform residuals predate plan metadata; "
                       "resetting to zero")
        return None
    for k in ("mode", "block", "error_feedback", "bucket_lengths"):
        if saved_plan.get(k) != target_plan.get(k):
            logger.warning(
                "comm transform residuals cannot be reshaped (%s changed: "
                "%r -> %r); resetting to zero",
                k, saved_plan.get(k), target_plan.get(k))
            return None
    buckets = _normalize_buckets(saved_buckets)
    if buckets is None:
        logger.warning("comm transform residuals have an unrecognized "
                       "container layout; resetting to zero")
        return None
    padded_new = [int(n) for n in target_plan["bucket_padded"]]
    if len(buckets) != len(padded_new):
        logger.warning(
            "comm transform residuals carry %d buckets but the plan has "
            "%d; resetting to zero", len(buckets), len(padded_new))
        return None
    out: List[Dict[str, np.ndarray]] = []
    for j, res in enumerate(buckets):
        ln = padded_new[j]
        new_res: Dict[str, np.ndarray] = {}
        for key, arr in res.items():
            flat = np.asarray(arr, np.float32).reshape(-1)
            if flat.shape[0] < ln:
                flat = np.pad(flat, (0, ln - flat.shape[0]))
            new_res[key] = flat[:ln]
        out.append(new_res)
    return out


def remap_data_state(state_dict: Optional[dict], saved_rows: Optional[int],
                     target_rows: int) -> Optional[dict]:
    """Remap a checkpointed ``DataState`` dict to the running global
    batch layout. `DataState` counters are global (cursor/samples index
    the sample stream itself), so an elastic world flip — which by
    construction keeps the global batch size — is the identity: the
    next batch starts at exactly the next unseen sample, no token
    skipped or repeated. A changed row count still resumes the exact
    sample stream but re-bases step-keyed schedules, which is worth a
    warning."""
    if state_dict is None:
        return None
    if saved_rows is not None and int(saved_rows) != int(target_rows):
        logger.warning(
            "datapipe: global batch rows changed %s -> %s across resume; "
            "the sample cursor resumes the exact stream, but step-keyed "
            "schedules (curriculum, batch-size ramps) now advance at the "
            "new per-step granularity", saved_rows, target_rows)
    return state_dict
