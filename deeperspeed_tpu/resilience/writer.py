"""Bounded-queue background checkpoint writer.

The async half of a resilience save: the engine snapshots device state
to host buffers at the step boundary (fast — one ``device_get`` sweep),
wraps the serialize+fsync+commit work in a callable job, and hands it
here. Training resumes immediately; the writer thread does the disk IO.

Durability properties:

  * the queue is BOUNDED (``max_pending``): a run that checkpoints
    faster than the disk drains blocks at ``submit`` instead of
    accumulating unbounded host snapshots.
  * the thread is a daemon, but an ``atexit`` hook drains the queue, so
    a clean interpreter exit never abandons an accepted save. (SIGKILL
    of course does — which is exactly what the two-phase commit in
    ``manifest.py`` protects against.)
  * a failed job parks its exception; the NEXT ``submit``/``wait`` call
    re-raises it as ``CheckpointWriteError`` on the training thread, so
    write errors surface where the user can see them instead of dying
    silently on a worker thread.
"""

import atexit
import queue
import threading
from typing import Callable, Optional

from ..utils.logging import logger


class CheckpointWriteError(RuntimeError):
    """A background checkpoint write failed (original error chained)."""


class AsyncCheckpointWriter:
    def __init__(self, max_pending: int = 2, name: str = "ckpt-writer"):
        self._q: "queue.Queue[Optional[Callable[[], None]]]" = queue.Queue(
            maxsize=max_pending)
        self._error: Optional[BaseException] = None
        self._error_lock = threading.Lock()
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, name=name, daemon=True)
        self._thread.start()
        atexit.register(self._drain_at_exit)

    # ---- worker ----------------------------------------------------- #

    def _loop(self) -> None:
        while True:
            job = self._q.get()
            if job is None:
                self._q.task_done()
                return
            try:
                job()
            except BaseException as e:  # noqa: BLE001 - park ANY failure
                with self._error_lock:
                    self._error = e
                logger.error("async checkpoint write failed: %s", e)
            finally:
                self._q.task_done()

    # ---- producer surface ------------------------------------------- #

    def submit(self, job: Callable[[], None]) -> None:
        """Enqueue one write job. Blocks when ``max_pending`` snapshots
        are already waiting (bounded backpressure). Raises a parked
        error from an earlier failed write."""
        if self._closed:
            raise CheckpointWriteError("writer is closed")
        self.raise_pending_error()
        self._q.put(job)

    def wait(self) -> None:
        """Block until every accepted job has been written; re-raise a
        parked write error."""
        self._q.join()
        self.raise_pending_error()

    def raise_pending_error(self) -> None:
        with self._error_lock:
            err, self._error = self._error, None
        if err is not None:
            raise CheckpointWriteError(
                f"background checkpoint write failed: {err}") from err

    @property
    def pending(self) -> int:
        """Jobs accepted but not yet fully written (approximate)."""
        return int(self._q.unfinished_tasks)

    def close(self, wait: bool = True) -> None:
        """Drain (optionally) and stop the worker thread. Idempotent."""
        if self._closed:
            return
        self._closed = True
        if wait:
            try:
                self._q.join()
            except Exception:  # pragma: no cover - interpreter teardown
                pass
        self._q.put(None)
        self._thread.join(timeout=30.0)

    def _drain_at_exit(self) -> None:
        # clean-exit insurance: the daemon thread keeps running during
        # atexit, so joining the queue here finishes accepted saves
        # before the interpreter tears the thread down
        try:
            if not self._closed:
                self._q.join()
        except Exception:  # pragma: no cover
            pass
