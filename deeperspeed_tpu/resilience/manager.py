"""ResilienceManager: the engine-facing composition of the subsystem.

One manager per process (installed by ``init_resilience``, adopted by
engines at init the way the monitor is) binds the five pieces together:

  * routes ``engine.save_checkpoint`` through the two-phase-commit
    writer — async (snapshot at the step boundary, serialize+fsync+
    commit on the writer thread) or sync, but ALWAYS atomic: a partial
    tag is never visible to a loader.
  * runs the step-boundary hook: fault injection, interval autosaves,
    and the preemption protocol (urgent checkpoint -> serving drain ->
    sentinel exit).
  * records telemetry into the monitor registry when one is installed:
    ``resilience_saves_total`` / ``resume_total`` / ``preemption_total``
    / ``fallback_total`` counters, the step-blocked-time gauge, and
    save-duration histograms, plus ``resilience/*`` trace spans.

The manager deliberately does NOT own load-time validation — that lives
in ``manifest.py`` and is wired into ``Engine.load_checkpoint`` so even
runs without a resilience block never load a torn checkpoint.
"""

import os
import shutil
import time
from typing import Optional

from ..monitor import get_monitor, trace_instant, trace_span
from ..utils.logging import log_dist, logger
from .config import ResilienceConfig
from .faults import FaultInjector, plan_from_config_and_env
from .manifest import (
    COMMITTED_MARKER,
    commit_checkpoint,
    find_latest_valid_tag,
    is_committed,
    list_tags,
    staging_dir_for,
    tag_step,
    write_manifest,
)
from .preemption import PreemptionGuard
from .writer import AsyncCheckpointWriter


class ResilienceManager:
    def __init__(self, config: ResilienceConfig):
        self.cfg = config
        self.faults = FaultInjector(plan_from_config_and_env(config.faults))
        self.writer: Optional[AsyncCheckpointWriter] = (
            AsyncCheckpointWriter(max_pending=config.max_pending_saves)
            if config.async_save else None)
        self.guard: Optional[PreemptionGuard] = None
        if config.preemption_guard:
            self.guard = PreemptionGuard(signals=config.preemption_signals)
            self.guard.install()
        self.serving = []  # live serving engines to drain on preemption
        self.lifecycle = []  # step-boundary hooks (re-mesh, publish)
        self._save_dir = config.save_dir
        self._warned_multiprocess = False
        self._warned_no_save_dir = False
        self._closed = False
        self._resumed_tag: Optional[str] = None  # protected from pruning
        self._restart_noted = False

    # ------------------------------------------------------------------ #
    # telemetry helpers
    # ------------------------------------------------------------------ #

    def _registry(self):
        mon = get_monitor()
        return mon.registry if mon is not None else None

    def _inc(self, name: str, help_: str, labels=None) -> None:
        reg = self._registry()
        if reg is not None:
            reg.counter(name, help_, labels=labels).inc()

    # ------------------------------------------------------------------ #
    # save path
    # ------------------------------------------------------------------ #

    def note_save_dir(self, save_dir: str) -> None:
        """Adopt the save dir of an explicit save so urgent/interval
        saves have a target even without ``resilience.save_dir``."""
        if self.cfg.save_dir is None:
            self._save_dir = save_dir

    @property
    def save_dir(self) -> Optional[str]:
        return self._save_dir

    def handles_save(self) -> bool:
        """Resilience saves are single-process (the async writer and the
        commit rename assume one writer per directory); multi-process
        runs keep the legacy engine path and get a one-time warning."""
        if not self.cfg.enabled:
            return False
        import jax

        if jax.process_count() > 1:
            if not self._warned_multiprocess:
                self._warned_multiprocess = True
                logger.warning(
                    "resilience checkpointing is single-process only; "
                    "multi-process runs fall back to the legacy save path "
                    "(no async, no two-phase commit)")
            return False
        return True

    def save_checkpoint(self, engine, save_dir, tag, client_state,
                        save_latest=True) -> bool:
        """The resilience save: blocking device->host snapshot, then a
        two-phase-commit write — handed to the writer thread when async
        is on. Returns once the save is durably ACCEPTED (committed for
        sync; queued for async, where ``wait_for_pending_saves`` or the
        exit hook guarantees completion)."""
        t0 = time.monotonic()
        if self.writer is not None:
            self.writer.raise_pending_error()
        if engine._config.checkpoint_sharded_io and engine._offload is None:
            # orbax drives its own device IO, so the sharded layout
            # commits synchronously — but still atomically: the shards
            # land in <tag>.tmp and rename in with a COMMITTED marker
            mode = "sync"
            with trace_span("resilience/write", lane="resilience",
                            step=engine.global_steps):
                from ..checkpoint.serialization import CheckpointEngine

                staging = staging_dir_for(save_dir, tag)
                shutil.rmtree(staging, ignore_errors=True)
                ck = CheckpointEngine(save_dir, os.path.basename(staging))
                engine._save_checkpoint_sharded(
                    ck, save_dir, tag, client_state, save_latest=False)
            self._commit(save_dir, tag, save_latest)
        else:
            with trace_span("resilience/snapshot", lane="resilience",
                            step=engine.global_steps):
                files = engine._host_checkpoint_payload(
                    client_state=client_state)
            job = _SaveJob(self, save_dir, tag, files, save_latest)
            if self.writer is not None:
                mode = "async"
                self.writer.submit(job)  # blocks only on full queue
            else:
                mode = "sync"
                job()
        blocked = time.monotonic() - t0
        reg = self._registry()
        if reg is not None:
            reg.counter("resilience_saves_total", "checkpoint saves",
                        labels={"mode": mode}).inc()
            reg.gauge("resilience_save_blocked_seconds",
                      "step-loop time blocked by the last save").set(blocked)
            if self.writer is not None:
                reg.gauge("resilience_queue_depth",
                          "checkpoint writes accepted but not finished"
                          ).set(self.writer.pending)
        log_dist(
            f"resilience: {mode} save of tag {tag} blocked the step loop "
            f"{blocked * 1e3:.1f} ms", ranks=[0])
        return True

    def _write_payload(self, save_dir, tag, files, save_latest) -> None:
        """Writer-thread body: staging-dir write + manifest + commit."""
        from ..checkpoint.serialization import save_tree
        from ..checkpoint.zero_to_fp32 import write_recovery_stub

        staging = staging_dir_for(save_dir, tag)
        shutil.rmtree(staging, ignore_errors=True)
        t0 = time.monotonic()
        with trace_span("resilience/write", lane="resilience"):
            for fname, tree in files.items():
                save_tree(os.path.join(staging, fname), tree)
                self.faults.on_save_file_written(fname)
            write_recovery_stub(staging)
        self._commit(save_dir, tag, save_latest=save_latest)
        reg = self._registry()
        if reg is not None:
            from ..monitor.metrics import DEFAULT_SAVE_BUCKETS

            reg.histogram("resilience_save_duration_seconds",
                          "write+commit wall time per checkpoint",
                          buckets=DEFAULT_SAVE_BUCKETS
                          ).observe(time.monotonic() - t0)

    def _commit(self, save_dir, tag, save_latest) -> None:
        from ..checkpoint.serialization import write_latest

        staging = staging_dir_for(save_dir, tag)
        final_dir = os.path.join(save_dir, str(tag))
        with trace_span("resilience/commit", lane="resilience"):
            write_manifest(staging)
            commit_checkpoint(staging, final_dir)
            if save_latest:
                write_latest(save_dir, str(tag))
        self.faults.after_commit(final_dir)
        if self.cfg.keep_last:
            self._prune(save_dir, keep=self.cfg.keep_last)

    def _prune(self, save_dir: str, keep: int) -> None:
        """Retention: drop the oldest COMMITTED tags past ``keep``.
        Legacy/unknown directories are never touched, and neither is the
        tag ``latest`` points at, the tag this run resumed from (it may
        be the only state that predates an in-flight experiment), nor
        the newest committed tag (an async save racing the interval
        autosave must never leave the directory empty of valid tags),
        nor any tag published as a LIVE weight version (the serving
        fleet may still be routing to — or rolling onto — it)."""
        from ..checkpoint.serialization import read_latest

        committed = [t for t in list_tags(save_dir)
                     if is_committed(os.path.join(save_dir, t))]
        protected = {read_latest(save_dir), self._resumed_tag}
        if committed:
            protected.add(committed[0])  # newest committed
        try:
            from ..lifecycle.versions import live_tags

            protected |= set(live_tags(save_dir))
        except Exception:  # noqa: BLE001 - retention is advisory
            pass
        for tag in committed[keep:]:
            if tag in protected:
                continue
            victim = os.path.join(save_dir, tag)
            logger.info("resilience: pruning old checkpoint %s "
                        "(keep_last=%d)", victim, keep)
            # drop the marker FIRST so a crash mid-delete leaves a
            # partial (skipped) dir, not a committed-looking torn one
            try:
                os.unlink(os.path.join(victim, COMMITTED_MARKER))
            except OSError:
                continue
            shutil.rmtree(victim, ignore_errors=True)

    def wait_for_pending_saves(self) -> None:
        if self.writer is not None:
            self.writer.wait()

    # ------------------------------------------------------------------ #
    # step-boundary protocol
    # ------------------------------------------------------------------ #

    def on_step_boundary(self, engine) -> None:
        """Called by the engine after every optimizer step: fault
        injection first (drills want the crash exactly where a real one
        lands), then preemption, then interval autosave, then the
        lifecycle hooks (version publish sees the fresh checkpoint; a
        pending live re-mesh lands AFTER the save so the tag predates
        the flip)."""
        if self.faults.armed:
            self.faults.on_step(engine.global_steps)
        if self.guard is not None and self.guard.requested:
            self.handle_preemption(engine)  # raises SystemExit
        if (self.cfg.save_interval_steps
                and engine.global_steps > 0
                and engine.global_steps % self.cfg.save_interval_steps == 0):
            if self._save_dir is not None:
                engine.save_checkpoint(self._save_dir)
            elif not self._warned_no_save_dir:
                self._warned_no_save_dir = True
                logger.warning(
                    "resilience.save_interval_steps is set but no save "
                    "dir is known (set resilience.save_dir or call "
                    "save_checkpoint once); autosaves skipped")
        for hook in list(self.lifecycle):
            hook.poll(engine)

    def handle_preemption(self, engine) -> None:
        """The orderly-exit protocol: urgent checkpoint, drain pending
        writes, drain serving, exit with the sentinel code."""
        signum = self.guard.signum if self.guard is not None else None
        self._inc("resilience_preemption_total",
                  "preemption signals honored")
        logger.warning(
            "preemption (signal %s): urgent checkpoint at step %d, then "
            "exit %d", signum, engine.global_steps,
            self.cfg.preemption_exit_code)
        if self._save_dir is not None:
            try:
                engine.save_checkpoint(self._save_dir)
                self.wait_for_pending_saves()
            except Exception as e:  # noqa: BLE001 - exit anyway
                logger.error("urgent checkpoint failed: %s", e)
        else:
            logger.warning(
                "no save dir known for the urgent checkpoint (set "
                "resilience.save_dir); exiting without one")
        for srv in list(self.serving):
            try:
                leftover = srv.drain()
                if leftover:
                    logger.warning(
                        "serving drain: %d queued requests never admitted",
                        len(leftover))
            except Exception as e:  # noqa: BLE001
                logger.error("serving drain failed: %s", e)
        if getattr(engine, "datapipe", None) is not None:
            # stop the prefetch thread before exiting; staged batches are
            # recomputed from the checkpointed DataState on resume
            try:
                engine.datapipe.close()
            except Exception as e:  # noqa: BLE001
                logger.error("datapipe close failed: %s", e)
        if self.guard is not None:
            self.guard.uninstall()
        raise SystemExit(self.cfg.preemption_exit_code)

    # ------------------------------------------------------------------ #
    # load-side + serving hooks
    # ------------------------------------------------------------------ #

    def note_resumed(self, tag) -> None:
        self._inc("resilience_resume_total", "checkpoint resumes")
        self._resumed_tag = str(tag)
        step = tag_step(str(tag))
        log_dist(f"resilience: resumed from tag {tag}"
                 + (f" (step {step})" if step is not None else ""),
                 ranks=[0])

    def note_fallback(self, skipped_tag: Optional[str] = None) -> None:
        self._inc("resilience_fallback_total",
                  "loads that fell back past an invalid tag")
        if skipped_tag is not None:
            self._inc("resilience_corrupt_tags",
                      "checkpoint tags skipped as torn/corrupt at load")
            trace_instant("resilience/corrupt_tag", lane="resilience",
                          tag=str(skipped_tag))
            logger.warning(
                "resilience: skipped corrupt/torn checkpoint tag %r",
                skipped_tag)

    def note_restart_context(self) -> None:
        """Child-side record of a supervisor restart: when the process
        was (re)launched by the supervisor (DS_TPU_RESTART_COUNT > 0),
        bump ``resilience_restarts`` and drop a trace instant carrying
        the restart reason and the chosen elastic world size. Once per
        process — engine re-inits in one process do not re-count."""
        if self._restart_noted:
            return
        self._restart_noted = True
        try:
            count = int(os.environ.get("DS_TPU_RESTART_COUNT", "0"))
        except ValueError:
            count = 0
        if count <= 0:
            return
        reason = os.environ.get("DS_TPU_RESTART_REASON", "unknown")
        world = os.environ.get("DS_TPU_WORLD_SIZE")
        self._inc("resilience_restarts",
                  "supervisor restarts observed by this run")
        trace_instant("resilience/restart", lane="resilience",
                      count=count, reason=reason,
                      world_size=int(world) if world else None)
        log_dist(f"resilience: restart #{count} (reason: {reason}"
                 + (f", world size {world}" if world else "") + ")",
                 ranks=[0])

    def attach_serving(self, serving_engine) -> None:
        if serving_engine not in self.serving:
            self.serving.append(serving_engine)

    def attach_lifecycle(self, hook) -> None:
        """Register a lifecycle step-boundary hook (anything with a
        ``poll(engine)`` method — the RemeshHook, the version
        publisher); polled after fault/preemption/autosave handling."""
        if hook not in self.lifecycle:
            self.lifecycle.append(hook)

    # ------------------------------------------------------------------ #

    def discover_resume_tag(self, load_dir: Optional[str] = None
                            ) -> Optional[str]:
        """Newest valid tag in ``load_dir`` (defaults to the known save
        dir) — what the supervisor exports to a restarted child."""
        load_dir = load_dir or self._save_dir
        if load_dir is None:
            return None
        return find_latest_valid_tag(
            load_dir, verify_checksums=self.cfg.verify_on_load)

    def close(self) -> None:
        """Uninstall handlers and stop the writer (draining first)."""
        if self._closed:
            return
        self._closed = True
        if self.guard is not None:
            self.guard.uninstall()
        if self.writer is not None:
            self.writer.close(wait=True)


class _SaveJob:
    """One queued write: binds the snapshot to its destination. A plain
    callable so the writer stays generic."""

    __slots__ = ("mgr", "save_dir", "tag", "files", "save_latest")

    def __init__(self, mgr, save_dir, tag, files, save_latest):
        self.mgr = mgr
        self.save_dir = save_dir
        self.tag = str(tag)
        self.files = files
        self.save_latest = save_latest

    def __call__(self) -> None:
        self.mgr._write_payload(self.save_dir, self.tag, self.files,
                                self.save_latest)
