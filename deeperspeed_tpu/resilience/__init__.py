"""Fault tolerance: async two-phase checkpointing, preemption handling,
fault injection, and auto-resume.

The subsystem the north star's "production TPU pool" requirement rests
on — long runs must survive being killed at any instant:

  * ``manager.ResilienceManager`` — engine-facing composition: async
    (or sync) two-phase-commit saves, interval autosaves, the
    preemption protocol, telemetry.
  * ``manifest`` — per-file checksum manifests, COMMITTED markers, the
    staging-dir commit dance, and valid-tag discovery/fallback.
  * ``writer.AsyncCheckpointWriter`` — bounded-queue background writer.
  * ``preemption.PreemptionGuard`` — SIGTERM/SIGINT -> urgent
    checkpoint at the next step boundary -> serving drain -> sentinel
    exit.
  * ``faults`` — deterministic fault injection (raise / SIGKILL
    mid-save / corruption) for drills and tests.
  * ``supervisor`` — ``python -m deeperspeed_tpu.resilience.supervisor
    -- <train cmd>``: restart on crash (exponential backoff, capped) or
    preemption (immediately), discovering the newest valid checkpoint
    and composing with ``elasticity/`` for resumes on a different chip
    count.

Lifecycle mirrors the monitor: ``init_resilience(config)`` installs the
process-global manager; engines adopt it at init, serving engines
register for preemption drain. Without a ``"resilience"`` config block
nothing is installed and the hot path pays one ``is None`` check.
"""

from typing import Optional, Union

from .config import PREEMPTION_EXIT_CODE_DEFAULT, ResilienceConfig
from .faults import (
    FAULTS_ENV_VAR,
    FaultInjector,
    FaultPlan,
    InjectedFault,
    PoolEvent,
    SpotPoolSimulator,
    corrupt_file,
)
from .manifest import (
    COMMITTED_MARKER,
    MANIFEST_FILE,
    STAGING_SUFFIX,
    CheckpointCorruption,
    commit_checkpoint,
    find_latest_valid_tag,
    is_committed,
    resolve_load_tag,
    tag_status,
    verify_manifest,
    write_manifest,
)
from .manager import ResilienceManager
from .preemption import PreemptionGuard
from .reshard import (
    plans_reshardable,
    remap_data_state,
    reshard_comm_residuals,
    reshard_transform_residuals,
)
from .supervisor import Supervisor, SupervisorPolicy, compute_backoff
from .writer import AsyncCheckpointWriter, CheckpointWriteError

__all__ = [
    "AsyncCheckpointWriter",
    "CheckpointCorruption",
    "CheckpointWriteError",
    "COMMITTED_MARKER",
    "FAULTS_ENV_VAR",
    "FaultInjector",
    "FaultPlan",
    "InjectedFault",
    "MANIFEST_FILE",
    "PREEMPTION_EXIT_CODE_DEFAULT",
    "PoolEvent",
    "PreemptionGuard",
    "SpotPoolSimulator",
    "ResilienceConfig",
    "ResilienceManager",
    "STAGING_SUFFIX",
    "Supervisor",
    "SupervisorPolicy",
    "commit_checkpoint",
    "compute_backoff",
    "corrupt_file",
    "find_latest_valid_tag",
    "get_resilience_manager",
    "init_resilience",
    "is_committed",
    "plans_reshardable",
    "remap_data_state",
    "reshard_comm_residuals",
    "reshard_transform_residuals",
    "resolve_load_tag",
    "shutdown_resilience",
    "tag_status",
    "verify_manifest",
    "write_manifest",
]

_manager: Optional[ResilienceManager] = None


def get_resilience_manager() -> Optional[ResilienceManager]:
    """The process-global manager, or None when resilience is off."""
    return _manager


def init_resilience(
        config: Union[ResilienceConfig, dict, None]) -> ResilienceManager:
    """Build + install the process-global ResilienceManager (closing a
    previously installed one first, so signal handlers and writer
    threads never stack)."""
    global _manager
    cfg = (config if isinstance(config, ResilienceConfig)
           else ResilienceConfig.from_dict(config))
    if _manager is not None:
        _manager.close()
    _manager = ResilienceManager(cfg)
    return _manager


def shutdown_resilience() -> None:
    """Drain pending saves, uninstall handlers, drop the global."""
    global _manager
    if _manager is not None:
        _manager.close()
        _manager = None
