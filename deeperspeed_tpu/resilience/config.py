"""Resilience-block configuration.

The fault-tolerance counterpart of the ``"serving"``/``"monitor"``
blocks: a ``"resilience"`` block in the master JSON config (or a plain
dict) builds a ``ResilienceConfig``. Block presence enables the
subsystem unless ``{"enabled": false}``; everything stays off (and the
step loop pays nothing) without it.

::

    "resilience": {
        "save_dir": "/ckpts/run7",     # urgent/interval saves target
        "async_save": true,            # background writer thread
        "max_pending_saves": 2,        # bounded queue (backpressure)
        "save_interval_steps": 500,    # 0 = manual saves only
        "keep_last": 3,                # prune older committed tags; 0 = keep all
        "verify_on_load": true,        # manifest checksums at load
        "preemption_guard": true,      # SIGTERM/SIGINT -> urgent ckpt + exit
        "preemption_signals": ["SIGTERM", "SIGINT"],
        "preemption_exit_code": 86,    # sentinel the supervisor keys on
        "faults": null                 # fault-injection plan (drills/tests)
    }
"""

import dataclasses
import signal
from typing import Optional, Tuple

_KNOWN_KEYS = frozenset({
    "enabled", "save_dir", "async_save", "max_pending_saves",
    "save_interval_steps", "keep_last", "verify_on_load",
    "preemption_guard", "preemption_signals", "preemption_exit_code",
    "faults",
})

# distinct sentinel so the supervisor can tell "preempted, restart now"
# from "crashed, back off": outside both the 0-127 plain-exit range a
# shell maps real signals into (128+N) and small user codes
PREEMPTION_EXIT_CODE_DEFAULT = 86


@dataclasses.dataclass(frozen=True)
class ResilienceConfig:
    # master switch; runtime/config.py treats block presence as enabled
    # unless {"enabled": false}
    enabled: bool = True
    # where urgent (preemption) and interval saves land; also adopted
    # from the first explicit save_checkpoint(save_dir) call
    save_dir: Optional[str] = None
    # hand the serialize+write to the background writer thread; the step
    # loop only blocks for the device->host snapshot
    async_save: bool = True
    # bounded writer queue: a submit past this many unwritten snapshots
    # blocks (backpressure) instead of accumulating host copies
    max_pending_saves: int = 2
    # automatic save every N optimizer steps; 0 = manual saves only
    save_interval_steps: int = 0
    # retention: after each commit keep only the newest N committed
    # tags (legacy/unknown dirs are never pruned); 0 = keep everything
    keep_last: int = 0
    # verify manifest checksums before trusting a tag at load
    verify_on_load: bool = True
    # install the SIGTERM/SIGINT handler (urgent checkpoint at the next
    # step boundary, serving drain, sentinel exit)
    preemption_guard: bool = True
    preemption_signals: Tuple[str, ...] = ("SIGTERM", "SIGINT")
    preemption_exit_code: int = PREEMPTION_EXIT_CODE_DEFAULT
    # fault-injection plan (resilience/faults.py) — drills and tests
    # only; merged with the DS_TPU_FAULTS env var (env wins)
    faults: Optional[dict] = None

    def __post_init__(self):
        if self.max_pending_saves < 1:
            raise ValueError(
                f"max_pending_saves must be >= 1, got {self.max_pending_saves}")
        if self.save_interval_steps < 0:
            raise ValueError(
                f"save_interval_steps must be >= 0, got "
                f"{self.save_interval_steps}")
        if self.keep_last < 0:
            raise ValueError(f"keep_last must be >= 0, got {self.keep_last}")
        if not (0 < int(self.preemption_exit_code) < 256):
            raise ValueError(
                f"preemption_exit_code must be in 1..255, got "
                f"{self.preemption_exit_code}")
        for name in self.preemption_signals:
            if not hasattr(signal, str(name)):
                raise ValueError(f"unknown signal name {name!r} in "
                                 f"preemption_signals")
        if self.faults is not None and not isinstance(self.faults, dict):
            raise ValueError('"faults" must be a dict (see resilience/'
                             'faults.py) or null')
        if self.faults is not None:
            from .faults import FaultPlan

            FaultPlan.from_dict(self.faults)  # validate eagerly

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "ResilienceConfig":
        d = dict(d or {})
        unknown = set(d) - _KNOWN_KEYS
        if unknown:
            raise ValueError(
                f"unknown resilience config keys {sorted(unknown)}; "
                f"valid keys: {sorted(_KNOWN_KEYS)}")
        if "preemption_signals" in d:
            d["preemption_signals"] = tuple(d["preemption_signals"])
        return cls(**d)
