"""Deterministic fault injection for resilience drills and tests.

A ``FaultPlan`` names WHERE to hurt the process; the ``FaultInjector``
holds the counters that decide WHEN. Faults come from the config block
(``"resilience": {"faults": {...}}``) and/or the ``DS_TPU_FAULTS`` env
var (JSON object, or ``k=v,k=v`` shorthand; env wins key-by-key) so a
drill script can arm a child trainer without touching its config.

Supported faults:

  * ``raise_at_step: N``      — raise ``InjectedFault`` at optimizer
    step N's boundary (generic crash).
  * ``sigkill_at_step: N``    — SIGKILL the process at step N's
    boundary (crash that skips every handler/atexit path).
  * ``sigkill_mid_save: K``   — SIGKILL while the K-th checkpoint file
    of the process's lifetime is being persisted, BEFORE the commit
    rename: the canonical "died mid-save" drill. The committed/latest
    state must be unaffected.
  * ``corrupt_after_save: "truncate" | "bitflip"`` — after a commit,
    damage one payload file in the published tag (simulated disk/bus
    corruption); the manifest check at load must catch it.
  * ``flag_file: path``       — one-shot latch: faults only fire while
    ``path`` does not exist, and the injector creates it just before
    firing. Lets a supervisor restart the SAME command line and have
    the second run proceed cleanly.

Serving-replica faults (fired from ``on_decode_step``, which a serving
replica worker calls once per engine step — the fleet drill's knobs):

  * ``replica_sigkill_at_decode: N`` — SIGKILL the replica process at
    its N-th decode step (mid-stream death; the router must requeue
    the replica's in-flight requests).
  * ``replica_stall_at_decode: N``  — from the N-th decode step on,
    ``on_decode_step`` returns ``"stall"`` and the worker stops
    stepping its engine while still heartbeating (a wedged-but-alive
    replica; the router's progress watchdog must catch it).
  * ``replica_slow_ms: K``          — sleep K ms inside every decode
    step (degraded replica for brownout drills).

Everything is deterministic — counters, not probabilities — so drills
are reproducible bit-for-bit.
"""

import dataclasses
import json
import os
import signal
import time
from typing import List, Optional, Sequence

from ..utils.logging import logger

FAULTS_ENV_VAR = "DS_TPU_FAULTS"

_CORRUPT_MODES = ("truncate", "bitflip")


class InjectedFault(RuntimeError):
    """Raised by ``raise_at_step`` — a reproducible generic crash."""


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    raise_at_step: Optional[int] = None
    sigkill_at_step: Optional[int] = None
    sigkill_mid_save: Optional[int] = None
    corrupt_after_save: Optional[str] = None
    flag_file: Optional[str] = None
    # serving-replica faults (see module docstring)
    replica_sigkill_at_decode: Optional[int] = None
    replica_stall_at_decode: Optional[int] = None
    replica_slow_ms: Optional[int] = None

    def __post_init__(self):
        for key in ("raise_at_step", "sigkill_at_step", "sigkill_mid_save",
                    "replica_sigkill_at_decode", "replica_stall_at_decode",
                    "replica_slow_ms"):
            v = getattr(self, key)
            if v is not None and int(v) < 1:
                raise ValueError(f"{key} must be >= 1, got {v}")
        if (self.corrupt_after_save is not None
                and self.corrupt_after_save not in _CORRUPT_MODES):
            raise ValueError(
                f"corrupt_after_save must be one of {_CORRUPT_MODES}, got "
                f"{self.corrupt_after_save!r}")

    @property
    def any_armed(self) -> bool:
        return any(getattr(self, f.name) is not None
                   for f in dataclasses.fields(self)
                   if f.name != "flag_file")

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "FaultPlan":
        d = dict(d or {})
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown fault keys {sorted(unknown)}; "
                             f"valid keys: {sorted(known)}")
        return cls(**d)


def _parse_env_spec(spec: str) -> dict:
    spec = spec.strip()
    if not spec:
        return {}
    if spec.startswith("{"):
        return json.loads(spec)
    out = {}
    for part in spec.split(","):
        k, _, v = part.partition("=")
        k = k.strip()
        v = v.strip()
        out[k] = int(v) if v.lstrip("-").isdigit() else v
    return out


def plan_from_config_and_env(config_faults: Optional[dict]) -> FaultPlan:
    merged = dict(config_faults or {})
    env = os.environ.get(FAULTS_ENV_VAR, "")
    if env:
        merged.update(_parse_env_spec(env))
    return FaultPlan.from_dict(merged)


def corrupt_file(path: str, mode: str = "truncate") -> None:
    """Damage one on-disk file in place (test/drill utility)."""
    if mode == "truncate":
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(size // 2, 0))
    elif mode == "bitflip":
        with open(path, "r+b") as f:
            f.seek(max(os.path.getsize(path) // 2 - 1, 0))
            byte = f.read(1) or b"\0"
            f.seek(-len(byte), os.SEEK_CUR)
            f.write(bytes([byte[0] ^ 0x40]))
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")


def _sigkill() -> None:  # pragma: no cover - kills the test process
    os.kill(os.getpid(), signal.SIGKILL)


class FaultInjector:
    """Counters + trigger points for one process. All hooks are no-ops
    when the plan is empty, so production runs pay one attribute read."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._files_written = 0
        self.armed = plan.any_armed
        if self.armed:
            logger.warning("fault injection ARMED: %s", plan)

    # ---- one-shot latch ------------------------------------------- #

    def _latched_out(self) -> bool:
        """True when the one-shot flag file says faults already fired."""
        return (self.plan.flag_file is not None
                and os.path.exists(self.plan.flag_file))

    def _latch(self) -> None:
        if self.plan.flag_file is not None:
            parent = os.path.dirname(self.plan.flag_file)
            if parent:
                os.makedirs(parent, exist_ok=True)
            with open(self.plan.flag_file, "w") as f:
                f.write("fired\n")
                f.flush()
                os.fsync(f.fileno())

    # ---- trigger points -------------------------------------------- #

    def on_step(self, global_step: int) -> None:
        """Step-boundary faults (called after each optimizer step)."""
        if not self.armed or self._latched_out():
            return
        if (self.plan.sigkill_at_step is not None
                and global_step == self.plan.sigkill_at_step):
            logger.warning("fault: SIGKILL at step %d", global_step)
            self._latch()
            _sigkill()
        if (self.plan.raise_at_step is not None
                and global_step == self.plan.raise_at_step):
            self._latch()
            raise InjectedFault(f"injected fault at step {global_step}")

    def on_decode_step(self, decode_step: int) -> Optional[str]:
        """Serving-replica trigger point, called by the replica worker
        once per engine step (1-based). Returns ``"stall"`` when the
        worker should stop stepping its engine (but keep heartbeating);
        ``replica_slow_ms`` sleeps here; ``replica_sigkill_at_decode``
        does not return."""
        if not self.armed:
            return None
        if self.plan.replica_slow_ms is not None:
            time.sleep(self.plan.replica_slow_ms / 1000.0)
        if self._latched_out():
            return None
        if (self.plan.replica_sigkill_at_decode is not None
                and decode_step >= self.plan.replica_sigkill_at_decode):
            logger.warning("fault: replica SIGKILL at decode step %d",
                           decode_step)
            self._latch()
            _sigkill()
        if (self.plan.replica_stall_at_decode is not None
                and decode_step >= self.plan.replica_stall_at_decode):
            # the caller keeps the wedge for the life of this process (a
            # stall is not a blip); the flag-file latch only stops a
            # RESTARTED replica from wedging again
            self._latch()
            return "stall"
        return None

    def on_save_file_written(self, path: str) -> None:
        """Called after each checkpoint payload file is written (still in
        the staging dir, before the commit rename)."""
        if not self.armed:
            return
        self._files_written += 1
        if (self.plan.sigkill_mid_save is not None
                and self._files_written >= self.plan.sigkill_mid_save
                and not self._latched_out()):
            logger.warning("fault: SIGKILL mid-save after writing %s", path)
            self._latch()
            _sigkill()

    def after_commit(self, ckpt_dir: str) -> None:
        """Called once per committed tag; corrupts one payload file when
        the plan asks for it (the NEXT load must detect and fall back)."""
        if (not self.armed or self.plan.corrupt_after_save is None
                or self._latched_out()):
            return
        from .manifest import MANIFEST_FILE, COMMITTED_MARKER

        for name in sorted(os.listdir(ckpt_dir)):
            full = os.path.join(ckpt_dir, name)
            if name in (MANIFEST_FILE, COMMITTED_MARKER):
                continue
            if os.path.isfile(full) and os.path.getsize(full) > 0:
                self._latch()
                corrupt_file(full, self.plan.corrupt_after_save)
                logger.warning("fault: %s-corrupted %s",
                               self.plan.corrupt_after_save, full)
                return


# ------------------------------------------------------------------- #
# spot-pool simulation (elastic drills)
# ------------------------------------------------------------------- #


@dataclasses.dataclass(frozen=True)
class PoolEvent:
    """One spot-pool episode: the trainer is SIGKILLed at optimizer step
    ``kill_at_step``, after which the surviving pool holds
    ``pool_after`` devices (shrink OR grow — preempted capacity often
    comes back bigger)."""

    kill_at_step: int
    pool_after: int

    def __post_init__(self):
        if int(self.kill_at_step) < 1:
            raise ValueError(
                f"kill_at_step must be >= 1, got {self.kill_at_step}")
        if int(self.pool_after) < 1:
            raise ValueError(
                f"pool_after must be >= 1, got {self.pool_after}")


class SpotPoolSimulator:
    """Deterministic spot-pool driver for elastic fault drills.

    Owns the pool file the supervisor's ``--pool-file`` flag re-reads
    before every launch, and a fixed schedule of :class:`PoolEvent`
    episodes. Drill flow per supervised launch:

      1. ``child_faults()`` -> the ``DS_TPU_FAULTS`` dict arming the
         child's injector with this episode's ``sigkill_at_step``
         (None once the schedule is drained — the final child runs to
         completion).
      2. the child dies; the drill calls ``on_child_exit(rc)``, which
         advances the schedule and rewrites the pool file with the
         surviving device count, so the supervisor's next
         ``_choose_world`` sees the new pool.

    Everything is schedule-driven — no clocks, no probabilities — so a
    drill replays bit-for-bit."""

    def __init__(self, pool_file: str, initial_pool: int,
                 events: Sequence[PoolEvent]):
        self.pool_file = pool_file
        self.events = list(events)
        self.index = 0
        self.transitions: List[dict] = []  # one record per fired episode
        self._write_pool(int(initial_pool))

    @property
    def current_event(self) -> Optional[PoolEvent]:
        return (self.events[self.index]
                if self.index < len(self.events) else None)

    def _write_pool(self, n: int) -> None:
        parent = os.path.dirname(self.pool_file)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = self.pool_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(f"{n}\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.pool_file)

    def read_pool(self) -> int:
        with open(self.pool_file) as f:
            return int(f.read().strip())

    def child_faults(self) -> Optional[dict]:
        """The DS_TPU_FAULTS plan for the current episode's child."""
        ev = self.current_event
        if ev is None:
            return None
        return {"sigkill_at_step": int(ev.kill_at_step)}

    def on_child_exit(self, rc: int) -> Optional[PoolEvent]:
        """Advance the schedule after a child death: rewrite the pool
        file with the episode's surviving device count and record the
        transition. A clean exit (rc == 0) never advances — the run
        outlived the schedule."""
        ev = self.current_event
        if ev is None or rc == 0:
            return None
        self.index += 1
        self._write_pool(int(ev.pool_after))
        self.transitions.append({
            "kill_at_step": int(ev.kill_at_step),
            "pool_after": int(ev.pool_after),
            "exit_code": int(rc),
        })
        logger.info(
            "spot-pool: episode %d fired (kill@%d, exit %d); surviving "
            "pool is %d device(s)", self.index, ev.kill_at_step, rc,
            ev.pool_after)
        return ev
