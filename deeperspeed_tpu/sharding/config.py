"""The ``"mesh"`` config block — one place where a run chooses its layout.

Validated eagerly at config-parse time (like ``serving``/``comm``/
``monitor``), so a typo'd axis name fails at load instead of silently
training replicated. The block maps directly onto the canonical named
mesh ``dp × fsdp × tp × sp`` built by :mod:`.mesh`:

.. code-block:: json

    {"mesh": {"dp": 2, "fsdp": 4, "tp": 1, "sp": 1}}

* ``dp``    — pure data parallelism: params replicated, batch sharded.
* ``fsdp``  — the ZeRO axis: batch sharded AND (per ``zero_optimization
  .stage``) master/grad/param trees sharded over it. ZeRO stages 1/2/3
  degenerate into fsdp-axis PartitionSpecs (ZeRO++, arXiv:2306.10209).
* ``tp``    — tensor parallelism (megatron column/row splits).
* ``sp``    — sequence/context parallelism (ring/Ulysses attention).

Exactly one axis may be ``-1`` (inferred from the device count). A
``rules`` sub-dict overrides individual logical-axis rules (see
:data:`..rules.DEFAULT_RULES`), e.g. ``{"rules": {"mlp": null}}`` to keep
MLP weights replicated on a tp mesh.
"""

import dataclasses
from typing import Dict, Optional, Tuple

__all__ = ["MeshConfig", "CANONICAL_AXES", "resolve_extents"]

# canonical axis order: batch-ish axes first, the axis with the heaviest
# steady-state communication (tp, then sp) last so it lands on the
# innermost ICI ring when the physical topology is folded in
CANONICAL_AXES: Tuple[str, ...] = ("dp", "fsdp", "tp", "sp")

_VALID_RULE_TARGETS = frozenset(CANONICAL_AXES) | {"expert"}


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Validated ``"mesh"`` block: axis extents + logical-rule overrides."""

    dp: int = -1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    rules: Optional[Dict[str, object]] = None
    enabled: bool = True

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "MeshConfig":
        d = dict(d or {})
        enabled = bool(d.pop("enabled", True))
        rules = d.pop("rules", None)
        if rules is not None:
            if not isinstance(rules, dict):
                raise ValueError(
                    f'"rules" must be a dict of logical-axis overrides, '
                    f"got {type(rules).__name__}")
            for k, v in rules.items():
                targets = v if isinstance(v, (tuple, list)) else (v,)
                for t in targets:
                    if t is not None and t not in _VALID_RULE_TARGETS:
                        raise ValueError(
                            f"rules[{k!r}] names unknown mesh axis {t!r} "
                            f"(valid: {sorted(_VALID_RULE_TARGETS)} or null)")
            rules = {k: (tuple(v) if isinstance(v, list) else v)
                     for k, v in rules.items()}
        unknown = set(d) - set(CANONICAL_AXES)
        if unknown:
            raise ValueError(
                f"unknown mesh keys {sorted(unknown)}; valid keys: "
                f"{list(CANONICAL_AXES)} + ['rules', 'enabled']")
        dims = {}
        for a in CANONICAL_AXES:
            v = d.get(a, -1 if a == "dp" else 1)
            if not isinstance(v, int) or isinstance(v, bool):
                raise ValueError(f'mesh axis "{a}" must be an int, got {v!r}')
            if v == 0 or v < -1:
                raise ValueError(
                    f'mesh axis "{a}" must be a positive extent or -1 '
                    f"(inferred), got {v}")
            dims[a] = v
        inferred = [a for a, v in dims.items() if v == -1]
        if len(inferred) > 1:
            raise ValueError(
                f"at most one mesh axis may be -1 (inferred); got "
                f"{inferred}")
        return cls(rules=rules, enabled=enabled, **dims)

    def axis_dims(self) -> Dict[str, int]:
        """{axis: extent} in canonical order (``-1`` still to be inferred)."""
        return {a: getattr(self, a) for a in CANONICAL_AXES}

    def as_dict(self) -> dict:
        out = {a: getattr(self, a) for a in CANONICAL_AXES}
        if self.rules:
            out["rules"] = {k: list(v) if isinstance(v, tuple) else v
                            for k, v in self.rules.items()}
        return out

    def resolve(self, world: int) -> Dict[str, int]:
        """Full extents for ``world`` devices — the same single ``-1``
        inference ``parallel.topology.build_mesh`` applies, but without
        needing jax devices, so enumeration/validation tooling (the
        autotuner's admissibility sweep, config linting) can reason
        about layouts on any host."""
        dims = self.axis_dims()
        inferred = [a for a, v in dims.items() if v == -1]
        known = 1
        for v in dims.values():
            if v != -1:
                known *= v
        if inferred:
            if world % known != 0:
                raise ValueError(
                    f"cannot infer mesh axis {inferred[0]!r}: known "
                    f"extents multiply to {known}, which does not divide "
                    f"world={world}")
            dims[inferred[0]] = world // known
        elif known != world:
            raise ValueError(
                f"mesh extents {dims} multiply to {known} != "
                f"world={world}")
        return dims


def resolve_extents(block: Optional[dict], world: int) -> Dict[str, int]:
    """Validate a ``"mesh"`` block and resolve it to full canonical
    extents for ``world`` devices (module-level convenience over
    :meth:`MeshConfig.resolve`)."""
    return MeshConfig.from_dict(block).resolve(world)
