"""Mesh factory — the one place a ``jax.sharding.Mesh`` is constructed.

Every subsystem (engine, serving, datapipe, comm, TP layers, pipeline
grid) historically built its own ``Mesh(...)`` ad hoc; this module owns
construction so they all share one instance and one naming scheme:

* :func:`make_mesh` — the single raw construction site. The legacy
  builders (``parallel.topology.build_mesh`` / ``single_device_mesh``)
  now route through it.
* :func:`from_config` — the ``"mesh"`` config block → a canonical named
  mesh over ``dp × fsdp × tp × sp`` (size-1 axes kept, so specs are
  uniform across layouts; :func:`..rules.translate_spec` drops them at
  constraint time).
* :func:`default_mesh` — what an engine gets with no mesh and no block:
  all devices on the legacy ``data`` axis (unchanged behavior).

CPU-testable by construction: under the test harness's
``xla_force_host_platform_device_count=8`` the same factory code builds
8-device host meshes, which is how every layout in
``tests/test_sharding.py`` and ``scripts/mesh_bench.py`` runs without
hardware.
"""

from typing import Optional, Sequence

import numpy as np

from .config import CANONICAL_AXES, MeshConfig

__all__ = [
    "DP_AXIS", "FSDP_AXIS", "TP_AXIS", "SP_AXIS", "CANONICAL_AXES",
    "make_mesh", "from_config", "default_mesh", "describe", "is_canonical",
]

DP_AXIS = "dp"
FSDP_AXIS = "fsdp"
TP_AXIS = "tp"
SP_AXIS = "sp"


def make_mesh(device_array, axis_names):
    """THE raw Mesh construction site. ``device_array`` must already be
    shaped to the axis extents (topology-aware ordering is the caller's
    job — see ``parallel.topology.build_mesh``)."""
    from jax.sharding import Mesh

    return Mesh(np.asarray(device_array), tuple(axis_names))


def from_config(cfg, devices: Optional[Sequence] = None):
    """``"mesh"`` block (dict or :class:`MeshConfig`) → canonical Mesh.

    Keeps all four named axes, including size-1 ones — a ``{"dp": 8}``
    mesh is ``dp=8, fsdp=1, tp=1, sp=1``, so the same PartitionSpecs
    resolve on every layout. Dims of -1 are inferred from the device
    count (at most one). Emits a ``mesh/build`` trace instant when a
    monitor is installed, so merged traces record which layout a run
    actually used.
    """
    if not isinstance(cfg, MeshConfig):
        cfg = MeshConfig.from_dict(cfg)
    # delegate dim inference + ICI-aware device arrangement to the shared
    # builder (which constructs through make_mesh above)
    from ..parallel.topology import build_mesh

    mesh = build_mesh(cfg.axis_dims(), devices=devices)
    try:  # observability is optional — never a hard dependency
        from ..monitor import trace_instant

        trace_instant("mesh/build", lane="mesh",
                      axes=dict(mesh.shape), devices=mesh.devices.size)
    except Exception:
        pass
    return mesh


def default_mesh():
    """All local devices on the legacy ``data`` axis — the engine's
    behavior when neither a mesh argument nor a ``"mesh"`` block is
    given. Kept legacy-named so existing data-parallel runs are
    byte-identical."""
    import jax

    from ..parallel.topology import DATA_AXIS, build_mesh, single_device_mesh

    n = len(jax.devices())
    if n == 1:
        return single_device_mesh((DATA_AXIS,))
    return build_mesh({DATA_AXIS: n})


def is_canonical(mesh) -> bool:
    """True when the mesh uses the canonical dp/fsdp/tp/sp naming."""
    return mesh is not None and any(a in mesh.axis_names
                                    for a in CANONICAL_AXES)


def describe(mesh) -> dict:
    """JSON-able layout descriptor (for BENCH files and trace args)."""
    if mesh is None:
        return {"axes": {}, "devices": 0, "generation": "none"}
    return {
        "axes": {a: int(s) for a, s in mesh.shape.items()},
        "devices": int(mesh.devices.size),
        "generation": "canonical" if is_canonical(mesh) else "legacy",
    }
