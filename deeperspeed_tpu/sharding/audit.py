"""Placement audit: stable digests of how arrays actually landed.

``jax.debug.visualize_array_sharding`` is great interactively but its
box-drawing output is useless in CI. This module turns committed
shardings into small JSON-able digests so benches and tests can assert
"this layout actually sharded the MLP over tp" instead of eyeballing:

* :func:`spec_digest` — one placed array → ``{"spec", "shape",
  "n_shards", "shard_shape", "viz_sha1"}`` where ``viz_sha1`` hashes the
  visualize_array_sharding rendering (layout changes flip the hash even
  when the spec string happens to match).
* :func:`tree_digest` — a placed pytree → per-leaf digests keyed by
  flattened path.
* :func:`audit_tree` — summary: total/sharded/replicated leaf counts,
  bytes by axis usage — the number ``scripts/mesh_bench.py`` publishes
  per layout in ``BENCH_mesh.json``.
"""

import hashlib
import io
from typing import Any, Dict

import jax
import numpy as np

__all__ = ["spec_digest", "tree_digest", "audit_tree"]


def _viz_sha1(x) -> str:
    """SHA-1 of the visualize_array_sharding rendering (empty on
    failure — some backends can't render >2-D layouts)."""
    try:
        buf = io.StringIO()
        import rich.console

        console = rich.console.Console(file=buf, force_terminal=False,
                                       width=120)
        jax.debug.visualize_array_sharding(
            x.reshape(x.shape[0], -1) if x.ndim > 2 else x,
            use_color=False, console=console)
        return hashlib.sha1(buf.getvalue().encode()).hexdigest()[:12]
    except Exception:
        return ""


def spec_digest(x) -> Dict[str, Any]:
    """Digest of one committed array's placement."""
    sharding = getattr(x, "sharding", None)
    spec = getattr(sharding, "spec", None)
    try:
        n_shards = len(x.addressable_shards)
        shard_shape = list(x.addressable_shards[0].data.shape)
    except Exception:
        n_shards, shard_shape = 1, list(getattr(x, "shape", ()))
    return {
        "spec": str(spec) if spec is not None else "unsharded",
        "shape": list(getattr(x, "shape", ())),
        "n_shards": int(n_shards),
        "shard_shape": shard_shape,
        "viz_sha1": _viz_sha1(x),
    }


def tree_digest(tree) -> Dict[str, Dict[str, Any]]:
    """Per-leaf placement digests keyed by flattened tree path."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return {jax.tree_util.keystr(path): spec_digest(leaf)
            for path, leaf in flat}


def audit_tree(tree, mesh=None) -> Dict[str, Any]:
    """Placement summary for a whole tree (params, optimizer state...).

    ``sharded_bytes`` counts leaves whose committed spec names at least
    one mesh axis; a ZeRO-3 run on an fsdp mesh should show nearly all
    parameter bytes there, a pure-dp run nearly none."""
    leaves = tree_digest(tree)
    total_b = sharded_b = 0
    sharded = replicated = 0
    for d in leaves.values():
        nbytes = int(np.prod(d["shape"], dtype=np.int64)) if d["shape"] else 1
        total_b += nbytes
        if d["n_shards"] > 1 and d["shard_shape"] != d["shape"]:
            sharded += 1
            sharded_b += nbytes
        else:
            replicated += 1
    out = {
        "leaves": len(leaves),
        "sharded_leaves": sharded,
        "replicated_leaves": replicated,
        "total_elems": int(total_b),
        "sharded_elems": int(sharded_b),
        "sharded_frac": round(sharded_b / total_b, 4) if total_b else 0.0,
        "digest": hashlib.sha1(
            "".join(sorted(f"{k}:{v['spec']}:{v['shard_shape']}"
                           for k, v in leaves.items())).encode()
        ).hexdigest()[:12],
    }
    if mesh is not None:
        from .mesh import describe

        out["mesh"] = describe(mesh)
    return out
