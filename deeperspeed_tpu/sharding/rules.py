"""Logical-axis rules: one table mapping *meaning* to mesh axes.

Everything that places an array — ZeRO spec derivation, TP layers, the
comm reducer, engine/serving/datapipe batch staging, activation
constraints inside the models — resolves through this module instead of
hard-coding mesh axis names. Three layers:

1. **The rule table** (:data:`DEFAULT_RULES`): logical tensor dimensions
   (``batch``, ``seq``, ``embed``, ``heads``, ``mlp``, ``vocab``, ...)
   → canonical mesh axes (``dp``/``fsdp``/``tp``/``sp``). This is the
   SNIPPETS-style partition-rule table, with the classic
   ``"seq": None  # TODO sequence parallel`` cue *implemented*: ``seq``
   maps to the ``sp`` axis and ring/Ulysses attention consumes it.

2. **Axis aliasing** (:func:`translate_spec`): the repo's existing spec
   trees name the legacy axes (``data``/``model``/``seq``). Translation
   maps either naming generation onto whatever axes the mesh actually
   carries — ``data`` ↔ ``(dp, fsdp)``, ``model`` ↔ ``tp``,
   ``seq`` ↔ ``sp`` — then drops axes the mesh lacks or carries at
   size 1 (the old ``filter_spec`` contract). One spec tree therefore
   places correctly on every layout.

3. **ZeRO as sharding policy** (:func:`zero_tree_specs`): stages 1/2/3
   are PartitionSpecs over the mesh's *zero axis* — ``fsdp`` on a
   canonical mesh, ``data`` on a legacy one. ``runtime/zero/partition``
   is now a thin adapter over this function (same ``tree_specs`` API).

All resolvers accept both mesh generations, so the engine, serving
stack, and tests migrate incrementally with bit-identical placement on
legacy meshes.
"""

from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import DP_AXIS, FSDP_AXIS, SP_AXIS, TP_AXIS

__all__ = [
    "DEFAULT_RULES", "resolve_rules", "logical_spec", "logical_constraint",
    "translate_spec", "batch_axes", "zero_axis", "tp_axis", "sp_axis",
    "data_parallel_size", "zero_size", "tp_size", "sp_size",
    "batch_spec", "place_batch", "constrain", "named_shardings",
    "zero_tree_specs", "choose_shard_dim", "add_zero_axis",
]

# ---------------------------------------------------------------------- #
# 1. the logical-axis rule table (SNIPPETS.md [3] style)
# ---------------------------------------------------------------------- #

# logical dim -> canonical mesh axis (None = replicated). The batch dim
# spans BOTH data-parallel axes: dp replicates params, fsdp additionally
# shards them (ZeRO), but each contributes a factor of batch parallelism.
DEFAULT_RULES: Dict[str, Union[None, str, Tuple[str, ...]]] = {
    "batch": (DP_AXIS, FSDP_AXIS),
    "seq": SP_AXIS,        # sequence parallel — the implemented TODO
    "embed": None,         # residual stream stays replicated
    "heads": TP_AXIS,
    "kv": None,
    "joined_kv": TP_AXIS,
    "mlp": TP_AXIS,
    "vocab": TP_AXIS,      # embedding DIM split (vocab-row split is an
                           # anti-layout on TPU — see tp.vocab_parallel_spec)
    "layers": None,        # scan-stacked layer axis
    "expert": "expert",
}

# legacy mesh axis name -> canonical candidates (and the reverse); used
# by translate_spec so one spec tree works on both naming generations
_LEGACY_TO_CANONICAL: Dict[str, Tuple[str, ...]] = {
    "data": (DP_AXIS, FSDP_AXIS),
    "model": (TP_AXIS,),
    "seq": (SP_AXIS,),
}
_CANONICAL_TO_LEGACY: Dict[str, Tuple[str, ...]] = {
    DP_AXIS: ("data",),
    FSDP_AXIS: ("data",),
    TP_AXIS: ("model",),
    SP_AXIS: ("seq",),
}


def resolve_rules(overrides: Optional[Dict] = None) -> Dict:
    """The rule table with per-run overrides (the mesh block's ``rules``
    sub-dict) applied."""
    if not overrides:
        return dict(DEFAULT_RULES)
    out = dict(DEFAULT_RULES)
    out.update(overrides)
    return out


# ---------------------------------------------------------------------- #
# 2. axis aliasing / spec translation
# ---------------------------------------------------------------------- #


def _expand_name(name: str, mesh) -> Tuple[str, ...]:
    """One spec axis name -> the axes this mesh carries for it."""
    if name in mesh.shape:
        return (name,)
    for table in (_LEGACY_TO_CANONICAL, _CANONICAL_TO_LEGACY):
        if name in table:
            return tuple(a for a in table[name] if a in mesh.shape)
    return ()


def translate_spec(spec, mesh):
    """Map a PartitionSpec onto whatever axes ``mesh`` carries.

    Superset of ``parallel.topology.filter_spec``: entries are first
    alias-translated across naming generations (``data`` ↔ dp/fsdp,
    ``model`` ↔ tp, ``seq`` ↔ sp), then axes the mesh lacks — or carries
    at size 1 — are dropped. ``None`` and ``P.UNCONSTRAINED`` pass
    through. On a spec already named in the mesh's own generation this
    is exactly filter_spec.
    """
    if spec is None or mesh is None:
        return spec

    def keep(a):
        return mesh.shape.get(a, 0) > 1

    parts = []
    used = set()  # a mesh axis may appear on at most one dim: when two
    # canonical axes collapse onto one legacy axis (dp+fsdp -> data),
    # the first dim keeps it
    for entry in tuple(spec):
        if entry is None or entry is P.UNCONSTRAINED:
            parts.append(entry)
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        kept = []
        for n in names:
            for a in _expand_name(n, mesh):
                if keep(a) and a not in used:
                    kept.append(a)
                    used.add(a)
        parts.append(tuple(kept) if len(kept) > 1
                     else (kept[0] if kept else None))
    return P(*parts)


# ---------------------------------------------------------------------- #
# per-mesh axis resolvers
# ---------------------------------------------------------------------- #


def batch_axes(mesh) -> Tuple[str, ...]:
    """Axes the batch dimension shards over (grad reduction runs over
    these): ``(dp, fsdp)`` on a canonical mesh, ``(data,)`` on a legacy
    one. Axes are returned even at size 1 — NamedSharding tolerates
    them, and keeping them makes placement uniform across layouts."""
    if mesh is None:
        return ()
    if DP_AXIS in mesh.shape or FSDP_AXIS in mesh.shape:
        return tuple(a for a in (DP_AXIS, FSDP_AXIS) if a in mesh.shape)
    return ("data",) if "data" in mesh.shape else ()


def zero_axis(mesh) -> Optional[str]:
    """The axis ZeRO shards params/grads/optimizer state over: ``fsdp``
    on a canonical mesh (dp replicates — that is the dp/fsdp split),
    ``data`` on a legacy one."""
    if mesh is None:
        return None
    if FSDP_AXIS in mesh.shape:
        return FSDP_AXIS
    if DP_AXIS in mesh.shape:
        return None  # canonical mesh with no fsdp axis: ZeRO sharding off
    return "data" if "data" in mesh.shape else None


def tp_axis(mesh) -> Optional[str]:
    if mesh is None:
        return None
    if TP_AXIS in mesh.shape:
        return TP_AXIS
    return "model" if "model" in mesh.shape else None


def sp_axis(mesh) -> Optional[str]:
    if mesh is None:
        return None
    if SP_AXIS in mesh.shape:
        return SP_AXIS
    return "seq" if "seq" in mesh.shape else None


def _size(mesh, axis: Optional[str]) -> int:
    return int(mesh.shape[axis]) if (mesh is not None and axis is not None
                                     and axis in mesh.shape) else 1


def data_parallel_size(mesh) -> int:
    """Product of the batch-axis extents (what the batch triple and the
    grad mean divide by)."""
    return int(np.prod([_size(mesh, a) for a in batch_axes(mesh)],
                       dtype=np.int64)) if mesh is not None else 1


def zero_size(mesh) -> int:
    return _size(mesh, zero_axis(mesh))


def tp_size(mesh) -> int:
    return _size(mesh, tp_axis(mesh))


def sp_size(mesh) -> int:
    return _size(mesh, sp_axis(mesh))


# ---------------------------------------------------------------------- #
# logical specs / constraints
# ---------------------------------------------------------------------- #


def logical_spec(logical_dims: Sequence[Optional[str]], mesh=None,
                 rules: Optional[Dict] = None) -> P:
    """``("batch", "seq", "embed")`` → a PartitionSpec.

    Each entry is a logical dim name from the rule table (or ``None`` /
    ``P.UNCONSTRAINED``, passed through). Without a mesh the spec names
    canonical axes; with one it is translated onto the axes the mesh
    carries. Unknown logical names raise — placement typos should fail
    loudly."""
    table = resolve_rules(rules)
    parts = []
    for name in logical_dims:
        if name is None or name is P.UNCONSTRAINED:
            parts.append(name)
            continue
        if name not in table:
            raise ValueError(
                f"unknown logical axis {name!r}; known: {sorted(table)}")
        parts.append(table[name])
    spec = P(*parts)
    return translate_spec(spec, mesh) if mesh is not None else spec


def logical_constraint(x, logical_dims: Sequence[Optional[str]], mesh,
                       rules: Optional[Dict] = None):
    """with_sharding_constraint by logical dim names."""
    if mesh is None:
        return x
    spec = logical_spec(logical_dims, mesh, rules)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def constrain(tree, specs, mesh):
    """with_sharding_constraint over a pytree of PartitionSpecs, with
    axis translation (both naming generations accepted)."""
    if mesh is None:
        return tree
    return jax.tree.map(
        lambda x, s: jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, translate_spec(s, mesh))),
        tree, specs)


def named_shardings(mesh, specs):
    """Spec pytree -> NamedSharding pytree (translated onto the mesh)."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, translate_spec(s, mesh)), specs)


# ---------------------------------------------------------------------- #
# batch placement (engine / serving / datapipe all stage through this)
# ---------------------------------------------------------------------- #


def batch_spec(mesh, ndim: int) -> P:
    """Leading-dim batch sharding spec for an ndim-D host array."""
    axes = batch_axes(mesh)
    lead = axes if len(axes) > 1 else (axes[0] if axes else None)
    return P(lead, *([None] * (max(ndim, 1) - 1)))


def place_batch(mesh, batch):
    """Shard a host batch pytree over the mesh's batch axes (leading
    dim). Multi-host: each process contributes its local slice via
    ``jax.make_array_from_process_local_data``. Scalars replicate."""
    multihost = jax.process_count() > 1

    def leaf(x):
        x = np.asarray(x)
        sh = NamedSharding(mesh, batch_spec(mesh, x.ndim) if x.ndim
                           else P())
        if multihost:
            return jax.make_array_from_process_local_data(sh, x)
        return jax.device_put(x, sh)

    return jax.tree.map(leaf, batch)


# ---------------------------------------------------------------------- #
# 3. ZeRO stages as zero-axis PartitionSpecs
# ---------------------------------------------------------------------- #


def choose_shard_dim(shape, spec: P, size: int) -> Optional[int]:
    """Pick the dim to shard over the zero axis: the largest dim
    divisible by ``size`` and not already sharded by another axis."""
    best = None
    best_size = 0
    for i, d in enumerate(shape):
        taken = i < len(spec) and spec[i] is not None
        if taken:
            continue
        if d % size == 0 and d >= size and d > best_size:
            best, best_size = i, d
    return best


def add_zero_axis(spec: Optional[P], shape, axis: Optional[str],
                  size: int) -> P:
    """Extend a (possibly empty) TP spec with zero-axis sharding on one
    structured dim. Leaves with no divisible free dim stay replicated
    (biases/layernorms — a negligible fraction)."""
    spec = spec if spec is not None else P()
    if size <= 1 or axis is None:
        return spec
    idx = choose_shard_dim(shape, spec, size)
    if idx is None:
        return spec
    parts = list(spec) + [None] * (len(shape) - len(spec))
    parts[idx] = axis
    return P(*parts)


def _zero_leaf_spec(leaf, tp_spec: Optional[P], stage: int, kind: str,
                    axis: Optional[str], size: int) -> P:
    base = tp_spec if tp_spec is not None else P()
    threshold = {"param": 3, "grad": 2, "master": 1}[kind]
    if stage >= threshold:
        return add_zero_axis(base, leaf.shape, axis, size)
    return base


def zero_tree_specs(params, tp_specs, stage: int, mesh, kind: str):
    """Map a params pytree (+ optional TP spec pytree) to ZeRO sharding
    specs over the mesh's zero axis.

    kind: ``'param'`` (sharded from stage 3), ``'grad'`` (stage 2 —
    reduce-scatter), ``'master'`` (stage 1 — sharded optimizer state).
    The reference's imperative stages degenerate into these specs under
    GSPMD; XLA emits the corresponding collectives.
    """
    axis = zero_axis(mesh)
    size = zero_size(mesh)
    if tp_specs is None:
        return jax.tree.map(
            lambda p: _zero_leaf_spec(p, None, stage, kind, axis, size),
            params)
    return jax.tree.map(
        lambda p, s: _zero_leaf_spec(p, translate_spec(s, mesh), stage,
                                     kind, axis, size),
        params, tp_specs)
