"""``sharding/`` — the named-mesh SPMD substrate.

One config block (``"mesh"``) chooses the layout; one rule table maps
logical tensor dims to mesh axes; ZeRO, TP, SP, the comm reducer, and
engine/serving/datapipe batch placement all resolve through here. See
``docs/tutorials/sharding.md``.
"""

from .audit import audit_tree, spec_digest, tree_digest
from .config import CANONICAL_AXES, MeshConfig, resolve_extents
from .mesh import (DP_AXIS, FSDP_AXIS, SP_AXIS, TP_AXIS, default_mesh,
                   describe, from_config, is_canonical, make_mesh)
from .rules import (DEFAULT_RULES, add_zero_axis, batch_axes, batch_spec,
                    choose_shard_dim, constrain, data_parallel_size,
                    logical_constraint, logical_spec, named_shardings,
                    place_batch, resolve_rules, sp_axis, sp_size,
                    translate_spec, tp_axis, tp_size, zero_axis, zero_size,
                    zero_tree_specs)

__all__ = [
    "MeshConfig", "CANONICAL_AXES", "resolve_extents",
    "DP_AXIS", "FSDP_AXIS", "TP_AXIS", "SP_AXIS",
    "make_mesh", "from_config", "default_mesh", "describe", "is_canonical",
    "DEFAULT_RULES", "resolve_rules", "translate_spec",
    "batch_axes", "zero_axis", "tp_axis", "sp_axis",
    "data_parallel_size", "zero_size", "tp_size", "sp_size",
    "batch_spec", "place_batch", "constrain", "named_shardings",
    "logical_spec", "logical_constraint",
    "zero_tree_specs", "choose_shard_dim", "add_zero_axis",
    "audit_tree", "spec_digest", "tree_digest",
]
