from .replace_module import (
    HFBertLayerPolicy,
    extract_layer_params,
    replace_transformer_layer,
    module_inject,
)
