"""Model surgery: swap HF/Megatron transformer layers for the fused layer.

Capability parity with /root/reference/deepspeed/module_inject/
(`replace_transformer_layer` replace_module.py:6, `module_inject`
inject.py:6). The reference mutates a torch model in place, replacing each
``nn.Module`` transformer block with a ``DeepSpeedTransformerLayer`` carrying
the original weights.

TPU-native meaning: the source model (usually a torch/HF checkpoint) is a
*weight container*, and "replacement" is extraction — a policy maps each
matched layer's tensors into our fused layer's param pytree (weights
transposed to (in, out) orientation). The result is a
``DeepSpeedTransformerLayer`` + a per-layer params list (and a stacked
pytree for scan-over-layers models), which is what a jax training/inference
step consumes. torch is only touched through ``.detach().cpu().numpy()``.
"""

from typing import Any, List, Optional, Tuple, Type

import jax.numpy as jnp

from ..ops.transformer import DeepSpeedTransformerConfig, DeepSpeedTransformerLayer
from ..ops.transformer.transformer import biases_to_params, weights_to_params
from ..utils.logging import logger


class HFBertLayerPolicy:
    """Weight-mapping policy for huggingface BertLayer (reference
    replace_module.py:20-35 builds the same qkvw/qkvb ordering)."""

    @staticmethod
    def orig_layer_class():
        from transformers.models.bert.modeling_bert import BertLayer

        return BertLayer

    def __init__(self, layer):
        self.layer = layer

    def get_weights_biases(self) -> Tuple[List[Any], List[Any]]:
        attn = self.layer.attention
        weights = [
            attn.self.query.weight,
            attn.self.key.weight,
            attn.self.value.weight,
            attn.output.dense.weight,
            attn.output.LayerNorm.weight,
            self.layer.intermediate.dense.weight,
            self.layer.output.dense.weight,
            self.layer.output.LayerNorm.weight,
        ]
        biases = [
            attn.self.query.bias,
            attn.self.key.bias,
            attn.self.value.bias,
            attn.output.dense.bias,
            attn.output.LayerNorm.bias,
            self.layer.intermediate.dense.bias,
            self.layer.output.dense.bias,
            self.layer.output.LayerNorm.bias,
        ]
        return weights, biases


def extract_layer_params(policy) -> dict:
    """One matched layer -> fused-layer param pytree (names as in
    ops/transformer/transformer.py, reference attrs transformer.py:502-525)."""
    weights, biases = policy.get_weights_biases()
    params = weights_to_params(weights)
    params.update(biases_to_params(biases))
    return params


def _find_layers(model, orig_layer_impl):
    found = []
    for module in model.modules() if hasattr(model, "modules") else []:
        if isinstance(module, orig_layer_impl):
            found.append(module)
    return found


def replace_transformer_layer(
    orig_layer_impl: Optional[Type] = None,
    model=None,
    micro_batch_size: int = -1,
    config=None,
    seed: int = -1,
    max_seq_length: int = -1,
    preln: bool = False,
    fp16: bool = True,
    huggingface: bool = False,
    policy_cls=HFBertLayerPolicy,
    attn_impl: str = "auto",
    stack: bool = True,
):
    """Reference replace_module.py:6, re-expressed as extraction.

    Returns ``(ds_layer, params_list, stacked_params)``: a fused
    ``DeepSpeedTransformerLayer`` whose apply consumes each element of
    ``params_list`` (or a lax.scan over ``stacked_params``). With
    ``stack=False`` the stacked copy is skipped (halves injection memory
    when only the per-layer list is needed) and ``stacked_params`` is None.
    """
    if orig_layer_impl is None:
        orig_layer_impl = policy_cls.orig_layer_class()
    layers = _find_layers(model, orig_layer_impl)
    if not layers:
        raise ValueError(f"no {orig_layer_impl.__name__} layers found in model")

    hf_config = config if config is not None else getattr(model, "config", None)
    ds_config = DeepSpeedTransformerConfig(
        batch_size=micro_batch_size,
        max_seq_length=(max_seq_length if max_seq_length > 0
                        else getattr(hf_config, "max_position_embeddings", -1)),
        hidden_size=getattr(hf_config, "hidden_size"),
        intermediate_size=getattr(hf_config, "intermediate_size", -1),
        heads=getattr(hf_config, "num_attention_heads"),
        attn_dropout_ratio=getattr(hf_config, "attention_probs_dropout_prob", 0.0),
        hidden_dropout_ratio=getattr(hf_config, "hidden_dropout_prob", 0.0),
        num_hidden_layers=getattr(hf_config, "num_hidden_layers", len(layers)),
        initializer_range=getattr(hf_config, "initializer_range", 0.02),
        layernorm_eps=getattr(hf_config, "layer_norm_eps", 1e-12),
        seed=seed,
        fp16=fp16,
        pre_layer_norm=preln,
        huggingface=huggingface,
        attn_impl=attn_impl,
    )
    params_list = [extract_layer_params(policy_cls(layer)) for layer in layers]
    stacked = None
    if stack:
        stacked = {
            k: jnp.stack([p[k] for p in params_list]) for k in params_list[0]
        }
    ds_layer = DeepSpeedTransformerLayer(ds_config)
    logger.info("injected %d %s layers into DeepSpeedTransformerLayer(params)",
                len(layers), orig_layer_impl.__name__)
    return ds_layer, params_list, stacked


def module_inject(layer_obj=None, model=None, config=None, micro_batch_size=-1,
                  max_seq_length=-1, seed=-1, preln=False, fp16=True):
    """Legacy API name (reference inject.py:6 / ops/module_inject.py)."""
    return replace_transformer_layer(
        orig_layer_impl=type(layer_obj) if layer_obj is not None else None,
        model=model,
        micro_batch_size=micro_batch_size,
        config=config,
        seed=seed,
        max_seq_length=max_seq_length,
        preln=preln,
        fp16=fp16,
    )
