"""Lifecycle configuration.

A ``"lifecycle"`` block in the master JSON config (or a plain dict)
builds a :class:`LifecycleConfig` — the policy for the zero-downtime
train→serve control plane: live re-mesh on pool-change signals and
weight-version publishing/rollout. Validated eagerly (unknown keys are
errors) like every other subsystem block, so a typo fails at config
load, not mid-rollout.
"""

import dataclasses
import signal
from typing import Optional

__all__ = ["LifecycleConfig"]

# config keys (declared so the analysis linter can enumerate them)
ENABLED = "enabled"
ENABLED_DEFAULT = True
POOL_FILE = "pool_file"
REMESH_ENABLED = "remesh_enabled"
REMESH_ENABLED_DEFAULT = True
REMESH_SIGNAL = "remesh_signal"
REMESH_SIGNAL_DEFAULT = "SIGUSR1"
REMESH_DEBOUNCE_S = "remesh_debounce_s"
REMESH_DEBOUNCE_S_DEFAULT = 0.25
PUBLISH = "publish"
PUBLISH_DEFAULT = True
PUBLISH_INTERVAL_STEPS = "publish_interval_steps"
PUBLISH_INTERVAL_STEPS_DEFAULT = 0
KEEP_LIVE_VERSIONS = "keep_live_versions"
KEEP_LIVE_VERSIONS_DEFAULT = 2
ROLLOUT_POLL_INTERVAL_S = "rollout_poll_interval_s"
ROLLOUT_POLL_INTERVAL_S_DEFAULT = 0.5
DRAIN_TIMEOUT_S = "drain_timeout_s"
DRAIN_TIMEOUT_S_DEFAULT = 30.0

_KNOWN_KEYS = frozenset({
    ENABLED, POOL_FILE, REMESH_ENABLED, REMESH_SIGNAL, REMESH_DEBOUNCE_S,
    PUBLISH, PUBLISH_INTERVAL_STEPS, KEEP_LIVE_VERSIONS,
    ROLLOUT_POLL_INTERVAL_S, DRAIN_TIMEOUT_S,
})


@dataclasses.dataclass(frozen=True)
class LifecycleConfig:
    """The ``"lifecycle"`` block: re-mesh + weight-version policy."""

    enabled: bool = ENABLED_DEFAULT
    # surviving-pool device count file (the supervisor's --pool-file);
    # re-read when the re-mesh signal arrives. None = signal-only mode:
    # the sender must deliver the target via DS_TPU_POOL_FILE instead.
    pool_file: Optional[str] = None
    # live re-mesh: respond to the pool-change signal at step boundaries
    remesh_enabled: bool = REMESH_ENABLED_DEFAULT
    # signal name the supervisor sends the RUNNING trainer (SIGUSR1 by
    # convention; configurable for embedders that already use it)
    remesh_signal: str = REMESH_SIGNAL_DEFAULT
    # coalesce signal bursts: pool-file writes arriving closer together
    # than this resolve to one re-mesh at the next step boundary
    remesh_debounce_s: float = REMESH_DEBOUNCE_S_DEFAULT
    # weight versions: publish COMMITTED checkpoint tags as WeightVersion
    # records in the checkpoint dir's VERSIONS.json
    publish: bool = PUBLISH_DEFAULT
    # 0 = publish every committed save; N > 0 = only saves whose step is
    # a multiple of N (decouples rollout cadence from save cadence)
    publish_interval_steps: int = PUBLISH_INTERVAL_STEPS_DEFAULT
    # live window: versions routable (and prune-protected) at once
    keep_live_versions: int = KEEP_LIVE_VERSIONS_DEFAULT
    # controller: how often the serving side polls VERSIONS.json
    rollout_poll_interval_s: float = ROLLOUT_POLL_INTERVAL_S_DEFAULT
    # rolling update: per-replica drain budget before a forced restart
    drain_timeout_s: float = DRAIN_TIMEOUT_S_DEFAULT

    def __post_init__(self):
        if self.publish_interval_steps < 0:
            raise ValueError(
                "lifecycle.publish_interval_steps must be >= 0, got "
                f"{self.publish_interval_steps}")
        if self.keep_live_versions < 1:
            raise ValueError(
                "lifecycle.keep_live_versions must be >= 1, got "
                f"{self.keep_live_versions}")
        if self.remesh_debounce_s < 0:
            raise ValueError(
                "lifecycle.remesh_debounce_s must be >= 0, got "
                f"{self.remesh_debounce_s}")
        if self.rollout_poll_interval_s <= 0:
            raise ValueError(
                "lifecycle.rollout_poll_interval_s must be > 0, got "
                f"{self.rollout_poll_interval_s}")
        if self.drain_timeout_s <= 0:
            raise ValueError(
                "lifecycle.drain_timeout_s must be > 0, got "
                f"{self.drain_timeout_s}")
        self.signal_number()  # validates the name eagerly

    def signal_number(self) -> int:
        """The configured re-mesh signal as a number."""
        name = self.remesh_signal
        num = getattr(signal, name, None)
        if not isinstance(num, signal.Signals):
            raise ValueError(
                f"lifecycle.remesh_signal {name!r} is not a signal name "
                "(expected e.g. 'SIGUSR1')")
        return int(num)

    @staticmethod
    def from_dict(d: dict) -> "LifecycleConfig":
        if not isinstance(d, dict):
            raise ValueError(
                f"lifecycle config must be a dict, got {type(d).__name__}")
        unknown = set(d) - _KNOWN_KEYS
        if unknown:
            raise ValueError(
                f"unknown lifecycle config keys {sorted(unknown)}; "
                f"valid keys: {sorted(_KNOWN_KEYS)}")
        kwargs = {k: d[k] for k in d}
        return LifecycleConfig(**kwargs)
