"""Operator CLI for the lifecycle control plane.

::

    python -m deeperspeed_tpu.lifecycle versions --ckpt-dir CKPTS
    python -m deeperspeed_tpu.lifecycle publish  --ckpt-dir CKPTS [--tag T]
    python -m deeperspeed_tpu.lifecycle retire   --ckpt-dir CKPTS --version N
    python -m deeperspeed_tpu.lifecycle pool     --pool-file F --size N

``versions`` prints the registry; ``publish`` turns a COMMITTED tag
(default: whatever ``latest`` points at) into the next weight version;
``retire`` takes a version out of rotation; ``pool`` atomically rewrites
the pool file the supervisor watches — the operator-facing way to
trigger a live re-mesh on a running trainer.

Stdlib-only on purpose: these verbs run on control hosts where jax may
not even import.
"""

import argparse
import json
import os
import sys
from typing import Optional, Sequence

from .versions import VersionRegistry


def _cmd_versions(args) -> int:
    reg = VersionRegistry(args.ckpt_dir)
    recs = reg.list()
    print(json.dumps({"versions": [r.to_dict() for r in recs]}, indent=1))
    return 0


def _cmd_publish(args) -> int:
    from ..checkpoint.serialization import read_latest

    tag = args.tag or read_latest(args.ckpt_dir)
    if not tag:
        print("publish: no --tag given and no `latest` pointer in "
              f"{args.ckpt_dir}", file=sys.stderr)
        return 2
    reg = VersionRegistry(args.ckpt_dir, keep_live=args.keep_live)
    try:
        rec = reg.publish(tag)
    except ValueError as e:
        print(f"publish: {e}", file=sys.stderr)
        return 1
    print(json.dumps(rec.to_dict()))
    return 0


def _cmd_retire(args) -> int:
    reg = VersionRegistry(args.ckpt_dir)
    if not reg.retire(args.version):
        print(f"retire: no live version {args.version} in "
              f"{reg.path}", file=sys.stderr)
        return 1
    print(json.dumps({"retired": args.version}))
    return 0


def _cmd_pool(args) -> int:
    # same atomic rewrite discipline as every other control file: the
    # supervisor's watcher must never read a torn value
    pool_dir = os.path.dirname(args.pool_file)
    if pool_dir:
        os.makedirs(pool_dir, exist_ok=True)
    tmp = args.pool_file + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(int(args.size)) + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, args.pool_file)
    print(json.dumps({"pool_file": args.pool_file, "size": int(args.size)}))
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeperspeed_tpu.lifecycle",
        description="train→serve lifecycle control plane")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("versions", help="print the weight-version registry")
    p.add_argument("--ckpt-dir", required=True)
    p.set_defaults(fn=_cmd_versions)

    p = sub.add_parser("publish",
                       help="publish a COMMITTED tag as the next version")
    p.add_argument("--ckpt-dir", required=True)
    p.add_argument("--tag", default=None,
                   help="checkpoint tag (default: the `latest` pointer)")
    p.add_argument("--keep-live", type=int, default=2)
    p.set_defaults(fn=_cmd_publish)

    p = sub.add_parser("retire", help="take a version out of rotation")
    p.add_argument("--ckpt-dir", required=True)
    p.add_argument("--version", type=int, required=True)
    p.set_defaults(fn=_cmd_retire)

    p = sub.add_parser("pool",
                       help="atomically rewrite the watched pool file")
    p.add_argument("--pool-file", required=True)
    p.add_argument("--size", type=int, required=True)
    p.set_defaults(fn=_cmd_pool)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
