"""Weight-version registry: COMMITTED checkpoint tags → serving rollouts.

The train→serve hinge of the lifecycle subsystem. The trainer side
publishes a checkpoint tag as a :class:`WeightVersion` — a monotonically
numbered, manifest-backed record — and the serving side rolls the fleet
onto it (``FleetRouter.rolling_update``). The registry is a single JSON
file (``VERSIONS.json``) living next to the checkpoint tags it points
at, written with the same atomic tmp+fsync+rename discipline as
``resilience/manifest.py`` so a torn write can never present a
half-published version.

Invariants:

  * only COMMITTED tags are publishable — ``publish`` re-verifies the
    two-phase-commit marker via ``manifest.tag_status`` and refuses
    anything else (staging/partial/corrupt tags stay invisible to the
    fleet);
  * version numbers are assigned here, monotonically, and are never
    reused — a replica pinned to v3 means one exact weight set forever;
  * a version is ``live`` until retired; ``resilience/manager.py``'s
    keep_last pruning reads ``live_tags`` so a tag the fleet may still
    be serving (or rolling onto) is never deleted out from under it;
  * the retire window (``keep_live``) keeps the last N versions live so
    a rolling update in flight can still fail back one version.

Stdlib-only (json/os/time) by design: the supervisor and the router
side both import this without pulling in jax.
"""

import dataclasses
import json
import os
import time
from typing import Dict, List, Optional

from ..resilience.manifest import tag_status, tag_step

__all__ = [
    "VERSIONS_FILE",
    "WeightVersion",
    "VersionRegistry",
    "live_tags",
]

VERSIONS_FILE = "VERSIONS.json"


@dataclasses.dataclass(frozen=True)
class WeightVersion:
    """One published weight set: an immutable (version, tag) pairing.

    When speculative decoding serves this version, ``drafter`` names
    the drafter checkpoint tag published WITH the target — the rollout
    ships both as one unit, because token-identical failover across a
    mixed spec-on/spec-off fleet only needs the target weights pinned,
    but acceptance-rate comparability needs the drafter pinned too.
    Absent in pre-pair registry files (serde defaults it to None)."""

    version: int               # monotonic, never reused
    tag: str                   # COMMITTED checkpoint tag in load_dir
    step: Optional[int]        # trainer step the tag was saved at
    published_ts: float        # wall-clock publish time
    live: bool = True          # still routable / prune-protected
    drafter: Optional[str] = None   # paired drafter checkpoint tag

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "WeightVersion":
        return WeightVersion(
            version=int(d["version"]),
            tag=str(d["tag"]),
            step=(int(d["step"]) if d.get("step") is not None else None),
            published_ts=float(d.get("published_ts", 0.0)),
            live=bool(d.get("live", True)),
            drafter=(str(d["drafter"])
                     if d.get("drafter") is not None else None),
        )


class VersionRegistry:
    """The ``VERSIONS.json`` ledger in a checkpoint directory.

    Every mutation re-reads the file, applies the change, and rewrites
    atomically — the registry is tiny and the publish/retire rate is
    per-checkpoint, so last-writer-wins over a fresh read is plenty
    (trainer publishes; the serving side only reads).
    """

    def __init__(self, ckpt_dir: str, keep_live: int = 2):
        if keep_live < 1:
            raise ValueError(f"keep_live must be >= 1, got {keep_live}")
        self.ckpt_dir = ckpt_dir
        self.keep_live = keep_live

    @property
    def path(self) -> str:
        return os.path.join(self.ckpt_dir, VERSIONS_FILE)

    # -------------------------------------------------------------- #
    # file plumbing

    def _read(self) -> List[WeightVersion]:
        try:
            with open(self.path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            return []
        out = []
        for rec in doc.get("versions", []):
            try:
                out.append(WeightVersion.from_dict(rec))
            except (KeyError, TypeError, ValueError):
                continue  # one bad record never hides the rest
        out.sort(key=lambda v: v.version)
        return out

    def _write(self, versions: List[WeightVersion]) -> None:
        doc = {"versions": [v.to_dict() for v in sorted(
            versions, key=lambda v: v.version)]}
        tmp = self.path + ".tmp"
        os.makedirs(self.ckpt_dir, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    # -------------------------------------------------------------- #
    # queries

    def list(self) -> List[WeightVersion]:
        """All versions ever published, oldest first."""
        return self._read()

    def latest(self) -> Optional[WeightVersion]:
        """Newest LIVE version (what a rollout should target)."""
        live = [v for v in self._read() if v.live]
        return live[-1] if live else None

    def get(self, version: int) -> Optional[WeightVersion]:
        for v in self._read():
            if v.version == version:
                return v
        return None

    def live_tags(self) -> Dict[str, int]:
        """tag -> version for every live version (prune protection)."""
        return {v.tag: v.version for v in self._read() if v.live}

    # -------------------------------------------------------------- #
    # mutations (trainer side)

    def publish(self, tag: str, step: Optional[int] = None,
                now: Optional[float] = None,
                drafter: Optional[str] = None) -> WeightVersion:
        """Publish a COMMITTED checkpoint tag as the next version.

        Re-publishing the tag of an existing live version with the same
        drafter pairing is idempotent (returns the existing record) —
        the controller may call this on every save interval without
        minting duplicate versions. The same target tag with a NEW
        drafter mints a new version: the pair is the routable unit.

        ``drafter`` names the drafter checkpoint tag published with the
        target (speculative decoding); it must also be COMMITTED.
        """
        status = tag_status(os.path.join(self.ckpt_dir, str(tag)))
        if status not in ("committed", "legacy"):
            raise ValueError(
                f"refusing to publish tag {tag!r}: status is {status!r} "
                "(only committed checkpoints become weight versions)")
        if drafter is not None:
            dstatus = tag_status(os.path.join(self.ckpt_dir, str(drafter)))
            if dstatus not in ("committed", "legacy"):
                raise ValueError(
                    f"refusing to publish drafter tag {drafter!r}: status "
                    f"is {dstatus!r} (the pair rolls out as one unit, so "
                    "both sides must be committed)")
        versions = self._read()
        for v in versions:
            if v.live and v.tag == tag and v.drafter == drafter:
                return v
        number = versions[-1].version + 1 if versions else 1
        rec = WeightVersion(
            version=number, tag=tag,
            step=step if step is not None else tag_step(tag),
            published_ts=float(now if now is not None else time.time()),
            drafter=drafter,
        )
        versions.append(rec)
        # retire past the live window, never the newest keep_live
        live = [v for v in versions if v.live]
        to_retire = {v.version for v in live[:-self.keep_live]}
        if to_retire:
            versions = [
                dataclasses.replace(v, live=False)
                if v.version in to_retire else v
                for v in versions
            ]
        self._write(versions)
        return rec

    def retire(self, version: int) -> bool:
        """Mark one version non-live (a tag the fleet must not pin to
        anymore). True when a live record was retired."""
        versions = self._read()
        hit = False
        out = []
        for v in versions:
            if v.version == version and v.live:
                out.append(dataclasses.replace(v, live=False))
                hit = True
            else:
                out.append(v)
        if hit:
            self._write(out)
        return hit


def live_tags(ckpt_dir: str) -> Dict[str, int]:
    """tag -> version for the live versions published under
    ``ckpt_dir`` (empty when no registry exists). Free-function form so
    the checkpoint pruner can consult the registry without constructing
    one."""
    try:
        return VersionRegistry(ckpt_dir).live_tags()
    except Exception:
        return {}
