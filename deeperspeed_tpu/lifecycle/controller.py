"""Lifecycle controller: the train→serve control plane, assembled.

Three cooperating pieces, each usable alone:

  * :class:`VersionPublisher` — trainer-side step-boundary hook. After
    each optimizer step it looks at the checkpoint directory's
    ``latest`` pointer; a tag it has never published that has reached
    COMMITTED becomes the next :class:`~.versions.WeightVersion`. Tags
    still staging (async writer in flight) are simply retried at the
    next boundary — the registry's two-phase-commit check is the gate,
    so a torn tag can never become a version.
  * :class:`RolloutDriver` — serving-side watcher. Polls the registry
    (``VERSIONS.json`` is the only coupling between the two processes)
    and rolls the fleet onto each new live version via
    ``FleetRouter.rolling_update``: drain → stage weights → restart,
    one replica at a time, mixed-version routing in between.
  * :class:`LifecycleController` — binds a :class:`~.remesh.RemeshHook`
    and a publisher into one object the resilience manager polls
    (``attach_lifecycle``), plus the rollout driver when a router is
    given. This is what ``python -m deeperspeed_tpu.lifecycle`` and the
    lifecycle drill drive.

The publisher and the driver never share memory: the trainer writes
``VERSIONS.json``, the serving host reads it. That is deliberate — the
two halves survive each other's restarts, and the drill runs them in
separate processes exactly as production would.
"""

import threading
import time
from typing import Callable, Optional

from ..checkpoint.serialization import read_latest
from ..monitor import get_monitor, trace_instant
from ..utils.logging import log_dist, logger
from .config import LifecycleConfig
from .remesh import RemeshHook
from .versions import VersionRegistry, WeightVersion

__all__ = ["VersionPublisher", "RolloutDriver", "LifecycleController"]


class VersionPublisher:
    """Publishes freshly COMMITTED checkpoint tags as weight versions.

    A step-boundary hook (``poll(engine)``), polled by the resilience
    manager right after its interval autosave — so the tag a save just
    committed is visible the same boundary it lands.
    """

    def __init__(self, ckpt_dir: str,
                 cfg: Optional[LifecycleConfig] = None,
                 registry: Optional[VersionRegistry] = None):
        self.cfg = cfg or LifecycleConfig()
        self.registry = registry or VersionRegistry(
            ckpt_dir, keep_live=self.cfg.keep_live_versions)
        self.published = 0
        self._last_publish_step: Optional[int] = None
        # when set (by the operator or a drafter-distillation job),
        # every subsequent publish pairs this COMMITTED drafter tag
        # with the target tag — the record rolls out as one unit
        self.drafter_tag: Optional[str] = None

    def poll(self, engine=None) -> Optional[WeightVersion]:
        """Publish the ``latest`` tag if it is new and committed.
        Returns the fresh record, or None when there is nothing to do
        (no new tag, tag still staging, or inside the publish
        interval)."""
        if not self.cfg.publish:
            return None
        tag = read_latest(self.registry.ckpt_dir)
        if not tag:
            return None
        if tag in {v.tag for v in self.registry.list()}:
            return None  # seen before (live OR retired): never re-mint
        step = (int(getattr(engine, "global_steps", 0))
                if engine is not None else None)
        if (step is not None
                and self.cfg.publish_interval_steps > 0
                and self._last_publish_step is not None
                and step - self._last_publish_step
                < self.cfg.publish_interval_steps):
            return None
        try:
            rec = self.registry.publish(tag, drafter=self.drafter_tag)
        except ValueError:
            # async writer still staging this tag, or it is torn; the
            # next boundary re-checks — commit is the publish gate
            return None
        self.published += 1
        self._last_publish_step = step
        trace_instant("lifecycle/publish", lane="lifecycle",
                      version=rec.version, tag=rec.tag, step=rec.step,
                      drafter=rec.drafter)
        mon = get_monitor()
        if mon is not None:
            mon.registry.counter(
                "lifecycle_publish_total",
                "checkpoint tags published as weight versions").inc()
            mon.registry.gauge(
                "lifecycle_latest_version",
                "newest published weight version").set(float(rec.version))
        log_dist(f"lifecycle: published weight version v{rec.version} "
                 f"(tag {rec.tag})", ranks=[0])
        return rec


class RolloutDriver:
    """Rolls a serving fleet onto new weight versions as they appear.

    ``weights_for(record)`` maps a version record to the payload handed
    to each replica's ``set_weights``; the default points subprocess
    workers at the published tag (``{"load_dir", "tag"}``).
    """

    def __init__(self, router, registry: VersionRegistry,
                 cfg: Optional[LifecycleConfig] = None,
                 weights_for: Optional[
                     Callable[[WeightVersion], Optional[dict]]] = None):
        self.router = router
        self.registry = registry
        self.cfg = cfg or LifecycleConfig()
        self._weights_for = weights_for or self._checkpoint_pointer
        self.applied: Optional[int] = None
        self.rollouts = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _checkpoint_pointer(self, rec: WeightVersion) -> dict:
        ptr = {"load_dir": self.registry.ckpt_dir, "tag": rec.tag}
        if rec.drafter is not None:
            # (target, drafter) pair: the worker loads both sides from
            # the same checkpoint dir, so a version's acceptance rate
            # is comparable across every replica serving it
            ptr["drafter_tag"] = rec.drafter
        return ptr

    def poll_once(self) -> Optional[WeightVersion]:
        """One registry check; rolls the fleet when a newer live
        version exists. Returns the version rolled onto, else None."""
        rec = self.registry.latest()
        if rec is None or rec.version == self.applied:
            return None
        log_dist(f"lifecycle: rolling fleet onto v{rec.version} "
                 f"(tag {rec.tag})", ranks=[0])
        self.router.rolling_update(
            rec.version, weights=self._weights_for(rec),
            timeout_s=self.cfg.drain_timeout_s)
        self.applied = rec.version
        self.rollouts += 1
        return rec

    # -- background watcher ------------------------------------------

    def start(self) -> "RolloutDriver":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="lifecycle-rollout", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout_s)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 - keep watching
                logger.error("lifecycle: rollout failed (%s); will "
                             "retry on the next version", e)
            self._stop.wait(self.cfg.rollout_poll_interval_s)


class LifecycleController:
    """One object owning both halves of the control plane.

    Trainer side: ``attach(engine)`` installs the re-mesh signal
    handler and registers this controller as a resilience step-boundary
    hook, so every optimizer step runs publish-then-remesh (publish
    first: the tag that predates a topology flip is still published
    under the old mesh, which keeps the serve side decoupled from the
    flip). Serving side: pass a router and call ``start_serving()``.
    """

    def __init__(self, ckpt_dir: str,
                 cfg: Optional[LifecycleConfig] = None,
                 router=None,
                 weights_for: Optional[
                     Callable[[WeightVersion], Optional[dict]]] = None):
        self.cfg = cfg or LifecycleConfig()
        self.registry = VersionRegistry(
            ckpt_dir, keep_live=self.cfg.keep_live_versions)
        self.remesh = RemeshHook(self.cfg)
        self.publisher = VersionPublisher(
            ckpt_dir, self.cfg, registry=self.registry)
        self.rollout = (RolloutDriver(router, self.registry, self.cfg,
                                      weights_for=weights_for)
                        if router is not None else None)

    # -- trainer side ------------------------------------------------

    def attach(self, engine) -> "LifecycleController":
        """Wire into a training engine: signal handler + step-boundary
        polling via the engine's resilience manager (or call
        ``poll(engine)`` manually from a bare loop)."""
        if self.cfg.remesh_enabled:
            self.remesh.install()
        mgr = getattr(engine, "_resilience", None)
        if mgr is not None and hasattr(mgr, "attach_lifecycle"):
            mgr.attach_lifecycle(self)
        else:
            logger.warning(
                "lifecycle: engine has no resilience manager; call "
                "controller.poll(engine) from the training loop")
        return self

    def poll(self, engine) -> None:
        """The step-boundary hook: publish, then apply any pending
        re-mesh."""
        self.publisher.poll(engine)
        self.remesh.poll(engine)

    # -- serving side ------------------------------------------------

    def start_serving(self) -> "LifecycleController":
        if self.rollout is None:
            raise RuntimeError(
                "no router was given to LifecycleController; rollouts "
                "need one")
        self.rollout.start()
        return self

    def wait_for_version(self, version: int,
                         timeout_s: float = 120.0) -> bool:
        """Block until the rollout driver has applied ``version`` (the
        drill's synchronization point between a publish and its serve-
        side effect)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if (self.rollout is not None
                    and self.rollout.applied is not None
                    and self.rollout.applied >= version):
                return True
            time.sleep(0.05)
        return False

    def close(self) -> None:
        if self.rollout is not None:
            self.rollout.stop()
        self.remesh.uninstall()
