"""lifecycle/: zero-downtime train→serve control plane.

Two capabilities the rest of the stack composes:

  * **Live re-mesh** — on a pool-change signal the trainer pauses at a
    step boundary and rebuilds its mesh in-process (``Engine.remesh``):
    ``jax.device_put`` re-placement onto the surviving devices plus the
    ``resilience/reshard.py`` residual math, no checkpoint round trip,
    no re-exec. Losses stay bit-identical to the kill-restart path.
  * **Weight versions** — COMMITTED checkpoint tags become monotonically
    numbered ``WeightVersion`` records (``VERSIONS.json``); the fleet
    router rolling-restarts replicas onto new versions with
    mixed-version routing, and failover retries stay pinned to the
    version that served the first dispatch.

``python -m deeperspeed_tpu.lifecycle`` is the operator CLI (inspect /
publish / retire versions, poke the pool file); the drill lives in
``scripts/lifecycle_drill.py``.
"""

from .config import LifecycleConfig
from .controller import LifecycleController, RolloutDriver, VersionPublisher
from .remesh import RemeshHook
from .versions import VERSIONS_FILE, VersionRegistry, WeightVersion, live_tags

__all__ = [
    "LifecycleConfig",
    "LifecycleController",
    "RolloutDriver",
    "VersionPublisher",
    "RemeshHook",
    "VERSIONS_FILE",
    "VersionRegistry",
    "WeightVersion",
    "live_tags",
]
