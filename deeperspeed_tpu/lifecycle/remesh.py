"""Live re-mesh hook: pool-change signal → in-process topology flip.

The kill-free half of elasticity. PR 7's path is: supervisor sees the
pool change, SIGKILLs the trainer, relaunches at the new world size, the
checkpoint loader reshards. This hook keeps the process alive instead:
the supervisor (or an operator) sends ``SIGUSR1`` to the RUNNING
trainer; the handler just latches a flag (signal context does no work);
at the next optimizer-step boundary :meth:`RemeshHook.poll` re-reads the
pool file, picks the largest admissible elastic world size that fits,
and calls :meth:`Engine.remesh` — ``jax.device_put`` re-placement plus
the same ``resilience/reshard.py`` residual math, no checkpoint round
trip, no re-exec.

Wiring: the resilience manager calls ``poll`` from its step-boundary
hook when a hook is attached (``attach_lifecycle``), so any engine with
a ``resilience`` block gets live re-mesh by adding a ``lifecycle``
block; a bare training loop can call ``hook.poll(engine)`` itself.

A pool *grow* beyond the process's device count cannot happen live (the
JAX device list is fixed at process start) — ``choose_world`` caps at
``len(jax.devices())``. Growth past that cap means adding *processes*,
which is the fleet supervisor's coordinated-restart path
(:class:`...distributed.fleet.FleetSupervisor` watching a pool file
that holds the PROCESS count): every host relaunches together at the
new process count and ``resilience/reshard.py`` carries residual state
across the world-size change. :func:`cross_host_growth_needed` is the
predicate both sides share.
"""

import os
import signal
import time
from typing import Optional

from ..resilience.supervisor import POOL_FILE_ENV
from ..utils.logging import logger
from .config import LifecycleConfig

__all__ = ["RemeshHook", "cross_host_growth_needed"]


def cross_host_growth_needed(pool: Optional[int],
                             device_cap: int) -> bool:
    """True when a pool target exceeds what THIS process can re-mesh to
    live — the point where elasticity must switch from the in-process
    flip to the fleet supervisor's coordinated process-count restart."""
    return pool is not None and int(pool) > int(device_cap)


class RemeshHook:
    """Latches the re-mesh signal and applies it at step boundaries."""

    def __init__(self, cfg: Optional[LifecycleConfig] = None,
                 pool_file: Optional[str] = None):
        self.cfg = cfg or LifecycleConfig()
        self.pool_file = (pool_file or self.cfg.pool_file
                          or os.environ.get(POOL_FILE_ENV))
        self._pending = 0
        self._signal_ts = 0.0
        self._prev_handler = None
        self._installed = False
        self.remeshes = 0        # applied flips
        self.last_world: Optional[int] = None

    # -------------------------------------------------------------- #
    # signal side (async-signal-safe: only sets flags)

    def install(self) -> "RemeshHook":
        """Register the signal handler (main thread only, per signal
        module rules). Idempotent."""
        if self._installed:
            return self
        try:
            self._prev_handler = signal.signal(
                self.cfg.signal_number(), self._on_signal)
        except ValueError:
            # not the main thread: signals can't be claimed here, but
            # request() / poll() still work for in-process controllers
            logger.warning(
                "lifecycle: cannot install the re-mesh signal handler "
                "off the main thread; use hook.request() instead")
            return self
        self._installed = True
        return self

    def uninstall(self) -> None:
        if self._installed:
            signal.signal(self.cfg.signal_number(),
                          self._prev_handler or signal.SIG_DFL)
            self._installed = False

    def _on_signal(self, signum, frame) -> None:
        self._pending += 1
        self._signal_ts = time.time()

    def request(self) -> None:
        """Programmatic trigger (tests / same-process controllers)."""
        self._on_signal(None, None)

    @property
    def pending(self) -> bool:
        return self._pending > 0

    # -------------------------------------------------------------- #
    # step-boundary side

    def read_pool(self) -> Optional[int]:
        """The surviving pool's device count, or None when unreadable."""
        if not self.pool_file:
            return None
        try:
            with open(self.pool_file) as f:
                return int(f.read().strip())
        except (OSError, ValueError) as e:
            logger.warning("lifecycle: unreadable pool file %s (%s)",
                           self.pool_file, e)
            return None

    def choose_world(self, engine) -> Optional[int]:
        """Largest admissible elastic world size fitting the pool AND
        this process's fixed device count."""
        import jax

        sizes = list(getattr(engine._config,
                             "elastic_valid_world_sizes", None) or [])
        if not sizes:
            logger.warning(
                "lifecycle: re-mesh signal with no elasticity block — "
                "no admissible world sizes, staying at %d",
                engine.data_parallel_size)
            return None
        cap = len(jax.devices())
        pool = self.read_pool()
        if cross_host_growth_needed(pool, cap):
            logger.info(
                "lifecycle: pool target %s exceeds this process's %d "
                "device(s) — growth past the cap needs new PROCESSES "
                "(distributed.fleet coordinated restart); re-meshing "
                "to the in-process cap", pool, cap)
        if pool is not None:
            cap = min(cap, pool)
        admissible = [s for s in sizes if s <= cap]
        if not admissible:
            logger.error(
                "lifecycle: no elastic world size fits the pool of %s "
                "(valid: %s); keeping the current topology", pool, sizes)
            return None
        return max(admissible)

    def poll(self, engine) -> bool:
        """Called at an optimizer-step boundary. Applies at most one
        re-mesh; True when the topology changed. Signal bursts within
        ``remesh_debounce_s`` coalesce — the flip waits for a boundary
        where the pool file has been quiet."""
        if not self._pending or not self.cfg.remesh_enabled:
            return False
        if (self.cfg.remesh_debounce_s > 0.0
                and time.time() - self._signal_ts
                < self.cfg.remesh_debounce_s):
            return False  # still settling; re-check next boundary
        self._pending = 0
        world = self.choose_world(engine)
        if world is None or world == engine.data_parallel_size:
            if world is not None:
                logger.info(
                    "lifecycle: pool change resolves to the current "
                    "world size (%d); nothing to do", world)
            return False
        engine.remesh(world)
        self.remeshes += 1
        self.last_world = world
        monitor = getattr(engine, "monitor", None)
        if monitor is not None:
            monitor.registry.counter(
                "lifecycle_remesh_total",
                "live in-process re-mesh flips applied").inc()
            monitor.registry.gauge(
                "lifecycle_world_size",
                "data-parallel world size after the last re-mesh",
            ).set(float(world))
        return True
