"""Default program suite: every jitted entry point the repo ships,
built at toy scale so the CLI can audit the real lowered programs
without hardware.

Three engines cover the jit surface:

* a ZeRO-3 train engine on the canonical ``dp × fsdp`` mesh — the
  fused ``engine/train_step`` plus the imperative pair
  (``engine/forward_grad``, ``engine/apply_update``); this is where
  donation, fp64, and the ZeRO-3 gather-leak checks bite,
* a comm engine (int8 bucketed collectives on the legacy data mesh) —
  the fused comm train step with its shard_map reduction buckets plus
  one standalone per-bucket reducer (``comm/reduce[b0]``); this is
  where the collective-axis checks see real named collectives,
* a serving engine — one prefill bucket and the donated decode step.

Multi-device engines are skipped gracefully on a 1-device host (the
``__main__`` CLI forces 8 virtual CPU devices before jax imports, so
the full suite runs there; ``scripts/tpu_smoke.py`` re-runs the same
suite against real-TPU lowerings).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from .hlo import ProgramSpec

__all__ = ["default_program_suite", "audit_default_programs"]


def _param_bytes(tree) -> Tuple[int, int]:
    import jax
    leaves = [x for x in jax.tree_util.tree_leaves(tree)
              if hasattr(x, "nbytes")]
    if not leaves:
        return 0, 0
    return sum(int(x.nbytes) for x in leaves), max(int(x.nbytes)
                                                  for x in leaves)


def _train_specs(notes: List[str]) -> List[ProgramSpec]:
    import jax
    import jax.numpy as jnp
    import numpy as np

    import deeperspeed_tpu as deepspeed

    n_dev = jax.device_count()
    multi = n_dev >= 2 and n_dev % 2 == 0

    def _loss(p, batch):
        h = jnp.tanh(batch @ p["w1"])
        return jnp.mean((h @ p["w2"]) ** 2)

    params = {"w1": jnp.zeros((64, 128), jnp.float32),
              "w2": jnp.zeros((128, 32), jnp.float32)}
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    }
    if multi:
        cfg["zero_optimization"] = {"stage": 3}
        cfg["mesh"] = {"dp": 2, "fsdp": -1}
        zero_stage = 3
    else:
        notes.append("train: single-device host — ZeRO-3 mesh audit "
                     "degraded to an unsharded engine")
        zero_stage = 0
    engine, _, _, _ = deepspeed.initialize(
        model=_loss, model_parameters=params, config_params=cfg)

    raw = np.ones((8, 64), np.float32)
    engine.train_batch(batch=raw)  # commit sharding + build every fn
    batch = engine._pack_pld(engine._place_batch(raw))
    rng = engine._rng_args()
    lr = np.float32(engine._current_lr())
    total, largest = _param_bytes(engine.state.params)

    specs = [ProgramSpec(
        name="engine/train_step", fn=engine._train_batch_fn(),
        args=(engine.state, batch, lr, rng), mesh=engine.mesh,
        zero_stage=zero_stage, hot=True,
        param_bytes_total=total, param_bytes_largest=largest)]
    specs.append(ProgramSpec(
        name="engine/forward_grad", fn=engine._forward_grad_fn(),
        args=(engine.state, batch, rng), mesh=engine.mesh,
        zero_stage=zero_stage, hot=True,
        param_bytes_total=total, param_bytes_largest=largest))
    grads = jax.tree.map(
        lambda p: jax.ShapeDtypeStruct(p.shape, p.dtype),
        engine.state.params)
    specs.append(ProgramSpec(
        name="engine/apply_update", fn=engine._apply_update_fn(),
        args=(engine.state, grads, lr, np.float32(1.0)),
        mesh=engine.mesh, zero_stage=zero_stage, hot=True,
        param_bytes_total=total, param_bytes_largest=largest))
    return specs


def _comm_specs(notes: List[str]) -> List[ProgramSpec]:
    import jax
    import jax.numpy as jnp
    import numpy as np

    import deeperspeed_tpu as deepspeed

    if jax.device_count() < 2:
        notes.append("comm: single-device host — bucketed-collective "
                     "audit skipped")
        return []

    def _loss(p, batch):
        return jnp.mean((batch @ p["w"]) ** 2)

    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "comm": {"mode": "int8", "bucket_mb": 0.001, "block": 128},
    }
    engine, _, _, _ = deepspeed.initialize(
        model=_loss, model_parameters={"w": jnp.zeros((64, 32),
                                                      jnp.float32)},
        config_params=cfg)

    raw = np.ones((8, 64), np.float32)
    engine.train_batch(batch=raw)  # builds the bucket plan + comm state
    batch = engine._pack_pld(engine._place_batch(raw))
    rng = engine._rng_args()
    lr = np.float32(engine._current_lr())
    total, largest = _param_bytes(engine.state.params)

    specs = [ProgramSpec(
        name="engine/train_step[comm]", fn=engine._train_batch_fn(),
        args=(engine.state, engine._comm_state, batch, lr, rng),
        mesh=engine.mesh, hot=True,
        param_bytes_total=total, param_bytes_largest=largest)]
    comm = engine.comm
    if comm is not None and getattr(comm, "n_buckets", 0) > 0:
        # the standalone reducer takes per-device LOCAL gradient stacks
        # (leading axis = data-parallel world), exactly what the
        # unfused backward() hands it
        ndev = int(np.prod(engine.mesh.devices.shape))
        from jax.sharding import NamedSharding, PartitionSpec as P
        ax = engine.mesh.axis_names[0]

        def _stack(p):
            sh = NamedSharding(engine.mesh,
                               P(ax, *([None] * len(p.shape))))
            return jax.device_put(
                jnp.zeros((ndev,) + tuple(p.shape), p.dtype), sh)

        stacked = jax.tree_util.tree_leaves(
            jax.tree.map(_stack, engine.state.params))
        b = comm.plan.buckets[0]
        specs.append(ProgramSpec(
            name="comm/reduce[b0]", fn=comm._bucket_reduce_fn(0),
            args=([stacked[i] for i in b.leaf_ids],
                  engine._comm_state[0]),
            mesh=engine.mesh, hot=True))
    return specs


def _serving_specs(notes: List[str]) -> List[ProgramSpec]:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models.gpt import GPTConfig, make_gpt
    from ..serving import ServingConfig, ServingEngine

    cfg = GPTConfig(vocab_size=97, n_layer=2, n_head=2, d_model=32,
                    max_seq=64, remat=False, dtype=jnp.float32,
                    attn_impl="xla")
    init_fn, _, _, _ = make_gpt(cfg)
    params = init_fn(jax.random.PRNGKey(0))
    scfg = ServingConfig(num_slots=2, block_size=4, num_blocks=32,
                         max_seq_len=48)
    eng = ServingEngine(cfg, params, scfg)

    bucket = eng.scfg.bucket_for(9)
    toks = jnp.zeros((1, bucket), jnp.int32)
    specs = [ProgramSpec(
        name=f"serving/prefill_step[b{bucket}]", fn=eng._prefill_step,
        args=(eng.params, toks), hot=False)]

    N = scfg.num_slots
    dargs = (eng.params, eng.kv.k, eng.kv.v,
             jnp.asarray(np.zeros((N, scfg.blocks_per_slot), np.int32)),
             jnp.asarray(np.zeros(N, np.int32)),
             jnp.asarray(np.zeros(N, np.int32)),
             jnp.asarray(np.zeros(N, np.float32)),
             jnp.asarray(np.zeros(N, np.int32)),
             jnp.asarray(np.zeros(N, np.int32)))
    specs.append(ProgramSpec(
        name="serving/decode_step", fn=eng._decode_step, args=dargs,
        hot=True))
    return specs


def default_program_suite(notes: Optional[List[str]] = None
                          ) -> List[ProgramSpec]:
    """Build every auditable entry point; ``notes`` collects coverage
    degradations (e.g. single-device hosts) so nothing is silently
    skipped."""
    if notes is None:
        notes = []
    specs: List[ProgramSpec] = []
    specs.extend(_train_specs(notes))
    specs.extend(_comm_specs(notes))
    specs.extend(_serving_specs(notes))
    return specs


def audit_default_programs(notes: Optional[List[str]] = None):
    from .hlo import audit_programs
    return audit_programs(default_program_suite(notes))
