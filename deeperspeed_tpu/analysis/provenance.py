"""Config-provenance check: "autotuned" must mean autotuned.

Walks the repo's ``configs/*.json`` (plus any explicitly given paths)
and, for every config that carries a ``"provenance"`` block, re-derives
the knob fingerprint over the tuned blocks
(:data:`deeperspeed_tpu.autotune.provenance.TUNED_KEYS`) and compares
it to the recorded ``knob_hash``. A mismatch — someone hand-edited a
mesh extent, ZeRO stage, comm knob, kernel route or serving shape after
the autotuner signed the file — is an **error** finding, so
``scripts/check.sh`` fails. Configs without a provenance block are
untouched: hand-rolled configs remain first-class, they just cannot
*claim* to be autotuned.

Malformed provenance blocks (missing required keys, wrong type) are
errors too: a half-deleted record is indistinguishable from tampering.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Sequence

from ..autotune.provenance import verify_provenance
from .findings import Finding

__all__ = ["check_config_provenance"]

RULE = "config-provenance"


def _config_files(root: str, subdir: str = "configs") -> List[str]:
    d = os.path.join(root, subdir)
    if not os.path.isdir(d):
        return []
    return sorted(
        os.path.join(d, f) for f in os.listdir(d) if f.endswith(".json"))


def check_config_provenance(
    root: str,
    paths: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Findings for every provenance violation under ``root``.

    ``paths`` overrides discovery (absolute or root-relative JSON
    files); default is every ``configs/*.json``.
    """
    files = ([os.path.join(root, p) if not os.path.isabs(p) else p
              for p in paths]
             if paths is not None else _config_files(root))
    out: List[Finding] = []
    for path in files:
        rel = os.path.relpath(path, root)
        try:
            with open(path) as fh:
                cfg = json.load(fh)
        except (OSError, ValueError) as e:
            out.append(Finding(
                rule=RULE, severity="error", path=rel, line=0,
                message=f"unreadable config: {e}"))
            continue
        if not isinstance(cfg, dict):
            continue
        ok, why = verify_provenance(cfg)
        if not ok:
            out.append(Finding(
                rule=RULE, severity="error", path=rel, line=0,
                message=why,
                detail={"provenance": cfg.get("provenance")}))
    return out
