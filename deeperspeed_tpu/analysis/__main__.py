"""``python -m deeperspeed_tpu.analysis`` — the pre-merge static gate.

Runs both levels (AST repo-rule linter + compiled-program auditor),
applies ``ANALYSIS_SUPPRESSIONS.json``, prints findings, optionally
writes the findings JSON, and exits non-zero iff any *error*-level
finding survives suppression. ``scripts/check.sh`` runs this between
ruff and the strict trace validator.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


_REEXEC_MARK = "_DSTPU_ANALYSIS_REEXEC"


def _force_cpu_devices(n: int) -> None:
    """The program audit needs a multi-device host to see the SPMD
    programs; on CPU that means forcing virtual devices BEFORE jax
    initializes. Running ``python -m deeperspeed_tpu.analysis`` imports
    the parent package (and with it jax) before main() ever runs, so
    the only reliable way to apply the flags is to re-exec ourselves
    once with the environment set. No-op on real accelerators (audit
    those lowerings instead) and when the operator pre-set the flags."""
    if os.environ.get(_REEXEC_MARK) == "1":
        return
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if os.environ.get("JAX_PLATFORMS", "") != "cpu":
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        return
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n}").strip()
    os.environ[_REEXEC_MARK] = "1"
    os.execv(sys.executable,
             [sys.executable, "-m", "deeperspeed_tpu.analysis"]
             + sys.argv[1:])


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m deeperspeed_tpu.analysis",
        description="static auditor for jitted programs + repo-rule linter")
    p.add_argument("--root", default=None,
                   help="repo root (default: auto-detected from the "
                        "installed package location)")
    p.add_argument("--json", default=None, metavar="PATH",
                   help="write the findings report JSON here")
    p.add_argument("--write-baseline", action="store_true",
                   help="write <root>/ANALYSIS_BASELINE.json (the file "
                        "monitor/ledger.py METRIC_SPECS gate on)")
    p.add_argument("--suppressions", default=None, metavar="PATH",
                   help="suppression file (default: "
                        "<root>/ANALYSIS_SUPPRESSIONS.json)")
    p.add_argument("--no-programs", action="store_true",
                   help="skip the compiled-program audit (level 1)")
    p.add_argument("--no-lint", action="store_true",
                   help="skip the AST repo-rule linter (level 2)")
    p.add_argument("--no-provenance", action="store_true",
                   help="skip the configs/ provenance check (level 3)")
    p.add_argument("--devices", type=int, default=8,
                   help="virtual CPU device count for the program audit")
    args = p.parse_args(argv)

    if not args.no_programs:
        _force_cpu_devices(args.devices)

    from .findings import (DEFAULT_BASELINE_FILE, DEFAULT_SUPPRESSIONS_FILE,
                           SuppressionError, apply_suppressions, format_text,
                           load_suppressions, report)

    root = args.root
    if root is None:
        here = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        root = here if os.path.isdir(os.path.join(here, "deeperspeed_tpu")) \
            else os.getcwd()

    findings = []
    notes = []
    if not args.no_lint:
        from .astlint import lint_paths
        findings.extend(lint_paths(root))
    if not args.no_programs:
        from .programs import audit_default_programs
        findings.extend(audit_default_programs(notes))
    if not args.no_provenance:
        from .provenance import check_config_provenance
        findings.extend(check_config_provenance(root))

    sup_path = args.suppressions or os.path.join(root,
                                                 DEFAULT_SUPPRESSIONS_FILE)
    try:
        sups = load_suppressions(sup_path)
    except SuppressionError as e:
        print(f"analysis: bad suppression file: {e}", file=sys.stderr)
        return 2
    kept, suppressed = apply_suppressions(findings, sups)
    for s in sups:
        if not s.used:
            notes.append(f"stale suppression never matched: "
                         f"{s.rule} @ {s.path} ({s.reason})")

    rep = report(kept, suppressed, root=root,
                 extra={"notes": notes} if notes else None)
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(rep, fh, indent=2, sort_keys=True)
    if args.write_baseline:
        with open(os.path.join(root, DEFAULT_BASELINE_FILE), "w") as fh:
            json.dump(rep, fh, indent=2, sort_keys=True)

    text = format_text(kept, suppressed)
    if text:
        print(text)
    for n in notes:
        print(f"note: {n}")
    c = rep["counts"]
    print(f"analysis: {c['error']} error(s), {c['warning']} warning(s), "
          f"{c['info']} info, {c['suppressed']} suppressed")
    return 1 if c["error"] else 0


if __name__ == "__main__":
    sys.exit(main())
