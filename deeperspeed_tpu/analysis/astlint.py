"""Level-2 repo-rule linter: AST checks for conventions that otherwise
live only in reviewers' heads.

Rules are pluggable: subclass :class:`Rule` and append to
:data:`RULES` (or pass your own list to :func:`lint_paths`). Two rule
shapes exist — per-module rules see one parsed file at a time, and
repo-level rules see the whole batch at once (needed for cross-file
checks like the trace-event-name registry diff).

The linter deliberately works on the AST, not regexes: calls split
across lines, aliased imports, and docstring mentions are all handled
correctly (a ``trace_span("fwd")`` inside a docstring is not an
emission).

Shipped rules:

``mesh-construction``
    ``Mesh(...)`` may only be constructed in ``sharding/mesh.py``
    (``make_mesh`` is the single raw-construction site; everything
    else routes through it so layout announcements and validation
    cannot be skipped).
``host-sync-in-jit``
    ``.item()`` / ``jax.device_get`` / ``jax.block_until_ready``
    inside a traced function — a host sync burned into the compiled
    program (or a tracer leak at trace time).
``prngkey-in-traced``
    fresh ``PRNGKey(...)`` inside a traced step function: the key is
    baked into the compiled program, so every step reuses the same
    randomness (nondeterminism bugs of the worst kind — silent).
``trace-event-names``
    every event name emitted in source must satisfy
    ``monitor/validate.py``'s strict-mode registry, and every
    registered exact name / arg schema must be emitted somewhere —
    the cross-check holds in both directions.
``config-key-undeclared``
    config modules (``**/config.py``) must read keys through declared
    constants (``runtime/constants.py`` etc.), not inline string
    literals — an undeclared key is invisible to schema validation.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .findings import Finding

# ---------------------------------------------------------------------------
# parsing + shared helpers


class Module:
    """One parsed source file."""

    def __init__(self, path: str, relpath: str, tree: ast.AST):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.tree = tree

    @classmethod
    def parse(cls, path: str, root: str) -> Optional["Module"]:
        try:
            with open(path, encoding="utf-8") as fh:
                src = fh.read()
            tree = ast.parse(src, filename=path)
        except (SyntaxError, UnicodeDecodeError, OSError):
            return None
        return cls(path, os.path.relpath(path, root), tree)


def _terminal_name(node: ast.AST) -> Optional[str]:
    """foo -> 'foo'; a.b.foo -> 'foo'; anything else -> None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name for error messages."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


# transforms whose function argument ends up traced by JAX
_TRACING_TRANSFORMS = {
    "jit", "pjit", "shard_map", "grad", "value_and_grad", "checkpoint",
    "remat", "vmap", "pmap", "scan", "custom_vjp", "custom_jvp",
}


def traced_function_defs(tree: ast.AST) -> List[ast.FunctionDef]:
    """Every FunctionDef in the module that JAX will trace.

    Detected two ways: decorated with a tracing transform (including
    ``@partial(jax.jit, ...)``), or referenced by name as the function
    argument of a tracing-transform call anywhere in the module
    (``self._fn = jax.jit(self._step_body, ...)`` marks a method named
    ``_step_body``).
    """
    defs: List[ast.FunctionDef] = []
    jitted_names: Set[str] = set()

    def _transform_call(call: ast.Call) -> bool:
        return _terminal_name(call.func) in _TRACING_TRANSFORMS

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _transform_call(node):
            for arg in node.args[:1]:  # the function argument is first
                name = _terminal_name(arg)
                if name:
                    jitted_names.add(name)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        traced = node.name in jitted_names
        for dec in node.decorator_list:
            if _terminal_name(dec) in _TRACING_TRANSFORMS:
                traced = True
            elif isinstance(dec, ast.Call):
                if _terminal_name(dec.func) in _TRACING_TRANSFORMS:
                    traced = True
                elif (_terminal_name(dec.func) == "partial" and dec.args
                      and _terminal_name(dec.args[0]) in _TRACING_TRANSFORMS):
                    traced = True
        if traced:
            defs.append(node)
    return defs


def _walk_body(fn: ast.FunctionDef) -> Iterable[ast.AST]:
    """Walk a traced function's body WITHOUT descending into nested
    defs that are themselves host-side helpers is over-engineering —
    nested defs inside a traced fn are traced too, so plain walk."""
    for stmt in fn.body:
        yield from ast.walk(stmt)


# ---------------------------------------------------------------------------
# rule plumbing


class Rule:
    name: str = "?"
    severity: str = "error"

    def check_module(self, mod: Module) -> List[Finding]:
        return []

    def check_repo(self, mods: Sequence[Module]) -> List[Finding]:
        return []

    def _finding(self, mod: Module, node: ast.AST, message: str,
                 severity: Optional[str] = None, **detail) -> Finding:
        return Finding(rule=self.name, severity=severity or self.severity,
                       path=mod.relpath, line=getattr(node, "lineno", 0),
                       message=message, detail=detail or None)


class MeshConstructionRule(Rule):
    """Mesh(...) anywhere but sharding/mesh.py."""

    name = "mesh-construction"
    severity = "error"
    allowed = ("sharding/mesh.py",)

    def check_module(self, mod: Module) -> List[Finding]:
        if mod.relpath.endswith(self.allowed):
            return []
        out = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and _terminal_name(node.func) == "Mesh":
                out.append(self._finding(
                    mod, node,
                    f"raw {_dotted(node.func)}(...) construction — route "
                    "through sharding.mesh.make_mesh so layout validation "
                    "and the mesh/build announcement cannot be skipped"))
        return out


class HostSyncInJitRule(Rule):
    """.item() / device_get / block_until_ready inside traced functions."""

    name = "host-sync-in-jit"
    severity = "error"
    _sync_names = {"item", "block_until_ready", "device_get"}

    def check_module(self, mod: Module) -> List[Finding]:
        out = []
        for fn in traced_function_defs(mod.tree):
            for node in _walk_body(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = _terminal_name(node.func)
                if name in self._sync_names:
                    out.append(self._finding(
                        mod, node,
                        f"host sync `{_dotted(node.func)}(...)` inside "
                        f"traced function `{fn.name}` — either burned into "
                        "the compiled program or a trace-time crash"))
        return out


class PRNGKeyInTracedRule(Rule):
    """fresh PRNGKey(...) inside a traced step function."""

    name = "prngkey-in-traced"
    severity = "error"
    _key_ctors = {"PRNGKey", "key"}

    def check_module(self, mod: Module) -> List[Finding]:
        out = []
        for fn in traced_function_defs(mod.tree):
            for node in _walk_body(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = _terminal_name(node.func)
                if name == "PRNGKey" or (
                        name == "key"
                        and isinstance(node.func, ast.Attribute)
                        and _dotted(node.func).endswith("random.key")):
                    out.append(self._finding(
                        mod, node,
                        f"fresh `{_dotted(node.func)}(...)` inside traced "
                        f"function `{fn.name}` — the key constant-folds "
                        "into the program, so every step reuses the same "
                        "randomness; thread keys in as arguments"))
        return out


class TraceEventNamesRule(Rule):
    """Two-directional diff between emitted event names and the strict
    registry in monitor/validate.py."""

    name = "trace-event-names"
    severity = "error"

    # call shapes that emit an event: the module-level tracer helpers
    # plus Tracer's span/instant methods. Deliberately NOT bare
    # `counter`/`gauge` — those are the metrics registry (prometheus
    # names), a different namespace from trace events.
    _emitters = {"trace_span", "trace_instant", "trace_counter",
                 "span", "instant"}

    def __init__(self, schemas=None, prefixes=None, names=None):
        if schemas is None:
            from ..monitor import validate as _v
            schemas = _v.EVENT_ARG_SCHEMAS
            prefixes = _v.KNOWN_EVENT_PREFIXES
            names = _v.KNOWN_EVENT_NAMES
        self.schemas = dict(schemas)
        self.prefixes = tuple(prefixes or ())
        self.names = frozenset(names or ())

    # -- collection ---------------------------------------------------
    def _static_name(self, node: ast.AST) -> Tuple[Optional[str], bool]:
        """(name, is_exact). For f-strings, the static leading text
        with is_exact=False."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value, True
        if isinstance(node, ast.JoinedStr):
            head = []
            for part in node.values:
                if isinstance(part, ast.Constant) and isinstance(part.value, str):
                    head.append(part.value)
                else:
                    break
            return ("".join(head) or None), False
        return None, False

    def _emitted(self, mods: Sequence[Module]):
        """[(name, exact, mod, node)] for every event emission site."""
        out = []
        for mod in mods:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call):
                    if _terminal_name(node.func) in self._emitters and node.args:
                        name, exact = self._static_name(node.args[0])
                        if name is not None:
                            out.append((name, exact, mod, node))
                    for kw in node.keywords:
                        if kw.arg == "name":
                            name, exact = self._static_name(kw.value)
                            if name is not None and self._looks_like_event(name):
                                out.append((name, exact, mod, node))
                elif isinstance(node, ast.Dict):
                    for k, v in zip(node.keys, node.values):
                        if (isinstance(k, ast.Constant) and k.value == "name"):
                            name, exact = self._static_name(v)
                            if name is not None and self._looks_like_event(name):
                                out.append((name, exact, mod, node))
        return out

    def _looks_like_event(self, name: str) -> bool:
        # dict-literal / name= collection is scoped to strings that are
        # plausibly event names, so cfg name="adam" style kwargs don't
        # drown the check
        return (name in self.names or name in self.schemas
                or name.startswith(self.prefixes))

    def _known(self, name: str, exact: bool) -> bool:
        if exact:
            return name in self.names or name.startswith(self.prefixes)
        # dynamic name: judge the static prefix if it reaches a
        # subsystem slash, else give it the benefit of the doubt
        if name.startswith(self.prefixes):
            return True
        return "/" not in name

    # -- the check ----------------------------------------------------
    def check_repo(self, mods: Sequence[Module]) -> List[Finding]:
        out: List[Finding] = []
        emitted = self._emitted(mods)
        for name, exact, mod, node in emitted:
            if not self._known(name, exact):
                out.append(self._finding(
                    mod, node,
                    f"event name {name!r} is not registered in "
                    "monitor/validate.py strict schemas (add it to "
                    "KNOWN_EVENT_PREFIXES / KNOWN_EVENT_NAMES or fix the "
                    "name) — strict trace validation would reject this run",
                    name=name))
        # reverse direction: registered names / schemas never emitted
        emitted_names = [(n, e) for n, e, _, _ in emitted]

        def _covered(reg: str) -> bool:
            for n, exact in emitted_names:
                if exact and (n == reg or n.startswith(reg)):
                    return True
                if not exact and (n.startswith(reg) or reg.startswith(n)):
                    return True
            return False

        registry_mod = next(
            (m for m in mods if m.relpath.endswith("monitor/validate.py")),
            mods[0] if mods else None)
        for reg in sorted(set(self.schemas) | set(self.names)):
            if not _covered(reg):
                out.append(Finding(
                    rule=self.name, severity="warning",
                    path=(registry_mod.relpath if registry_mod
                          else "monitor/validate.py"),
                    line=0,
                    message=(f"registered event name {reg!r} is never "
                             "emitted by any source file — dead schema "
                             "entry (or the emitter builds the name in a "
                             "way the linter cannot see; suppress with a "
                             "reason if so)"),
                    detail={"name": reg}))
        for pref in self.prefixes:
            if not any(n.startswith(pref) for n, _ in emitted_names):
                out.append(Finding(
                    rule=self.name, severity="warning",
                    path=(registry_mod.relpath if registry_mod
                          else "monitor/validate.py"),
                    line=0,
                    message=(f"registered event prefix {pref!r} has no "
                             "emission site in the scanned sources"),
                    detail={"prefix": pref}))
        return out


class ConfigKeyUndeclaredRule(Rule):
    """Inline string-literal config keys in config modules.

    Config parsing modules (``**/config.py``) must read keys through
    declared constants so the set of recognized keys is enumerable in
    one place. The declared set is every string constant assigned to an
    UPPER_CASE name in the repo's constants modules plus the scanned
    module itself.
    """

    name = "config-key-undeclared"
    severity = "error"
    _registry_files = (
        "runtime/constants.py",
        "elasticity/constants.py",
    )

    def __init__(self, extra_declared: Iterable[str] = ()):
        self._extra = set(extra_declared)

    @staticmethod
    def _declared_in(tree: ast.AST) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
            else:
                continue
            names = [t.id for t in targets
                     if isinstance(t, ast.Name) and t.id.isupper()]
            if not names:
                continue
            if (isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)):
                out.add(node.value.value)
        return out

    def check_repo(self, mods: Sequence[Module]) -> List[Finding]:
        declared: Set[str] = set(self._extra)
        for mod in mods:
            if mod.relpath.endswith(self._registry_files):
                declared |= self._declared_in(mod.tree)
        out: List[Finding] = []
        for mod in mods:
            if not mod.relpath.endswith("config.py"):
                continue
            declared_here = declared | self._declared_in(mod.tree)
            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "get" and node.args):
                    continue
                key = node.args[0]
                if not (isinstance(key, ast.Constant)
                        and isinstance(key.value, str)):
                    continue
                if key.value not in declared_here:
                    out.append(self._finding(
                        mod, node,
                        f"config key {key.value!r} read via .get() but "
                        "never declared as a constant — undeclared keys "
                        "are invisible to config validation and typo-prone",
                        key=key.value))
        return out


RULES = (
    MeshConstructionRule,
    HostSyncInJitRule,
    PRNGKeyInTracedRule,
    TraceEventNamesRule,
    ConfigKeyUndeclaredRule,
)


# ---------------------------------------------------------------------------
# driver

DEFAULT_SCAN_DIRS = ("deeperspeed_tpu", "scripts")


def collect_modules(root: str,
                    dirs: Sequence[str] = DEFAULT_SCAN_DIRS) -> List[Module]:
    mods: List[Module] = []
    for d in dirs:
        base = os.path.join(root, d)
        if os.path.isfile(base) and base.endswith(".py"):
            m = Module.parse(base, root)
            if m:
                mods.append(m)
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [x for x in dirnames
                           if x not in ("__pycache__", ".git")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    m = Module.parse(os.path.join(dirpath, fn), root)
                    if m:
                        mods.append(m)
    return mods


def lint_paths(root: str,
               dirs: Sequence[str] = DEFAULT_SCAN_DIRS,
               rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    """Run every rule over the python sources under root/dirs."""
    mods = collect_modules(root, dirs)
    if rules is None:
        rules = [cls() for cls in RULES]
    findings: List[Finding] = []
    for rule in rules:
        for mod in mods:
            findings.extend(rule.check_module(mod))
        findings.extend(rule.check_repo(mods))
    return findings
