"""Static analysis for DeeperSpeed-TPU: a compiled-program auditor
(donation aliasing, fp64/weak promotions, collective axes, ZeRO-3
gather leaks, host callbacks — all read from donation-safe AOT
lowerings) plus an AST repo-rule linter (mesh construction sites, host
syncs in traced code, PRNGKey hygiene, trace-event-name registry
cross-check, undeclared config keys) and a config-provenance check
(a config claiming autotuned provenance whose tuned knobs were
hand-edited afterward is an error — see analysis/provenance.py).

CLI: ``python -m deeperspeed_tpu.analysis`` — see ``__main__.py`` and
``docs/tutorials/analysis.md``.
"""

from .findings import (
    DEFAULT_BASELINE_FILE,
    DEFAULT_SUPPRESSIONS_FILE,
    Finding,
    Suppression,
    SuppressionError,
    apply_suppressions,
    counts,
    format_text,
    load_suppressions,
    report,
)
from .astlint import (
    RULES,
    ConfigKeyUndeclaredRule,
    HostSyncInJitRule,
    MeshConstructionRule,
    Module,
    PRNGKeyInTracedRule,
    Rule,
    TraceEventNamesRule,
    collect_modules,
    lint_paths,
    traced_function_defs,
)
from .hlo import (
    ProgramSpec,
    all_gather_result_bytes,
    audit_program,
    audit_programs,
    collect_collectives,
    count_alias_pairs,
    known_rule_axes,
)
from .programs import audit_default_programs, default_program_suite
from .provenance import check_config_provenance

__all__ = [
    "DEFAULT_BASELINE_FILE",
    "DEFAULT_SUPPRESSIONS_FILE",
    "Finding",
    "Suppression",
    "SuppressionError",
    "apply_suppressions",
    "counts",
    "format_text",
    "load_suppressions",
    "report",
    "RULES",
    "ConfigKeyUndeclaredRule",
    "HostSyncInJitRule",
    "MeshConstructionRule",
    "Module",
    "PRNGKeyInTracedRule",
    "Rule",
    "TraceEventNamesRule",
    "collect_modules",
    "lint_paths",
    "traced_function_defs",
    "ProgramSpec",
    "all_gather_result_bytes",
    "audit_program",
    "audit_programs",
    "collect_collectives",
    "count_alias_pairs",
    "known_rule_axes",
    "audit_default_programs",
    "default_program_suite",
    "check_config_provenance",
]
