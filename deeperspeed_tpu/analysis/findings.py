"""Finding model shared by both analysis levels.

A ``Finding`` is one violation: a rule id, a severity, where it was
found (a source file:line for AST rules, a program entry-point name for
compiled-program audits), and a human message. The CLI collects
findings from every checker, applies the suppression file, and exits
non-zero iff any *error*-level finding survives.

Suppressions live in ``ANALYSIS_SUPPRESSIONS.json`` at the repo root —
a list of ``{"rule": ..., "path": ..., "reason": ...}`` entries. The
``reason`` is mandatory: a suppression without one is itself an error,
so intent is always recorded next to the waiver. ``path`` matches the
finding's location (source path relative to the root, or the program
entry name for level-1 findings); an optional ``line`` pins the
suppression to one statement so it cannot silently absorb new
violations elsewhere in the file.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

SEVERITIES = ("error", "warning", "info")

DEFAULT_SUPPRESSIONS_FILE = "ANALYSIS_SUPPRESSIONS.json"
DEFAULT_BASELINE_FILE = "ANALYSIS_BASELINE.json"


@dataclasses.dataclass
class Finding:
    rule: str
    severity: str  # "error" | "warning" | "info"
    path: str      # source file (relative) or program entry name
    line: int      # 0 for program-level findings
    message: str
    detail: Optional[Dict[str, Any]] = None

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(f"bad severity {self.severity!r} for rule {self.rule}")

    def to_dict(self) -> Dict[str, Any]:
        d = {
            "rule": self.rule,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }
        if self.detail:
            d["detail"] = self.detail
        return d

    def key(self) -> str:
        return f"{self.rule}:{self.path}:{self.line}"


@dataclasses.dataclass
class Suppression:
    rule: str
    path: str
    reason: str
    line: Optional[int] = None
    used: bool = dataclasses.field(default=False, compare=False)

    def matches(self, f: Finding) -> bool:
        if self.rule != f.rule and not fnmatch.fnmatch(f.rule, self.rule):
            return False
        if self.path != f.path and not fnmatch.fnmatch(f.path, self.path):
            return False
        if self.line is not None and int(self.line) != int(f.line):
            return False
        return True


class SuppressionError(ValueError):
    """Malformed suppression file (missing reason, bad shape, ...)."""


def load_suppressions(path: str) -> List[Suppression]:
    """Parse the suppression file; a missing file means no suppressions.

    Every entry MUST carry a non-empty ``reason`` — the whole point of
    the file is that waivers are documented where they are granted.
    """
    if not os.path.exists(path):
        return []
    with open(path) as fh:
        raw = json.load(fh)
    entries = raw.get("suppressions", raw) if isinstance(raw, dict) else raw
    if not isinstance(entries, list):
        raise SuppressionError(f"{path}: expected a list of suppressions")
    out: List[Suppression] = []
    for i, e in enumerate(entries):
        if not isinstance(e, dict):
            raise SuppressionError(f"{path}[{i}]: entry must be an object")
        for field in ("rule", "path", "reason"):
            if not str(e.get(field, "")).strip():
                raise SuppressionError(
                    f"{path}[{i}]: missing mandatory field {field!r}"
                    + (" — every suppression needs a reason"
                       if field == "reason" else ""))
        out.append(Suppression(rule=e["rule"], path=e["path"],
                               reason=e["reason"], line=e.get("line")))
    return out


def apply_suppressions(
    findings: Sequence[Finding], sups: Sequence[Suppression]
) -> Tuple[List[Finding], List[Tuple[Finding, Suppression]]]:
    """Split findings into (kept, suppressed) and mark used waivers."""
    kept: List[Finding] = []
    suppressed: List[Tuple[Finding, Suppression]] = []
    for f in findings:
        hit = next((s for s in sups if s.matches(f)), None)
        if hit is None:
            kept.append(f)
        else:
            hit.used = True
            suppressed.append((f, hit))
    return kept, suppressed


def counts(findings: Sequence[Finding]) -> Dict[str, int]:
    c = {s: 0 for s in SEVERITIES}
    for f in findings:
        c[f.severity] += 1
    return c


def report(
    findings: Sequence[Finding],
    suppressed: Sequence[Tuple[Finding, Suppression]] = (),
    root: str = ".",
    extra: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """The findings JSON the CLI writes (and the ledger baselines)."""
    c = counts(findings)
    c["suppressed"] = len(suppressed)
    out = {
        "version": 1,
        "root": os.path.abspath(root),
        "counts": c,
        "findings": sorted((f.to_dict() for f in findings),
                           key=lambda d: (SEVERITIES.index(d["severity"]),
                                          d["path"], d["line"], d["rule"])),
        "suppressed": [
            dict(f.to_dict(), reason=s.reason) for f, s in suppressed
        ],
    }
    if extra:
        out.update(extra)
    return out


def format_text(findings: Sequence[Finding],
                suppressed: Sequence[Tuple[Finding, Suppression]] = ()) -> str:
    lines = []
    for f in findings:
        loc = f.path if f.line == 0 else f"{f.path}:{f.line}"
        lines.append(f"{f.severity.upper():7s} {f.rule:24s} {loc}: {f.message}")
    if suppressed:
        lines.append(f"({len(suppressed)} finding(s) suppressed with reasons)")
    return "\n".join(lines)
