"""Level-1 compiled-program auditor.

Audits jitted entry points the same donation-safe way the perf doctor
captures cost analysis (monitor/perf.py): AOT ``fn.lower(...)`` over
``ShapeDtypeStruct``s — the jit cache is never touched, so auditing a
live engine cannot trip the recompile watchdog.

Checks per program:

``donation-dropped`` / ``donation-partial``
    ``donate_argnums`` declared but the compiled executable has no (or
    fewer) input-output aliases than donated input leaves. A dropped
    donation silently doubles HBM for the donated tree; XLA does NOT
    warn on CPU, so the only reliable detection is exactly this diff
    between ``lowered.args_info`` (declared) and the compiled HLO's
    ``input_output_alias`` table (honored).
``fp64-in-program``
    a float64/complex128 value anywhere in the step jaxpr — on TPU
    this is an emulation cliff, and in this codebase always a leaked
    python float via x64 mode.
``weak-promotion``
    an elementwise op whose output is a wider float than one of its
    array inputs — an accidental upcast (bf16 tensor silently computed
    in f32). Explicit ``convert_element_type`` (master-weight casts)
    is intentionally out of scope.
``collective-axis`` / ``collective-axis-unknown``
    every collective's named axis must exist in the mesh the program
    runs under, and belong to the axis vocabulary of the
    ``sharding/rules.py`` table (canonical dp/fsdp/tp/sp + the legacy
    aliases ``translate_spec`` accepts).
``zero3-allgather-leak``
    under ZeRO-3 no single all-gather result may approach the full
    parameter footprint — a gather whose result is larger than any
    parameter leaf by a wide margin means sharding leaked and the
    "partitioned" params are materialized whole.
``host-callback``
    callback primitives (``jax.debug.print``, ``pure_callback``, ...)
    inside a hot entry point: a host round-trip per step.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from .findings import Finding

try:  # jaxpr node types moved around across jax versions
    from jax._src.core import ClosedJaxpr, Jaxpr
except ImportError:  # pragma: no cover
    from jax.core import ClosedJaxpr, Jaxpr  # type: ignore


@dataclasses.dataclass
class ProgramSpec:
    """One jitted entry point to audit.

    ``fn`` must be a jitted callable (supports ``.lower``); ``args`` /
    ``kwargs`` may be real arrays or ShapeDtypeStructs — they are
    abstractified before lowering either way.
    """

    name: str
    fn: Any
    args: Tuple = ()
    kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    mesh: Any = None             # jax.sharding.Mesh the program runs under
    zero_stage: int = 0
    hot: bool = True             # per-step entry point?
    param_bytes_total: int = 0   # for the ZeRO-3 gather-leak bound
    param_bytes_largest: int = 0


# ---------------------------------------------------------------------------
# jaxpr walking

_COLLECTIVE_AXIS_PARAMS = ("axis_name", "axes")
_CALLBACK_MARKERS = ("callback", "outside_call", "host_call")
_PROMOTION_PRIMS = {"add", "sub", "mul", "div", "max", "min"}


def _sub_jaxprs(value):
    if isinstance(value, ClosedJaxpr):
        yield value.jaxpr
    elif isinstance(value, Jaxpr):
        yield value
    elif isinstance(value, (list, tuple)):
        for v in value:
            yield from _sub_jaxprs(v)


def iter_eqns(jaxpr):
    """Depth-first over every equation, descending through pjit/scan/
    while/cond/shard_map/custom_* sub-jaxprs."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from iter_eqns(sub)


def _aval(v):
    return getattr(v, "aval", None)


def collect_collectives(closed) -> List[Tuple[str, Tuple[str, ...]]]:
    """[(primitive_name, (axis, ...))] for every collective in the jaxpr."""
    out = []
    for eqn in iter_eqns(closed.jaxpr):
        axes: List[str] = []
        for key in _COLLECTIVE_AXIS_PARAMS:
            if key in eqn.params:
                val = eqn.params[key]
                vals = val if isinstance(val, (tuple, list)) else (val,)
                axes.extend(a for a in vals if isinstance(a, str))
        if axes:
            out.append((eqn.primitive.name, tuple(axes)))
    return out


def known_rule_axes() -> Set[str]:
    """Axis vocabulary of the sharding rules table: the canonical mesh
    axes plus every legacy alias translate_spec understands."""
    axes: Set[str] = set()
    try:
        from ..sharding import mesh as _m
        axes |= {_m.DP_AXIS, _m.FSDP_AXIS, _m.TP_AXIS, _m.SP_AXIS}
    except Exception:  # pragma: no cover
        axes |= {"dp", "fsdp", "tp", "sp"}
    try:
        from ..sharding import rules as _r
        for spec in getattr(_r, "DEFAULT_RULES", {}).values():
            parts = spec if isinstance(spec, (tuple, list)) else (spec,)
            for part in parts:
                sub = part if isinstance(part, (tuple, list)) else (part,)
                axes |= {a for a in sub if isinstance(a, str)}
        axes |= {a for a in getattr(_r, "LEGACY_AXES", ()) or ()}
    except Exception:  # pragma: no cover
        pass
    # legacy generation (parallel/topology.py constants)
    try:
        from ..parallel import topology as _t
        for const in ("DATA_AXIS", "PIPE_AXIS", "MODEL_AXIS", "SEQ_AXIS",
                      "EXPERT_AXIS"):
            v = getattr(_t, const, None)
            if isinstance(v, str):
                axes.add(v)
    except Exception:  # pragma: no cover
        axes |= {"data", "pipe", "model", "seq", "expert"}
    return axes


# ---------------------------------------------------------------------------
# HLO text parsing

_HLO_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]*)\[([0-9,]*)\]")


def _shape_bytes(dtype: str, dims: str) -> int:
    n = _HLO_BYTES.get(dtype, 4)
    for d in dims.split(","):
        if d.strip():
            n *= int(d)
    return n


def count_alias_pairs(hlo_text: str) -> int:
    """Number of honored input→output aliases in a compiled HLO module
    header (``input_output_alias={ {0}: (0, {}, may-alias), ... }``).
    Brace-matched by hand — the table nests braces."""
    start = hlo_text.find("input_output_alias={")
    if start < 0:
        return 0
    i = hlo_text.index("{", start)
    depth, j = 0, i
    while j < len(hlo_text):
        if hlo_text[j] == "{":
            depth += 1
        elif hlo_text[j] == "}":
            depth -= 1
            if depth == 0:
                break
        j += 1
    table = hlo_text[i:j + 1]
    return table.count("-alias")


def all_gather_result_bytes(hlo_text: str) -> List[int]:
    """Result size (bytes) of every all-gather in the HLO text."""
    out = []
    for line in hlo_text.splitlines():
        if "all-gather(" not in line and "all-gather-start(" not in line:
            continue
        lhs = line.split("all-gather", 1)[0]
        shapes = _SHAPE_RE.findall(lhs)
        if shapes:
            # tuple results (all-gather-start) list operand+result
            # shapes; the result is the largest
            out.append(max(_shape_bytes(d, dims) for d, dims in shapes))
    return out


# ---------------------------------------------------------------------------
# the audit


def _abstractify(args, kwargs):
    """Like monitor/perf.py's donation-safe abstractify, but KEEPING
    each array's sharding: the audit must see the SPMD program (its
    collectives and gathers), not a single-device re-lowering."""
    import jax

    def one(x):
        if isinstance(x, jax.Array):
            # only pin COMMITTED placements: a ShapeDtypeStruct sharding
            # is always treated as committed, so carrying over the
            # default single-device placement of an uncommitted scalar
            # (e.g. a step counter) fails lowering against mesh-wide
            # params that jit would happily have co-located at runtime
            if getattr(x, "committed", False):
                try:
                    return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                                sharding=x.sharding)
                except Exception:
                    return jax.ShapeDtypeStruct(x.shape, x.dtype)
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return x

    return (jax.tree.map(one, args),
            jax.tree.map(one, kwargs if kwargs is not None else {}))


def _donated_leaves(lowered) -> int:
    import jax
    n = 0
    for leaf in jax.tree_util.tree_leaves(lowered.args_info):
        if getattr(leaf, "donated", False):
            n += 1
    return n


def audit_program(spec: ProgramSpec) -> List[Finding]:
    """Run every compiled-program check against one entry point."""
    import jax

    findings: List[Finding] = []

    def add(rule, severity, message, **detail):
        findings.append(Finding(rule=rule, severity=severity, path=spec.name,
                                line=0, message=message,
                                detail=detail or None))

    a_args, a_kwargs = _abstractify(spec.args, spec.kwargs)
    try:
        lowered = spec.fn.lower(*a_args, **a_kwargs)
        compiled = lowered.compile()
    except Exception as e:  # lowering itself failed — that IS a finding
        add("lowering-failed", "error",
            f"entry point failed to lower/compile: {type(e).__name__}: {e}")
        return findings

    # ---- donation: declared vs honored ------------------------------
    donated = _donated_leaves(lowered)
    hlo_text = ""
    try:
        hlo_text = compiled.as_text()
    except Exception:  # pragma: no cover - backend without text dump
        pass
    if donated and hlo_text:
        pairs = count_alias_pairs(hlo_text)
        if pairs == 0:
            add("donation-dropped", "error",
                f"{donated} input leaf/leaves declared donated but the "
                "compiled executable has NO input-output aliases — the "
                "donation was silently dropped (double HBM for the "
                "donated tree)",
                donated_leaves=donated, alias_pairs=0)
        elif pairs < donated:
            add("donation-partial", "warning",
                f"only {pairs}/{donated} donated input leaves alias an "
                "output in the compiled executable — the rest are "
                "retained alongside their replacements",
                donated_leaves=donated, alias_pairs=pairs)

    # ---- jaxpr-level checks -----------------------------------------
    try:
        closed = jax.make_jaxpr(spec.fn)(*a_args, **a_kwargs)
    except Exception as e:
        add("lowering-failed", "error",
            f"make_jaxpr failed: {type(e).__name__}: {e}")
        return findings

    import numpy as np

    import jax.numpy as jnp

    def _is_float(dt):
        # jnp.issubdtype, not np: bf16/fp8 are ml_dtypes extension
        # types that numpy does not place under np.floating
        try:
            return bool(jnp.issubdtype(dt, jnp.floating))
        except Exception:
            return False

    seen_f64 = set()
    seen_promo = set()
    # jnp dtype promotion inserts a convert_element_type BEFORE the
    # arithmetic op, so the op itself sees uniform dtypes — the implicit
    # upcast is only visible as a widening float convert whose result
    # feeds arithmetic. Track those converts by their output var.
    widened: Dict[Any, Tuple[str, str]] = {}
    for eqn in iter_eqns(closed.jaxpr):
        prim = eqn.primitive.name
        if prim == "convert_element_type" and eqn.invars and eqn.outvars:
            av_in = _aval(eqn.invars[0])
            av_out = _aval(eqn.outvars[0])
            dt_in = getattr(av_in, "dtype", None)
            dt_out = getattr(av_out, "dtype", None)
            if (dt_in is not None and dt_out is not None
                    and _is_float(dt_in)
                    and _is_float(dt_out)
                    and getattr(av_in, "ndim", 0) > 0
                    and np.dtype(dt_in).itemsize
                    < np.dtype(dt_out).itemsize):
                try:
                    widened[eqn.outvars[0]] = (np.dtype(dt_in).name,
                                               np.dtype(dt_out).name)
                except TypeError:
                    pass
        # fp64 / complex128 anywhere
        for v in list(eqn.invars) + list(eqn.outvars):
            av = _aval(v)
            dt = getattr(av, "dtype", None)
            if dt is not None and dt in (np.float64, np.complex128):
                if prim not in seen_f64:
                    seen_f64.add(prim)
                    add("fp64-in-program", "error",
                        f"{np.dtype(dt).name} value flows through "
                        f"`{prim}` — double precision leaked into the "
                        "step program (x64 promotion)",
                        primitive=prim, dtype=np.dtype(dt).name)
        # implicit widening in elementwise arithmetic
        if prim in _PROMOTION_PRIMS:
            out_av = _aval(eqn.outvars[0])
            out_dt = getattr(out_av, "dtype", None)
            if out_dt is not None and _is_float(out_dt):
                for v in eqn.invars:
                    try:
                        conv = widened.get(v)
                    except TypeError:
                        conv = None
                    av = _aval(v)
                    dt = getattr(av, "dtype", None)
                    direct = (dt is not None
                              and _is_float(dt)
                              and getattr(av, "ndim", 0) > 0
                              and np.dtype(dt).itemsize
                              < np.dtype(out_dt).itemsize)
                    if conv is None and not direct:
                        continue
                    narrow = conv[0] if conv else np.dtype(dt).name
                    key = (prim, narrow, np.dtype(out_dt).name)
                    if key not in seen_promo:
                        seen_promo.add(key)
                        add("weak-promotion", "warning",
                            f"`{prim}` widens a {narrow} array to "
                            f"{np.dtype(out_dt).name} — implicit "
                            "promotion; cast explicitly if intended",
                            primitive=prim, narrow=narrow,
                            wide=np.dtype(out_dt).name)
        # host callbacks in hot paths
        if any(m in prim for m in _CALLBACK_MARKERS):
            add("host-callback", "error" if spec.hot else "info",
                f"host callback primitive `{prim}` inside "
                + ("hot entry point — a host round-trip every step"
                   if spec.hot else "entry point"),
                primitive=prim)

    # ---- collective axes vs mesh + rules table ----------------------
    mesh_axes = set(getattr(spec.mesh, "axis_names", ()) or ())
    vocab = known_rule_axes()
    for prim, axes in collect_collectives(closed):
        for ax in axes:
            if mesh_axes and ax not in mesh_axes:
                add("collective-axis", "error",
                    f"collective `{prim}` reduces over axis {ax!r} which "
                    f"does not exist in the program's mesh "
                    f"{sorted(mesh_axes)}",
                    primitive=prim, axis=ax, mesh_axes=sorted(mesh_axes))
            elif ax not in vocab:
                add("collective-axis-unknown", "warning",
                    f"collective `{prim}` uses axis {ax!r} that is outside "
                    "the sharding/rules.py axis vocabulary "
                    f"{sorted(vocab)}",
                    primitive=prim, axis=ax)

    # ---- ZeRO-3 full-param gather leak ------------------------------
    if spec.zero_stage >= 3 and spec.param_bytes_total > 0 and hlo_text:
        bound = max(1.5 * spec.param_bytes_largest,
                    0.6 * spec.param_bytes_total)
        for nbytes in all_gather_result_bytes(hlo_text):
            if nbytes > bound:
                add("zero3-allgather-leak", "error",
                    f"all-gather materializes {nbytes} bytes under ZeRO-3 "
                    f"(largest param leaf {spec.param_bytes_largest}, "
                    f"total {spec.param_bytes_total}) — the partitioned "
                    "parameters are being gathered whole",
                    gather_bytes=nbytes,
                    bound_bytes=int(bound))
                break  # one finding per program is enough signal

    return findings


def audit_programs(specs: Sequence[ProgramSpec]) -> List[Finding]:
    out: List[Finding] = []
    for spec in specs:
        out.extend(audit_program(spec))
    return out
