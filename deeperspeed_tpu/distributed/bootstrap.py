"""Multi-host bootstrap: bring up ``jax.distributed`` under policy.

:func:`bootstrap` is the one place the runtime crosses from "a process"
to "process k of N": it resolves the fleet shape (config pins or the
launcher/MPI environment), applies the CPU-collectives backend and the
simulated-device count *before* jax initializes its backend, runs
``jax.distributed.initialize`` with retry + exponential backoff around
the configured init/heartbeat timeouts, stamps the per-host run context
(``role.h<proc>`` — every host gets its own obs files), writes this
host's rendezvous record, and emits a ``dist/init`` trace span carrying
the resulting process topology.

The jaxlib build's CPU platform ships with cross-process collectives
DISABLED (``jax_cpu_collectives_implementation`` defaults to none): a
2-process CPU mesh would rendezvous fine and then fail on the first
``psum``. ``cpu_collectives: "auto"`` flips it to gloo whenever the run
spans processes on CPU — which is precisely what makes every multi-host
drill in this repo runnable on localhost.

Idempotent: a second call (engine re-init inside one process, the
legacy :func:`...utils.distributed.init_distributed` path having run
first) returns the existing topology.
"""

import dataclasses
import os
import sys
import time
from typing import Dict, Optional

from ..utils.logging import logger
from .config import DistributedConfig

__all__ = [
    "ProcessTopology",
    "bootstrap",
    "current_topology",
    "initialize_jax_distributed",
    "multiprocess_cpu_probe",
    "shutdown",
]

_XLA_DEVCOUNT_FLAG = "--xla_force_host_platform_device_count"

_state: Dict[str, object] = {"initialized": False, "topology": None}


@dataclasses.dataclass(frozen=True)
class ProcessTopology:
    """What :func:`bootstrap` established — one record per process."""

    process_id: int
    process_count: int
    local_devices: int
    global_devices: int
    coordinator_address: Optional[str] = None
    cpu_collectives: str = "off"

    @property
    def multihost(self) -> bool:
        return self.process_count > 1

    def host_role(self, base: str) -> str:
        """Per-host role label: ``trainer`` -> ``trainer.h1`` so each
        host's obs files (``<role>.i<inc>.trace.json``) are distinct."""
        from ..monitor.runctx import host_role

        return host_role(base, self.process_id, self.process_count)

    def as_args(self) -> Dict[str, object]:
        return {
            "process": self.process_id,
            "processes": self.process_count,
            "local_devices": self.local_devices,
            "global_devices": self.global_devices,
        }


def _apply_local_devices(n: Optional[int]) -> None:
    """Pin the simulated CPU device count (drills). Must land before
    jax builds its backend; warns instead of lying when it can't."""
    if n is None:
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if _XLA_DEVCOUNT_FLAG in flags:
        return  # launcher/conftest already pinned it; theirs wins
    if "jax" in sys.modules:
        # merely-imported jax is fine (XLA reads XLA_FLAGS at backend
        # creation); an already-built backend is not. The probe must
        # NOT be jax.local_device_count() — that call would itself
        # build the backend it is checking for.
        import jax

        try:
            from jax._src import xla_bridge as _xb

            backend_up = _xb.backends_are_initialized()
        except ImportError:  # pragma: no cover - layout drift
            backend_up = False
        if backend_up:
            have = jax.local_device_count()
            if have != int(n):
                logger.warning(
                    "distributed.local_devices=%s requested but jax "
                    "already initialized %s local devices; flag ignored "
                    "(set XLA_FLAGS before the first jax computation)",
                    n, have)
            return
    os.environ["XLA_FLAGS"] = (
        f"{flags} {_XLA_DEVCOUNT_FLAG}={int(n)}".strip())


def _apply_cpu_collectives(choice: str, num_processes: int) -> str:
    """Select the CPU cross-process collectives backend. Returns the
    backend applied ("off" = left at the platform default)."""
    import jax

    if choice == "off" or num_processes <= 1:
        return "off"
    if os.environ.get("JAX_PLATFORMS", "").lower() not in ("", "cpu"):
        if choice in ("gloo", "mpi"):
            logger.warning(
                "distributed.cpu_collectives=%s requested on a non-CPU "
                "platform; ignored", choice)
        return "off"
    backend = "gloo" if choice == "auto" else choice
    try:
        jax.config.update("jax_cpu_collectives_implementation", backend)
    except Exception as e:  # unknown option on exotic jaxlib builds
        logger.warning(
            "could not enable CPU collectives backend %r (%s); "
            "cross-process CPU collectives will fail", backend, e)
        return "off"
    return backend


def initialize_jax_distributed(coordinator_address: str,
                               num_processes: int, process_id: int,
                               *, init_timeout_s: float = 120.0,
                               heartbeat_timeout_s: float = 100.0,
                               init_retries: int = 3,
                               retry_backoff_s: float = 1.0) -> None:
    """``jax.distributed.initialize`` under a retry + backoff policy.

    The heartbeat budget maps onto the coordination service's
    interval x max-missed knobs (a silent peer is declared dead after
    ~``heartbeat_timeout_s``); older jax builds without those knobs fall
    back to the public API and its defaults.
    """
    import jax
    from jax._src import distributed as _jdist

    hb_interval = max(1, int(round(float(heartbeat_timeout_s) / 10.0)))
    hb_missing = max(2, int(round(float(heartbeat_timeout_s) / hb_interval)))
    last: Optional[BaseException] = None
    for attempt in range(1, int(init_retries) + 1):
        try:
            try:
                from jax._src import xla_bridge as _xb
            except ImportError:  # pragma: no cover - layout drift
                _xb = None
            if (_xb is not None
                    and _xb.backends_are_initialized()):
                raise RuntimeError(
                    "jax backend already initialized; bootstrap must "
                    "run before any jax computation")
            try:
                _jdist.global_state.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=int(num_processes),
                    process_id=int(process_id),
                    initialization_timeout=int(init_timeout_s),
                    service_heartbeat_interval_seconds=hb_interval,
                    service_max_missing_heartbeats=hb_missing,
                    client_heartbeat_interval_seconds=hb_interval,
                    client_max_missing_heartbeats=hb_missing,
                )
            except TypeError:
                # jax build without heartbeat knobs: public API
                jax.distributed.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=int(num_processes),
                    process_id=int(process_id),
                    initialization_timeout=int(init_timeout_s),
                )
            return
        except RuntimeError:
            raise  # double init / backend-already-up: retrying can't help
        except Exception as e:  # transient: coordinator not up yet, etc.
            last = e
            if attempt >= int(init_retries):
                break
            delay = float(retry_backoff_s) * (2.0 ** (attempt - 1))
            logger.warning(
                "jax.distributed.initialize attempt %d/%d failed (%s); "
                "retrying in %.1fs", attempt, init_retries, e, delay)
            time.sleep(delay)
    raise RuntimeError(
        f"jax.distributed.initialize failed after {init_retries} "
        f"attempt(s): {last}") from last


def _distributed_client_up() -> bool:
    """Is the jax.distributed client already connected in this process?"""
    try:
        from jax._src import distributed as _jdist

        return _jdist.global_state.client is not None
    except Exception:
        return False


def current_topology() -> Optional[ProcessTopology]:
    """The topology :func:`bootstrap` established, or None."""
    return _state["topology"]  # type: ignore[return-value]


def bootstrap(cfg: Optional[DistributedConfig] = None,
              *, role: Optional[str] = None) -> ProcessTopology:
    """Establish the process topology for this run.

    Single-process (no fleet shape anywhere) is not an error — the
    returned topology simply has ``process_count == 1`` and nothing was
    initialized, so every config works unchanged on a laptop.
    """
    if _state["initialized"]:
        return _state["topology"]  # type: ignore[return-value]
    cfg = cfg or DistributedConfig()
    if not cfg.enabled:
        raise ValueError("bootstrap() called with a disabled config")

    shape = None
    if cfg.num_processes is not None:
        addr = cfg.coordinator_address
        if addr is None:
            raise ValueError(
                "distributed.num_processes pinned without "
                "coordinator_address (and no launcher environment)")
        shape = dict(coordinator_address=addr,
                     num_processes=int(cfg.num_processes),
                     process_id=int(cfg.process_id))
    else:
        from ..utils import distributed as _legacy

        shape = _legacy.discover()
        if shape is not None and cfg.coordinator_address is not None:
            shape["coordinator_address"] = cfg.coordinator_address

    _apply_local_devices(cfg.local_devices)

    if shape is None or int(shape["num_processes"]) <= 1:
        import jax

        topo = ProcessTopology(
            process_id=0, process_count=1,
            local_devices=int(jax.local_device_count()),
            global_devices=int(jax.device_count()),
            coordinator_address=None, cpu_collectives="off")
        _state.update(initialized=True, topology=topo)
        return topo

    import jax

    from ..utils import distributed as _legacy

    if _legacy._initialized or _distributed_client_up():
        # the legacy init_distributed path (or an embedding application)
        # already brought jax.distributed up; adopt its topology
        backend = "external"
    else:
        backend = _apply_cpu_collectives(
            cfg.cpu_collectives, int(shape["num_processes"]))
        initialize_jax_distributed(
            shape["coordinator_address"], int(shape["num_processes"]),
            int(shape["process_id"]),
            init_timeout_s=cfg.init_timeout_s,
            heartbeat_timeout_s=cfg.heartbeat_timeout_s,
            init_retries=cfg.init_retries,
            retry_backoff_s=cfg.retry_backoff_s)
        # mark the legacy entry point initialized too — both guards
        # protect the same jax.distributed singleton
        _legacy._initialized = True

    topo = ProcessTopology(
        process_id=int(jax.process_index()),
        process_count=int(jax.process_count()),
        local_devices=int(jax.local_device_count()),
        global_devices=int(jax.device_count()),
        coordinator_address=str(shape["coordinator_address"]),
        cpu_collectives=backend)
    _state.update(initialized=True, topology=topo)

    # per-host run context: every process of the fleet keeps the run id
    # but gets its own role lane (trainer.h0, trainer.h1, ...)
    from ..monitor import runctx

    base_role = role or os.environ.get(runctx.ROLE_ENV, "trainer")
    os.environ[runctx.ROLE_ENV] = runctx.host_role(
        base_role, topo.process_id, topo.process_count)

    # the fleet supervisor hands children the record directory via env;
    # a config pin wins when both are present
    rdzv_dir = cfg.rendezvous_dir or os.environ.get("DS_TPU_RENDEZVOUS_DIR")
    if rdzv_dir:
        from . import rendezvous

        rendezvous.write_record(
            rdzv_dir,
            rendezvous.HostRecord(
                host=topo.process_id, pid=os.getpid(),
                incarnation=runctx.current().incarnation,
                epoch=int(os.environ.get("DS_TPU_FLEET_EPOCH", "0")),
                role=os.environ[runctx.ROLE_ENV], status="ready",
                clock=runctx.clock_anchor()))

    from ..monitor import trace_span

    with trace_span("dist/init", lane="dist",
                    coordinator=topo.coordinator_address,
                    cpu_collectives=backend, **topo.as_args()):
        pass
    logger.info(
        "distributed bootstrap: process %d/%d, %d local / %d global "
        "devices, coordinator=%s, cpu_collectives=%s",
        topo.process_id, topo.process_count, topo.local_devices,
        topo.global_devices, topo.coordinator_address, backend)
    return topo


def shutdown() -> None:
    """Tear down jax.distributed (subprocess drills/tests)."""
    if not _state["initialized"]:
        return
    import jax

    try:
        jax.distributed.shutdown()
    except Exception:
        pass
    _state.update(initialized=False, topology=None)


# ---------------------------------------------------------------------- #
# capability probe
# ---------------------------------------------------------------------- #

_PROBE_CHILD = r"""
import os, sys
rank = int(sys.argv[1]); port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)
import jax
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(f"127.0.0.1:{port}", 2, rank,
                           initialization_timeout=30)
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
import numpy as np
mesh = Mesh(np.asarray(jax.devices()), ("d",))
x = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("d")), np.full((1,), rank + 1, np.float32))
total = jax.jit(lambda a: jnp.sum(a), out_shardings=NamedSharding(mesh, P()))(x)
assert float(total) == 3.0, float(total)
print("PROBE-OK", flush=True)
"""

_probe_cache: Dict[str, bool] = {}


def multiprocess_cpu_probe(timeout_s: float = 90.0) -> bool:
    """Can THIS jaxlib build run 2-process CPU collectives on localhost?

    Spawns two throwaway processes that rendezvous on a free port and
    psum across the process boundary via gloo. Cached per process; the
    multiprocess tests and the check.sh smoke hang their skip condition
    on this instead of a hardcoded assumption about the build.
    """
    if "ok" in _probe_cache:
        return _probe_cache["ok"]
    import socket
    import subprocess

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _PROBE_CHILD, str(r), str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for r in (0, 1)
    ]
    ok = True
    deadline = time.monotonic() + timeout_s
    for p in procs:
        try:
            out, _ = p.communicate(timeout=max(1.0,
                                               deadline - time.monotonic()))
            ok = ok and p.returncode == 0 and "PROBE-OK" in out
        except subprocess.TimeoutExpired:
            p.kill()
            p.communicate()
            ok = False
    _probe_cache["ok"] = ok
    return ok


if __name__ == "__main__":
    sys.exit(0 if multiprocess_cpu_probe() else 1)
