"""Per-host rendezvous records and the fleet clock handshake.

The fleet supervisor and every trainer process share one directory
(local disk on the localhost harness, NFS/GCS on a real fleet). Each
host owns exactly one file in it — ``host<k>.json`` — written
atomically, so readers never see a torn record:

* the **supervisor** stamps ``launched`` (with the launch epoch and its
  ``t_send`` wall clock) before exec'ing host k's trainer, and
  ``exited``/``crashed``/``preempted`` with the exit code after;
* the **trainer** (via :func:`..bootstrap.bootstrap`) overwrites it
  with ``ready`` once ``jax.distributed`` is up, stamping its own
  clock anchor — which doubles as the ``t_remote`` of an NTP-style
  handshake: the supervisor's ``t_send`` (from the launched record it
  wrote) and its ``t_recv`` (when it observes the flip to ready)
  bracket the child's stamp, so
  :func:`...monitor.runctx.estimate_clock_offset` yields a per-host
  wall-clock offset without any extra channel.

:func:`write_offsets` persists those estimates as ``offsets.json``
keyed by host role — exactly the sidecar
:func:`...monitor.aggregate.merge_files` consumes to rebase per-host
trace lanes onto one fleet timeline.
"""

import dataclasses
import json
import os
import tempfile
import time
from typing import Dict, List, Optional

__all__ = [
    "HostRecord",
    "record_path",
    "write_record",
    "read_record",
    "read_records",
    "wait_all_ready",
    "write_offsets",
    "read_offsets",
    "OFFSETS_FILE",
]

OFFSETS_FILE = "offsets.json"

_STATUSES = ("launched", "ready", "exited", "crashed", "preempted")


@dataclasses.dataclass(frozen=True)
class HostRecord:
    """One host's latest rendezvous state."""

    host: int                    # process id within the fleet
    pid: int = 0                 # OS pid of the trainer (0 = not spawned)
    incarnation: int = 0         # restarts of this host's logical slot
    epoch: int = 0               # fleet launch epoch (bumps per restart)
    role: str = "trainer"        # obs role lane (trainer.h<k>)
    status: str = "launched"     # launched|ready|exited|crashed|preempted
    exit_code: Optional[int] = None
    reason: Optional[str] = None  # crash/preempt cause, supervisor-stamped
    clock: Optional[Dict[str, float]] = None  # runctx.clock_anchor()
    wall: float = 0.0            # when this record was written

    def __post_init__(self):
        if self.status not in _STATUSES:
            raise ValueError(
                f"rendezvous status must be one of {_STATUSES}, "
                f"got {self.status!r}")
        if self.host < 0:
            raise ValueError(f"rendezvous host must be >= 0, got {self.host}")

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        return {k: v for k, v in d.items() if v is not None}

    @staticmethod
    def from_dict(d: dict) -> "HostRecord":
        fields = {f.name for f in dataclasses.fields(HostRecord)}
        return HostRecord(**{k: d[k] for k in d if k in fields})


def record_path(dirpath: str, host: int) -> str:
    return os.path.join(dirpath, f"host{int(host)}.json")


def write_record(dirpath: str, rec: HostRecord) -> str:
    """Atomically (write + rename) persist ``rec`` as host<k>.json."""
    os.makedirs(dirpath, exist_ok=True)
    if not rec.wall:
        rec = dataclasses.replace(rec, wall=time.time())
    path = record_path(dirpath, rec.host)
    fd, tmp = tempfile.mkstemp(dir=dirpath, prefix=".rdzv.")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(rec.to_dict(), f)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def read_record(dirpath: str, host: int) -> Optional[HostRecord]:
    try:
        with open(record_path(dirpath, host)) as f:
            return HostRecord.from_dict(json.load(f))
    except (OSError, json.JSONDecodeError, ValueError, TypeError):
        return None


def read_records(dirpath: str) -> List[HostRecord]:
    """All hosts' records, sorted by host id; unreadable files skipped."""
    out = []
    try:
        names = os.listdir(dirpath)
    except OSError:
        return out
    for name in names:
        if not (name.startswith("host") and name.endswith(".json")):
            continue
        try:
            host = int(name[4:-5])
        except ValueError:
            continue
        rec = read_record(dirpath, host)
        if rec is not None:
            out.append(rec)
    return sorted(out, key=lambda r: r.host)


def wait_all_ready(dirpath: str, hosts: int, epoch: int,
                   timeout_s: float = 60.0,
                   poll_s: float = 0.05) -> List[HostRecord]:
    """Block until every host of ``epoch`` reports ``ready`` (the
    coordinator's half of the restart barrier). Raises TimeoutError
    with the stragglers' current statuses."""
    deadline = time.monotonic() + timeout_s
    while True:
        recs = {r.host: r for r in read_records(dirpath)}
        ready = [recs.get(h) for h in range(hosts)]
        if all(r is not None and r.status == "ready" and r.epoch == epoch
               for r in ready):
            return [recs[h] for h in range(hosts)]
        if time.monotonic() > deadline:
            statuses = {h: (recs[h].status if h in recs else "missing")
                        for h in range(hosts)}
            raise TimeoutError(
                f"rendezvous epoch {epoch}: not all {hosts} hosts ready "
                f"within {timeout_s}s: {statuses}")
        time.sleep(poll_s)


def write_offsets(dirpath: str, offsets_by_role: Dict[str, float]) -> str:
    """Persist per-host clock offsets (seconds the host's wall clock is
    AHEAD of the supervisor's) keyed by role — the aggregator's
    ``offsets.json`` sidecar."""
    os.makedirs(dirpath, exist_ok=True)
    path = os.path.join(dirpath, OFFSETS_FILE)
    fd, tmp = tempfile.mkstemp(dir=dirpath, prefix=".off.")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump({k: float(v) for k, v in offsets_by_role.items()}, f)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def read_offsets(dirpath: str) -> Dict[str, float]:
    try:
        with open(os.path.join(dirpath, OFFSETS_FILE)) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}
    return {str(k): float(v) for k, v in doc.items()
            if isinstance(v, (int, float))}
