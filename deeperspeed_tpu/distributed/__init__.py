"""Multi-host runtime: process-spanning meshes over ``jax.distributed``.

The pieces, bottom-up:

* :mod:`.config` — the validated ``"distributed"`` config block
  (coordinator address, process shape, timeouts, CPU collectives).
* :mod:`.bootstrap` — ``bootstrap()``: idempotent ``jax.distributed``
  init with retry/backoff and heartbeat mapping, per-host run-context
  roles, the localhost multiprocess capability probe.
* :mod:`.topology` — pure reads over device→process placement
  (``derive_intra_size``, ``intra_inter_split``, ``describe``).
* :mod:`.rendezvous` — atomic per-host records + the clock handshake.
* :mod:`.fleet` — the N-process supervisor: coordinated restart
  barrier and cross-host pool growth.

Submodules load lazily: the comm reducer imports ``.topology`` on its
hot path and must not drag ``.fleet``'s subprocess machinery (or jax
itself) in with it.
"""

import importlib

__all__ = [
    "DistributedConfig",
    "bootstrap",
    "config",
    "fleet",
    "rendezvous",
    "topology",
]

_SUBMODULES = ("bootstrap", "config", "fleet", "rendezvous", "topology")


def __getattr__(name):
    if name in _SUBMODULES:
        return importlib.import_module(f".{name}", __name__)
    if name == "DistributedConfig":
        return importlib.import_module(".config", __name__).DistributedConfig
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(__all__)
