"""Process topology: which devices live on which host.

The comm layer's hierarchical two-level schedule and the mesh factory
both need one fact the flat device list hides: the partition of the
global device set into *processes* (hosts). Every helper here reads it
from ``device.process_index`` — the source of truth jax maintains once
``jax.distributed`` is initialized — so the answers stay correct on
single-process simulated meshes (one process owning every device) and
on real process-spanning fleets alike.

Pure reads over jax device metadata; no collectives, no config.
"""

from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "process_count",
    "local_device_count",
    "process_groups",
    "is_process_spanning",
    "derive_intra_size",
    "describe",
    "intra_inter_split",
]


def _mesh_axis_devices(mesh, axes: Sequence[str]):
    """Flatten a mesh's device array so the reduction ``axes`` vary
    fastest-last in rank order — the order ``axis_index_groups`` address
    (rank r = position in the axis-major enumeration)."""
    import numpy as np

    names = list(mesh.axis_names)
    order = ([n for n in names if n not in axes]
             + [n for n in names if n in axes])
    perm = [names.index(n) for n in order]
    return np.transpose(mesh.devices, perm).reshape(-1)


def process_count() -> int:
    import jax

    return int(jax.process_count())


def local_device_count() -> int:
    import jax

    return int(jax.local_device_count())


def process_groups(devices=None) -> Dict[int, List[int]]:
    """``{process_index: [global device ids]}`` for ``devices``
    (default: the global device list), ids in ``jax.devices()`` order."""
    import jax

    devs = list(jax.devices()) if devices is None else list(devices)
    groups: Dict[int, List[int]] = {}
    for i, d in enumerate(devs):
        groups.setdefault(int(d.process_index), []).append(i)
    return groups


def is_process_spanning(mesh) -> bool:
    """Does this mesh place shards on more than one process?"""
    return len({int(d.process_index)
                for d in mesh.devices.reshape(-1)}) > 1


def derive_intra_size(mesh, axes: Sequence[str]) -> Optional[int]:
    """The in-host group size for a hierarchical reduction over
    ``axes`` — the count of consecutive same-process ranks along the
    reduction order — or None when host boundaries don't form equal
    contiguous rank blocks (the hierarchical schedule's
    ``axis_index_groups`` are contiguous ``[n*k, (n+1)*k)`` blocks, so a
    straddling layout must fall back to the flat schedule rather than
    silently put the "intra" hop on the cross-host wire)."""
    devs = _mesh_axis_devices(mesh, tuple(axes))
    procs = [int(d.process_index) for d in devs]
    n = len(procs)
    if n <= 1 or len(set(procs)) <= 1:
        return None
    # run-length check: equal-sized runs, each process exactly one run
    k = 1
    while k < n and procs[k] == procs[0]:
        k += 1
    if n % k:
        return None
    seen = set()
    for g in range(n // k):
        block = procs[g * k:(g + 1) * k]
        if len(set(block)) != 1 or block[0] in seen:
            return None
        seen.add(block[0])
    return k


def describe(mesh) -> Dict[str, object]:
    """JSON-ready process-topology descriptor for a mesh (stamped into
    ``dist/init`` trace events and BENCH files)."""
    import jax

    flat = mesh.devices.reshape(-1)
    per: Dict[int, int] = {}
    for d in flat:
        p = int(d.process_index)
        per[p] = per.get(p, 0) + 1
    return {
        "processes": int(jax.process_count()),
        "process_index": int(jax.process_index()),
        "devices": int(flat.size),
        "local_devices": int(jax.local_device_count()),
        "devices_per_process": {str(k): v for k, v in sorted(per.items())},
        "process_spanning": len(per) > 1,
    }


def intra_inter_split(world: int, k: int) -> Tuple[List[List[int]],
                                                   List[List[int]]]:
    """The (intra, inter) ``axis_index_groups`` of the two-level
    schedule for a world of ``world`` ranks in host blocks of ``k`` —
    shared by the reducer (which executes them) and the wire model
    (which prices each hop against its link)."""
    if world % k:
        raise ValueError(f"intra size {k} must divide world {world}")
    nn = world // k
    intra = [[n * k + i for i in range(k)] for n in range(nn)]
    inter = [[n * k + i for n in range(nn)] for i in range(k)]
    return intra, inter
