"""Fleet supervisor: the multi-host launch story of the resilience layer.

:class:`~...resilience.supervisor.Supervisor` restarts ONE child; a
multi-host run is N children that must live and die *together* —
``jax.distributed`` tears the whole fleet down when any process drops,
so restarting just the dead host would strand the survivors at a
collective. :class:`FleetSupervisor` owns that coordination:

* **launch** — spawns one trainer process per host with the rendezvous
  env (``DS_COORDINATOR_ADDRESS`` / ``DS_NUM_PROCESSES`` /
  ``DS_PROCESS_ID``) on a fresh coordinator port per epoch, per-host
  role/incarnation run context, and per-host ``launched`` rendezvous
  records carrying the handshake ``t_send``;
* **restart barrier** — on any non-zero child exit it classifies the
  cause (the preemption sentinel vs a crash; SIGKILL arrives as a
  negative returncode), stamps the dead host's record, tears the
  survivors down (SIGTERM, grace, SIGKILL), stamps THEIRS with reason
  ``fleet_restart``, then relaunches every host at epoch+1 from the
  newest valid checkpoint tag. Preemptions restart free; crashes pay
  exponential backoff and count against the cap — per host, the
  restart log preserves who actually died and why vs who was
  barrier-recycled;
* **cross-host pool growth** — with ``watch_pool`` the pool file holds
  the fleet's PROCESS count. A debounced change triggers a *planned*
  re-mesh transition: graceful fleet stop (reason ``pool_change``,
  zero crash-restarts), relaunch at the new process count. This is the
  growth path live re-mesh cannot take (a process's jax device list is
  fixed at backend init — :mod:`...lifecycle.remesh` grows within a
  process's devices; the fleet supervisor grows the process count),
  and checkpoint resharding (:mod:`...resilience.reshard`) carries
  optimizer/residual state across the world-size change;
* **clock offsets** — when a host's record flips ``launched``→``ready``
  the supervisor closes the NTP-style handshake
  (:func:`...monitor.runctx.estimate_clock_offset`) and persists
  per-role offsets for the trace aggregator.

Localhost drills pass ``simulate_cpu_devices`` so every "host" is a
process with ``local_devices`` simulated CPU devices — the same
process-spanning code paths as a real pod, minus the machines.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from ..monitor.runctx import (
    INCARNATION_ENV,
    ROLE_ENV,
    clock_anchor,
    ensure_run_id,
    estimate_clock_offset,
    host_role,
)
from ..resilience.config import PREEMPTION_EXIT_CODE_DEFAULT
from ..resilience.manifest import find_latest_valid_tag
from ..resilience.supervisor import (
    POOL_FILE_ENV,
    RESTART_COUNT_ENV,
    RESTART_REASON_ENV,
    RESUME_DIR_ENV,
    RESUME_TAG_ENV,
    WORLD_SIZE_ENV,
    compute_backoff,
)
from ..utils.logging import logger
from . import rendezvous

__all__ = ["FleetPolicy", "FleetSupervisor", "classify_exit", "free_port"]

FLEET_EPOCH_ENV = "DS_TPU_FLEET_EPOCH"


def classify_exit(code: int, preempt_exit_code: int) -> str:
    """Exit-code taxonomy shared by the barrier and the restart log:
    ``done`` (0), ``preempted`` (the sentinel), ``crashed`` (anything
    else, including negative = killed by that signal)."""
    if code == 0:
        return "done"
    if code == int(preempt_exit_code):
        return "preempted"
    return "crashed"


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class FleetPolicy:
    procs: int = 2                      # hosts (processes) to launch
    local_devices: int = 1              # devices per host
    base_role: str = "trainer"          # runctx role (gets .h<k> suffix)
    coordinator_host: str = "127.0.0.1"
    checkpoint_dir: Optional[str] = None
    rendezvous_dir: Optional[str] = None
    restart_log: Optional[str] = None   # JSONL transition record
    max_restarts: int = 10              # crash restarts; preemptions free
    backoff_base: float = 0.2
    backoff_factor: float = 2.0
    backoff_max: float = 30.0
    preempt_exit_code: int = PREEMPTION_EXIT_CODE_DEFAULT
    # cross-host growth: pool file holds the fleet PROCESS count,
    # re-read while the fleet runs; a debounced change = planned re-mesh
    pool_file: Optional[str] = None
    watch_pool: bool = False
    pool_poll_interval_s: float = 0.25
    pool_debounce_s: float = 0.5
    term_grace_s: float = 10.0          # SIGTERM -> SIGKILL budget
    ready_timeout_s: float = 120.0      # barrier: fleet must re-arrive
    # drills: export JAX_PLATFORMS=cpu + the simulated per-host device
    # count so each "host" is a localhost process over virtual devices
    simulate_cpu_devices: bool = False
    extra_env: Dict[str, str] = field(default_factory=dict)


class FleetSupervisor:
    """Coordinated restart/growth loop around N trainer processes."""

    def __init__(self, cmd: Sequence[str], policy: FleetPolicy):
        if not cmd:
            raise ValueError("fleet supervisor needs a command to run")
        if policy.procs < 1:
            raise ValueError(f"fleet needs >= 1 process, got {policy.procs}")
        self.cmd = list(cmd)
        self.policy = policy
        self.procs = int(policy.procs)
        self.epoch = 0
        self.crashes = 0          # crash barriers (drive backoff + cap)
        self.preemptions = 0
        self.remeshes = 0         # planned pool-change transitions
        self.history: List[Dict[int, int]] = []  # per-epoch exit codes
        self._incarnation = [0] * self.procs
        self._children: List[subprocess.Popen] = []
        self._t_send: Dict[int, float] = {}
        self._offsets: Dict[str, float] = {}
        self._offset_done: set = set()
        self._pool_mtime: Optional[float] = None
        self._pool_pending: Optional[tuple] = None
        ensure_run_id()

    # ------------------------------------------------------------------ #
    # bookkeeping
    # ------------------------------------------------------------------ #

    def _log_event(self, event: str, **fields) -> None:
        if not self.policy.restart_log:
            return
        rec = {"event": event, "wall": time.time(), "epoch": self.epoch,
               **fields}
        with open(self.policy.restart_log, "a") as f:
            f.write(json.dumps(rec) + "\n")

    def _resume_env(self) -> Dict[str, str]:
        env: Dict[str, str] = {}
        ckdir = self.policy.checkpoint_dir
        if ckdir:
            tag = find_latest_valid_tag(ckdir)
            if tag is not None:
                env[RESUME_TAG_ENV] = tag
                env[RESUME_DIR_ENV] = ckdir
        return env

    def _child_env(self, host: int, port: int, reason: str) -> dict:
        p = self.policy
        env = dict(os.environ)
        env.update(p.extra_env)
        env["DS_COORDINATOR_ADDRESS"] = f"{p.coordinator_host}:{port}"
        env["DS_NUM_PROCESSES"] = str(self.procs)
        env["DS_PROCESS_ID"] = str(host)
        env[ROLE_ENV] = p.base_role  # bootstrap appends .h<proc>
        env[INCARNATION_ENV] = str(self._incarnation[host])
        env[FLEET_EPOCH_ENV] = str(self.epoch)
        env[WORLD_SIZE_ENV] = str(self.procs * p.local_devices)
        env[RESTART_COUNT_ENV] = str(self.epoch)
        env[RESTART_REASON_ENV] = reason
        if p.pool_file:
            env[POOL_FILE_ENV] = p.pool_file
        if p.rendezvous_dir:
            env["DS_TPU_RENDEZVOUS_DIR"] = p.rendezvous_dir
        if p.simulate_cpu_devices:
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                                f"{p.local_devices}")
        env.update(self._resume_env())
        return env

    # ------------------------------------------------------------------ #
    # launch / stop
    # ------------------------------------------------------------------ #

    def _launch_fleet(self, reason: str) -> None:
        p = self.policy
        port = free_port()
        self._children = []
        self._t_send = {}
        self._offset_done = set()
        for host in range(self.procs):
            if p.rendezvous_dir:
                self._t_send[host] = time.time()
                rendezvous.write_record(p.rendezvous_dir, rendezvous.HostRecord(
                    host=host, incarnation=self._incarnation[host],
                    epoch=self.epoch,
                    role=host_role(p.base_role, host, self.procs),
                    status="launched", clock=clock_anchor(),
                    wall=self._t_send[host]))
            child = subprocess.Popen(
                self.cmd, env=self._child_env(host, port, reason))
            self._children.append(child)
            if p.rendezvous_dir:
                rendezvous.write_record(p.rendezvous_dir, rendezvous.HostRecord(
                    host=host, pid=child.pid,
                    incarnation=self._incarnation[host], epoch=self.epoch,
                    role=host_role(p.base_role, host, self.procs),
                    status="launched", clock=clock_anchor(),
                    wall=self._t_send[host]))
        self._log_event("launch", procs=self.procs, port=port, reason=reason,
                        incarnations=list(self._incarnation),
                        world=self.procs * p.local_devices)
        logger.info("fleet epoch %d: launched %d process(es) on port %d "
                    "(%s)", self.epoch, self.procs, port, reason)

    def _harvest_offsets(self) -> None:
        """Close the launched->ready clock handshake for newly-ready
        hosts and persist offsets.json for the aggregator."""
        p = self.policy
        if not p.rendezvous_dir:
            return
        changed = False
        for rec in rendezvous.read_records(p.rendezvous_dir):
            if (rec.status != "ready" or rec.epoch != self.epoch
                    or rec.host in self._offset_done
                    or rec.host not in self._t_send):
                continue
            t_remote = (rec.clock or {}).get("wall", rec.wall)
            off = estimate_clock_offset(
                self._t_send[rec.host], t_remote, time.time())
            self._offsets[rec.role] = off
            self._offset_done.add(rec.host)
            changed = True
        if changed:
            rendezvous.write_offsets(p.rendezvous_dir, self._offsets)

    def _stop_survivors(self, dead_host: Optional[int], reason: str) -> None:
        """Coherent teardown of every still-running child."""
        p = self.policy
        live = [(h, c) for h, c in enumerate(self._children)
                if h != dead_host and c.poll() is None]
        for _, c in live:
            try:
                c.send_signal(signal.SIGTERM)
            except OSError:
                pass
        deadline = time.monotonic() + p.term_grace_s
        for h, c in live:
            try:
                c.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                c.kill()
                c.wait()
            if p.rendezvous_dir:
                rendezvous.write_record(p.rendezvous_dir, rendezvous.HostRecord(
                    host=h, pid=c.pid, incarnation=self._incarnation[h],
                    epoch=self.epoch,
                    role=host_role(p.base_role, h, self.procs),
                    status="exited", exit_code=c.returncode, reason=reason))
            self._log_event("exit", host=h, code=c.returncode, reason=reason)

    # ------------------------------------------------------------------ #
    # pool watching (cross-host growth)
    # ------------------------------------------------------------------ #

    def _read_pool(self) -> Optional[int]:
        p = self.policy
        if not p.pool_file:
            return None
        try:
            with open(p.pool_file) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return None

    def _poll_pool_change(self) -> Optional[int]:
        """Debounced pool-file watch. Returns the new process count once
        a change has held still for pool_debounce_s, else None."""
        p = self.policy
        if not (p.watch_pool and p.pool_file):
            return None
        try:
            mtime = os.stat(p.pool_file).st_mtime
        except OSError:
            return None
        if self._pool_mtime is None:
            self._pool_mtime = mtime
            return None
        if mtime != self._pool_mtime:
            self._pool_mtime = mtime
            self._pool_pending = (time.monotonic(), self._read_pool())
            return None
        if self._pool_pending is not None:
            t0, target = self._pool_pending
            if time.monotonic() - t0 >= p.pool_debounce_s:
                self._pool_pending = None
                if target is not None and target >= 1 and target != self.procs:
                    return target
        return None

    # ------------------------------------------------------------------ #
    # the loop
    # ------------------------------------------------------------------ #

    def run(self) -> int:
        """Run the fleet to completion. Returns the final exit code (0
        when every host exits 0 within the crash cap)."""
        p = self.policy
        self._launch_fleet(reason="start")
        while True:
            self._harvest_offsets()

            target = self._poll_pool_change()
            if target is not None:
                # planned cross-host re-mesh: coherent stop, relaunch at
                # the new process count — zero crash-restarts
                old = self.procs
                self._log_event("fleet_remesh", procs_from=old,
                                procs_to=target)
                logger.info("fleet: pool change %d -> %d process(es); "
                            "coordinated re-mesh restart", old, target)
                self._stop_survivors(None, reason="pool_change")
                self.history.append({h: (c.returncode if c.returncode is
                                         not None else 0)
                                     for h, c in enumerate(self._children)})
                self.remeshes += 1
                self.procs = target
                inc = max(self._incarnation) + 1
                self._incarnation = [inc] * self.procs
                self.epoch += 1
                self._launch_fleet(reason="pool_change")
                continue

            exited = [(h, c) for h, c in enumerate(self._children)
                      if c.poll() is not None]
            if not exited:
                time.sleep(p.pool_poll_interval_s)
                continue

            codes = {h: c.returncode for h, c in exited}
            if all(c.poll() is not None for c in self._children):
                if all(code == 0 for code in
                       (c.returncode for c in self._children)):
                    for h, c in enumerate(self._children):
                        self._log_event("exit", host=h, code=0,
                                        reason="done")
                    self.history.append(
                        {h: c.returncode
                         for h, c in enumerate(self._children)})
                    self._log_event("done", crashes=self.crashes,
                                    preemptions=self.preemptions,
                                    remeshes=self.remeshes)
                    return 0

            # someone died non-zero (or a mixed exit): pick the first
            # failed host as the barrier trigger
            trigger = next(((h, code) for h, code in codes.items()
                            if code != 0), None)
            if trigger is None:
                # some hosts done (exit 0) while others still run — keep
                # waiting; jax.distributed keeps the fleet coherent
                time.sleep(p.pool_poll_interval_s)
                continue
            host, code = trigger
            cause = classify_exit(code, p.preempt_exit_code)
            if p.rendezvous_dir:
                rendezvous.write_record(p.rendezvous_dir, rendezvous.HostRecord(
                    host=host, pid=self._children[host].pid,
                    incarnation=self._incarnation[host], epoch=self.epoch,
                    role=host_role(p.base_role, host, self.procs),
                    status=cause, exit_code=code, reason=cause))
            self._log_event("exit", host=host, code=code, reason=cause)
            logger.warning("fleet epoch %d: host %d exited %d (%s); "
                           "restart barrier", self.epoch, host, code, cause)
            self._stop_survivors(host, reason="fleet_restart")
            self.history.append({h: c.returncode
                                 for h, c in enumerate(self._children)})
            self._log_event("barrier", trigger_host=host, cause=cause)

            if cause == "crashed":
                self.crashes += 1
                if self.crashes > p.max_restarts:
                    self._log_event("give_up", crashes=self.crashes)
                    logger.error("fleet: crash cap (%d) exceeded; giving "
                                 "up", p.max_restarts)
                    return code if code > 0 else 1
                delay = compute_backoff(self.crashes, p.backoff_base,
                                        p.backoff_factor, p.backoff_max)
                if delay > 0:
                    time.sleep(delay)
            else:
                self.preemptions += 1
            for h in range(self.procs):
                self._incarnation[h] += 1
            self.epoch += 1
            self._launch_fleet(reason=cause)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="Multi-host fleet supervisor: coordinated restart "
        "barrier + cross-host pool growth around N trainer processes.")
    ap.add_argument("--procs", type=int, default=2)
    ap.add_argument("--local-devices", type=int, default=1)
    ap.add_argument("--checkpoint-dir")
    ap.add_argument("--rendezvous-dir")
    ap.add_argument("--restart-log")
    ap.add_argument("--pool-file")
    ap.add_argument("--watch-pool", action="store_true")
    ap.add_argument("--max-restarts", type=int, default=10)
    ap.add_argument("--simulate-cpu-devices", action="store_true")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="-- trainer command")
    args = ap.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
    policy = FleetPolicy(
        procs=args.procs, local_devices=args.local_devices,
        checkpoint_dir=args.checkpoint_dir,
        rendezvous_dir=args.rendezvous_dir, restart_log=args.restart_log,
        pool_file=args.pool_file, watch_pool=args.watch_pool,
        max_restarts=args.max_restarts,
        simulate_cpu_devices=args.simulate_cpu_devices)
    return FleetSupervisor(cmd, policy).run()


if __name__ == "__main__":
    sys.exit(main())
