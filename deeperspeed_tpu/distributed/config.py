"""Distributed-runtime configuration.

A ``"distributed"`` block in the master JSON config (or a plain dict)
builds a :class:`DistributedConfig` — the policy for the multi-host
runtime: how ``jax.distributed`` rendezvouses (coordinator address,
process id/count, init/heartbeat timeouts, retry backoff), which CPU
collectives backend backs cross-process reductions on CPU meshes, and
where per-host rendezvous records live. Validated eagerly (unknown keys
are errors) like every other subsystem block, so a typo'd coordinator
address fails at config load, not after a 300 s rendezvous timeout.

Every shape field defaults to ``None`` = *discover from the
environment* (``DS_COORDINATOR_ADDRESS`` / ``DS_NUM_PROCESSES`` /
``DS_PROCESS_ID`` from the launcher, then the reference-compatible
``MASTER_ADDR``/``WORLD_SIZE``/``RANK``, then OpenMPI env — the
:func:`...utils.distributed.discover` chain), so one committed config
serves every host of the fleet.
"""

import dataclasses
from typing import Optional

__all__ = ["DistributedConfig"]

# config keys (declared so the analysis linter can enumerate them)
ENABLED = "enabled"
ENABLED_DEFAULT = True
COORDINATOR_ADDRESS = "coordinator_address"
NUM_PROCESSES = "num_processes"
PROCESS_ID = "process_id"
CPU_COLLECTIVES = "cpu_collectives"
CPU_COLLECTIVES_DEFAULT = "auto"
INIT_TIMEOUT_S = "init_timeout_s"
INIT_TIMEOUT_S_DEFAULT = 120.0
HEARTBEAT_TIMEOUT_S = "heartbeat_timeout_s"
HEARTBEAT_TIMEOUT_S_DEFAULT = 100.0
INIT_RETRIES = "init_retries"
INIT_RETRIES_DEFAULT = 3
RETRY_BACKOFF_S = "retry_backoff_s"
RETRY_BACKOFF_S_DEFAULT = 1.0
RENDEZVOUS_DIR = "rendezvous_dir"
LOCAL_DEVICES = "local_devices"

CPU_COLLECTIVES_CHOICES = ("auto", "gloo", "mpi", "off")

_KNOWN_KEYS = frozenset({
    ENABLED, COORDINATOR_ADDRESS, NUM_PROCESSES, PROCESS_ID,
    CPU_COLLECTIVES, INIT_TIMEOUT_S, HEARTBEAT_TIMEOUT_S, INIT_RETRIES,
    RETRY_BACKOFF_S, RENDEZVOUS_DIR, LOCAL_DEVICES,
})


@dataclasses.dataclass(frozen=True)
class DistributedConfig:
    """The ``"distributed"`` block: multi-host rendezvous policy."""

    enabled: bool = ENABLED_DEFAULT
    # "host:port" of the coordination service (process 0 binds it).
    # None = discover from the launcher/MPI environment; a bare host
    # (no ":") is rejected so a forgotten port fails loudly.
    coordinator_address: Optional[str] = None
    # global process count / this process's id; None = discover. Both
    # must come from the same source — a config pinning only one of the
    # pair is almost always a copy-paste error on a fleet.
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    # cross-process collectives backend for CPU meshes: "auto" enables
    # gloo whenever the run spans processes on the CPU platform (the
    # jaxlib build's default "none" cannot execute cross-process
    # collectives at all), "gloo"/"mpi" force a backend, "off" leaves
    # the platform default untouched (TPU/GPU runs: collectives ride
    # ICI/NCCL and this knob is irrelevant).
    cpu_collectives: str = CPU_COLLECTIVES_DEFAULT
    # rendezvous budget for ONE jax.distributed.initialize attempt; the
    # whole fleet must arrive within it
    init_timeout_s: float = INIT_TIMEOUT_S_DEFAULT
    # how long a silent peer stays "alive" before the coordination
    # service declares it dead and tears the fleet down (maps onto the
    # service's heartbeat interval x max-missed budget)
    heartbeat_timeout_s: float = HEARTBEAT_TIMEOUT_S_DEFAULT
    # transient-failure policy around initialize(): attempts beyond the
    # first wait retry_backoff_s * 2^(attempt-1) between tries (the
    # coordinator's socket may simply not be up yet on a cold fleet)
    init_retries: int = INIT_RETRIES_DEFAULT
    retry_backoff_s: float = RETRY_BACKOFF_S_DEFAULT
    # shared directory for per-host rendezvous records (host<k>.json:
    # pid, incarnation, epoch, status, clock anchor) + the fleet
    # supervisor's clock-offset ledger; None = no records written
    rendezvous_dir: Optional[str] = None
    # CPU drills only: simulated device count per process
    # (--xla_force_host_platform_device_count, which the bootstrap must
    # apply BEFORE jax initializes its backend); None = leave alone
    local_devices: Optional[int] = None

    def __post_init__(self):
        if self.cpu_collectives not in CPU_COLLECTIVES_CHOICES:
            raise ValueError(
                "distributed.cpu_collectives must be one of "
                f"{list(CPU_COLLECTIVES_CHOICES)}, "
                f'got "{self.cpu_collectives}"')
        if (self.coordinator_address is not None
                and ":" not in self.coordinator_address):
            raise ValueError(
                "distributed.coordinator_address must be 'host:port', "
                f'got "{self.coordinator_address}"')
        if self.num_processes is not None and int(self.num_processes) < 1:
            raise ValueError(
                "distributed.num_processes must be >= 1, got "
                f"{self.num_processes}")
        if self.process_id is not None:
            if int(self.process_id) < 0:
                raise ValueError(
                    "distributed.process_id must be >= 0, got "
                    f"{self.process_id}")
            if (self.num_processes is not None
                    and int(self.process_id) >= int(self.num_processes)):
                raise ValueError(
                    f"distributed.process_id {self.process_id} out of "
                    f"range for num_processes {self.num_processes}")
        if (self.process_id is None) != (self.num_processes is None):
            raise ValueError(
                "distributed.process_id and distributed.num_processes "
                "must be pinned together (or both discovered from the "
                "environment)")
        if not (float(self.init_timeout_s) > 0):
            raise ValueError(
                "distributed.init_timeout_s must be > 0, got "
                f"{self.init_timeout_s}")
        if not (float(self.heartbeat_timeout_s) > 0):
            raise ValueError(
                "distributed.heartbeat_timeout_s must be > 0, got "
                f"{self.heartbeat_timeout_s}")
        if int(self.init_retries) < 1:
            raise ValueError(
                "distributed.init_retries must be >= 1, got "
                f"{self.init_retries}")
        if float(self.retry_backoff_s) < 0:
            raise ValueError(
                "distributed.retry_backoff_s must be >= 0, got "
                f"{self.retry_backoff_s}")
        if self.local_devices is not None and int(self.local_devices) < 1:
            raise ValueError(
                "distributed.local_devices must be >= 1, got "
                f"{self.local_devices}")

    @staticmethod
    def from_dict(d: dict) -> "DistributedConfig":
        if not isinstance(d, dict):
            raise ValueError(
                f"distributed config must be a dict, got {type(d).__name__}")
        unknown = set(d) - _KNOWN_KEYS
        if unknown:
            raise ValueError(
                f"unknown distributed config keys {sorted(unknown)}; "
                f"valid keys: {sorted(_KNOWN_KEYS)}")
        return DistributedConfig(**{k: d[k] for k in d})
