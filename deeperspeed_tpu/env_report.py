"""Environment / op-compatibility report: the ``ds_report`` CLI.

Analog of reference deepspeed/env_report.py (:23 op report, :103 main):
prints a matrix of native ops (installed? compatible?) plus the JAX/TPU
environment, instead of torch/CUDA versions.

Run as ``python -m deeperspeed_tpu.env_report``.
"""

from __future__ import annotations

import os
import sys

GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"
OKAY = f"{GREEN}[OKAY]{END}"
WARNING = f"{YELLOW}[WARNING]{END}"
NO = f"{RED}[NO]{END}"


def op_report():
    from .ops.op_builder import ALL_OPS

    max_dots = 23
    print("-" * 64)
    print("DeeperSpeed-TPU native op report")
    print("-" * 64)
    print(
        "JIT-compiled ops build on first use with g++ and are cached; "
        "'compatible' means the toolchain and sources are present."
    )
    print("-" * 64)
    print(f"{'op name':<20} {'built (cached)':<18} compatible")
    print("-" * 64)
    for name, builder in sorted(ALL_OPS.items()):
        built = builder.so_path().exists() if builder.is_compatible() else False
        status = OKAY if builder.is_compatible() else NO
        note = builder.compatibility_message()
        built_str = "[CACHED]" if built else "[JIT]"
        print(f"{name:.<{max_dots}} {built_str:<14} {status} ({note})")


def simd_report():
    """Host SIMD width, relevant for the native CPU Adam (csrc/adam)."""
    flags = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    flags = line
                    break
    except OSError:
        pass
    if "avx512f" in flags:
        return "AVX512"
    if "avx2" in flags:
        return "AVX256"
    return "scalar"


def environment_report():
    print("-" * 64)
    print("DeeperSpeed-TPU general environment info:")
    print("-" * 64)
    print(f"python version ......... {sys.version.split()[0]}")
    try:
        import jax
        import jaxlib

        print(f"jax version ............ {jax.__version__}")
        print(f"jaxlib version ......... {jaxlib.__version__}")
        devices = jax.devices()
        plat = devices[0].platform
        print(f"platform ............... {plat}")
        print(f"device count ........... {len(devices)}")
        print(f"local device count ..... {jax.local_device_count()}")
        print(f"process count .......... {jax.process_count()}")
        if plat == "tpu":
            print(f"device kind ............ {devices[0].device_kind}")
    except Exception as e:  # jax init can fail off-accelerator
        print(f"jax .................... unavailable ({e})")
    from .version import __version__

    print(f"deeperspeed_tpu version  {__version__}")
    import deeperspeed_tpu

    print(
        "deeperspeed_tpu install path "
        f"{os.path.dirname(deeperspeed_tpu.__file__)}"
    )
    print(f"host SIMD .............. {simd_report()}")


def main():
    op_report()
    environment_report()


def cli_main():
    main()


if __name__ == "__main__":
    main()
