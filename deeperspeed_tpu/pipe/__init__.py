"""Public pipeline-parallelism namespace (reference deepspeed/pipe/
__init__.py re-exports the runtime.pipe containers the same way)."""

from ..runtime.pipe.module import LayerSpec, PipelineModule, TiedLayerSpec
