"""Collective bytes-on-wire audit from compiled HLO.

The reference proved its 1-bit optimizer's communication claim with NCCL
byte counters; the XLA analog is the compiled program itself: every
collective op's result shape is in the HLO text, so the bytes a program
moves per step can be read without multi-chip hardware. Used by
scripts/onebit_wire_bytes.py to compare the fp32-warmup vs compressed-phase
programs of runtime/comm/onebit_spmd.py.
"""

import re
from typing import Dict

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2, "f8e4m3": 1, "f8e5m2": 1,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "pred": 1,
}

_COLLECTIVES = ("all-reduce", "all-gather", "all-to-all", "reduce-scatter",
                "collective-permute")

# one typed buffer, e.g. f32[8,128]{1,0} or u8[64]
_SHAPE = re.compile(r"(\w+?)\[([\d,]*)\](?:\{[^}]*\})?")


def _shape_bytes(type_str: str) -> int:
    total = 0
    for dtype, dims in _SHAPE.findall(type_str):
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def collective_wire_bytes(hlo_text: str, world: int = 0) -> Dict[str, int]:
    """Audit every collective op in an HLO module.

    Returns per-op RESULT bytes plus ``total`` (their sum) and — when
    ``world`` is given — ``wire_total``: the standard per-device link-cost
    model (ring all-reduce moves 2(W-1)/W x result; all-gather /
    reduce-scatter / all-to-all move (W-1)/W x result; collective-permute
    moves 1x). Comparing two programs by wire_total gives the physical
    bytes-on-wire reduction factor without multi-chip hardware."""
    out: Dict[str, float] = {op: 0 for op in _COLLECTIVES}
    for line in hlo_text.splitlines():
        line = line.strip()
        m = re.match(r"%?[\w.\-]+ = (.+?) (" + "|".join(_COLLECTIVES)
                     + r")(-start|-done)?\(", line)
        if not m:
            continue
        if m.group(3) == "-done":  # started op already counted
            continue
        out[m.group(2)] += _shape_bytes(m.group(1))
    out["total"] = sum(out[op] for op in _COLLECTIVES)
    if world > 1:
        f = (world - 1) / world
        out["wire_total"] = int(
            out["all-reduce"] * 2 * f
            + (out["all-gather"] + out["reduce-scatter"]
               + out["all-to-all"]) * f
            + out["collective-permute"])
    return out


def compiled_wire_bytes(jitted, *args, world: int = 0,
                        **kwargs) -> Dict[str, int]:
    """Lower+compile a jitted callable and audit its collective bytes."""
    compiled = jitted.lower(*args, **kwargs).compile()
    text = "\n".join(m.to_string() for m in compiled.runtime_executable()
                     .hlo_modules()) if hasattr(
        compiled, "runtime_executable") else compiled.as_text()
    return collective_wire_bytes(text, world=world)
