"""Flops profiler, TPU-native.

Capability parity with /root/reference/deepspeed/profiling/flops_profiler/
profiler.py (`FlopsProfiler` :11, `get_model_profile` :781). The reference
monkey-patches torch.nn.functional to count MACs and hangs hooks on every
module for latency; under XLA both jobs are done by the compiler:

  * totals come from the compiled executable's own cost model
    (``jax.jit(fn).lower(...).compile().cost_analysis()``) — flops, bytes
    accessed, optimal seconds;
  * the per-module breakdown becomes a per-PRIMITIVE breakdown from walking
    the jaxpr (dot_general/conv/elementwise...), with scan bodies multiplied
    by their trip count — the structural analog of the reference's
    per-module MACs tree for functional models;
  * latency is measured by timing the compiled function (block_until_ready).

`get_model_profile(fn, args)` mirrors the reference's
`get_model_profile(model, input_res)` entry point.
"""

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

from ...utils.logging import logger


# --------------------------------------------------------------------------
# human-readable units (reference profiler.py flops_to_string etc.)
# --------------------------------------------------------------------------


def number_to_string(num, units=None, precision=2):
    if units is None:
        if num >= 1e12:
            return f"{num / 1e12:.{precision}f} T"
        if num >= 1e9:
            return f"{num / 1e9:.{precision}f} G"
        if num >= 1e6:
            return f"{num / 1e6:.{precision}f} M"
        if num >= 1e3:
            return f"{num / 1e3:.{precision}f} K"
        return f"{num:.{precision}f} "
    scale = {"T": 1e12, "G": 1e9, "M": 1e6, "K": 1e3, "": 1.0}[units]
    return f"{num / scale:.{precision}f} {units}"


def flops_to_string(flops, units=None, precision=2):
    return number_to_string(flops, units, precision) + "FLOPS"


def macs_to_string(macs, units=None, precision=2):
    return number_to_string(macs, units, precision) + "MACs"


def params_to_string(params, units=None, precision=2):
    return number_to_string(params, units, precision).rstrip()


def duration_to_string(duration, units=None, precision=2):
    if duration >= 1:
        return f"{duration:.{precision}f} s"
    if duration >= 1e-3:
        return f"{duration * 1e3:.{precision}f} ms"
    return f"{duration * 1e6:.{precision}f} us"


# --------------------------------------------------------------------------
# jaxpr flop walk (per-primitive breakdown)
# --------------------------------------------------------------------------

_ELEMENTWISE_1 = {
    "add", "sub", "mul", "div", "max", "min", "neg", "abs", "sign",
    "floor", "ceil", "round", "and", "or", "xor", "not", "select_n",
    "ge", "gt", "le", "lt", "eq", "ne", "convert_element_type",
}
_TRANSCENDENTAL = {
    "exp", "log", "tanh", "sin", "cos", "logistic", "erf", "rsqrt",
    "sqrt", "pow", "integer_pow", "erf_inv", "cbrt", "atan2", "expm1",
    "log1p",
}


def _out_size(eqn) -> int:
    return int(sum(int(np.prod(v.aval.shape)) for v in eqn.outvars
                   if hasattr(v.aval, "shape")))


def _dot_flops(eqn) -> int:
    a, b = eqn.invars[0].aval, eqn.invars[1].aval
    dims = eqn.params["dimension_numbers"]
    (lc, rc), (lb, rb) = dims
    contract = int(np.prod([a.shape[i] for i in lc])) or 1
    batch = int(np.prod([a.shape[i] for i in lb])) or 1
    m = int(np.prod([a.shape[i] for i in range(a.ndim)
                     if i not in lc and i not in lb])) or 1
    n = int(np.prod([b.shape[i] for i in range(b.ndim)
                     if i not in rc and i not in rb])) or 1
    return 2 * batch * m * n * contract


def _conv_flops(eqn) -> int:
    rhs = eqn.invars[1].aval
    out = eqn.outvars[0].aval
    dn = eqn.params["dimension_numbers"]
    out_feature_dim = dn.rhs_spec[0]
    per_output = int(np.prod(rhs.shape)) // max(int(rhs.shape[out_feature_dim]), 1)
    return 2 * int(np.prod(out.shape)) * per_output


def flops_of_jaxpr(jaxpr, counts: Optional[Dict[str, int]] = None,
                   multiplier: int = 1) -> Dict[str, int]:
    """Walk a (closed) jaxpr accumulating estimated flops per primitive."""
    if counts is None:
        counts = {}
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in inner.eqns:
        name = eqn.primitive.name
        sub = None
        mult = multiplier
        if name == "scan":
            sub = eqn.params["jaxpr"]
            mult = multiplier * int(eqn.params["length"])
        elif name in ("pjit", "closed_call", "custom_jvp_call",
                      "custom_vjp_call", "custom_vjp_call_jaxpr", "remat",
                      "checkpoint", "while", "cond"):
            sub = (eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
                   or eqn.params.get("body_jaxpr"))
            if sub is None and "branches" in eqn.params:
                for br in eqn.params["branches"]:
                    flops_of_jaxpr(br, counts, mult)
                continue
        if sub is not None:
            flops_of_jaxpr(sub, counts, mult)
            continue
        if name == "dot_general":
            f = _dot_flops(eqn)
        elif name == "conv_general_dilated":
            f = _conv_flops(eqn)
        elif name in _TRANSCENDENTAL:
            f = _out_size(eqn) * 10  # transcendental cost factor
        elif name in _ELEMENTWISE_1:
            f = _out_size(eqn)
        elif name.startswith("reduce_"):
            f = int(sum(int(np.prod(v.aval.shape)) for v in eqn.invars
                        if hasattr(v.aval, "shape")))
        else:
            continue
        counts[name] = counts.get(name, 0) + f * mult
    return counts


# --------------------------------------------------------------------------


class FlopsProfiler:
    """Profile a jittable function (reference FlopsProfiler :11).

    Usage::

        prof = FlopsProfiler(fn)
        prof.start_profile(*example_args)
        prof.get_total_flops(); prof.get_total_duration()
        prof.print_model_profile()
        prof.end_profile()
    """

    def __init__(self, model: Callable = None, config=None):
        self.model = model
        self.config = config
        self._started = False
        self._flops = 0
        self._macs = 0
        self._params = 0
        self._duration = 0.0
        self._bytes = 0.0
        self._per_primitive: Dict[str, int] = {}

    def start_profile(self, *args, params_tree=None, **kwargs):
        """Compile + run the model on args, collecting cost analysis."""
        fn = self.model
        jitted = jax.jit(fn)
        lowered = jitted.lower(*args, **kwargs)
        compiled = lowered.compile()
        # cost_analysis() is None on backends without a cost model, a
        # list of per-computation dicts on some jaxlibs, and a partial
        # dict elsewhere — the monitor's extractor is the one place
        # that mess is normalized
        from ...monitor.perf import extract_cost_analysis

        ca = extract_cost_analysis(compiled)
        self._flops = int(ca["flops"])
        self._bytes = float(ca["bytes_accessed"])
        self._per_primitive = flops_of_jaxpr(jax.make_jaxpr(fn)(*args, **kwargs))
        if self._flops == 0:  # backend without a cost model
            self._flops = sum(self._per_primitive.values())
        self._macs = self._per_primitive.get("dot_general", 0) // 2
        if params_tree is None and args:
            params_tree = args[0]
        self._params = int(sum(np.prod(x.shape) for x in
                               jax.tree.leaves(params_tree)
                               if hasattr(x, "shape")))
        # timed execution (compiled; excludes compile time)
        t0 = time.perf_counter()
        out = compiled(*args, **kwargs)
        jax.block_until_ready(out)
        self._duration = time.perf_counter() - t0
        self._started = True
        return self

    def stop_profile(self):
        return self

    def get_total_flops(self, as_string=False):
        return flops_to_string(self._flops) if as_string else self._flops

    def get_total_macs(self, as_string=False):
        return macs_to_string(self._macs) if as_string else self._macs

    def get_total_params(self, as_string=False):
        return params_to_string(self._params) if as_string else self._params

    def get_total_duration(self, as_string=False):
        return duration_to_string(self._duration) if as_string else self._duration

    def get_total_bytes(self):
        return self._bytes

    def print_model_profile(self, profile_step=None, top_modules=3):
        """Log the summary + top primitives by flops (the reference's
        per-module tree, per-primitive here)."""
        hdr = "-------------------------- DeepSpeed Flops Profiler --------------------------"
        lines = [hdr]
        if profile_step is not None:
            lines.append(f"profile step:                   {profile_step}")
        lines += [
            f"params:                         {self.get_total_params(True)}",
            f"fwd flops (cost model):         {self.get_total_flops(True)}",
            f"fwd MACs:                       {self.get_total_macs(True)}",
            f"bytes accessed:                 {number_to_string(self._bytes)}B",
            f"fwd latency:                    {self.get_total_duration(True)}",
            f"fwd FLOPS/s:                    "
            f"{flops_to_string(self._flops / self._duration if self._duration else 0)}",
        ]
        top = sorted(self._per_primitive.items(), key=lambda kv: -kv[1])
        lines.append(f"top {top_modules} primitives by flops:")
        for name, f in top[:top_modules]:
            lines.append(f"    {name:<26} {flops_to_string(f)}")
        lines.append("-" * len(hdr))
        msg = "\n".join(lines)
        logger.info(msg)
        return msg

    def end_profile(self):
        self._started = False


def get_model_profile(model: Callable, args=(), kwargs=None,
                      print_profile=True, detailed=True, as_string=True,
                      warm_up=1, ignore_modules=None):
    """Reference get_model_profile (profiler.py:781): returns
    (flops, macs, params) for one forward of ``model(*args)``."""
    kwargs = kwargs or {}
    prof = FlopsProfiler(model)
    jitted = jax.jit(model)
    for _ in range(max(warm_up - 1, 0)):
        jax.block_until_ready(jitted(*args, **kwargs))
    prof.start_profile(*args, **kwargs)
    if print_profile:
        prof.print_model_profile()
    out = (
        prof.get_total_flops(as_string),
        prof.get_total_macs(as_string),
        prof.get_total_params(as_string),
    )
    prof.end_profile()
    return out
