"""Measured confirmation of the cost model's ranking.

The static model earns trust by being checked, not believed: the top-K
(plus, in the bench, a mid-ranked and a worst-ranked candidate so the
spread is real) are re-run through the SAME engine path mesh_bench
times — full ``train_batch`` steps on the synthetic token stream,
median wall time over the post-warmup steps — and the predicted order
is compared to the measured order with Spearman's rank correlation.

On the single-core 8-virtual-device host the absolute milliseconds
price compile + dispatch, not interconnect (mesh_bench's caveat applies
verbatim); the claim under test is only *monotonicity*: a config the
model calls faster should measure faster.
"""

import time
from typing import Dict, List, Optional, Sequence

from .costmodel import CandidatePrice, build_candidate_engine
from .space import LayoutCandidate, ModelSpec, resolve_block

__all__ = ["confirm_candidates", "rank_correlation", "select_spread",
           "spearman"]


def select_spread(
    ranked: Sequence[CandidatePrice],
    k: int = 4,
    resolution_s: float = 5e-4,
) -> List[CandidatePrice]:
    """Pick up to ``k`` candidates with pairwise-distinct predicted
    costs (fastest first), always keeping the predicted-best and the
    predicted-worst. Near-ties are skipped on purpose: a rank check
    over candidates the model itself calls equal would measure
    scheduler noise, not the model — Spearman needs a real spread to
    say anything."""
    sel: List[CandidatePrice] = []
    last = None
    for p in ranked:
        if last is None or p.predicted_step_s - last >= resolution_s:
            sel.append(p)
            last = p.predicted_step_s
        if len(sel) >= k:
            break
    if ranked and ranked[-1].name not in {p.name for p in sel}:
        sel.append(ranked[-1])
    return sel


def _ranks(xs: Sequence[float]) -> List[float]:
    """Average ranks (ties share their mean rank)."""
    order = sorted(range(len(xs)), key=lambda i: xs[i])
    ranks = [0.0] * len(xs)
    i = 0
    while i < len(order):
        j = i
        while j + 1 < len(order) and xs[order[j + 1]] == xs[order[i]]:
            j += 1
        mean = (i + j) / 2.0 + 1.0
        for k in range(i, j + 1):
            ranks[order[k]] = mean
        i = j + 1
    return ranks


def spearman(xs: Sequence[float], ys: Sequence[float]) -> float:
    """Spearman rank correlation (Pearson over average ranks)."""
    if len(xs) != len(ys) or len(xs) < 2:
        return 0.0
    rx, ry = _ranks(xs), _ranks(ys)
    n = len(xs)
    mx = sum(rx) / n
    my = sum(ry) / n
    cov = sum((a - mx) * (b - my) for a, b in zip(rx, ry))
    vx = sum((a - mx) ** 2 for a in rx)
    vy = sum((b - my) ** 2 for b in ry)
    if vx == 0 or vy == 0:
        return 0.0
    return cov / (vx * vy) ** 0.5


def _layout_from_price(price: CandidatePrice, world: int) -> LayoutCandidate:
    extents = resolve_block(price.detail["mesh"], world)
    return LayoutCandidate(
        name=price.name, axes=tuple(extents.items()),
        zero_stage=int(price.detail.get("zero_stage", 1)))


def _token_stream(model: ModelSpec, rows: int, steps: int, seed: int = 0):
    import numpy as np

    rs = np.random.RandomState(seed)
    base = rs.randint(0, model.vocab,
                      size=(rows * steps, model.seq + 1)).astype(np.int32)
    base[:, 1::2] = base[:, :-1:2]  # learnable periodic structure
    return base


def confirm_candidates(
    prices: Sequence[CandidatePrice],
    model: ModelSpec,
    world: int,
    *,
    steps: int = 6,
    warmup: int = 2,
    micro: int = 2,
    gas: int = 1,
    seed: int = 0,
    log=None,
) -> List[Dict[str, object]]:
    """Short measured runs for each candidate; returns one entry per
    candidate with predicted and measured cost side by side."""
    import numpy as np

    out: List[Dict[str, object]] = []
    for price in prices:
        entry: Dict[str, object] = {
            "name": price.name,
            "predicted_step_s": round(price.predicted_step_s, 9),
        }
        try:
            layout = _layout_from_price(price, world)
            engine = build_candidate_engine(
                model, layout, world, micro=micro, gas=gas,
                comm_block=price.detail.get("comm"))
            rows = (engine.train_micro_batch_size_per_gpu() * gas
                    * engine.data_parallel_size)
            data = _token_stream(model, rows, steps + warmup, seed)
            times, losses = [], []
            for i in range(steps + warmup):
                batch = data[i * rows:(i + 1) * rows]
                t0 = time.perf_counter()
                loss = float(engine.train_batch(batch=batch))
                dt = time.perf_counter() - t0
                if i >= warmup:
                    times.append(dt)
                losses.append(loss)
            entry["step_ms"] = round(float(np.median(times)) * 1e3, 3)
            entry["final_loss"] = round(losses[-1], 6)
            del engine
        except Exception as e:  # noqa: BLE001 — a candidate that cannot
            # run is itself a finding; keep it visible, rank it last
            entry["error"] = f"{type(e).__name__}: {e}"
        if log is not None:
            log(f"confirm {entry['name']}: "
                f"{entry.get('step_ms', 'FAILED')} ms")
        out.append(entry)
    return out


def rank_correlation(
    confirmed: Sequence[Dict[str, object]],
) -> Optional[float]:
    """Spearman between predicted and measured cost over the entries
    that actually ran (None with fewer than 2)."""
    ran = [e for e in confirmed if "step_ms" in e]
    if len(ran) < 2:
        return None
    return spearman([float(e["predicted_step_s"]) for e in ran],
                    [float(e["step_ms"]) for e in ran])
