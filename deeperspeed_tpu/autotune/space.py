"""The admissible config space — enumerated through the runtime's own validators.

Every candidate this module yields has already passed the exact
validation the engine applies at load time: mesh layouts go through
:class:`deeperspeed_tpu.sharding.MeshConfig`, comm variants through
:class:`deeperspeed_tpu.runtime.comm.CommConfig`, kernel routes through
``ops.kernel_config.validate`` and serving buckets through
:class:`deeperspeed_tpu.serving.ServingConfig`. The tuner therefore
cannot propose a config the runtime would reject — and anything the
runtime would reject never shows up as a "pruned" candidate either;
it simply is not part of the space.

Admissibility here is *structural* (divisibility, validator rules).
Feasibility (does it fit in HBM?) is priced later by
:mod:`.costmodel`, which keeps infeasible candidates visible with a
stated reason instead of dropping them.
"""

import dataclasses
import hashlib
import json
from typing import Dict, List, Optional, Sequence, Tuple

from ..ops import kernel_config as _kernel_config
from ..runtime.comm.config import MODES as COMM_MODES
from ..runtime.comm.config import CommConfig
from ..serving.config import ServingConfig
from ..sharding.config import CANONICAL_AXES, resolve_extents

__all__ = [
    "CommCandidate",
    "LayoutCandidate",
    "ModelSpec",
    "ServingCandidate",
    "enumerate_comm_variants",
    "enumerate_kernel_routes",
    "enumerate_mesh_layouts",
    "enumerate_serving_buckets",
    "kv_pool_bytes",
    "resolve_block",
    "space_hash",
]


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """The handful of model facts admissibility + pricing need.

    Deliberately NOT a GPTConfig: the enumerator must stay importable
    without jax so ``space_hash`` and the analysis provenance check can
    run anywhere.
    """

    vocab: int = 256
    n_layer: int = 2
    n_head: int = 4
    d_model: int = 64
    seq: int = 32
    n_kv_head: int = 0  # 0 => n_head (classic MHA)
    dtype_bytes: int = 2  # bf16 activations / KV cache

    @property
    def kv_heads(self) -> int:
        return self.n_kv_head or self.n_head

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head

    def param_count(self) -> int:
        """Transformer parameter count (embeddings + blocks + head)."""
        d, v, ff = self.d_model, self.vocab, 4 * self.d_model
        kv_dim = self.kv_heads * self.head_dim
        per_layer = (
            d * (d + 2 * kv_dim)  # qkv projection (GQA-aware)
            + d * d               # attn output
            + 2 * d * ff          # mlp in/out
            + 4 * d               # two layernorms (scale + bias)
        )
        return v * d + self.n_layer * per_layer + d * v + 2 * d

    def param_bytes(self, dtype_bytes: Optional[int] = None) -> int:
        return self.param_count() * (dtype_bytes or self.dtype_bytes)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class LayoutCandidate:
    """One admissible (mesh layout, ZeRO stage) point.

    ``name`` follows mesh_bench's convention: the >1 axis extents joined
    in canonical order ("dp2_fsdp4"), with a ``_zero{stage}`` suffix for
    stages above 1 ("dp2_fsdp4_zero2").
    """

    name: str
    axes: Tuple[Tuple[str, int], ...]  # full canonical extents, resolved
    zero_stage: int = 1

    def block(self) -> Dict[str, int]:
        """The ``"mesh"`` config block (only >1 extents, like configs/)."""
        b = {a: n for a, n in self.axes if n > 1}
        return b or {"dp": 1}

    def extents(self) -> Dict[str, int]:
        return dict(self.axes)

    @property
    def dp_size(self) -> int:
        """Batch-sharded world: dp × fsdp extents."""
        e = self.extents()
        return e["dp"] * e["fsdp"]


@dataclasses.dataclass(frozen=True)
class CommCandidate:
    """One admissible comm variant; ``block`` of None means "no comm block"
    (the engine's plain fp32 psum path, no reducer)."""

    name: str
    block: Optional[Dict[str, object]] = None


@dataclasses.dataclass(frozen=True)
class ServingCandidate:
    """One admissible serving shape: the validated block plus the derived
    bucket set and KV-pool size the cost model prices."""

    name: str
    block: Dict[str, object]
    prefill_buckets: Tuple[int, ...]
    kv_pool_bytes: int


def resolve_block(block: Optional[dict], world: int) -> Dict[str, int]:
    """Resolve a ``"mesh"`` block to full canonical extents for ``world``.

    Delegates to :func:`deeperspeed_tpu.sharding.config.resolve_extents`:
    the block passes :meth:`MeshConfig.from_dict` (unknown keys, bad
    extents and multiple ``-1`` raise exactly as they would at config
    load) and the single ``-1`` is inferred exactly as
    ``parallel.topology.build_mesh`` would — without needing jax
    devices."""
    return resolve_extents(block, world)


def _layout_name(extents: Dict[str, int], zero_stage: int) -> str:
    parts = [f"{a}{n}" for a, n in extents.items() if n > 1]
    name = "_".join(parts) or "dp1"
    if zero_stage > 1:
        name += f"_zero{zero_stage}"
    return name


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def enumerate_mesh_layouts(
    world: int,
    model: Optional[ModelSpec] = None,
    *,
    max_tp: Optional[int] = None,
    max_sp: Optional[int] = None,
    zero_stages: Sequence[int] = (1, 2, 3),
) -> List[LayoutCandidate]:
    """All structurally admissible (layout, ZeRO stage) candidates.

    A factorization ``dp × fsdp × tp × sp == world`` is admissible when

      * the resulting block passes :class:`MeshConfig` validation;
      * ``tp`` divides both ``model.n_head`` and ``model.d_model`` (the
        megatron column/row splits need whole heads and even rows);
      * ``sp`` divides ``model.seq`` (ring/Ulysses shard the sequence).

    ZeRO stages: a layout with ``fsdp == 1`` has nothing to shard the
    optimizer over, so only stage 1 is admitted; ``fsdp > 1`` admits every
    requested stage. Candidates come back in a deterministic order —
    fewest parallel axes first, then by name — so ``max_candidates``-style
    truncation upstream is reproducible.
    """
    if world < 1:
        raise ValueError(f"world must be >= 1, got {world}")
    model = model or ModelSpec()
    out: List[LayoutCandidate] = []
    seen = set()
    for dp in _divisors(world):
        for fsdp in _divisors(world // dp):
            rem = world // (dp * fsdp)
            for tp in _divisors(rem):
                sp = rem // tp
                if max_tp is not None and tp > max_tp:
                    continue
                if max_sp is not None and sp > max_sp:
                    continue
                if tp > 1 and (model.n_head % tp or model.d_model % tp):
                    continue
                if sp > 1 and model.seq % sp:
                    continue
                block = {a: n for a, n in
                         zip(CANONICAL_AXES, (dp, fsdp, tp, sp)) if n > 1}
                # the validator is the source of truth for admissibility
                extents = resolve_block(block, world)
                key = tuple(extents.items())
                if key in seen:
                    continue
                seen.add(key)
                stages = tuple(zero_stages) if fsdp > 1 else (1,)
                for stage in stages:
                    out.append(LayoutCandidate(
                        name=_layout_name(extents, stage),
                        axes=tuple(extents.items()),
                        zero_stage=int(stage)))
    out.sort(key=lambda c: (sum(1 for _, n in c.axes if n > 1),
                            c.zero_stage, c.name))
    return out


def enumerate_comm_variants(
    *,
    modes: Sequence[str] = ("fp32", "bf16", "int8", "lossless"),
    bucket_mbs: Sequence[float] = (0.05, 1.0, 25.0),
    overlaps: Sequence[str] = ("off",),
    include_none: bool = True,
) -> List[CommCandidate]:
    """Admissible ``"comm"`` blocks (each validated via CommConfig) plus,
    optionally, the no-comm-block baseline."""
    for m in modes:
        if m not in COMM_MODES:
            raise ValueError(f"unknown comm mode {m!r}; valid: {COMM_MODES}")
    out: List[CommCandidate] = []
    if include_none:
        out.append(CommCandidate(name="psum_fp32", block=None))
    for mode in modes:
        for mb in bucket_mbs:
            for ov in overlaps:
                block = {"mode": mode, "bucket_mb": float(mb), "overlap": ov}
                CommConfig.from_dict(block)  # raises on anything bogus
                name = f"{mode}_b{mb:g}mb" + ("" if ov == "off" else f"_{ov}")
                out.append(CommCandidate(name=name, block=block))
    return out


def enumerate_kernel_routes(
    routes: Sequence[str] = ("off", "fused", "auto"),
) -> List[Dict[str, object]]:
    """Admissible ``"kernels"`` blocks, validated through ops.kernel_config."""
    return [_kernel_config.validate({"mode": r}) for r in routes]


def kv_pool_bytes(model: ModelSpec, block_size: int, num_blocks: int) -> int:
    """Bytes for the paged KV pool (delegates to
    :meth:`ServingConfig.kv_pool_bytes` so serving/ owns the formula)."""
    sc = ServingConfig(block_size=block_size, num_blocks=num_blocks)
    return sc.kv_pool_bytes(model.n_layer, model.kv_heads, model.head_dim,
                            model.dtype_bytes)


def enumerate_serving_buckets(
    model: ModelSpec,
    *,
    num_slots: int = 8,
    max_seq_len: Optional[int] = None,
    block_sizes: Sequence[int] = (16, 32),
    pool_doublings: int = 4,
    draft_ks: Sequence[int] = (0,),
    drafter_layers: Optional[int] = None,
) -> List[ServingCandidate]:
    """Serving shape candidates over (block_size, num_blocks, draft_k).

    For each block size the pool is doubled from the minimum that can
    hold every decode slot at ``max_seq_len`` up through
    ``pool_doublings`` steps — deliberately overshooting so the HBM
    frontier is explored and the cost model always has an infeasible
    candidate to *report* (never to silently drop) on any platform.

    ``draft_ks`` adds speculative-decoding variants: ``0`` is the plain
    candidate, ``k > 0`` emits a ``_spec{k}`` variant whose block
    carries a ``"speculative"`` sub-block (truncated drafter of
    ``drafter_layers`` layers, defaulting to the engine's quarter-depth
    rule). A spec variant's ``kv_pool_bytes`` includes the drafter's
    own paged pool, so the HBM gate prices the pair, not just the
    target.
    """
    max_seq_len = max_seq_len or max(model.seq, 64)
    d_layers = (int(drafter_layers) if drafter_layers is not None
                else max(1, model.n_layer // 4))
    out: List[ServingCandidate] = []
    for bs in block_sizes:
        if max_seq_len % bs:
            continue
        min_blocks = num_slots * (max_seq_len // bs) + 1  # +1: null block
        blocks = min_blocks
        for _ in range(pool_doublings + 1):
            for k in draft_ks:
                block = {
                    "num_slots": num_slots,
                    "block_size": bs,
                    "num_blocks": int(blocks),
                    "max_seq_len": max_seq_len,
                }
                name = f"bs{bs}_nb{int(blocks)}"
                layers = model.n_layer
                if k:
                    block["speculative"] = {
                        "draft_k": int(k),
                        "drafter": {"n_layer": d_layers},
                    }
                    name += f"_spec{int(k)}"
                    layers = model.n_layer + d_layers  # target + drafter
                sc = ServingConfig.from_dict(block)  # validator gates
                out.append(ServingCandidate(
                    name=name,
                    block=block,
                    prefill_buckets=tuple(sc.prefill_buckets),
                    kv_pool_bytes=sc.kv_pool_bytes(
                        layers, model.kv_heads, model.head_dim,
                        model.dtype_bytes),
                ))
            blocks *= 2
    return out


def space_hash(
    world: int,
    model: ModelSpec,
    layouts: Sequence[LayoutCandidate],
    comms: Sequence[CommCandidate],
    kernel_routes: Sequence[dict],
    servings: Sequence[ServingCandidate] = (),
) -> str:
    """Deterministic fingerprint of the searched space.

    Canonical-JSON sha256 over every candidate's identity — two runs
    that searched different spaces can never share a hash, and the same
    space always reproduces it (sorted keys, no floats from timing).
    """
    doc = {
        "world": int(world),
        "model": model.as_dict(),
        "mesh": [
            {"name": c.name, "axes": list(c.axes), "zero": c.zero_stage}
            for c in layouts
        ],
        "comm": [{"name": c.name, "block": c.block} for c in comms],
        "kernels": [dict(sorted(k.items())) for k in kernel_routes],
        "serving": [{"name": s.name, "block": s.block} for s in servings],
    }
    blob = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]
