"""Watchdog-safe AOT cost capture for candidate entry points.

The autotuner prices candidates by lowering + compiling them ahead of
time — ``fn.lower(*abstract_avals).compile()`` — which never executes
the function and never inserts into the jit's dispatch cache
(``fn._cache_size()`` stays put; only a real call populates it). That
property is what makes in-process tuning safe: the recompile watchdog
keys off the same cache counter, so a capture that grew it would fire
"recompile" alarms inside a healthy training loop.

Two further pollution channels exist beyond the cache, and this module
closes both:

  * the live :class:`~..monitor.perf.CompiledCostIndex` stamps a
    ``perf/compiled`` trace instant, refreshes Prometheus gauges, and
    overwrites the tracer's ``perf`` process-metadata table on every
    capture — dozens of speculative candidates would bury the real
    entry points. :func:`sandboxed_cost_index` builds an index with
    ``registry=None, emit=False``: same capture math, zero side
    effects on the live monitor/tracer.
  * a buggy capture path that *called* the candidate (even once) would
    silently grow its cache. :func:`aot_capture` asserts the cache
    counter is unchanged across the capture and raises if not — the
    regression test sweeps 10 candidates against a strict watchdog.
"""

from typing import Callable, Optional, Tuple

from ..monitor.perf import CompiledCostIndex, CostRecord, _cache_size

__all__ = ["aot_capture", "sandboxed_cost_index"]


def sandboxed_cost_index(peaks: Optional[dict] = None) -> CompiledCostIndex:
    """A CompiledCostIndex that cannot touch the live process.

    No metrics registry (no gauges), ``emit=False`` (no trace instants,
    no tracer-metadata stamping). Use one per search; throw it away."""
    return CompiledCostIndex(registry=None, peaks=peaks, emit=False)


def aot_capture(
    name: str,
    fn: Callable,
    args: Tuple = (),
    kwargs: Optional[dict] = None,
    *,
    index: Optional[CompiledCostIndex] = None,
) -> CostRecord:
    """Capture ``fn``'s compiled cost without executing it.

    Verifies the no-pollution contract: ``fn``'s jit cache size must be
    identical before and after (AOT lower/compile bypasses the dispatch
    cache entirely). A change means the capture path executed the
    candidate — exactly the bug that would trip a live recompile
    watchdog — so it raises instead of returning a tainted record.
    """
    idx = index if index is not None else sandboxed_cost_index()
    before = _cache_size(fn)
    rec = idx.observe(name, fn, args, kwargs)
    after = _cache_size(fn)
    if before is not None and after != before:
        raise RuntimeError(
            f"aot_capture({name!r}) grew the candidate's jit cache "
            f"({before} -> {after}): the capture executed the function "
            f"instead of AOT-lowering it; inside a training process this "
            f"would fire the recompile watchdog")
    return rec
