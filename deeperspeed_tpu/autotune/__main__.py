"""``python -m deeperspeed_tpu.autotune`` — search the knob space AOT.

Walkthrough (full detail in docs/tutorials/autotune.md):

.. code-block:: console

    $ python -m deeperspeed_tpu.autotune --devices 8
    space  : 40 layout, 7 comm, 3 kernel, 10 serving candidates (hash 1a2b…)
    pruned : bs16_nb4225: HBM: KV pool 1.031 GiB + params … exceeds 1.000 GiB (cpu)
    rank   : 1. dp2_fsdp4      predicted 4.1ms   … (table)
    confirm: dp2_fsdp4 13.9ms | dp8 14.2ms | …   spearman=1.0
    emitted: autotuned.json (mesh + zero + comm + kernels + serving + provenance)

Stages: enumerate (space.py, via the runtime's own validators) → price
(costmodel.py, AOT compiled cost + wire model + HBM fit; infeasible
candidates reported with reasons) → confirm top-K (confirm.py, real
``train_batch`` steps) → emit (winning blocks + a provenance record the
analysis gate can verify, see autotune/provenance.py).

The emitted config is round-tripped through ``runtime/config.py``
validation before it is written — the tuner refuses to emit anything
the engine would refuse to load.
"""

import argparse
import json
import os
import subprocess
import sys

REEXEC_FLAG = "DS_AUTOTUNE_REEXEC"


def _reexec_if_needed(devices: int):
    """Same virtual-device trick as mesh_bench: restart under
    ``--xla_force_host_platform_device_count`` when the host has fewer
    devices than the search targets."""
    import jax

    if len(jax.devices()) >= devices or os.environ.get(REEXEC_FLAG):
        return
    env = dict(os.environ)
    env[REEXEC_FLAG] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + f" --xla_force_host_platform_device_count={devices}"
                        ).strip()
    sys.exit(subprocess.call(
        [sys.executable, "-m", "deeperspeed_tpu.autotune"] + sys.argv[1:],
        env=env))


def _parse_args(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m deeperspeed_tpu.autotune",
        description="AOT cost-model config search: mesh layouts, comm "
                    "modes, kernel routes, serving buckets.")
    ap.add_argument("--devices", type=int, default=8,
                    help="mesh size to tune for (virtual devices are "
                         "forced on a smaller host)")
    ap.add_argument("--quick", action="store_true",
                    help="small space for CI smoke (<60s with "
                         "--no-confirm): dp/fsdp layouts, stage 1, "
                         "two comm variants")
    ap.add_argument("--top-k", type=int, default=4)
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--micro", type=int, default=2)
    ap.add_argument("--gas", type=int, default=1)
    ap.add_argument("--no-confirm", action="store_true",
                    help="rank only; skip the measured confirmation runs")
    ap.add_argument("--hbm-gb", type=float, default=None,
                    help="override per-device HBM capacity (GiB)")
    ap.add_argument("--max-candidates", type=int, default=0,
                    help="cap priced layout candidates (0 = no cap); "
                         "skipped candidates are reported, not dropped")
    ap.add_argument("--max-tp", type=int, default=None,
                    help="cap the tensor-parallel extent (big models: "
                         "each tp/sp candidate is a fresh AOT compile)")
    ap.add_argument("--max-sp", type=int, default=None,
                    help="cap the sequence-parallel extent")
    ap.add_argument("--comm-buckets", default=None,
                    help="comma-separated bucket_mb grid override, e.g. "
                         "'25' to price one bucket size per mode")
    ap.add_argument("--out", default=None,
                    help="write the winning config JSON here")
    ap.add_argument("--report", default=None,
                    help="write the full search report JSON here")
    ap.add_argument("--draft-ks", default="0,4",
                    help="comma list of speculative draft_k values to "
                         "explore (0 = plain decode); each k > 0 adds a "
                         "_spec{k} serving variant priced with its "
                         "drafter pool and weights")
    ap.add_argument("--spec-accept", type=float, default=0.7,
                    help="modeled per-token draft/target agreement used "
                         "to price speculative serving variants")
    # model facts (defaults = the tiny mesh_bench model: CPU-priceable)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--n-layer", type=int, default=2)
    ap.add_argument("--n-head", type=int, default=4)
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--seq", type=int, default=32)
    return ap.parse_args(argv)


def _enumerate_space(args, model, budget):
    from .space import (enumerate_comm_variants, enumerate_kernel_routes,
                        enumerate_mesh_layouts, enumerate_serving_buckets,
                        kv_pool_bytes, space_hash)

    if args.quick:
        layouts = enumerate_mesh_layouts(
            args.devices, model, max_tp=1, max_sp=1, zero_stages=(1,))
        comms = enumerate_comm_variants(
            modes=("fp32",), bucket_mbs=(25.0,), overlaps=("off",))
    else:
        layouts = enumerate_mesh_layouts(args.devices, model,
                                         max_tp=args.max_tp,
                                         max_sp=args.max_sp)
        if args.comm_buckets:
            comms = enumerate_comm_variants(bucket_mbs=tuple(
                float(x) for x in args.comm_buckets.split(",")))
        else:
            comms = enumerate_comm_variants()
    routes = enumerate_kernel_routes()
    # double the KV pool until it crosses the HBM budget: the serving
    # frontier is explored past feasibility on EVERY platform, so the
    # cost model always has an infeasible candidate to report
    max_seq = max(model.seq, 64)
    min_pool = kv_pool_bytes(model, 16, 8 * (max_seq // 16) + 1)
    doublings = 1
    while (min_pool * (2 ** doublings) <= budget["hbm_bytes"]
           and doublings < 24):
        doublings += 1
    draft_ks = tuple(int(x) for x in
                     str(getattr(args, "draft_ks", "0")).split(",") if x)
    servings = enumerate_serving_buckets(model, pool_doublings=doublings,
                                         draft_ks=draft_ks or (0,))
    return {
        "layouts": layouts, "comms": comms, "routes": routes,
        "servings": servings,
        "hash": space_hash(args.devices, model, layouts, comms, routes,
                           servings),
    }


def _price_kernel_routes(routes, base_price, budget):
    """Kernel routes are priced analytically: off-TPU 'fused' forces
    interpret-mode Pallas launches (debug path, ~100x), 'auto' lowers to
    the same XLA program as 'off'; on TPU the fused routes are the
    measured winners (BENCH_kernels.json), modeled as a modest discount."""
    from .costmodel import CandidatePrice

    on_tpu = budget["source"] not in ("cpu",)
    out = []
    for blk in routes:
        mode = blk.get("mode", "off")
        if on_tpu:
            factor = {"off": 1.0, "auto": 0.9, "fused": 0.9}[mode]
        else:
            factor = {"off": 1.0, "auto": 1.0, "fused": 100.0}[mode]
        p = CandidatePrice(
            name=f"kernels_{mode}", kind="kernels",
            predicted_step_s=base_price * factor,
            components={"route_factor": factor},
            detail={"kernels": dict(blk)})
        if mode == "fused" and not on_tpu:
            p.feasible = False
            p.reason = ("kernel route 'fused' off-TPU runs Pallas in "
                        "interpret mode (debug path); use 'auto' so the "
                        "fused kernels engage only on TPU")
        out.append(p)
    return out


def run_search(args, log=print):
    """The whole pipeline; returns the report dict (json-ready)."""
    from ..runtime.config import TrainingConfig
    from .confirm import confirm_candidates, rank_correlation, select_spread
    from .costmodel import (platform_budget, price_comm_variants,
                            price_layout, price_serving, rank_candidates)
    from .provenance import make_provenance, verify_provenance
    from .space import ModelSpec
    from .capture import sandboxed_cost_index

    model = ModelSpec(vocab=args.vocab, n_layer=args.n_layer,
                      n_head=args.n_head, d_model=args.d_model,
                      seq=args.seq)
    budget = platform_budget(hbm_gb=args.hbm_gb)
    space = _enumerate_space(args, model, budget)
    layouts, comms = space["layouts"], space["comms"]
    log(f"space  : {len(layouts)} layout x {len(comms)} comm x "
        f"{len(space['routes'])} kernel x {len(space['servings'])} serving "
        f"candidates (hash {space['hash']}) on {budget['source']}")

    skipped = []
    if args.max_candidates and len(layouts) > args.max_candidates:
        skipped = [{"name": c.name, "reason":
                    f"skipped: --max-candidates {args.max_candidates} cap"}
                   for c in layouts[args.max_candidates:]]
        layouts = layouts[:args.max_candidates]
        log(f"cap    : pricing {len(layouts)} of "
            f"{len(layouts) + len(skipped)} layouts "
            f"({len(skipped)} skipped, reported below)")

    # stage A: AOT-price every layout (no comm block)
    index = sandboxed_cost_index()
    prices = []
    for lc in layouts:
        p, _ = price_layout(lc, model, args.devices, budget,
                            micro=args.micro, gas=args.gas, index=index)
        prices.append(p)
        log(f"price  : {p.name:<24} {p.predicted_step_s * 1e3:8.3f} ms"
            + ("" if p.feasible else f"  INFEASIBLE: {p.reason}"))
    ranked, pruned = rank_candidates(prices)
    if not ranked:
        raise SystemExit("autotune: no feasible layout candidate "
                         f"(pruned: {[p.reason for p in pruned]})")

    # stage B: comm variants on the winning layout
    best_layout = next(lc for lc in layouts if lc.name == ranked[0].name)
    comm_prices = price_comm_variants(
        best_layout, comms, model, args.devices, budget,
        micro=args.micro, gas=args.gas, index=index)
    comm_ranked, comm_pruned = rank_candidates(comm_prices)
    for p in comm_prices:
        log(f"comm   : {p.name:<32} {p.predicted_step_s * 1e3:8.3f} ms"
            + ("" if p.feasible else f"  INFEASIBLE: {p.reason}"))

    # stage C: kernel routes (analytic, see _price_kernel_routes)
    kernel_prices = _price_kernel_routes(
        space["routes"], comm_ranked[0].predicted_step_s, budget)
    kernel_ranked, kernel_pruned = rank_candidates(kernel_prices)

    # stage D: serving shape buckets (analytic pool/bucket model;
    # speculative variants priced at the modeled acceptance)
    serving_prices = [price_serving(s, model, budget,
                                    accept_rate=args.spec_accept)
                      for s in space["servings"]]
    serving_ranked, serving_pruned = rank_candidates(serving_prices)
    for p in serving_pruned:
        log(f"pruned : {p.name}: {p.reason}")

    all_pruned = pruned + comm_pruned + kernel_pruned + serving_pruned

    # confirm: measured runs over a top-K SPREAD of distinct predicted
    # tiers from the LAYOUT ranking (near-ties would only measure
    # scheduler noise, and comm variants are indistinguishable in
    # measured time on CPU where the collectives fuse into one program
    # — see scripts/autotune_bench.py); the predicted-worst rides along
    # so the correlation has range
    confirm_set = select_spread(ranked, k=max(1, args.top_k))
    confirmed, corr = [], None
    if not args.no_confirm:
        confirmed = confirm_candidates(
            confirm_set, model, args.devices, steps=args.steps,
            warmup=args.warmup, micro=args.micro, gas=args.gas, log=log)
        corr = rank_correlation(confirmed)
        log(f"confirm: spearman(predicted, measured) = {corr}")

    # emit: winning blocks + provenance, round-tripped through the
    # runtime's validation before anything is written
    winner = comm_ranked[0]
    best_serving = serving_ranked[0] if serving_ranked else None
    from .costmodel import effective_micro
    micro_eff = effective_micro(best_layout, args.devices, args.micro)
    config = {
        "train_micro_batch_size_per_gpu": micro_eff,
        "gradient_accumulation_steps": args.gas,
        "train_batch_size": micro_eff * args.gas * best_layout.dp_size,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "mesh": winner.detail["mesh"],
        "zero_optimization": {"stage": winner.detail["zero_stage"]},
        "kernels": kernel_ranked[0].detail["kernels"],
        "steps_per_print": 10 ** 9,
    }
    if winner.detail.get("comm"):
        config["comm"] = winner.detail["comm"]
    if best_serving is not None:
        config["serving"] = dict(best_serving.detail["serving"],
                                 enabled=False)
    measured = next((e.get("step_ms") for e in confirmed
                     if e["name"] in (winner.name, ranked[0].name)), None)
    config["provenance"] = make_provenance(
        config, space_hash=space["hash"], platform=budget["source"],
        devices=args.devices, predicted_step_s=winner.predicted_step_s,
        measured_step_ms=measured, rank_correlation=corr)

    before = json.dumps(config, sort_keys=True)
    TrainingConfig(config, world_size=best_layout.dp_size)  # must load
    after = json.dumps(config, sort_keys=True)
    if before != after:
        raise SystemExit("autotune: emitted config was mutated by "
                         "runtime validation — refusing to emit")
    ok, why = verify_provenance(config)
    if not ok:
        raise SystemExit(f"autotune: self-check failed: {why}")

    report = {
        "world": args.devices,
        "platform": budget["source"],
        "model": model.as_dict(),
        "space_hash": space["hash"],
        "space_sizes": {
            "layouts": len(layouts) + len(skipped), "comms": len(comms),
            "kernel_routes": len(space["routes"]),
            "servings": len(space["servings"]),
        },
        "ranking": [p.as_dict() for p in ranked],
        "comm_ranking": [p.as_dict() for p in comm_ranked],
        "kernel_ranking": [p.as_dict() for p in kernel_ranked],
        "serving_ranking": [p.as_dict() for p in serving_ranked],
        "pruned": [{"name": p.name, "kind": p.kind, "reason": p.reason}
                   for p in all_pruned] + skipped,
        "confirm": {
            "k": len(confirm_set),
            "entries": confirmed,
            "rank_correlation": corr,
        },
        "best": {
            "name": winner.name,
            "predicted_step_s": round(winner.predicted_step_s, 9),
            "measured_step_ms": measured,
            "config": config,
        },
    }
    return report


def main(argv=None):
    args = _parse_args(argv)
    _reexec_if_needed(args.devices)
    report = run_search(args)
    best = report["best"]
    print(f"best   : {best['name']} "
          f"(predicted {best['predicted_step_s'] * 1e3:.3f} ms, "
          f"measured {best['measured_step_ms']} ms)")
    print(f"pruned : {len(report['pruned'])} candidate(s) with stated "
          f"reasons")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(best["config"], f, indent=1, sort_keys=True)
        print(f"emitted: {args.out}")
    if args.report:
        with open(args.report, "w") as f:
            json.dump(report, f, indent=1)
        print(f"report : {args.report}")


if __name__ == "__main__":
    main()
