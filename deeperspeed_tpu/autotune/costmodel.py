"""Static candidate pricing: rank configs without running them.

Per candidate the model combines three sources, every term attributable
in the emitted ``components`` dict:

  * **AOT compiled cost** — the candidate's real fused train step is
    built through ``deepspeed.initialize`` + ``engine._train_batch_fn()``
    and AOT lowered/compiled against abstract avals by the sandboxed
    capture (:mod:`.capture`). XLA's cost model supplies per-device
    ``flops`` / ``bytes_accessed`` / ``peak_bytes`` (verified per-device
    on sharded programs: argument bytes come back divided by the mesh
    size). The roofline max of compute and memory floors is the base
    step time — same methodology as ``CompiledCostIndex.step_stats``.
    One correction rides on top: XLA prices ZeRO-sharded programs
    per-SHARD (8x fewer flops for identical math), so ZeRO >= 2
    candidates are clamped to their same-mesh stage-1 sibling's
    captured compute/memory — ZeRO shards storage, never the math —
    and pay an explicit param re-gather wire term instead.
  * **Modeled wire traffic** — :mod:`~..runtime.comm.wiremodel` prices
    the reducer's actual :class:`BucketPlan` (mode bits × padded
    elements × ring factor) plus two collective launches per bucket;
    the launch-overhead term is what sinks tiny-bucket configs. Model-
    parallel layouts additionally pay for their per-layer activation
    collectives (tp all-reduces, sp ring-attention permutes) — without
    that term the AOT flops alone would call ``sp8`` the cheapest
    layout on a host where it measures slowest.
  * **HBM fit** — per-device ``peak_bytes`` (and the serving KV pool)
    against the platform's capacity. Infeasible candidates keep their
    price and gain ``feasible=False`` + a human-readable ``reason`` —
    they are REPORTED, never silently dropped.

CPU caveat (also in docs/tutorials/autotune.md): on the 8-virtual-device
host the roofline peaks are nominal, so absolute predictions are
meaningless — only the *ordering* is claimed, and
``scripts/autotune_bench.py`` measures exactly that (Spearman).
"""

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from ..monitor.perf import platform_peaks
from ..runtime.comm import wiremodel
from ..runtime.comm.config import CommConfig
from .capture import aot_capture, sandboxed_cost_index
from .space import CommCandidate, LayoutCandidate, ModelSpec, ServingCandidate

__all__ = [
    "CandidatePrice",
    "platform_budget",
    "price_comm_variants",
    "price_layout",
    "price_serving",
    "rank_candidates",
]

# fixed per-collective dispatch overhead (seconds): the term a
# bucket_mb=0.05 config multiplies 40x. TPU launches cost microseconds;
# the single-core host pays python dispatch + thread fan-out per
# collective, which is why tiny buckets crater measured step time there.
LAUNCH_OVERHEAD_S = {"cpu": 1.5e-3, "tpu": 5e-6}


@dataclasses.dataclass
class CandidatePrice:
    """One priced candidate — kept whether or not it is feasible."""

    name: str
    kind: str  # "layout" | "comm" | "serving"
    feasible: bool = True
    reason: str = ""  # stated pruning reason when infeasible
    predicted_step_s: float = 0.0
    flops: float = 0.0            # per device, from the compiled cost model
    bytes_accessed: float = 0.0   # per device
    peak_hbm_bytes: float = 0.0   # per device
    wire_bytes: float = 0.0       # per device, modeled
    launches: float = 0.0
    components: Dict[str, float] = dataclasses.field(default_factory=dict)
    detail: Dict[str, object] = dataclasses.field(default_factory=dict)

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["predicted_step_s"] = round(self.predicted_step_s, 9)
        return d


def platform_budget(
    hbm_gb: Optional[float] = None,
    peaks: Optional[dict] = None,
) -> Dict[str, float]:
    """Roofline + capacity numbers for the current platform (env
    overrides via ``PALLAS_AXON_TPU_GEN`` exactly like the benches);
    ``hbm_gb`` overrides capacity — the tests use that to force the
    HBM frontier onto tiny models."""
    p = dict(peaks or platform_peaks())
    src = str(p.get("source", "cpu"))
    is_tpu = src not in ("cpu",) and not src.startswith("cpu")
    return {
        "source": src,
        "peak_flops": p["peak_tflops"] * 1e12,
        "peak_bw": p["peak_gbps"] * 1e9,
        "ici_bw": p.get("ici_gbps", 10.0) * 1e9,
        "hbm_bytes": (hbm_gb if hbm_gb is not None
                      else p.get("hbm_gib", 1.0)) * (1 << 30),
        "launch_overhead_s": LAUNCH_OVERHEAD_S["tpu" if is_tpu else "cpu"],
    }


def effective_micro(layout: LayoutCandidate, world: int, micro: int) -> int:
    """Per-device microbatch holding the GLOBAL token count constant
    across layouts: a tp8 mesh has dp_size 1, so its microbatch is 8x
    the dp8 microbatch — otherwise candidates would be priced on
    different workloads and the ranking would be meaningless."""
    return micro * (world // layout.dp_size)


def _train_config(model: ModelSpec, layout: LayoutCandidate, world: int,
                  micro: int, gas: int, comm_block: Optional[dict]) -> dict:
    micro = effective_micro(layout, world, micro)
    cfg = {
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "train_batch_size": micro * gas * layout.dp_size,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": layout.zero_stage},
        "mesh": layout.block(),
        "steps_per_print": 10 ** 9,
    }
    if comm_block is not None:
        cfg["comm"] = dict(comm_block)
    return cfg


def build_candidate_engine(model: ModelSpec, layout: LayoutCandidate,
                           world: int, *, micro: int = 2, gas: int = 1,
                           comm_block: Optional[dict] = None):
    """A real engine for ``layout`` — the same construction path
    mesh_bench uses, minus any ``monitor``/``resilience`` block so a
    speculative candidate can never install process-global state."""
    import jax
    import jax.numpy as jnp

    import deeperspeed_tpu as deepspeed
    from ..models.gpt import GPTConfig, make_gpt

    gcfg = GPTConfig(vocab_size=model.vocab, n_layer=model.n_layer,
                     n_head=model.n_head, n_kv_head=model.n_kv_head,
                     d_model=model.d_model, max_seq=model.seq,
                     remat=False, dtype=jnp.float32, attn_impl="xla",
                     rotary=True)
    init_fn, _, loss_fn, _ = make_gpt(gcfg)
    params = init_fn(jax.random.PRNGKey(0))
    engine, _, _, _ = deepspeed.initialize(
        model=loss_fn, model_parameters=params,
        config_params=_train_config(model, layout, world, micro, gas,
                                    comm_block))
    return engine


def _abstract_step_args(engine, model: ModelSpec):
    import jax
    import jax.numpy as jnp

    rows = (engine.train_micro_batch_size_per_gpu()
            * engine.gradient_accumulation_steps()
            * engine.data_parallel_size)
    batch = jax.ShapeDtypeStruct((rows, model.seq + 1), jnp.int32)
    import numpy as np
    lr = np.float32(1e-3)
    rng = (engine.rng, 0)
    if engine.comm is not None:
        return (engine.state, engine._comm_state, batch, lr, rng)
    return (engine.state, batch, lr, rng)


def price_layout(
    layout: LayoutCandidate,
    model: ModelSpec,
    world: int,
    budget: Dict[str, float],
    *,
    micro: int = 2,
    gas: int = 1,
    comm: Optional[CommCandidate] = None,
    index=None,
    keep_engine: bool = False,
):
    """Price one (layout[, comm]) candidate via AOT capture.

    Returns ``(CandidatePrice, engine_or_None)``. The engine comes back
    only with ``keep_engine=True`` (the confirm stage reuses it);
    otherwise it is dropped before returning so candidate sweeps hold
    one model's memory at a time.
    """
    comm_block = comm.block if comm is not None else None
    name = layout.name if comm is None else f"{layout.name}+{comm.name}"
    price = CandidatePrice(
        name=name, kind="layout" if comm is None else "comm",
        detail={"mesh": layout.block(), "zero_stage": layout.zero_stage,
                **({"comm": comm_block} if comm is not None else {})})
    engine = None
    try:
        engine = build_candidate_engine(model, layout, world, micro=micro,
                                        gas=gas, comm_block=comm_block)
    except Exception as e:  # noqa: BLE001 — report, never crash the sweep
        price.feasible = False
        price.reason = f"engine construction failed: {type(e).__name__}: {e}"
        return price, None

    idx = index if index is not None else sandboxed_cost_index()
    rec = aot_capture(name, engine._train_batch_fn(),
                      _abstract_step_args(engine, model), index=idx)
    if rec is None or rec.error is not None:
        price.feasible = False
        price.reason = (f"AOT capture failed: "
                        f"{rec.error if rec else 'no record'}")
        if not keep_engine:
            engine = None
        return price, engine

    price.flops = rec.flops
    price.bytes_accessed = rec.bytes_accessed
    price.peak_hbm_bytes = rec.peak_bytes

    # ZeRO >= 2 clamp: XLA's cost analysis prices ZeRO-sharded programs
    # per-SHARD — captured flops/bytes come back divided by the fsdp
    # extent (measured: fsdp8_zero3 reports 8x fewer flops than fsdp8
    # for identical math), which would rank ZeRO candidates as cheaper
    # COMPUTE, not just cheaper memory. ZeRO shards storage, never the
    # math: each device still runs the full forward/backward on its
    # rows. So clamp compute/memory to the same-mesh stage-1 sibling's
    # captured cost (cached in the index by mesh name — free when the
    # sibling is in the sweep, one extra AOT compile when not). The HBM
    # footprint is NOT clamped — sharded residency is the whole point.
    if layout.zero_stage >= 2:
        dense = dataclasses.replace(
            layout, name=layout.name.rsplit("_zero", 1)[0], zero_stage=1)
        ref = idx.get(dense.name)
        if ref is None or ref.error is not None:
            try:
                ref_engine = build_candidate_engine(
                    model, dense, world, micro=micro, gas=gas,
                    comm_block=comm_block)
                ref = aot_capture(dense.name, ref_engine._train_batch_fn(),
                                  _abstract_step_args(ref_engine, model),
                                  index=idx)
                del ref_engine
            except Exception:  # noqa: BLE001 — no ref, keep raw capture
                ref = None
        if ref is not None and ref.error is None:
            price.flops = max(price.flops, ref.flops)
            price.bytes_accessed = max(price.bytes_accessed,
                                       ref.bytes_accessed)
            price.detail["zero_dense_ref"] = dense.name

    # wire model: the reducer's real plan when a comm block rides along,
    # else one dense fp32 all-reduce of the whole gradient tree
    grad_elements = model.param_count()
    if engine.comm is not None:
        ccfg = CommConfig.from_dict(comm_block)
        wire = wiremodel.wire_summary(engine.comm.plan, ccfg,
                                      engine.comm.world, grad_elements)
    else:
        wire = wiremodel.wire_summary(None, None, layout.dp_size,
                                      grad_elements)
    price.wire_bytes = wire["wire_bytes_per_device"]
    price.launches = wire["collective_launches"]
    price.detail["wire"] = wire

    ext = layout.extents()

    # a 2D data mesh (dp x fsdp both > 1) reduces gradients in one
    # phase per sharded axis — same bytes on the wire, one extra
    # dispatch per collective (dp2_fsdp4 measures ~65% slower than dp8
    # on the launch-bound host while its captured cost is identical)
    n_data_axes = (1 if ext["dp"] > 1 else 0) + (1 if ext["fsdp"] > 1 else 0)
    if n_data_axes > 1:
        price.launches *= n_data_axes
        price.detail["data_axes"] = n_data_axes

    # ZeRO re-materialization traffic: stage 3 all-gathers the sharded
    # params for forward and again for backward; stage 2 broadcasts the
    # updated shard once per step. This is the comm ZeRO trades for its
    # memory savings — unpriced, ZeRO-3 looks like a free lunch.
    if layout.zero_stage >= 2 and ext["fsdp"] > 1:
        gathers = 2.0 if layout.zero_stage >= 3 else 1.0
        zb = (gathers * model.param_count() * 4
              * wiremodel.ring_factor(ext["fsdp"]))
        price.wire_bytes += zb
        price.launches += gathers
        price.detail["zero_gather"] = {"launches": gathers, "bytes": zb}

    # activation collectives on the model-parallel axes. The gradient
    # wire model above prices only the dp/fsdp reduction; tp inserts
    # per-layer activation all-reduces (2 fwd + 2 bwd, megatron) and sp
    # ring attention circulates KV blocks ((sp-1) permute steps fwd,
    # ~2x for backward), every layer, every step. On a launch-bound
    # host the DISPATCH COUNT of these is what buries sp8 — the AOT
    # flops alone would call it the cheapest layout while it measures
    # slowest (cf. BENCH_mesh.json step times).
    rows = effective_micro(layout, world, micro)
    act_bytes = 0.0
    act_launches = 0.0
    if ext["tp"] > 1:
        n = 4.0 * model.n_layer
        act_launches += n
        act_bytes += (n * rows * model.seq * model.d_model * 4
                      * 2 * wiremodel.ring_factor(ext["tp"]))
    if ext["sp"] > 1:
        n = 3.0 * (ext["sp"] - 1) * model.n_layer
        act_launches += n
        act_bytes += (n * rows * (model.seq / ext["sp"])
                      * 2 * model.kv_heads * model.head_dim * 4)
    price.launches += act_launches
    price.detail["act"] = {"launches": act_launches, "bytes": act_bytes}

    compute_s = price.flops / budget["peak_flops"]
    memory_s = price.bytes_accessed / budget["peak_bw"]
    wire_s = (price.wire_bytes + act_bytes) / budget["ici_bw"]
    launch_s = price.launches * budget["launch_overhead_s"]
    price.components = {
        "compute_s": compute_s, "memory_s": memory_s,
        "wire_s": wire_s, "launch_s": launch_s,
    }
    price.predicted_step_s = max(compute_s, memory_s) + wire_s + launch_s

    if rec.peak_bytes > budget["hbm_bytes"]:
        price.feasible = False
        price.reason = (
            f"HBM: per-device footprint {rec.peak_bytes / (1 << 30):.3f} "
            f"GiB exceeds {budget['hbm_bytes'] / (1 << 30):.3f} GiB "
            f"({budget['source']})")
    if not keep_engine:
        engine = None
    return price, engine


def price_comm_variants(
    layout: LayoutCandidate,
    comms: Sequence[CommCandidate],
    model: ModelSpec,
    world: int,
    budget: Dict[str, float],
    *,
    micro: int = 2,
    gas: int = 1,
    index=None,
) -> List[CandidatePrice]:
    """Price every comm variant on a fixed layout (engine per variant —
    the quantize/pack arithmetic lands in the AOT flops, the wire in
    the model)."""
    out = []
    for c in comms:
        p, _ = price_layout(layout, model, world, budget, micro=micro,
                            gas=gas, comm=c, index=index)
        out.append(p)
    return out


def price_serving(
    cand: ServingCandidate,
    model: ModelSpec,
    budget: Dict[str, float],
    *,
    dtype_bytes: int = 4,
    accept_rate: float = 0.7,
) -> CandidatePrice:
    """Price a serving shape analytically: the KV pool + resident params
    must fit; among the fits, prefer the largest pool (fewest preempted
    sequences) then the tighter bucket grid (less prefill padding).

    Speculative variants (``"speculative"`` in the block) add the
    drafter's resident weights to the HBM gate (the drafter pool is
    already inside ``cand.kv_pool_bytes``) and scale the decode-cost
    component by the modeled round speedup at ``accept_rate`` per-token
    draft/target agreement: a round of K+1 drafter steps (each
    ``n_drafter/n_layer`` of a target step) plus one verify emits
    ``1 + sum(p^i, i=1..K)`` tokens, so a weak drafter or an
    over-greedy K prices WORSE than plain decode instead of silently
    winning on pool size."""
    params = model.param_bytes(dtype_bytes)
    spec = (cand.block.get("speculative")
            if isinstance(cand.block, dict) else None) or None
    spec_speedup, drafter_params = 1.0, 0
    if spec:
        K = int(spec.get("draft_k", 4))
        n_d = int((spec.get("drafter") or {}).get(
            "n_layer", max(1, model.n_layer // 4)))
        ratio = n_d / float(model.n_layer)
        p = min(max(float(accept_rate), 0.0), 1.0)
        emitted = 1.0 + sum(p ** i for i in range(1, K + 1))
        round_cost = (K + 1) * ratio + 1.0   # in target-step units
        spec_speedup = emitted / round_cost
        # truncated drafter: its layers are resident copies of the
        # target's first n_d — layer params dominate, embeddings shared
        drafter_params = int(params * ratio)
    need = cand.kv_pool_bytes + params + drafter_params
    price = CandidatePrice(
        name=cand.name, kind="serving",
        peak_hbm_bytes=float(need),
        detail={"serving": dict(cand.block),
                "prefill_buckets": list(cand.prefill_buckets),
                "kv_pool_bytes": cand.kv_pool_bytes,
                "param_bytes": params,
                "drafter_param_bytes": drafter_params})
    # waste proxy: mean padded fraction if prompts land uniformly in
    # [1, max bucket] — a finer grid scores lower
    buckets = sorted(cand.prefill_buckets)
    prev, waste = 0, 0.0
    for b in buckets:
        waste += (b - (prev + b + 1) / 2.0) * (b - prev)
        prev = b
    span = buckets[-1] if buckets else 1
    waste_frac = waste / (span * span) if span else 0.0
    pool_tokens = (int(cand.block["num_blocks"])
                   * int(cand.block["block_size"]))
    price.components = {"waste_frac": round(waste_frac, 6),
                        "pool_tokens": float(pool_tokens),
                        "decode_cost": round(1.0 / spec_speedup, 6)}
    if spec:
        price.components["spec_speedup"] = round(spec_speedup, 6)
        price.components["spec_accept_rate_assumed"] = float(accept_rate)
    # smaller is better for the ranking key; feasible pools are ranked
    # by decode cost then padding waste, with a tiny tie-break rewarding
    # pool headroom. decode_cost is 1.0 for plain decode on every
    # candidate, so the pre-speculative ordering is preserved exactly.
    price.predicted_step_s = (1.0 / spec_speedup + waste_frac
                              + 1.0 / (1.0 + pool_tokens))
    if need > budget["hbm_bytes"]:
        price.feasible = False
        price.reason = (
            f"HBM: KV pool {cand.kv_pool_bytes / (1 << 30):.3f} GiB + "
            f"params {(params + drafter_params) / (1 << 30):.3f} GiB "
            f"exceeds {budget['hbm_bytes'] / (1 << 30):.3f} GiB "
            f"({budget['source']})")
    return price


def rank_candidates(
    prices: Sequence[CandidatePrice],
) -> Tuple[List[CandidatePrice], List[CandidatePrice]]:
    """Split into (ranked feasible, pruned) — pruned candidates all carry
    a non-empty ``reason`` and stay in every report."""
    feasible = sorted((p for p in prices if p.feasible),
                      key=lambda p: (p.predicted_step_s, p.name))
    pruned = [p for p in prices if not p.feasible]
    for p in pruned:
        assert p.reason, f"pruned candidate {p.name} has no stated reason"
    return feasible, pruned
