"""Provenance records: proof a config's knobs came out of the tuner.

A config the autotuner emits carries a ``"provenance"`` block:

.. code-block:: json

    {"provenance": {
        "tool": "deeperspeed_tpu.autotune",
        "space_hash": "…",          # fingerprint of the searched space
        "knob_hash": "…",           # fingerprint of the tuned knob blocks
        "git_rev": "…", "platform": "cpu", "devices": 8,
        "predicted_step_s": 0.0123, "measured_step_ms": 14.1,
        "rank_correlation": 1.0}}

``knob_hash`` is a canonical-JSON sha256 over exactly the blocks the
tuner chose (:data:`TUNED_KEYS`). Hand-editing any tuned knob after the
fact breaks the hash, and the analysis gate
(:func:`deeperspeed_tpu.analysis.provenance.check_config_provenance`)
turns that into an *error* finding — so a config cannot silently claim
"autotuned" while running hand-rolled knobs. Editing non-tuned keys
(batch sizes, optimizer, monitor…) does not disturb the hash; those are
the user's to own.

This module is deliberately jax-free so the linter can import it.
"""

import hashlib
import json
import subprocess
from typing import Dict, Optional, Tuple

__all__ = [
    "PROVENANCE_REQUIRED_KEYS",
    "TUNED_KEYS",
    "git_rev",
    "knob_fingerprint",
    "make_provenance",
    "verify_provenance",
]

# exactly the config blocks the tuner chooses; everything else in the
# config is user-owned and excluded from the fingerprint
TUNED_KEYS: Tuple[str, ...] = (
    "mesh", "zero_optimization", "comm", "kernels", "serving",
)

PROVENANCE_REQUIRED_KEYS: Tuple[str, ...] = (
    "tool", "space_hash", "knob_hash", "platform", "devices",
)

TOOL_NAME = "deeperspeed_tpu.autotune"


def knob_fingerprint(config: Dict[str, object]) -> str:
    """sha256 (hex, 16 chars) over the tuned knob blocks, canonical JSON."""
    knobs = {k: config[k] for k in TUNED_KEYS if k in config}
    blob = json.dumps(knobs, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def git_rev(default: str = "unknown") -> str:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10)
        rev = out.stdout.strip()
        return rev if out.returncode == 0 and rev else default
    except Exception:
        return default


def make_provenance(
    config: Dict[str, object],
    *,
    space_hash: str,
    platform: str,
    devices: int,
    predicted_step_s: Optional[float] = None,
    measured_step_ms: Optional[float] = None,
    rank_correlation: Optional[float] = None,
    rev: Optional[str] = None,
) -> Dict[str, object]:
    """The ``"provenance"`` block for ``config`` (knob hash computed here,
    so call this AFTER the tuned blocks are final)."""
    rec: Dict[str, object] = {
        "tool": TOOL_NAME,
        "space_hash": str(space_hash),
        "knob_hash": knob_fingerprint(config),
        "git_rev": rev if rev is not None else git_rev(),
        "platform": str(platform),
        "devices": int(devices),
    }
    if predicted_step_s is not None:
        rec["predicted_step_s"] = round(float(predicted_step_s), 9)
    if measured_step_ms is not None:
        rec["measured_step_ms"] = round(float(measured_step_ms), 6)
    if rank_correlation is not None:
        rec["rank_correlation"] = round(float(rank_correlation), 6)
    return rec


def verify_provenance(config: Dict[str, object]) -> Tuple[bool, str]:
    """Check a config's provenance claim. Returns ``(ok, detail)``.

    A config without a ``"provenance"`` key trivially verifies (nothing
    claimed). One WITH the key must be well-formed and its recorded
    ``knob_hash`` must match a fresh fingerprint of the tuned blocks —
    i.e. nobody hand-edited a tuned knob after the tuner signed it.
    """
    prov = config.get("provenance")
    if prov is None:
        return True, "no provenance claimed"
    if not isinstance(prov, dict):
        return False, f'"provenance" must be a dict, got {type(prov).__name__}'
    missing = [k for k in PROVENANCE_REQUIRED_KEYS if k not in prov]
    if missing:
        return False, f"provenance record missing keys {missing}"
    expect = knob_fingerprint(config)
    got = prov.get("knob_hash")
    if got != expect:
        return False, (
            f"knob_hash mismatch: provenance records {got!r} but the "
            f"config's tuned blocks {[k for k in TUNED_KEYS if k in config]} "
            f"hash to {expect!r} — a tuned knob was edited after the "
            f"autotuner signed this config (re-run the tuner or drop the "
            f'"provenance" block)')
    return True, "knob_hash verified"
