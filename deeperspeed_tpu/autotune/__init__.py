"""``autotune/`` — AOT cost-model config search over the repo's knobs.

Every performance knob the runtime grew across PRs 3–12 — mesh extents
(dp×fsdp×tp×sp), comm ``{mode, bucket_mb, overlap}``, kernel routes,
serving shape buckets — is enumerable and priceable without running a
single training step. This package composes the pieces that already
exist into a search:

  * :mod:`.space`     — the admissible config space, enumerated through
    the SAME validators the runtime uses (``MeshConfig`` /
    ``CommConfig`` / ``ServingConfig`` / ``kernel_config.validate``),
    so the tuner can never propose a config the engine would reject;
  * :mod:`.costmodel` — static ranking: AOT ``fn.lower`` compiled cost
    (flops + bytes_accessed via a sandboxed :class:`CompiledCostIndex`
    capture that never touches a live jit cache), modeled wire bytes
    from the GradReducer's bucket plans, and HBM fit against the
    platform peak table — infeasible candidates are pruned with a
    stated reason, never silently;
  * :mod:`.confirm`   — short measured runs through the real engine
    for the top-K, plus the Spearman rank correlation between the
    predicted and measured orders (the headline honesty metric);
  * :mod:`.provenance`— the knob fingerprint + ``"provenance"`` record
    emitted with a winning config, verifiable by the analysis gate
    (a hand-edited "autotuned" config fails ``scripts/check.sh``).

CLI: ``python -m deeperspeed_tpu.autotune --devices 8`` — see
``__main__.py`` and ``docs/tutorials/autotune.md``.
"""

from .capture import aot_capture, sandboxed_cost_index
from .confirm import (confirm_candidates, rank_correlation, select_spread,
                      spearman)
from .costmodel import (
    CandidatePrice,
    platform_budget,
    price_comm_variants,
    price_layout,
    price_serving,
    rank_candidates,
)
from .provenance import (
    PROVENANCE_REQUIRED_KEYS,
    TUNED_KEYS,
    knob_fingerprint,
    make_provenance,
    verify_provenance,
)
from .space import (
    CommCandidate,
    LayoutCandidate,
    ModelSpec,
    ServingCandidate,
    enumerate_comm_variants,
    enumerate_kernel_routes,
    enumerate_mesh_layouts,
    enumerate_serving_buckets,
    resolve_block,
    space_hash,
)

__all__ = [
    "CandidatePrice",
    "CommCandidate",
    "LayoutCandidate",
    "ModelSpec",
    "PROVENANCE_REQUIRED_KEYS",
    "ServingCandidate",
    "TUNED_KEYS",
    "aot_capture",
    "confirm_candidates",
    "enumerate_comm_variants",
    "enumerate_kernel_routes",
    "enumerate_mesh_layouts",
    "enumerate_serving_buckets",
    "knob_fingerprint",
    "make_provenance",
    "platform_budget",
    "price_comm_variants",
    "price_layout",
    "price_serving",
    "rank_candidates",
    "rank_correlation",
    "resolve_block",
    "sandboxed_cost_index",
    "select_spread",
    "spearman",
    "space_hash",
    "verify_provenance",
]
