"""Speculative decoding: draft-model proposal + single-pass target verify.

Beyond the reference (whose generation path recomputes the prefix per
token through ``PipelineEngine.inference_batch``): a small DRAFT model
proposes K tokens autoregressively, then the TARGET model scores all K+1
positions in ONE cached forward; matching tokens are accepted and the
target's own prediction at the first mismatch is emitted as the bonus
token. Greedy (temperature=0) acceptance makes the output BIT-IDENTICAL
to plain greedy decoding of the target model, for any draft — the draft
only changes how many target forwards are needed (1 per ~n_accepted+1
tokens instead of 1 per token).

Precision caveat (measured on the v5e chip): the guarantee holds exactly
when the verify pass's logits match per-token logits bitwise — true in
fp32; under bf16 the batched (K+1)-token matmuls reduce in a different
order than S=1 decode steps, so near-tie argmaxes can flip and sequences
may diverge at such positions (either branch is a legitimate greedy
decode; this is the usual batched-vs-incremental nondeterminism, not an
acceptance-logic error).

TPU-native shape discipline: everything is static — the outer loop is a
``lax.while_loop`` whose body always drafts exactly K tokens and verifies
K+1; accepted counts vary as DATA (masked writes into a preallocated
output buffer, offsets advance by the accepted length). Stale KV-cache
entries beyond the rolled-back offset need no cleanup: the attention mask
is offset-derived, so they are invisible until overwritten.

BATCHED decoding (B > 1): rows accept different draft lengths, so their
caches desynchronize — per-row offsets flow through ``apply_with_cache``
(vector-offset cache writes + per-row positional masks/rotary), per-row
output cursors advance independently, and finished rows keep looping as
masked no-ops until the slowest row reaches ``max_new_tokens``. Each
row's greedy output is bit-identical to its own B=1 decode (fp32).

``temperature > 0`` runs standard speculative SAMPLING (Leviathan et
al.): accept draft token d with probability min(1, p_t(d)/p_d(d)); on
rejection, sample the replacement from norm(max(p_t - p_d, 0)) with a
key independent of the rejected draw. Sampling keys fold per OUTPUT
POSITION (per row when B > 1), so a perfect draft reproduces plain
ancestral sampling of the target exactly. An explicit ``rng`` is
REQUIRED when sampling — a silent default key would return identical
"samples" on every call.

Compilation note: ``max_new_tokens``, ``temperature`` and ``top_k`` are
static jit arguments — every distinct sampling configuration compiles its
own program (the two-model loop re-specializes). Reuse configurations
rather than sweeping them per call.

Usage::

    gen = make_speculative_generator(target_cfg, draft_cfg, k_draft=4)
    out = gen(target_params, draft_params, prompt, max_new_tokens=64)
    out = gen(target_params, draft_params, prompt, max_new_tokens=64,
              temperature=0.9, top_k=40, rng=key)
"""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from .generation import apply_with_cache, init_cache, prep_sampling_logits
from .gpt import GPTConfig

# one transform for draft AND target (and make_generator): identical
# temperature/top-k filtering is what the acceptance ratio assumes
_prep_logits = prep_sampling_logits


def engine_sample_key(seed, count):
    """The serving engine's sampling-key contract: the key for a
    request's ``count``-th generated token is
    ``fold_in(fold_in(PRNGKey(0), seed), count)`` — a pure function of
    (seed, token index) with no global stream, so retries and replica
    moves replay token-identically. serving/engine.request_sample_key
    delegates here; ``make_matched_speculative_generator`` uses the same
    keys so its output matches plain engine decode token-for-token."""
    key = jax.random.fold_in(jax.random.PRNGKey(0), seed)
    return jax.random.fold_in(key, count)


def _pos_key(rng, pos):
    """Per-absolute-position sampling key: deterministic in the position,
    independent of HOW decoding reached it — this is what makes
    speculative sampling with draft == target reproduce plain ancestral
    sampling exactly (same key at the same position -> same draw)."""
    return jax.random.fold_in(rng, pos)


def _row_streams(rng, B: int):
    """(B,) key array: row r's stream. B == 1 keeps the stream EXACTLY as
    the unbatched convention (no row fold), preserving the documented
    draft==target == ancestral-sampling bit-parity; B > 1 folds the row
    index for independent per-row streams."""
    if B == 1:
        return rng[None]
    return jax.vmap(lambda r: jax.random.fold_in(rng, r))(
        jnp.arange(B, dtype=jnp.uint32))


def make_speculative_generator(target_cfg: GPTConfig, draft_cfg: GPTConfig,
                               k_draft: int = 4):
    """Build a jitted speculative generate(target_params, draft_params,
    prompt, max_new_tokens, temperature=0.0, top_k=None, rng=None)
    -> (B, S+max_new_tokens) tokens. temperature<=0 = greedy (bit-parity
    with plain greedy target decoding, per row); >0 = rejection sampling
    (explicit rng required)."""
    assert target_cfg.vocab_size == draft_cfg.vocab_size, (
        "target and draft must share a vocabulary")
    K = int(k_draft)
    assert K >= 1

    @partial(jax.jit,
             static_argnames=("max_new_tokens", "temperature", "top_k"))
    def generate(target_params, draft_params, prompt, max_new_tokens: int,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 rng=None):
        B, S = prompt.shape
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        # slack: the final block may draft past the requested length
        max_len = S + max_new_tokens + K + 1
        for cfg in (target_cfg, draft_cfg):
            if not cfg.rotary and max_len > cfg.max_seq:
                raise ValueError(
                    f"prompt ({S}) + max_new_tokens ({max_new_tokens}) + "
                    f"draft slack ({K + 1}) exceeds max_seq ({cfg.max_seq})")
        sampling = temperature > 0.0
        if sampling and rng is None:
            raise ValueError(
                "temperature > 0 requires an explicit rng: a default key "
                "would return the same 'samples' on every call")
        if rng is None:
            rng = jax.random.PRNGKey(0)
        # three independent streams: proposal/bonus draws, acceptance
        # uniforms, and rejection replacements. The replacement MUST NOT
        # reuse the proposal key: categorical with the same key replays
        # the same Gumbel vector, conditioning the replacement on the
        # rejected token and skewing it away from norm(max(p_t - p_d, 0)).
        rng_tok, rng_acc, rng_fix = jax.random.split(rng, 3)
        tok_s = _row_streams(rng_tok, B)
        acc_s = _row_streams(rng_acc, B)
        fix_s = _row_streams(rng_fix, B)
        rows_i = jnp.arange(B, dtype=jnp.int32)

        def draw(streams, pos, logits):
            """Per-row categorical with per-(row, position) keys.
            pos (B,); logits (B, V)."""
            return jax.vmap(
                lambda st, p, l: jax.random.categorical(
                    _pos_key(st, p), l, axis=-1)
            )(streams, pos, logits).astype(jnp.int32)

        t_cache = init_cache(target_cfg, B, max_len)
        d_cache = init_cache(draft_cfg, B, max_len)
        t_logits, t_cache = apply_with_cache(
            target_cfg, target_params, prompt, t_cache, 0)
        _, d_cache = apply_with_cache(
            draft_cfg, draft_params, prompt, d_cache, 0)
        if sampling:
            first = draw(tok_s, jnp.zeros((B,), jnp.int32),
                         _prep_logits(t_logits[:, -1], temperature, top_k))
        else:
            first = jnp.argmax(t_logits[:, -1], axis=-1).astype(jnp.int32)

        W = max_new_tokens + K + 1
        out = jnp.zeros((B, W), jnp.int32)
        out = out.at[:, 0].set(first)

        # invariant at loop top, PER ROW r: n[r] tokens emitted
        # (out[r, :n[r]]); last[r] is the newest emitted token, not yet in
        # either cache; both caches hold the S + n[r] - 1 tokens before it.
        def cond(carry):
            n = carry[1]
            return jnp.any(n < max_new_tokens)

        def body(carry):
            out, n, last, t_cache, d_cache = carry
            offsets = S + n - 1  # (B,) tokens in both caches, per row

            # --- draft phase: propose K tokens (and cache d_K too, so the
            # draft cache stays ahead even on full acceptance) ---
            def draft_step(carry, j):
                tok, cache = carry
                logits, cache = apply_with_cache(
                    draft_cfg, draft_params, tok[:, None], cache,
                    offsets + j)
                row = logits[:, -1]  # (B, V)
                if sampling:
                    # the PER-OUTPUT-POSITION key: a token proposed for
                    # output index n+j draws with the same key ancestral
                    # sampling would use there, so draft == target
                    # reproduces plain sampling exactly
                    nxt = draw(tok_s, n + j,
                               _prep_logits(row, temperature, top_k))
                else:
                    nxt = jnp.argmax(row, axis=-1).astype(jnp.int32)
                return (nxt, cache), (nxt, row)

            (_, d_cache), (drafts_all, d_rows) = jax.lax.scan(
                draft_step, (last, d_cache), jnp.arange(K + 1))
            drafts = drafts_all[:K].T  # (B, K) proposed tokens d_1..d_K
            d_rows = jnp.swapaxes(d_rows, 0, 1)  # (B, K+1, V)

            # --- verify: one target forward over [last, d_1..d_K] ---
            block = jnp.concatenate([last[:, None], drafts], axis=1)
            t_logits, t_cache = apply_with_cache(
                target_cfg, target_params, block, t_cache, offsets)

            idx = jnp.arange(K + 1, dtype=jnp.int32)
            if sampling:
                # Leviathan et al. rejection rule: accept d_{j+1} with
                # prob min(1, p_t/p_d); on first rejection sample the
                # replacement from norm(max(p_t - p_d, 0)). Padding p_d
                # with a zero row makes the full-acceptance bonus draw
                # come from p_t[K] through the same expression.
                p_t = jax.nn.softmax(
                    _prep_logits(t_logits, temperature, top_k), axis=-1)
                p_d = jax.nn.softmax(
                    _prep_logits(d_rows[:, :K], temperature, top_k), axis=-1)
                kk = jnp.arange(K)
                ratio = (
                    jnp.take_along_axis(
                        p_t[:, :K], drafts[:, :, None], axis=-1)[..., 0]
                    / (jnp.take_along_axis(
                        p_d, drafts[:, :, None], axis=-1)[..., 0] + 1e-20))
                u = jax.vmap(lambda st, nr: jax.vmap(
                    lambda j: jax.random.uniform(_pos_key(st, nr + j))
                )(kk))(acc_s, n)  # (B, K)
                accept = (u <= ratio).astype(jnp.int32)
                n_acc = jnp.sum(jnp.cumprod(accept, axis=1), axis=1)  # (B,)
                p_d_pad = jnp.concatenate(
                    [p_d, jnp.zeros_like(p_d[:, :1])], axis=1)
                p_t_at = p_t[rows_i, n_acc]          # (B, V)
                p_d_at = p_d_pad[rows_i, n_acc]
                resid = jnp.clip(p_t_at - p_d_at, 0.0)
                total = jnp.sum(resid, axis=-1, keepdims=True)
                q = jnp.where(total > 0, resid / jnp.maximum(total, 1e-20),
                              p_t_at)
                # full acceptance (n_acc == K): the bonus comes from p_t[K]
                # and must use the POSITIONAL token key so a perfect draft
                # reproduces ancestral sampling. A rejection replacement
                # needs a key INDEPENDENT of the rejected proposal's draw.
                bonus = jax.vmap(
                    lambda ts, fs, nr, na, qr: jax.random.categorical(
                        jnp.where(na == K, _pos_key(ts, nr + na),
                                  _pos_key(fs, nr + na)),
                        jnp.log(qr + 1e-20))
                )(tok_s, fix_s, n, n_acc, q).astype(jnp.int32)
            else:
                t_preds = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
                # t_preds[r, j] = target's token after consuming block[:j+1]
                matches = (drafts == t_preds[:, :K]).astype(jnp.int32)
                n_acc = jnp.sum(jnp.cumprod(matches, axis=1), axis=1)
                bonus = t_preds[rows_i, n_acc]

            # emitted this round, per row: accepted drafts then the
            # replacement / bonus at the first mismatch (or after full
            # acceptance); finished rows re-write their existing tokens
            drafts_pad = jnp.concatenate(
                [drafts, jnp.zeros((B, 1), jnp.int32)], axis=1)
            emitted = jnp.where(idx[None] < n_acc[:, None], drafts_pad,
                                bonus[:, None])
            done = n >= max_new_tokens
            cols = jnp.clip(n[:, None] + idx[None], 0, W - 1)
            cur = out[rows_i[:, None], cols]
            vals = jnp.where(done[:, None], cur, emitted)
            out = out.at[rows_i[:, None], cols].set(vals)
            n = jnp.where(done, n, n + n_acc + 1)
            last = jnp.where(done, last, bonus)
            return (out, n, last, t_cache, d_cache)

        n0 = jnp.ones((B,), jnp.int32)
        out, _, _, _, _ = jax.lax.while_loop(
            cond, body, (out, n0, first, t_cache, d_cache))
        return jnp.concatenate([prompt, out[:, :max_new_tokens]], axis=1)

    return generate


def make_matched_speculative_generator(target_cfg: GPTConfig,
                                       draft_cfg: GPTConfig,
                                       k_draft: int = 4):
    """Speculative decoding under the SERVING ENGINE's determinism
    contract (matched-key verification, the scheme serving/spec uses).

    Instead of the Leviathan rejection rule, draft and target both
    SAMPLE their next token with the same per-position key
    ``engine_sample_key(seed, output_index)`` over their own
    temperature/top-k-filtered logits; a draft token is accepted iff it
    equals the target's own draw at that position. The emitted stream —
    accepted drafts then the target's draw at the first disagreement —
    is therefore EXACTLY the token sequence plain per-token decode of
    the target would produce with the same (seed, index) keys, for any
    draft model and any temperature (greedy included: temperature<=0
    degenerates to argmax agreement). The draft only changes how many
    target forwards that stream costs, never its contents, which is
    what lets a fleet mix spec-on and spec-off replicas and retry
    failed-over requests token-identically.

    The price is a lower acceptance rate than rejection sampling at
    high temperature (the draft must hit the target's exact draw, not
    merely be plausible under p_t), so matched-key verification favors
    drafts distilled from — or truncated out of — the target.

    Returns generate(target_params, draft_params, prompt,
    max_new_tokens, temperature=0.0, top_k=None, seeds=None) ->
    (B, S+max_new_tokens). ``seeds`` is a (B,) int array of per-row
    engine seeds (e.g. serving/engine.derive_request_seed); defaults to
    zeros. temperature/top_k are static (one program per config)."""
    assert target_cfg.vocab_size == draft_cfg.vocab_size, (
        "target and draft must share a vocabulary")
    K = int(k_draft)
    assert K >= 1

    @partial(jax.jit,
             static_argnames=("max_new_tokens", "temperature", "top_k"))
    def generate(target_params, draft_params, prompt, max_new_tokens: int,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 seeds=None):
        B, S = prompt.shape
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        max_len = S + max_new_tokens + K + 1
        for cfg in (target_cfg, draft_cfg):
            if not cfg.rotary and max_len > cfg.max_seq:
                raise ValueError(
                    f"prompt ({S}) + max_new_tokens ({max_new_tokens}) + "
                    f"draft slack ({K + 1}) exceeds max_seq ({cfg.max_seq})")
        if seeds is None:
            seeds = jnp.zeros((B,), jnp.int32)
        seeds = jnp.asarray(seeds, jnp.int32)
        rows_i = jnp.arange(B, dtype=jnp.int32)
        sampling = temperature > 0.0

        def choose(logits, idx):
            """The engine's per-token selection: argmax when greedy,
            else categorical over filtered logits with the matched
            (seed, output-index) key. logits (B, V); idx (B,)."""
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            if not sampling:
                return greedy
            prepped = _prep_logits(logits, temperature, top_k)
            return jax.vmap(
                lambda sd, i, l: jax.random.categorical(
                    engine_sample_key(sd, i), l, axis=-1)
            )(seeds, idx, prepped).astype(jnp.int32)

        t_cache = init_cache(target_cfg, B, max_len)
        d_cache = init_cache(draft_cfg, B, max_len)
        t_logits, t_cache = apply_with_cache(
            target_cfg, target_params, prompt, t_cache, 0)
        _, d_cache = apply_with_cache(
            draft_cfg, draft_params, prompt, d_cache, 0)
        first = choose(t_logits[:, -1], jnp.zeros((B,), jnp.int32))

        W = max_new_tokens + K + 1
        out = jnp.zeros((B, W), jnp.int32)
        out = out.at[:, 0].set(first)
        idx = jnp.arange(K + 1, dtype=jnp.int32)

        def cond(carry):
            n = carry[1]
            return jnp.any(n < max_new_tokens)

        def body(carry):
            out, n, last, t_cache, d_cache = carry
            offsets = S + n - 1

            # draft K+1 proposals with the ENGINE's keys (the extra one
            # only keeps the draft cache ahead on full acceptance)
            def draft_step(carry, j):
                tok, cache = carry
                logits, cache = apply_with_cache(
                    draft_cfg, draft_params, tok[:, None], cache,
                    offsets + j)
                nxt = choose(logits[:, -1], n + j)
                return (nxt, cache), nxt

            (_, d_cache), drafts_all = jax.lax.scan(
                draft_step, (last, d_cache), jnp.arange(K + 1))
            drafts = drafts_all[:K].T  # (B, K)

            block = jnp.concatenate([last[:, None], drafts], axis=1)
            t_logits, t_cache = apply_with_cache(
                target_cfg, target_params, block, t_cache, offsets)
            # target's own draw at every position, same keys as plain
            # per-token decode would use
            choice = jnp.stack(
                [choose(t_logits[:, t], n + t) for t in range(K + 1)],
                axis=1)  # (B, K+1)
            matches = (drafts == choice[:, :K]).astype(jnp.int32)
            n_acc = jnp.sum(jnp.cumprod(matches, axis=1), axis=1)
            bonus = choice[rows_i, n_acc]

            drafts_pad = jnp.concatenate(
                [drafts, jnp.zeros((B, 1), jnp.int32)], axis=1)
            emitted = jnp.where(idx[None] < n_acc[:, None], drafts_pad,
                                bonus[:, None])
            done = n >= max_new_tokens
            cols = jnp.clip(n[:, None] + idx[None], 0, W - 1)
            cur = out[rows_i[:, None], cols]
            vals = jnp.where(done[:, None], cur, emitted)
            out = out.at[rows_i[:, None], cols].set(vals)
            n = jnp.where(done, n, n + n_acc + 1)
            last = jnp.where(done, last, bonus)
            return (out, n, last, t_cache, d_cache)

        n0 = jnp.ones((B,), jnp.int32)
        out, _, _, _, _ = jax.lax.while_loop(
            cond, body, (out, n0, first, t_cache, d_cache))
        return jnp.concatenate([prompt, out[:, :max_new_tokens]], axis=1)

    return generate
