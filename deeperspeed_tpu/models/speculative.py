"""Speculative decoding: draft-model proposal + single-pass target verify.

Beyond the reference (whose generation path recomputes the prefix per
token through ``PipelineEngine.inference_batch``): a small DRAFT model
proposes K tokens autoregressively, then the TARGET model scores all K+1
positions in ONE cached forward; matching tokens are accepted and the
target's own prediction at the first mismatch is emitted as the bonus
token. Greedy (temperature=0) acceptance makes the output BIT-IDENTICAL
to plain greedy decoding of the target model, for any draft — the draft
only changes how many target forwards are needed (1 per ~n_accepted+1
tokens instead of 1 per token).

Precision caveat (measured on the v5e chip): the guarantee holds exactly
when the verify pass's logits match per-token logits bitwise — true in
fp32; under bf16 the batched (K+1)-token matmuls reduce in a different
order than S=1 decode steps, so near-tie argmaxes can flip and sequences
may diverge at such positions (either branch is a legitimate greedy
decode; this is the usual batched-vs-incremental nondeterminism, not an
acceptance-logic error).

TPU-native shape discipline: everything is static — the outer loop is a
``lax.while_loop`` whose body always drafts exactly K tokens and verifies
K+1; accepted counts vary as DATA (masked writes into a preallocated
output buffer, offsets advance by the accepted length). Stale KV-cache
entries beyond the rolled-back offset need no cleanup: the attention mask
is offset-derived, so they are invisible until overwritten.

``temperature > 0`` runs standard speculative SAMPLING (Leviathan et
al.): accept draft token d with probability min(1, p_t(d)/p_d(d)); on
rejection, sample the replacement from norm(max(p_t - p_d, 0)) with a
key independent of the rejected draw. Sampling keys fold per OUTPUT
POSITION, so a perfect draft reproduces plain ancestral sampling of the
target exactly.

Usage::

    gen = make_speculative_generator(target_cfg, draft_cfg, k_draft=4)
    out = gen(target_params, draft_params, prompt, max_new_tokens=64)
    out = gen(target_params, draft_params, prompt, max_new_tokens=64,
              temperature=0.9, top_k=40, rng=key)

Batch size 1 (the speculative serving case; per-row accept counts would
need per-row cache offsets).
"""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from .generation import apply_with_cache, init_cache, prep_sampling_logits
from .gpt import GPTConfig

# one transform for draft AND target (and make_generator): identical
# temperature/top-k filtering is what the acceptance ratio assumes
_prep_logits = prep_sampling_logits


def _pos_key(rng, pos):
    """Per-absolute-position sampling key: deterministic in the position,
    independent of HOW decoding reached it — this is what makes
    speculative sampling with draft == target reproduce plain ancestral
    sampling exactly (same key at the same position -> same draw)."""
    return jax.random.fold_in(rng, pos)


def make_speculative_generator(target_cfg: GPTConfig, draft_cfg: GPTConfig,
                               k_draft: int = 4):
    """Build a jitted speculative generate(target_params, draft_params,
    prompt, max_new_tokens, temperature=0.0, top_k=None, rng=None)
    -> (B, S+max_new_tokens) tokens. temperature<=0 = greedy (bit-parity
    with plain greedy target decoding); >0 = rejection sampling."""
    assert target_cfg.vocab_size == draft_cfg.vocab_size, (
        "target and draft must share a vocabulary")
    K = int(k_draft)
    assert K >= 1

    @partial(jax.jit,
             static_argnames=("max_new_tokens", "temperature", "top_k"))
    def generate(target_params, draft_params, prompt, max_new_tokens: int,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 rng=None):
        B, S = prompt.shape
        if B != 1:
            raise ValueError(
                "speculative decoding supports batch size 1 (per-row accept "
                f"counts would need per-row cache offsets); got B={B}")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        # slack: the final block may draft past the requested length
        max_len = S + max_new_tokens + K + 1
        for cfg in (target_cfg, draft_cfg):
            if not cfg.rotary and max_len > cfg.max_seq:
                raise ValueError(
                    f"prompt ({S}) + max_new_tokens ({max_new_tokens}) + "
                    f"draft slack ({K + 1}) exceeds max_seq ({cfg.max_seq})")
        sampling = temperature > 0.0
        if rng is None:
            rng = jax.random.PRNGKey(0)
        # three independent streams: proposal/bonus draws, acceptance
        # uniforms, and rejection replacements. The replacement MUST NOT
        # reuse the proposal key: categorical with the same key replays
        # the same Gumbel vector, conditioning the replacement on the
        # rejected token and skewing it away from norm(max(p_t - p_d, 0)).
        rng_tok, rng_acc, rng_fix = jax.random.split(rng, 3)

        t_cache = init_cache(target_cfg, B, max_len)
        d_cache = init_cache(draft_cfg, B, max_len)
        t_logits, t_cache = apply_with_cache(
            target_cfg, target_params, prompt, t_cache, 0)
        _, d_cache = apply_with_cache(
            draft_cfg, draft_params, prompt, d_cache, 0)
        if sampling:
            first = jax.random.categorical(
                _pos_key(rng_tok, 0),
                _prep_logits(t_logits[:, -1], temperature, top_k),
                axis=-1).astype(jnp.int32)
        else:
            first = jnp.argmax(t_logits[:, -1], axis=-1).astype(jnp.int32)

        out = jnp.zeros((B, max_new_tokens + K + 1), jnp.int32)
        out = jax.lax.dynamic_update_slice(out, first[:, None], (0, 0))

        # invariant at loop top: `n` tokens emitted (out[:, :n]); `last` is
        # the newest emitted token, NOT yet in either cache; both caches
        # hold exactly the S + n - 1 tokens before it.
        def cond(carry):
            n = carry[1]
            return n < max_new_tokens

        def body(carry):
            out, n, last, t_cache, d_cache = carry
            offset = S + n - 1  # tokens in both caches

            # --- draft phase: propose K tokens (and cache d_K too, so the
            # draft cache stays ahead even on full acceptance) ---
            def draft_step(carry, j):
                tok, cache = carry
                logits, cache = apply_with_cache(
                    draft_cfg, draft_params, tok[:, None], cache, offset + j)
                row = logits[:, -1]
                if sampling:
                    # the PER-OUTPUT-POSITION key: a token proposed for
                    # output index n+j draws with the same key ancestral
                    # sampling would use there, so draft == target
                    # reproduces plain sampling exactly
                    nxt = jax.random.categorical(
                        _pos_key(rng_tok, n + j),
                        _prep_logits(row, temperature, top_k),
                        axis=-1).astype(jnp.int32)
                else:
                    nxt = jnp.argmax(row, axis=-1).astype(jnp.int32)
                return (nxt, cache), (nxt, row[0])

            (_, d_cache), (drafts_all, d_rows) = jax.lax.scan(
                draft_step, (last, d_cache), jnp.arange(K + 1))
            drafts = drafts_all[:K, 0]  # (K,) proposed tokens d_1..d_K

            # --- verify phase: one target forward over [last, d_1..d_K] ---
            block = jnp.concatenate([last, drafts], axis=0)[None]  # (1, K+1)
            t_logits, t_cache = apply_with_cache(
                target_cfg, target_params, block, t_cache, offset)

            idx = jnp.arange(K + 1, dtype=jnp.int32)
            if sampling:
                # Leviathan et al. rejection rule: accept d_{j+1} with
                # prob min(1, p_t/p_d); on first rejection sample the
                # replacement from norm(max(p_t - p_d, 0)). Padding p_d
                # with a zero row makes the full-acceptance bonus draw
                # come from p_t[K] through the same expression.
                p_t = jax.nn.softmax(
                    _prep_logits(t_logits[0], temperature, top_k), axis=-1)
                p_d = jax.nn.softmax(
                    _prep_logits(d_rows[:K], temperature, top_k), axis=-1)
                ratio = (p_t[jnp.arange(K), drafts]
                         / (p_d[jnp.arange(K), drafts] + 1e-20))
                u = jax.vmap(
                    lambda j: jax.random.uniform(_pos_key(rng_acc, n + j))
                )(jnp.arange(K))
                accept = (u <= ratio).astype(jnp.int32)
                n_acc = jnp.sum(jnp.cumprod(accept))
                p_d_pad = jnp.concatenate(
                    [p_d, jnp.zeros((1,) + p_d.shape[1:], p_d.dtype)], axis=0)
                resid = jnp.clip(p_t[n_acc] - p_d_pad[n_acc], 0.0)
                total = jnp.sum(resid)
                q = jnp.where(total > 0, resid / jnp.maximum(total, 1e-20),
                              p_t[n_acc])
                # full acceptance (n_acc == K): the bonus comes from p_t[K]
                # and must use the POSITIONAL token key so a perfect draft
                # reproduces ancestral sampling. A rejection replacement
                # needs a key INDEPENDENT of the rejected proposal's draw.
                bonus_key = jnp.where(
                    n_acc == K,
                    _pos_key(rng_tok, n + n_acc),
                    _pos_key(rng_fix, n + n_acc),
                )
                bonus = jax.random.categorical(
                    bonus_key, jnp.log(q + 1e-20)).astype(jnp.int32)
            else:
                t_preds = jnp.argmax(t_logits[0], axis=-1).astype(jnp.int32)
                # t_preds[j] = target's token after consuming block[:j+1]
                matches = (drafts == t_preds[:K]).astype(jnp.int32)
                n_acc = jnp.sum(jnp.cumprod(matches))  # 0..K
                bonus = t_preds[n_acc]

            # emitted this round: accepted drafts then the replacement /
            # bonus token at the first mismatch (or after full acceptance)
            emitted = jnp.where(idx < n_acc, jnp.append(drafts, 0), bonus)
            # positions >= n_acc+1 hold `bonus` copies: they are either
            # overwritten by the next round's write at n + n_acc + 1 or
            # fall beyond max_new_tokens and are sliced off.
            out = jax.lax.dynamic_update_slice(out, emitted[None], (0, n))
            return (out, n + n_acc + 1, bonus[None], t_cache, d_cache)

        out, _, _, _, _ = jax.lax.while_loop(
            cond, body, (out, jnp.int32(1), first, t_cache, d_cache))
        return jnp.concatenate([prompt, out[:, :max_new_tokens]], axis=1)

    return generate
