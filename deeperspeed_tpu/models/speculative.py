"""Speculative decoding: draft-model proposal + single-pass target verify.

Beyond the reference (whose generation path recomputes the prefix per
token through ``PipelineEngine.inference_batch``): a small DRAFT model
proposes K tokens autoregressively, then the TARGET model scores all K+1
positions in ONE cached forward; matching tokens are accepted and the
target's own prediction at the first mismatch is emitted as the bonus
token. Greedy (temperature=0) acceptance makes the output BIT-IDENTICAL
to plain greedy decoding of the target model, for any draft — the draft
only changes how many target forwards are needed (1 per ~n_accepted+1
tokens instead of 1 per token).

Precision caveat (measured on the v5e chip): the guarantee holds exactly
when the verify pass's logits match per-token logits bitwise — true in
fp32; under bf16 the batched (K+1)-token matmuls reduce in a different
order than S=1 decode steps, so near-tie argmaxes can flip and sequences
may diverge at such positions (either branch is a legitimate greedy
decode; this is the usual batched-vs-incremental nondeterminism, not an
acceptance-logic error).

TPU-native shape discipline: everything is static — the outer loop is a
``lax.while_loop`` whose body always drafts exactly K tokens and verifies
K+1; accepted counts vary as DATA (masked writes into a preallocated
output buffer, offsets advance by the accepted length). Stale KV-cache
entries beyond the rolled-back offset need no cleanup: the attention mask
is offset-derived, so they are invisible until overwritten.

Usage::

    gen = make_speculative_generator(target_cfg, draft_cfg, k_draft=4)
    out = gen(target_params, draft_params, prompt, max_new_tokens=64)

Batch size 1 (the speculative serving case; per-row accept counts would
need per-row cache offsets).
"""

from functools import partial

import jax
import jax.numpy as jnp

from .generation import apply_with_cache, init_cache
from .gpt import GPTConfig


def make_speculative_generator(target_cfg: GPTConfig, draft_cfg: GPTConfig,
                               k_draft: int = 4):
    """Build a jitted speculative generate(target_params, draft_params,
    prompt, max_new_tokens) -> (B, S+max_new_tokens) tokens (greedy)."""
    assert target_cfg.vocab_size == draft_cfg.vocab_size, (
        "target and draft must share a vocabulary")
    K = int(k_draft)
    assert K >= 1

    @partial(jax.jit, static_argnames=("max_new_tokens",))
    def generate(target_params, draft_params, prompt, max_new_tokens: int):
        B, S = prompt.shape
        if B != 1:
            raise ValueError(
                "speculative decoding supports batch size 1 (per-row accept "
                f"counts would need per-row cache offsets); got B={B}")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        # slack: the final block may draft past the requested length
        max_len = S + max_new_tokens + K + 1
        for cfg in (target_cfg, draft_cfg):
            if not cfg.rotary and max_len > cfg.max_seq:
                raise ValueError(
                    f"prompt ({S}) + max_new_tokens ({max_new_tokens}) + "
                    f"draft slack ({K + 1}) exceeds max_seq ({cfg.max_seq})")

        t_cache = init_cache(target_cfg, B, max_len)
        d_cache = init_cache(draft_cfg, B, max_len)
        t_logits, t_cache = apply_with_cache(
            target_cfg, target_params, prompt, t_cache, 0)
        _, d_cache = apply_with_cache(
            draft_cfg, draft_params, prompt, d_cache, 0)
        first = jnp.argmax(t_logits[:, -1], axis=-1).astype(jnp.int32)  # (B,)

        out = jnp.zeros((B, max_new_tokens + K + 1), jnp.int32)
        out = jax.lax.dynamic_update_slice(out, first[:, None], (0, 0))

        # invariant at loop top: `n` tokens emitted (out[:, :n]); `last` is
        # the newest emitted token, NOT yet in either cache; both caches
        # hold exactly the S + n - 1 tokens before it.
        def cond(carry):
            n = carry[1]
            return n < max_new_tokens

        def body(carry):
            out, n, last, t_cache, d_cache = carry
            offset = S + n - 1  # tokens in both caches

            # --- draft phase: propose K tokens (and cache d_K too, so the
            # draft cache stays ahead even on full acceptance) ---
            def draft_step(carry, j):
                tok, cache = carry
                logits, cache = apply_with_cache(
                    draft_cfg, draft_params, tok[:, None], cache, offset + j)
                nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                return (nxt, cache), nxt

            (_, d_cache), drafts = jax.lax.scan(
                draft_step, (last, d_cache), jnp.arange(K + 1))
            drafts = drafts[:K, 0]  # (K,) proposed tokens d_1..d_K

            # --- verify phase: one target forward over [last, d_1..d_K] ---
            block = jnp.concatenate([last, drafts], axis=0)[None]  # (1, K+1)
            t_logits, t_cache = apply_with_cache(
                target_cfg, target_params, block, t_cache, offset)
            t_preds = jnp.argmax(t_logits[0], axis=-1).astype(jnp.int32)
            # t_preds[j] = target's token after consuming block[:j+1]

            # --- acceptance: longest prefix where draft == target ---
            matches = (drafts == t_preds[:K]).astype(jnp.int32)
            n_acc = jnp.sum(jnp.cumprod(matches))  # 0..K

            # emitted this round: accepted drafts then the target's token
            # at the first mismatch (or bonus token on full acceptance)
            idx = jnp.arange(K + 1, dtype=jnp.int32)
            bonus = t_preds[n_acc]
            emitted = jnp.where(idx < n_acc, jnp.append(drafts, 0), bonus)
            # positions >= n_acc+1 hold `bonus` copies: they are either
            # overwritten by the next round's write at n + n_acc + 1 or
            # fall beyond max_new_tokens and are sliced off.
            out = jax.lax.dynamic_update_slice(out, emitted[None], (0, n))
            return (out, n + n_acc + 1, bonus[None], t_cache, d_cache)

        out, _, _, _, _ = jax.lax.while_loop(
            cond, body, (out, jnp.int32(1), first, t_cache, d_cache))
        return jnp.concatenate([prompt, out[:, :max_new_tokens]], axis=1)

    return generate
