"""Mixture-of-Experts layer with expert parallelism, TPU-native.

The reference framework (DeepSpeed v0.3.15) predates DeepSpeed-MoE; expert
parallelism is listed as ABSENT in SURVEY.md §2.3. This module supplies the
capability the modern stack expects, designed for XLA/SPMD rather than the
later torch implementation:

  * GShard/Switch-style FIXED-CAPACITY routing: top-k gating produces dense
    dispatch/combine tensors (one-hot matmuls — static shapes, MXU-friendly,
    no data-dependent gather/scatter that would defeat jit).
  * expert weights carry a leading E axis sharded over the 'expert' mesh
    axis (PartitionSpec('expert', ...)); constraining the dispatched
    activations to the same axis makes XLA emit the all-to-all pair
    (tokens->experts, experts->tokens) over ICI — the pjit analog of
    DeepSpeed-MoE's torch.distributed.all_to_all.
  * the auxiliary load-balancing loss (Switch Transformer eq. 4) and router
    z-loss are returned for the caller to add to the task loss.

Public surface:
  init_moe_params / moe_param_specs — expert FFN + router pytrees
  moe_ffn(params, x, ...) -> (y, aux) — drop-in replacement for a dense FFN
  load_balancing_loss / router_z_loss
"""

import dataclasses
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.topology import DATA_AXIS, EXPERT_AXIS, SEQ_AXIS


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2
    # capacity per expert = ceil(top_k * tokens / num_experts * capacity_factor)
    capacity_factor: float = 1.25
    aux_loss_coef: float = 0.01
    z_loss_coef: float = 1e-3
    # router computations always run in fp32 (small, numerically sensitive)

    # "dense": one-hot (T, E, C) dispatch/combine einsums — O(T*E*C*D) but
    #   pure matmuls, fastest at small E. "sorted": sort assignments by
    #   expert and build the (E, C, D) buffers with gather/scatter-add —
    #   O(T*k*(log(T*k) + D)), independent of E, the scalable path for
    #   E >= ~16. "auto" picks by num_experts. Both produce identical
    #   buffers (same drop order), so they are loss-equivalent.
    #   "dropless": MegaBlocks-style — sorted assignments feed
    #   jax.lax.ragged_dot grouped matmuls with NO capacity and NO token
    #   drops (dropped_frac is identically 0). With a live 'expert' mesh
    #   axis the dispatch becomes an explicit shard_map: lax.all_to_all
    #   with fixed per-destination slots routes each shard's assignments
    #   to the shard owning the expert (see _moe_ffn_dropless_ep for the
    #   slot/truncation contract), a local ragged_dot runs the shard's
    #   experts, and the reverse all_to_all brings outputs home.
    dispatch_impl: str = "auto"  # "auto" | "dense" | "sorted" | "dropless"

    # EP-dropless receive-buffer headroom: each expert shard statically
    # reserves ep_buffer_factor * (k * T / world) rows (1.0 = perfectly
    # balanced load). Under skew beyond the factor, overflow assignments
    # are dropped DETERMINISTICALLY (every shard computes the same greedy
    # truncation from the all-gathered counts) and reported in
    # dropped_frac. Set >= the 'expert' axis size for a mathematical
    # zero-drop guarantee (worst case: every token routes to one shard) at
    # the cost of proportional buffer memory and ragged_dot padding FLOPs.
    ep_buffer_factor: float = 2.0

    # Combine weights default to RAW softmax probabilities (Switch-style:
    # the mass of unselected experts damps the MoE branch, the residual
    # stream carries the rest). Set True for GShard/Mixtral convention:
    # renormalize the chosen top-k gates to sum to 1.
    normalize_gates: bool = False

    def resolved_dispatch_impl(self) -> str:
        if self.dispatch_impl != "auto":
            return self.dispatch_impl
        return "sorted" if self.num_experts >= 16 else "dense"


def init_moe_params(rng, d_model: int, d_ff: int, cfg: MoEConfig,
                    out_std: Optional[float] = None):
    """Expert FFN params stacked on a leading E axis + router weights."""
    E, D, F = cfg.num_experts, d_model, d_ff
    k1, k2, k3 = jax.random.split(rng, 3)
    std = 0.02
    out_std = out_std if out_std is not None else std
    return {
        "router": {"wg": (jax.random.normal(k1, (D, E), jnp.float32) * std)},
        "experts": {
            "wi": jax.random.normal(k2, (E, D, F), jnp.float32) * std,
            "bi": jnp.zeros((E, F), jnp.float32),
            "wo": jax.random.normal(k3, (E, F, D), jnp.float32) * out_std,
            "bo": jnp.zeros((E, D), jnp.float32),
        },
    }


def moe_param_specs():
    """Experts sharded over the 'expert' mesh axis; router replicated."""
    return {
        "router": {"wg": P(None, None)},
        "experts": {
            "wi": P(EXPERT_AXIS, None, None),
            "bi": P(EXPERT_AXIS, None),
            "wo": P(EXPERT_AXIS, None, None),
            "bo": P(EXPERT_AXIS, None),
        },
    }


def _constrain(x, mesh, spec):
    from .gpt import _shard_act

    return _shard_act(x, mesh, spec)


def router_topk(logits, top_k: int, normalize_gates: bool = False):
    """Shared routing decision: (probs (T,E), expert_idx (T,k), gate (T,k)).

    gate values are the chosen experts' softmax probabilities (raw Switch
    convention), optionally renormalized over the kept top-k
    (GShard/Mixtral). Both dispatch impls consume exactly this."""
    probs = jax.nn.softmax(logits, axis=-1)
    gate, expert_idx = jax.lax.top_k(probs, top_k)  # values ARE the gates
    if normalize_gates:
        gate = gate / (jnp.sum(gate, axis=1, keepdims=True) + 1e-9)
    return probs, expert_idx, gate


def top_k_gating(logits, top_k: int, capacity: int,
                 normalize_gates: bool = False):
    """GShard-style dense routing tensors from router logits.

    logits: (T, E) fp32. Returns (dispatch (T, E, C) bool-ish fp32,
    combine (T, E, C) fp32, aux_metrics dict).

    Position of a token inside its expert's buffer = its rank among the
    tokens that chose that expert (cumsum over the token dim); tokens past
    capacity are dropped (their combine weight is 0 — the residual stream
    carries them, the standard Switch behavior).

    Combine weights are RAW softmax probabilities by default (Switch
    convention — see MoEConfig.normalize_gates); ``normalize_gates=True``
    renormalizes each token's chosen top-k gates to sum to 1
    (GShard/Mixtral convention)."""
    T, E = logits.shape
    probs, expert_idx, gate = router_topk(logits, top_k, normalize_gates)
    mask = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # (T, k, E)

    # buffer positions: rank each (token, choice) among all assignments to
    # that expert — cumulate over the flattened (k, T) order so the k=0
    # choice of every token ranks before k=1 overflow
    mask_kt = mask.transpose(1, 0, 2).reshape(top_k * T, E)
    pos_kt = jnp.cumsum(mask_kt, axis=0) - mask_kt  # (k*T, E)
    pos = pos_kt.reshape(top_k, T, E).transpose(1, 0, 2)  # (T, k, E)

    keep = (pos < capacity).astype(jnp.float32) * mask  # (T, k, E)

    # scatter the k choices into (T, E, C)
    pos_c = jax.nn.one_hot(
        jnp.sum(pos * mask, axis=-1).astype(jnp.int32), capacity,
        dtype=jnp.float32,
    )  # (T, k, C)
    dispatch = jnp.einsum("tke,tkc->tec", keep, pos_c)
    combine = jnp.einsum("tke,tkc,tk->tec", keep, pos_c, gate)

    # Switch aux loss ingredients (computed on the FULL router distribution)
    me = jnp.mean(probs, axis=0)  # mean router prob per expert
    ce = jnp.mean(mask[:, 0, :], axis=0)  # fraction routed (top-1) per expert
    aux = {
        "mean_prob": me,
        "top1_frac": ce,
        # fraction of (token, choice) assignments that overflowed capacity
        "dropped_frac": 1.0 - jnp.sum(keep) / (T * top_k),
    }
    return dispatch, combine, aux


def sorted_assignments(expert_idx, capacity: int, num_experts: int):
    """Sort (token, choice) assignments by expert; rank within each expert.

    expert_idx: (T, k) int. Returns (order, tid, expert, pos, keep) — all
    (k*T,) arrays in sorted-by-expert order: the originating token id, the
    expert id, the rank of the assignment inside that expert's buffer, and
    whether it fits under ``capacity``.

    Assignments are flattened CHOICE-major (all tokens' choice 0, then
    choice 1, ...) before the stable sort, so ranks — and therefore which
    assignments overflow — match the dense path's cumsum order exactly:
    every token's primary choice outranks any token's secondary choice.
    """
    T, k = expert_idx.shape
    e_flat = expert_idx.T.reshape(-1)  # (k*T,) choice-major
    tid_flat = jnp.tile(jnp.arange(T, dtype=jnp.int32), k)
    order = jnp.argsort(e_flat, stable=True)
    e_s = e_flat[order]
    tid_s = tid_flat[order]
    starts = jnp.searchsorted(e_s, jnp.arange(num_experts))  # (E,)
    pos_s = jnp.arange(k * T, dtype=jnp.int32) - starts[e_s].astype(jnp.int32)
    keep_s = pos_s < capacity
    return order, tid_s, e_s, pos_s, keep_s


def load_balancing_loss(mean_prob, top1_frac, num_experts: int):
    """Switch Transformer eq. 4: E * sum_e me_e * ce_e (==1 when uniform)."""
    return num_experts * jnp.sum(mean_prob * top1_frac)


def router_z_loss(logits):
    """Stabilizes router logits (ST-MoE): mean logsumexp^2."""
    return jnp.mean(jax.scipy.special.logsumexp(logits, axis=-1) ** 2)


def _moe_ffn_dropless(params, x, cfg: MoEConfig, act, logits, mesh):
    """MegaBlocks-style dropless dispatch: assignments sorted by expert
    feed ``jax.lax.ragged_dot`` grouped matmuls — every token is processed
    (no capacity, no drops), and compute scales with T*k regardless of the
    load distribution across experts."""
    B, S, D = x.shape
    E, k = cfg.num_experts, cfg.top_k
    T = B * S
    xt = x.reshape(T, D)
    probs, expert_idx, gate = router_topk(logits, k, cfg.normalize_gates)
    # capacity = k*T keeps every assignment; reuse the shared sorter
    order, tid_s, e_s, _pos_s, _keep_s = sorted_assignments(
        expert_idx, k * T, E)
    gate_s = gate.T.reshape(-1)[order]
    group_sizes = jnp.zeros((E,), jnp.int32).at[e_s].add(1)

    xs = xt[tid_s]  # (k*T, D) sorted by expert
    wi = params["experts"]["wi"].astype(x.dtype)
    wo = params["experts"]["wo"].astype(x.dtype)
    h = jax.lax.ragged_dot(xs, wi, group_sizes).astype(x.dtype)
    h = h + params["experts"]["bi"].astype(x.dtype)[e_s]
    h = act(h)
    eo = jax.lax.ragged_dot(h, wo, group_sizes).astype(x.dtype)
    eo = eo + params["experts"]["bo"].astype(x.dtype)[e_s]

    # combine accumulates k expert outputs per token in fp32 (the dense
    # path's combine einsum accumulates fp32 on the MXU; a bf16 scatter
    # here would make the impls numerically different, not just faster)
    yt = jnp.zeros((T, D), jnp.float32).at[tid_s].add(
        (eo * gate_s.astype(x.dtype)[:, None]).astype(jnp.float32))
    y = yt.astype(x.dtype).reshape(B, S, D)
    y = _constrain(y, mesh, P(DATA_AXIS, SEQ_AXIS, None))

    aux = {
        "aux_loss": load_balancing_loss(
            jnp.mean(probs, axis=0),
            jnp.zeros(E, jnp.float32).at[expert_idx[:, 0]].add(1.0) / T, E),
        "z_loss": router_z_loss(logits),
        "dropped_frac": jnp.float32(0.0),  # dropless by construction
    }
    return y, aux


def _moe_ffn_dropless_ep(params, x, cfg: MoEConfig, act, mesh):
    """Dropless dispatch composed with EXPERT PARALLELISM.

    shard_map over the token axes ('data' x 'expert'): every device owns
    T/world tokens and E/ep experts. Each shard sorts its (token, choice)
    assignments by global expert id, packs them into fixed per-destination
    slots, exchanges with ``lax.all_to_all`` (the explicit-SPMD analog of
    DeepSpeed-MoE's torch all_to_all; portable to XLA:CPU where
    ragged-all-to-all is not implemented), runs its local experts with ONE
    ragged_dot (a zero-weight padding group absorbs empty slots), and
    reverses the exchange to combine at home.

    Static-shape contract: each (sender, destination) pair carries
    ``cap_pp = ceil(ep_buffer_factor * k * T_local / ep)`` slots.
    Assignments beyond a pair's slots drop DETERMINISTICALLY (reported in
    dropped_frac); since one sender holds at most k*T_local assignments
    for any destination, ``ep_buffer_factor >= ep`` is mathematically
    dropless under arbitrary routing skew."""
    from ..ops.ring_attention import _SHMAP_CHECK_KWARGS, shard_map
    from ..parallel.topology import filter_spec

    B, S, D = x.shape
    E, k = cfg.num_experts, cfg.top_k
    T = B * S
    if SEQ_AXIS in mesh.axis_names and mesh.shape[SEQ_AXIS] > 1:
        raise ValueError(
            "dropless EP does not compose with sequence parallelism; "
            "use dispatch_impl='sorted' when the 'seq' axis is live"
        )
    token_axes = tuple(
        a for a in (DATA_AXIS, EXPERT_AXIS)
        if a in mesh.axis_names and mesh.shape[a] > 1
    )
    ep = mesh.shape[EXPERT_AXIS]
    world = math.prod(mesh.shape[a] for a in token_axes)
    if E % ep:
        raise ValueError(f"num_experts {E} not divisible by expert axis {ep}")
    e_loc = E // ep
    if T % world:
        raise ValueError(f"tokens {T} not divisible by mesh world {world}")
    t_loc = T // world
    cap_pp = max(1, int(math.ceil(cfg.ep_buffer_factor * k * t_loc / ep)))
    cap = ep * cap_pp

    def body(xt, wg, wi, bi, wo, bo):
        # xt (t_loc, D); wi/bi/wo/bo carry this shard's e_loc experts
        xt = xt.reshape(t_loc, D)
        my = jax.lax.axis_index(EXPERT_AXIS)
        logits = xt.astype(jnp.float32) @ wg.astype(jnp.float32)
        probs, expert_idx, gate = router_topk(logits, k, cfg.normalize_gates)
        # choice-major flatten + stable sort by global expert id: rows for
        # each destination shard are contiguous runs
        e_flat = expert_idx.T.reshape(-1)
        tid = jnp.tile(jnp.arange(t_loc, dtype=jnp.int32), k)
        order = jnp.argsort(e_flat, stable=True)
        e_s = e_flat[order]
        tid_s = tid[order]
        gate_s = gate.T.reshape(-1)[order]
        dest = e_s // e_loc  # (k*t_loc,) destination shard per assignment
        shard_starts = jnp.searchsorted(
            e_s, jnp.arange(ep, dtype=jnp.int32) * e_loc).astype(jnp.int32)
        pos = (jnp.arange(k * t_loc, dtype=jnp.int32)
               - shard_starts[dest])  # rank within my run for that dest
        ok = pos < cap_pp  # pair-level slots; beyond = deterministic drop
        dropped = jnp.sum(1.0 - ok.astype(jnp.float32))
        slot = jnp.where(ok, dest * cap_pp + pos, cap)  # cap = dump row

        xs = xt[tid_s]  # (k*t_loc, D)
        sendx = jnp.zeros((cap + 1, D), xs.dtype).at[slot].set(xs)[:cap]
        sende = jnp.full((cap + 1,), E, jnp.int32).at[slot].set(e_s)[:cap]
        # (ep, cap_pp, ...) blocks; device d receives every sender's d-th
        # block — DeepSpeed-MoE's all_to_all with explicit slot packing
        x_recv = jax.lax.all_to_all(
            sendx.reshape(ep, cap_pp, D), EXPERT_AXIS, 0, 0).reshape(cap, D)
        e_recv = jax.lax.all_to_all(
            sende.reshape(ep, cap_pp), EXPERT_AXIS, 0, 0).reshape(cap)

        # group received rows by local expert; sentinel padding sorts last
        e_local = jnp.where(e_recv >= E, e_loc, e_recv - my * e_loc)
        order2 = jnp.argsort(e_local, stable=True)
        xs2 = x_recv[order2]
        e2 = e_local[order2]
        group_sizes = jnp.zeros((e_loc + 1,), jnp.int32).at[e2].add(1)

        zpadW = lambda w: jnp.concatenate(
            [w, jnp.zeros((1,) + w.shape[1:], w.dtype)])
        h = jax.lax.ragged_dot(
            xs2, zpadW(wi.astype(xs2.dtype)), group_sizes).astype(xs2.dtype)
        h = h + zpadW(bi.astype(xs2.dtype))[e2]
        h = act(h)
        eo = jax.lax.ragged_dot(
            h, zpadW(wo.astype(xs2.dtype)), group_sizes).astype(xs2.dtype)
        eo = eo + zpadW(bo.astype(xs2.dtype))[e2]
        eo = jnp.zeros_like(eo).at[order2].set(eo)  # back to recv order

        # reverse exchange brings each slot home to its sender
        eo_home = jax.lax.all_to_all(
            eo.reshape(ep, cap_pp, D), EXPERT_AXIS, 0, 0).reshape(cap, D)

        # fp32 combine at home; dropped assignments contribute zero
        okf = ok.astype(jnp.float32)
        eo_s = eo_home[jnp.clip(slot, 0, cap - 1)]
        contrib = (eo_s.astype(jnp.float32)
                   * (gate_s.astype(jnp.float32) * okf)[:, None])
        yt = jnp.zeros((t_loc, D), jnp.float32).at[tid_s].add(contrib)

        pmean = lambda v: jax.lax.pmean(
            v, token_axes if len(token_axes) > 1 else token_axes[0])
        aux_local = {
            "mean_prob": jnp.mean(probs, axis=0),
            "top1_frac": jnp.zeros(E, jnp.float32)
                           .at[expert_idx[:, 0]].add(1.0) / t_loc,
            "dropped_frac": dropped / (k * t_loc),
            "z": router_z_loss(logits),
        }
        return yt.astype(x.dtype), jax.tree.map(pmean, aux_local)

    tok_spec = P(token_axes if len(token_axes) > 1 else
                 (token_axes[0] if token_axes else None), None)
    exp = lambda *rest: filter_spec(P(EXPERT_AXIS, *rest), mesh)
    yt, aux_s = shard_map(
        body, mesh=mesh,
        in_specs=(tok_spec, P(None, None), exp(None, None), exp(None),
                  exp(None, None), exp(None)),
        out_specs=(tok_spec, P()),
        **_SHMAP_CHECK_KWARGS,
    )(x.reshape(T, D),
      params["router"]["wg"],
      params["experts"]["wi"], params["experts"]["bi"],
      params["experts"]["wo"], params["experts"]["bo"])

    y = yt.reshape(B, S, D)
    y = _constrain(y, mesh, P(DATA_AXIS, SEQ_AXIS, None))
    aux = {
        "aux_loss": load_balancing_loss(
            aux_s["mean_prob"], aux_s["top1_frac"], E),
        "z_loss": aux_s["z"],
        "dropped_frac": aux_s["dropped_frac"],
    }
    return y, aux


def moe_ffn(params, x, cfg: MoEConfig, mesh=None, activation=None):
    """Drop-in MoE replacement for a dense FFN block.

    params: init_moe_params pytree (experts possibly 'expert'-sharded).
    x: (B, S, D) activations (any float dtype; router runs fp32).
    Returns (y (B, S, D), aux dict with 'aux_loss' and 'z_loss' scalars —
    scale by cfg.*_coef and add to the task loss)."""
    B, S, D = x.shape
    E, k = cfg.num_experts, cfg.top_k
    T = B * S
    act = activation or (lambda h: jax.nn.gelu(h, approximate=True))

    impl = cfg.resolved_dispatch_impl()
    if impl == "dropless" and (
            mesh is not None and EXPERT_AXIS in mesh.axis_names
            and mesh.shape[EXPERT_AXIS] > 1):
        # EP path computes its router on per-shard tokens inside shard_map
        return _moe_ffn_dropless_ep(params, x, cfg, act, mesh)

    xt = x.reshape(T, D)
    logits = (xt.astype(jnp.float32)
              @ params["router"]["wg"].astype(jnp.float32))  # (T, E)
    # k*T assignments spread over E buffers (GShard convention: capacity
    # scales with top_k, else top-2 structurally drops second choices)
    capacity = max(1, math.ceil(k * T / E * cfg.capacity_factor))

    if impl == "dropless":
        return _moe_ffn_dropless(params, x, cfg, act, logits, mesh)

    if impl == "sorted":
        probs, expert_idx, gate = router_topk(logits, k, cfg.normalize_gates)
        order, tid_s, e_s, pos_s, keep_s = sorted_assignments(
            expert_idx, capacity, E)
        gate_s = gate.T.reshape(-1)[order]  # choice-major, sorted
        slot_s = e_s * capacity + jnp.minimum(pos_s, capacity - 1)
        contrib = xt[tid_s] * keep_s.astype(x.dtype)[:, None]  # (k*T, D)
        expert_in = jnp.zeros((E * capacity, D), x.dtype).at[slot_s].add(
            contrib).reshape(E, capacity, D)
        gaux = {
            "mean_prob": jnp.mean(probs, axis=0),
            "top1_frac": jnp.zeros(E, jnp.float32)
                           .at[expert_idx[:, 0]].add(1.0) / T,
            "dropped_frac": 1.0 - jnp.sum(keep_s) / (T * k),
        }
        combine = None
    else:
        dispatch, combine, gaux = top_k_gating(
            logits, k, capacity, normalize_gates=cfg.normalize_gates)
        # tokens -> expert buffers (XLA lowers the einsum + sharding
        # constraint to an all-to-all over the 'expert' axis when experts
        # are sharded)
        expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), xt)
    expert_in = _constrain(expert_in, mesh, P(EXPERT_AXIS, None, None))

    wi = params["experts"]["wi"].astype(x.dtype)
    wo = params["experts"]["wo"].astype(x.dtype)
    h = jnp.einsum("ecd,edf->ecf", expert_in, wi)
    h = h + params["experts"]["bi"].astype(x.dtype)[:, None, :]
    h = act(h)
    h = _constrain(h, mesh, P(EXPERT_AXIS, None, None))
    eo = jnp.einsum("ecf,efd->ecd", h, wo)
    eo = eo + params["experts"]["bo"].astype(x.dtype)[:, None, :]
    eo = _constrain(eo, mesh, P(EXPERT_AXIS, None, None))

    # expert buffers -> tokens
    if impl == "sorted":
        eo_flat = eo.reshape(E * capacity, D)
        w_s = (gate_s * keep_s).astype(x.dtype)[:, None]
        # fp32 combine accumulator, matching the dense path's fp32 MXU
        # accumulation (see the dropless combine above)
        yt = jnp.zeros((T, D), jnp.float32).at[tid_s].add(
            (eo_flat[slot_s] * w_s).astype(jnp.float32)).astype(x.dtype)
    else:
        yt = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), eo,
                        preferred_element_type=jnp.float32).astype(x.dtype)
    y = yt.reshape(B, S, D)
    y = _constrain(y, mesh, P(DATA_AXIS, SEQ_AXIS, None))

    aux = {
        "aux_loss": load_balancing_loss(gaux["mean_prob"], gaux["top1_frac"], E),
        "z_loss": router_z_loss(logits),
        "dropped_frac": gaux["dropped_frac"],
    }
    return y, aux


def moe_loss(aux, cfg: MoEConfig):
    """Total auxiliary loss term for one (or summed) moe_ffn aux dicts."""
    return cfg.aux_loss_coef * aux["aux_loss"] + cfg.z_loss_coef * aux["z_loss"]
