"""GPT / GPT-NeoX decoder-only transformer, TPU-native.

This is the flagship model family the reference framework was built to train
(GPT-NeeoX used DeeperSpeed's PipelineModule + Megatron mpu; see SURVEY §1).
Design is jax-first rather than a port:

  * params are a plain pytree with per-layer tensors STACKED on a leading
    layer axis, so the forward is a `lax.scan` over layers — this is what
    makes ZeRO-3 parameter gathering per-layer (XLA all-gathers each layer's
    slice inside the scan, the analog of stage3's fetch/release hooks) and
    keeps compile time O(1) in depth.
  * `jax.checkpoint` (remat) per scan step == activation checkpointing with
    checkpoint_interval=1 (reference activation_checkpointing/checkpointing.py).
  * tensor parallelism is a PartitionSpec pytree over the 'model' axis
    (attention heads / ffn columns), the native replacement for the external
    Megatron mpu the reference consumed (engine.py:630-641).
  * sequence-axis sharding constraints give context-parallel long-sequence
    training over the 'seq' mesh axis.

Supports GPT-2 (learned positions, serial residual) and GPT-NeoX (rotary,
parallel attention+MLP residual) variants.
"""

import dataclasses
import math
from functools import partial
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from ..parallel.topology import DATA_AXIS, MODEL_AXIS, SEQ_AXIS
from ..utils import hooks


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304
    n_layer: int = 12
    n_head: int = 12
    # grouped-query attention: number of K/V heads (0 = n_head = classic
    # MHA; 1 = MQA). Shrinks the qkv projection and the decode KV cache by
    # n_head/n_kv_head; attention repeats K/V heads to match Q
    n_kv_head: int = 0
    d_model: int = 768
    d_ff: int = 0  # 0 => 4 * d_model
    max_seq: int = 1024
    rotary: bool = True  # NeoX-style rotary; False => learned positions
    rotary_pct: float = 1.0
    parallel_residual: bool = True  # NeoX parallel attn+mlp
    layernorm_eps: float = 1e-5
    tie_embeddings: bool = False
    remat: bool = True
    # remat policy: 'full' recomputes everything (min memory); 'flash'
    # additionally saves the flash-attention output+logsumexp so the
    # backward skips re-running the attention forward kernel; 'matmuls'
    # saves flash o/lse + post-rotary q/k/v + pre-gelu ffn — the backward
    # recomputes only layernorms/gelu/residuals (near-zero recompute FLOPs
    # at ~1/2 the no-remat activation memory); 'dots_all' saves every dot
    # output; 'dots' saves only batch-free dots (weight-stationary)
    remat_policy: str = "full"
    dtype: Any = jnp.bfloat16  # compute dtype for activations
    # 'auto' | 'pallas' | 'xla' | 'ring' | 'ulysses' (the last two are the
    # context-parallel paths over the 'seq' mesh axis)
    attn_impl: str = "auto"
    # cross-entropy sequence chunk: the (B, S, V) logits tensor is never
    # materialized; the loss scans over S-chunks of this many tokens,
    # rematerializing each chunk's logits in the backward (softmax - onehot).
    # 0 disables chunking (single fused logits+lse).
    ce_chunk: int = 128
    # Mixture-of-Experts: 0 = dense MLP; >0 replaces every layer's FFN with
    # an expert-parallel MoE (models/moe.py) of this many experts, sharded
    # over the 'expert' mesh axis. A capability BEYOND the reference, which
    # predates DeepSpeed-MoE (SURVEY.md §2.3 lists EP as absent).
    moe_num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_coef: float = 0.01
    moe_z_coef: float = 1e-3
    moe_dispatch_impl: str = "auto"  # auto | dense | sorted | dropless
    moe_normalize_gates: bool = False
    # EP-dropless receive-buffer headroom (see MoEConfig.ep_buffer_factor);
    # >= the 'expert' axis size guarantees zero drops under any skew
    moe_ep_buffer_factor: float = 2.0

    @property
    def moe(self):
        if not self.moe_num_experts:
            return None
        from .moe import MoEConfig

        return MoEConfig(
            num_experts=self.moe_num_experts,
            top_k=self.moe_top_k,
            capacity_factor=self.moe_capacity_factor,
            aux_loss_coef=self.moe_aux_coef,
            z_loss_coef=self.moe_z_coef,
            dispatch_impl=self.moe_dispatch_impl,
            normalize_gates=self.moe_normalize_gates,
            ep_buffer_factor=self.moe_ep_buffer_factor,
        )

    def __post_init__(self):
        kv = self.n_kv_head or self.n_head
        if self.n_head % kv:
            raise ValueError(
                f"n_head ({self.n_head}) must be a multiple of n_kv_head "
                f"({kv})"
            )
        if self.remat_policy not in ("full", "flash", "matmuls", "dots",
                                     "dots_all"):
            raise ValueError(
                f"remat_policy must be 'full', 'flash', 'matmuls', 'dots', "
                f"or 'dots_all', got {self.remat_policy!r}"
            )

    @property
    def ffn_dim(self):
        return self.d_ff if self.d_ff else 4 * self.d_model

    @property
    def head_dim(self):
        assert self.d_model % self.n_head == 0
        return self.d_model // self.n_head

    @property
    def kv_heads(self):
        return self.n_kv_head or self.n_head  # validated in __post_init__

    @property
    def qkv_dim(self):
        """Width of the fused qkv projection: H*Dh + 2*Hkv*Dh."""
        return (self.n_head + 2 * self.kv_heads) * self.head_dim


# ------------------------------------------------------------------ #
# init
# ------------------------------------------------------------------ #


def init_params(rng, cfg: GPTConfig):
    """Initial fp32 params. Per-layer tensors stacked on axis 0."""
    D, F, L, V = cfg.d_model, cfg.ffn_dim, cfg.n_layer, cfg.vocab_size
    k = iter(jax.random.split(rng, 16))
    std = 0.02
    # output projections scaled by 1/sqrt(2L) (GPT-2/NeoX convention)
    out_std = std / math.sqrt(2.0 * L)

    def norm(key, shape, s):
        return (jax.random.normal(key, shape, jnp.float32) * s).astype(jnp.float32)

    params = {
        "embed": {"wte": norm(next(k), (V, D), std)},
        "layers": {
            "ln1_scale": jnp.ones((L, D), jnp.float32),
            "ln1_bias": jnp.zeros((L, D), jnp.float32),
            "ln2_scale": jnp.ones((L, D), jnp.float32),
            "ln2_bias": jnp.zeros((L, D), jnp.float32),
            "attn": {
                "wqkv": norm(next(k), (L, D, cfg.qkv_dim), std),
                "bqkv": jnp.zeros((L, cfg.qkv_dim), jnp.float32),
                "wo": norm(next(k), (L, D, D), out_std),
                "bo": jnp.zeros((L, D), jnp.float32),
            },
            "mlp": {
                "wi": norm(next(k), (L, D, F), std),
                "bi": jnp.zeros((L, F), jnp.float32),
                "wo": norm(next(k), (L, F, D), out_std),
                "bo": jnp.zeros((L, D), jnp.float32),
            },
        },
        "final_ln": {
            "scale": jnp.ones((D,), jnp.float32),
            "bias": jnp.zeros((D,), jnp.float32),
        },
    }
    if cfg.moe is not None:
        from .moe import init_moe_params

        moe_keys = jax.random.split(next(k), L)
        per_layer = [
            init_moe_params(moe_keys[i], D, F, cfg.moe, out_std=out_std)
            for i in range(L)
        ]
        params["layers"]["moe"] = jax.tree.map(
            lambda *xs: jnp.stack(xs), *per_layer
        )
        del params["layers"]["mlp"]
    if not cfg.rotary:
        params["embed"]["wpe"] = norm(next(k), (cfg.max_seq, D), std)
    if not cfg.tie_embeddings:
        params["lm_head"] = norm(next(k), (D, V), std)
    return params


def param_specs(cfg: GPTConfig):
    """Tensor-parallel PartitionSpecs over the 'model' axis (megatron-style
    column/row split: qkv+ffn-in column-parallel, attn-out+ffn-out
    row-parallel, embeddings vocab-sharded)."""
    M = MODEL_AXIS
    specs = {
        # wte sharded over d_model, not vocab: XLA's sharded-gather from a
        # vocab-sharded table falls back to full replication (SPMD warning),
        # while column-sharded embedding rows gather cleanly
        "embed": {"wte": P(None, M)},
        "layers": {
            "ln1_scale": P(None, None),
            "ln1_bias": P(None, None),
            "ln2_scale": P(None, None),
            "ln2_bias": P(None, None),
            "attn": {
                "wqkv": P(None, None, M),
                "bqkv": P(None, M),
                "wo": P(None, M, None),
                "bo": P(None, None),
            },
            "mlp": {
                "wi": P(None, None, M),
                "bi": P(None, M),
                "wo": P(None, M, None),
                "bo": P(None, None),
            },
        },
        "final_ln": {"scale": P(None), "bias": P(None)},
    }
    if cfg.moe is not None:
        from .moe import moe_param_specs

        # prepend the stacked layer axis to every expert/router spec
        specs["layers"]["moe"] = jax.tree.map(
            lambda s: P(None, *s), moe_param_specs(),
            is_leaf=lambda x: isinstance(x, P),
        )
        del specs["layers"]["mlp"]
    if not cfg.rotary:
        specs["embed"]["wpe"] = P(None, None)
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, M)
    return specs


# ------------------------------------------------------------------ #
# building blocks
# ------------------------------------------------------------------ #


def pick_ce_chunk(S: int, chunk: int) -> int:
    """Streaming-CE chunk for sequence length S: the configured chunk when
    it divides S, else the largest divisor of S not above it. Below 32 the
    scan would degenerate into tiny matmuls (prime S) — return 0 (fused
    path) instead. Shared by the GPT and BERT loss functions."""
    if not chunk or S <= chunk:
        return 0
    if S % chunk:
        chunk = next(c for c in range(min(chunk, S), 0, -1) if S % c == 0)
        if chunk < 32:
            return 0
    return chunk


def layer_norm(x, scale, bias, eps):
    # dispatches through the "kernels" config block: fused Pallas LN on
    # TPU when enabled, else the exact fp32-stats XLA math this function
    # used to inline (fused_blocks._ln_ref)
    from ..ops.pallas import fused_blocks

    return fused_blocks.layer_norm(x, scale, bias, eps)


def layer_norm2(x, scale1, bias1, scale2, bias2, eps):
    """Two layernorms of the SAME input (the NeoX parallel-residual block
    applies ln1 and ln2 both to x): mean/var are computed once and only
    the affine differs — halves the fp32 reduction passes over x in both
    the forward and the backward."""
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return ((y * scale1 + bias1).astype(x.dtype),
            (y * scale2 + bias2).astype(x.dtype))


def rotary_embedding(x, positions, rotary_dims):
    """Apply rotary position embedding to the first rotary_dims of head_dim.

    x: (B, S, H, Dh); positions: (S,) shared across the batch, or (B, S)
    per-row absolute positions (batched cache decode, where rows sit at
    different offsets)."""
    dh = x.shape[-1]
    rot, rest = x[..., :rotary_dims], x[..., rotary_dims:]
    half = rotary_dims // 2
    freq = jnp.exp(
        -math.log(10000.0) * jnp.arange(0, half, dtype=jnp.float32) / half
    )
    angles = positions[..., None].astype(jnp.float32) * freq  # (..., S, half)
    if positions.ndim == 1:
        angles = angles[None]
    cos = jnp.cos(angles)[:, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[:, :, None, :].astype(x.dtype)
    x1, x2 = rot[..., :half], rot[..., half:]
    rotated = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    if rest.shape[-1]:
        return jnp.concatenate([rotated, rest], axis=-1)
    return rotated


def _xla_causal_attention(q, k, v):
    """Reference attention; XLA fuses this well on the MXU. (B,S,H,Dh)."""
    dh = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores / math.sqrt(dh)
    s_q, s_k = q.shape[1], k.shape[1]
    mask = jnp.tril(jnp.ones((s_q, s_k), bool))
    scores = jnp.where(mask[None, None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


_ATTN_IMPLS = ("auto", "pallas", "pallas_interpret", "xla", "ring", "ulysses")


def expand_kv_heads(q, k, v):
    """GQA: repeat K/V heads to match Q's head count (q head i attends to
    kv head i // rep, the HF repeat_kv convention). The projection and the
    decode cache keep the small Hkv; full-H tensors only exist transiently
    for the attention kernels. The decode path avoids even that via a
    grouped einsum (models/generation.py)."""
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return k, v


def causal_attention(q, k, v, impl="auto"):
    if impl not in _ATTN_IMPLS:
        raise ValueError(f"unknown attn_impl {impl!r}; choose from {_ATTN_IMPLS}")
    if impl in ("ring", "ulysses"):
        raise ValueError(
            f"attn_impl {impl!r} is context-parallel and needs a mesh; use "
            "ops.ring_attention.make_context_parallel_attention (make_gpt "
            "wires it automatically when given a mesh)"
        )
    if impl in ("auto", "pallas", "pallas_interpret"):
        from ..ops.pallas.flash_attention import (attention_dispatch,
                                                  flash_attention,
                                                  is_available)

        if impl == "pallas_interpret":  # CPU testing path
            return flash_attention(q, k, v, causal=True, interpret=True)
        # auto avoids flash at short S: its per-(batch, head, q-block)
        # dynamic k-loop overhead beats the compute there and XLA's
        # batched-GEMM scores path is faster (hardware-measured at S<=256)
        # — unless the "kernels" config routes the geometry to the dense
        # super-tile kernel, which packs short sequences into MXU-sized
        # tiles and beats the batched-GEMM path
        B, S, H, Dh = q.shape
        supertile = attention_dispatch(
            (B, H, S, Dh), q.dtype.itemsize, causal=True
        ) == "supertile"
        if impl == "pallas" or supertile or (is_available(q) and S > 256):
            return flash_attention(q, k, v, causal=True)
    return _xla_causal_attention(q, k, v)


# ------------------------------------------------------------------ #
# forward
# ------------------------------------------------------------------ #


def _shard_act(x, mesh, spec):
    if mesh is None:
        return x
    from jax.sharding import NamedSharding

    from ..sharding.rules import translate_spec

    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, translate_spec(spec, mesh))
    )


def decoder_block(cfg: GPTConfig, mesh, x, layer_params, positions, attend,
                  mlp_fn=None):
    """One decoder layer shared by training (make_gpt) and KV-cache decoding
    (models/generation.py): qkv projection, rotary, residual/MLP wiring.

    ``attend(q, k, v) -> (ctx, aux)`` supplies the attention core — dense /
    flash / context-parallel for training, cache-updating for decode.
    ``mlp_fn(mlp_in) -> (mlp_out, moe_aux_or_None)`` overrides the dense FFN
    (the MoE hook). Returns (x_out, aux) — with an mlp_fn override, aux is
    (attend_aux, moe_aux)."""
    cdt = cfg.dtype
    B, S, D = x.shape
    H, Dh = cfg.n_head, cfg.head_dim
    mlp_in_shared = None
    if cfg.parallel_residual:
        # ln1(x) and ln2(x) normalize the SAME x — share the mean/var pass
        attn_in, mlp_in_shared = layer_norm2(
            x, layer_params["ln1_scale"], layer_params["ln1_bias"],
            layer_params["ln2_scale"], layer_params["ln2_bias"],
            cfg.layernorm_eps,
        )
    else:
        attn_in = layer_norm(
            x, layer_params["ln1_scale"], layer_params["ln1_bias"],
            cfg.layernorm_eps,
        )
    qkv = attn_in @ layer_params["attn"]["wqkv"].astype(cdt) + layer_params[
        "attn"
    ]["bqkv"].astype(cdt)
    Hkv = cfg.kv_heads
    q = qkv[..., : H * Dh].reshape(B, S, H, Dh)
    k = qkv[..., H * Dh: (H + Hkv) * Dh].reshape(B, S, Hkv, Dh)
    v = qkv[..., (H + Hkv) * Dh:].reshape(B, S, Hkv, Dh)
    if cfg.rotary:
        rd = int(cfg.rotary_pct * Dh) // 2 * 2
        q = rotary_embedding(q, positions, rd)
        k = rotary_embedding(k, positions, rd)
    # named for selective remat (remat_policy='matmuls'): saving the
    # post-rotary q/k/v lets the backward skip the qkv projection+rotary
    q = checkpoint_name(q, "attn_q")
    k = checkpoint_name(k, "attn_k")
    v = checkpoint_name(v, "attn_v")
    ctx, aux = attend(q, k, v)
    attn = ctx.reshape(B, S, D)
    attn_out = attn @ layer_params["attn"]["wo"].astype(cdt) + layer_params[
        "attn"
    ]["bo"].astype(cdt)

    if cfg.parallel_residual:
        # NeoX: x + attn(ln1(x)) + mlp(ln2(x)); mlp_in computed above in
        # the shared-normalization pass
        mlp_in = mlp_in_shared
    else:
        x = x + attn_out
        mlp_in = layer_norm(
            x, layer_params["ln2_scale"], layer_params["ln2_bias"], cfg.layernorm_eps
        )
    if mlp_fn is not None:
        mlp_out, moe_aux = mlp_fn(mlp_in)
        aux = (aux, moe_aux)
    else:
        from ..ops.pallas.fused_blocks import bias_gelu

        h = mlp_in @ layer_params["mlp"]["wi"].astype(cdt)
        # pre-gelu: saving it skips the ffn-in matmul recompute while the
        # bias+gelu stays cheap to replay (saved pre-bias so the fused
        # kernel owns the add)
        h = checkpoint_name(h, "mlp_pre")
        h = bias_gelu(h, layer_params["mlp"]["bi"].astype(cdt),
                      approximate=True)
        h = _shard_act(h, mesh, P(DATA_AXIS, SEQ_AXIS, MODEL_AXIS))
        mlp_out = h @ layer_params["mlp"]["wo"].astype(cdt) + layer_params[
            "mlp"
        ]["bo"].astype(cdt)

    if cfg.parallel_residual:
        x = x + attn_out + mlp_out
    else:
        x = x + mlp_out
    x = _shard_act(x, mesh, P(DATA_AXIS, SEQ_AXIS, None))
    return x, aux


def make_gpt(cfg: GPTConfig, mesh=None):
    """Returns (init_fn, apply_fn, loss_fn, specs).

    apply_fn(params, tokens) -> logits (B, S, V)
    loss_fn(params, batch) with batch = tokens (B, S+1) or (inputs, targets)
    """

    cp_attend = None
    if cfg.attn_impl in ("ring", "ulysses"):
        if mesh is None:
            raise ValueError(
                f"attn_impl={cfg.attn_impl!r} is a context-parallel strategy "
                "and needs a mesh with a 'seq' axis; pass mesh= to make_gpt"
            )
        from ..ops.ring_attention import make_context_parallel_attention

        # raises if the mesh has no usable 'seq' axis — never silently dense
        cp_attend = make_context_parallel_attention(
            mesh, strategy=cfg.attn_impl, causal=True
        )

    def attend(q, k, v):
        k, v = expand_kv_heads(q, k, v)
        q = _shard_act(q, mesh, P(DATA_AXIS, SEQ_AXIS, MODEL_AXIS, None))
        k = _shard_act(k, mesh, P(DATA_AXIS, SEQ_AXIS, MODEL_AXIS, None))
        v = _shard_act(v, mesh, P(DATA_AXIS, SEQ_AXIS, MODEL_AXIS, None))
        if cp_attend is not None:
            return cp_attend(q, k, v), None
        return causal_attention(q, k, v, impl=cfg.attn_impl), None

    moe_cfg = cfg.moe

    def block(carry, layer_params, positions):
        """-> (x, this layer's scalar moe auxiliary loss; 0 when dense)."""
        if moe_cfg is None:
            x, _ = decoder_block(cfg, mesh, carry, layer_params, positions,
                                 attend)
            return x, jnp.float32(0.0)
        from .moe import moe_ffn, moe_loss

        def mlp_fn(mlp_in):
            return moe_ffn(layer_params["moe"], mlp_in, moe_cfg, mesh=mesh)

        x, (_, moe_aux) = decoder_block(cfg, mesh, carry, layer_params,
                                        positions, attend, mlp_fn=mlp_fn)
        return x, moe_loss(moe_aux, moe_cfg)

    def hidden_fn(params, tokens):
        """tokens (B, S) int32 -> (final-layernormed hidden states (B, S, D),
        summed moe auxiliary loss — 0.0 for dense models)."""
        cdt = cfg.dtype
        B, S = tokens.shape
        wte = params["embed"]["wte"].astype(cdt)
        x = jnp.take(wte, tokens, axis=0)  # (B, S, D)
        positions = jnp.arange(S, dtype=jnp.int32)
        if not cfg.rotary:
            x = x + params["embed"]["wpe"][:S].astype(cdt)
        x = _shard_act(x, mesh, P(DATA_AXIS, SEQ_AXIS, None))

        step = partial(block, positions=positions)
        if cfg.remat:
            policy = {
                "full": None,
                "flash": jax.checkpoint_policies.save_only_these_names(
                    "flash_o", "flash_lse"
                ),
                "matmuls": jax.checkpoint_policies.save_only_these_names(
                    "flash_o", "flash_lse", "attn_q", "attn_k", "attn_v",
                    "mlp_pre"
                ),
                "dots": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                "dots_all": jax.checkpoint_policies.dots_saveable,
            }[cfg.remat_policy]
            step = jax.checkpoint(step, prevent_cse=False, policy=policy)

        def scan_body(carry, xs):
            x, aux_sum = carry
            layer_params, layer_idx = xs
            out, layer_aux = step(x, layer_params)
            # cooperative layer-output tap (engine.register_forward_hook);
            # identity unless a collector is active at trace time
            out = hooks.record_layer_output("transformerlayer", out, layer_idx)
            return (out, aux_sum + layer_aux), None

        layer_ids = jnp.arange(cfg.n_layer, dtype=jnp.int32)
        (x, moe_aux), _ = jax.lax.scan(
            scan_body, (x, jnp.float32(0.0)), (params["layers"], layer_ids)
        )
        x = layer_norm(
            x, params["final_ln"]["scale"], params["final_ln"]["bias"], cfg.layernorm_eps
        )
        return x, moe_aux

    def head_weight(params):
        cdt = cfg.dtype
        if cfg.tie_embeddings:
            return params["embed"]["wte"].astype(cdt).T
        return params["lm_head"].astype(cdt)

    def apply_fn(params, tokens):
        """tokens (B, S) int32 -> logits (B, S, V)."""
        return hidden_fn(params, tokens)[0] @ head_weight(params)

    def loss_fn(params, batch):
        """batch: (inputs, targets) int (B, S) each, or tokens (B, S+1)."""
        if isinstance(batch, (tuple, list)):
            inputs, targets = batch
        else:
            inputs, targets = batch[:, :-1], batch[:, 1:]
        x, moe_aux = hidden_fn(params, inputs)
        w = head_weight(params)
        B, S, D = x.shape
        chunk = pick_ce_chunk(S, cfg.ce_chunk)
        if chunk and S > chunk:
            # stream the cross-entropy over sequence chunks: the (B, S, V)
            # logits are never materialized. Each chunk's logits are
            # recomputed in the backward (one extra head matmul) in exchange
            # for GBs of saved HBM — this is what unlocks large micro-batches
            # (the reference's fp16 fused softmax-xent serves the same role,
            # csrc/transformer/softmax_kernels.cu)
            n = S // chunk
            xs = jnp.moveaxis(x.reshape(B, n, chunk, D), 1, 0)
            ts = jnp.moveaxis(targets.reshape(B, n, chunk), 1, 0)

            @jax.checkpoint
            def chunk_nll(xc, tc):
                logits = (xc @ w).astype(jnp.float32)  # (B, chunk, V)
                lse = jax.scipy.special.logsumexp(logits, axis=-1)
                tgt = jnp.take_along_axis(logits, tc[..., None], axis=-1)[..., 0]
                return jnp.sum(lse - tgt)

            def body(acc, xt):
                return acc + chunk_nll(*xt), None

            total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), (xs, ts))
            return total / (B * S) + moe_aux
        logits = (x @ w).astype(jnp.float32)
        # nll = logsumexp - target_logit, WITHOUT materializing the fp32
        # log-softmax over the full (B, S, V) tensor (pure HBM traffic)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
        return jnp.mean(lse - tgt) + moe_aux

    def init_fn(rng):
        return init_params(rng, cfg)

    return init_fn, apply_fn, loss_fn, param_specs(cfg)


def params_from_hf(model, cfg: Optional[GPTConfig] = None):
    """Import a huggingface GPT2LMHeadModel/GPT2Model checkpoint into the
    stacked param pytree (the GPT-family counterpart of
    bert.params_from_hf), giving bit-compatible fine-tuning starts.

    HF GPT-2's Conv1D weights are already (in, out), matching this module's
    layout; c_attn's fused q|k|v column order matches the wqkv split.
    Returns (cfg, params) with tie_embeddings=True (HF GPT-2 ties lm_head
    to wte)."""
    from ..ops.transformer.transformer import to_numpy_f32

    def f32(t):
        return jnp.asarray(to_numpy_f32(t))

    gpt2 = getattr(model, "transformer", model)
    hf_cfg = model.config
    if cfg is None:
        cfg = GPTConfig(
            vocab_size=hf_cfg.vocab_size,
            n_layer=hf_cfg.n_layer,
            n_head=hf_cfg.n_head,
            d_model=hf_cfg.n_embd,
            max_seq=hf_cfg.n_positions,
            rotary=False,
            parallel_residual=False,
            tie_embeddings=True,
            layernorm_eps=hf_cfg.layer_norm_epsilon,
            dtype=jnp.float32,
        )
    if cfg.rotary or cfg.parallel_residual:
        raise ValueError(
            "HF GPT-2 is learned-position + serial-residual; pass a "
            "matching cfg"
        )
    if (cfg.kv_heads != cfg.n_head or cfg.n_head != hf_cfg.n_head
            or cfg.d_model != hf_cfg.n_embd or cfg.n_layer != hf_cfg.n_layer):
        raise ValueError(
            f"cfg (layers={cfg.n_layer}, d={cfg.d_model}, heads="
            f"{cfg.n_head}, kv_heads={cfg.kv_heads}) does not match the HF "
            f"checkpoint (layers={hf_cfg.n_layer}, d={hf_cfg.n_embd}, "
            f"heads={hf_cfg.n_head}, MHA) — GQA cannot import MHA weights"
        )

    blocks = list(gpt2.h)
    stack = lambda ts: jnp.stack([f32(t) for t in ts])
    params = {
        "embed": {
            "wte": f32(gpt2.wte.weight),
            "wpe": f32(gpt2.wpe.weight),
        },
        "layers": {
            "ln1_scale": stack([b.ln_1.weight for b in blocks]),
            "ln1_bias": stack([b.ln_1.bias for b in blocks]),
            "ln2_scale": stack([b.ln_2.weight for b in blocks]),
            "ln2_bias": stack([b.ln_2.bias for b in blocks]),
            "attn": {
                "wqkv": stack([b.attn.c_attn.weight for b in blocks]),
                "bqkv": stack([b.attn.c_attn.bias for b in blocks]),
                "wo": stack([b.attn.c_proj.weight for b in blocks]),
                "bo": stack([b.attn.c_proj.bias for b in blocks]),
            },
            "mlp": {
                "wi": stack([b.mlp.c_fc.weight for b in blocks]),
                "bi": stack([b.mlp.c_fc.bias for b in blocks]),
                "wo": stack([b.mlp.c_proj.weight for b in blocks]),
                "bo": stack([b.mlp.c_proj.bias for b in blocks]),
            },
        },
        "final_ln": {
            "scale": f32(gpt2.ln_f.weight),
            "bias": f32(gpt2.ln_f.bias),
        },
    }
    return cfg, params


# convenience presets ------------------------------------------------- #

PRESETS = {
    "gpt2-125m": GPTConfig(n_layer=12, n_head=12, d_model=768, rotary=False,
                           parallel_residual=False),
    "gpt2-350m": GPTConfig(n_layer=24, n_head=16, d_model=1024, rotary=False,
                           parallel_residual=False),
    "neox-125m": GPTConfig(n_layer=12, n_head=12, d_model=768),
    "neox-1.3b": GPTConfig(n_layer=24, n_head=16, d_model=2048),
    "neox-6.7b": GPTConfig(n_layer=32, n_head=32, d_model=4096),
    "neox-20b": GPTConfig(
        n_layer=44, n_head=64, d_model=6144, d_ff=24576, vocab_size=50432,
        rotary_pct=0.25,
    ),
}


def get_preset(name: str, **overrides) -> GPTConfig:
    cfg = PRESETS[name]
    return dataclasses.replace(cfg, **overrides) if overrides else cfg
