"""BERT encoder family, TPU-native.

The reference framework's headline results are BERT pretraining (SURVEY §6:
64 TFLOPS/GPU seq128 — docs/_posts/2020-05-28-fastest-bert-training.md) and
its kernel tests compare against HF BERT layers (tests/unit/modeling.py).
This module is the rebuild's BERT: embeddings + a scan over fused
transformer layers (ops/transformer) + pooler + tied MLM head.

Design mirrors models/gpt.py: params are a pytree with per-layer tensors
stacked on a leading axis so the encoder is one `lax.scan` (O(1) compile in
depth, per-layer gather under ZeRO-3), remat per layer, TP/sequence sharding
via PartitionSpecs over the same mesh axes.

`params_from_hf(model)` imports a huggingface BertModel checkpoint wholesale
(embeddings + every layer via module_inject), giving bit-compatible
fine-tuning starts.
"""

import dataclasses
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..ops.transformer import DeepSpeedTransformerConfig, init_transformer_params
from ..ops.transformer.transformer import (
    _layer_norm,
    _transformer_forward,
    to_numpy_f32,
)
from ..parallel.topology import DATA_AXIS, MODEL_AXIS, SEQ_AXIS
from .gpt import _shard_act, pick_ce_chunk
from ..utils import hooks


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    d_ff: int = 0  # 0 => 4 * d_model
    max_seq: int = 512
    type_vocab_size: int = 2
    layernorm_eps: float = 1e-12
    initializer_range: float = 0.02
    pre_layer_norm: bool = False  # classic BERT is post-LN
    remat: bool = True
    # 'full' recomputes the whole layer in backward (min memory, ~+33%
    # matmul flops); 'matmuls' saves the qkv / attention-ctx / pre-gelu
    # matmul outputs so only the elementwise tail recomputes — the same
    # selective policy the GPT flagship benches with (gpt.py remat_policy)
    remat_policy: str = "full"
    dtype: Any = jnp.bfloat16
    attn_impl: str = "auto"
    attn_dropout: float = 0.0
    hidden_dropout: float = 0.0
    # MLM-loss sequence chunk (streaming CE, no (B,S,V) fp32 logits);
    # 0 disables chunking
    ce_chunk: int = 64
    # when > 0, the MLM head runs only on scored positions: the (B*S)
    # hidden rows are stably ordered scored-first and the head consumes the
    # first ceil(frac*B*S) (lane-aligned) rows — at 15% masking the
    # vocab-width matmul drops ~4x in flops. frac must upper-bound the true
    # scored fraction: positions past the cut are silently unscored (the
    # loss normalizer counts only gathered positions), so keep a margin
    # (0.25 for standard 15% MLM). 0 = score every position (exact).
    mlm_gather_frac: float = 0.0

    def __post_init__(self):
        if self.remat_policy not in ("full", "matmuls", "dots_all"):
            raise ValueError(
                f"remat_policy must be 'full', 'matmuls' or 'dots_all', "
                f"got {self.remat_policy!r}")
        if not 0.0 <= self.mlm_gather_frac <= 1.0:
            raise ValueError("mlm_gather_frac must be in [0, 1]")

    @property
    def ffn_dim(self):
        return self.d_ff if self.d_ff else 4 * self.d_model

    def layer_config(self) -> DeepSpeedTransformerConfig:
        return DeepSpeedTransformerConfig(
            batch_size=-1,
            max_seq_length=self.max_seq,
            hidden_size=self.d_model,
            intermediate_size=self.ffn_dim,
            heads=self.n_head,
            attn_dropout_ratio=self.attn_dropout,
            hidden_dropout_ratio=self.hidden_dropout,
            num_hidden_layers=self.n_layer,
            initializer_range=self.initializer_range,
            fp16=self.dtype == jnp.bfloat16,
            pre_layer_norm=self.pre_layer_norm,
            layernorm_eps=self.layernorm_eps,
            attn_impl=self.attn_impl,
        )


def init_params(rng, cfg: BertConfig):
    ks = jax.random.split(rng, cfg.n_layer + 5)
    std = cfg.initializer_range
    f32 = jnp.float32
    layer_cfg = cfg.layer_config()
    per_layer = [init_transformer_params(ks[i], layer_cfg)
                 for i in range(cfg.n_layer)]
    layers = {k: jnp.stack([p[k] for p in per_layer]) for k in per_layer[0]}
    D = cfg.d_model
    return {
        "embed": {
            "word": jax.random.normal(ks[-4], (cfg.vocab_size, D), f32) * std,
            "pos": jax.random.normal(ks[-3], (cfg.max_seq, D), f32) * std,
            "type": jax.random.normal(ks[-2], (cfg.type_vocab_size, D), f32) * std,
            "ln_w": jnp.ones((D,), f32),
            "ln_b": jnp.zeros((D,), f32),
        },
        "layers": layers,
        "pooler": {
            "w": jax.random.normal(ks[-1], (D, D), f32) * std,
            "b": jnp.zeros((D,), f32),
        },
        "mlm": {  # transform dense + LN; decoder tied to word embeddings
            "w": jax.random.normal(ks[-5], (D, D), f32) * std,
            "b": jnp.zeros((D,), f32),
            "ln_w": jnp.ones((D,), f32),
            "ln_b": jnp.zeros((D,), f32),
            "bias": jnp.zeros((cfg.vocab_size,), f32),
        },
    }


def param_specs(cfg: BertConfig):
    """TP sharding over the 'model' axis, matching gpt.param_specs: QKV/FFN
    columns sharded, output rows sharded, embeddings vocab-sharded."""
    from jax.sharding import PartitionSpec as P

    L = P  # brevity
    return {
        # word embedding sharded over d_model, not vocab — XLA's gather from
        # a vocab-sharded table falls back to full replication (see the same
        # note in gpt.param_specs)
        "embed": {"word": L(None, MODEL_AXIS), "pos": L(), "type": L(),
                  "ln_w": L(), "ln_b": L()},
        "layers": {
            "attn_qkvw": L(None, None, MODEL_AXIS),
            "attn_qkvb": L(None, MODEL_AXIS),
            "attn_ow": L(None, MODEL_AXIS, None),
            "attn_ob": L(None, None),
            "attn_nw": L(None, None), "attn_nb": L(None, None),
            "inter_w": L(None, None, MODEL_AXIS),
            "inter_b": L(None, MODEL_AXIS),
            "output_w": L(None, MODEL_AXIS, None),
            "output_b": L(None, None),
            "norm_w": L(None, None), "norm_b": L(None, None),
        },
        "pooler": {"w": L(), "b": L()},
        "mlm": {"w": L(), "b": L(), "ln_w": L(), "ln_b": L(),
                "bias": L()},
    }


def make_bert(cfg: BertConfig, mesh=None):
    """Returns (init_fn, apply_fn, mlm_loss_fn, specs).

    apply_fn(params, input_ids, token_type_ids=None, attention_mask=None)
        -> (sequence_output, pooled_output)
    mlm_loss_fn(params, batch) with batch = (input_ids, labels) where
        labels == -100 marks unscored positions (HF convention).
    """
    layer_cfg = cfg.layer_config()

    def apply_fn(params, input_ids, token_type_ids=None, attention_mask=None,
                 rng=None):
        cdt = cfg.dtype
        B, S = input_ids.shape
        e = params["embed"]
        x = jnp.take(e["word"].astype(cdt), input_ids, axis=0)
        x = x + e["pos"][:S].astype(cdt)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = x + jnp.take(e["type"].astype(cdt), token_type_ids, axis=0)
        x = _layer_norm(x, e["ln_w"].astype(cdt), e["ln_b"].astype(cdt),
                        cfg.layernorm_eps)
        # context-parallel long sequences: activations sharded over the
        # 'seq' axis (as in make_gpt)
        from jax.sharding import PartitionSpec as P

        x = _shard_act(x, mesh, P(DATA_AXIS, SEQ_AXIS, None))

        additive = None
        if attention_mask is not None:
            additive = (1.0 - attention_mask[:, None, None, :].astype(jnp.float32)) * -1e4

        def block(h, layer_params, layer_rng):
            return _transformer_forward(layer_params, h, layer_cfg,
                                        attention_mask=additive,
                                        rng=layer_rng)

        if cfg.remat:
            policy = {
                "full": None,
                "matmuls": jax.checkpoint_policies.save_only_these_names(
                    "bert_qkv", "bert_ctx", "bert_mlp_pre"
                ),
                # save every dot output: the backward replays only
                # elementwise ops (no matmul recompute) at far less
                # memory than remat=False, which misses HBM by ~16MB at
                # the mb64/seq128 bench point
                "dots_all": jax.checkpoint_policies.dots_saveable,
            }[cfg.remat_policy]
            step = jax.checkpoint(block, prevent_cse=False, policy=policy)
        else:
            step = block

        def scan_body(carry, xs):
            layer_params, idx = xs
            layer_rng = None if rng is None else jax.random.fold_in(rng, idx)
            out = step(carry, layer_params, layer_rng)
            out = hooks.record_layer_output("bertlayer", out, idx)
            return out, None

        layer_ids = jnp.arange(cfg.n_layer, dtype=jnp.int32)
        x, _ = jax.lax.scan(scan_body, x, (params["layers"], layer_ids))

        pooled = jnp.tanh(x[:, 0] @ params["pooler"]["w"].astype(cdt)
                          + params["pooler"]["b"].astype(cdt))
        return x, pooled

    def mlm_logits(params, sequence_output):
        cdt = cfg.dtype
        m = params["mlm"]
        from ..ops.pallas.fused_blocks import bias_gelu

        h = bias_gelu(sequence_output @ m["w"].astype(cdt),
                      m["b"].astype(cdt), approximate=False)
        h = _layer_norm(h, m["ln_w"], m["ln_b"], cfg.layernorm_eps)
        return h @ params["embed"]["word"].astype(cdt).T + m["bias"].astype(cdt)

    def _chunk_nll(params, seq_chunk, labels_chunk):
        """Masked-LM nll over one sequence chunk WITHOUT materializing the
        fp32 log-softmax (nll = logsumexp - target logit); rematerialized in
        the backward — the same streaming trick as gpt.py's chunked CE (the
        reference's fused fp16 softmax-xent kernel served this role,
        csrc/transformer/softmax_kernels.cu)."""
        logits = mlm_logits(params, seq_chunk).astype(jnp.float32)
        valid = labels_chunk != -100
        safe = jnp.where(valid, labels_chunk, 0)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
        nll = lse - tgt
        return jnp.sum(jnp.where(valid, nll, 0.0)), jnp.sum(valid)

    def mlm_loss_fn(params, batch, rng=None):
        input_ids, labels = batch[0], batch[1]
        attention_mask = batch[2] if len(batch) > 2 else None
        seq_out, _ = apply_fn(params, input_ids, attention_mask=attention_mask,
                              rng=rng)
        B, S, D = seq_out.shape
        if cfg.mlm_gather_frac:
            # run the vocab-width head only on scored positions: stable
            # argsort orders scored rows first, the head consumes a
            # lane-aligned prefix (see mlm_gather_frac docstring for the
            # upper-bound contract)
            BS = B * S
            K = min(BS, int(math.ceil(cfg.mlm_gather_frac * BS / 128)) * 128)
            flat_lab = labels.reshape(BS)
            n_scored = jnp.sum(flat_lab != -100)
            order = jnp.argsort(flat_lab == -100, stable=True)[:K]
            seq_out = seq_out.reshape(BS, D)[order][None]
            labels = flat_lab[order][None]
            # overflow telemetry (MoE dropped_frac analog): positions past
            # the cut are silently unscored, so surface the count to layer-
            # output collectors instead of hiding it
            hooks.record_layer_output(
                "mlm_dropped", jnp.maximum(n_scored - K, 0))
            B, S = 1, K
        chunk = pick_ce_chunk(S, cfg.ce_chunk)
        if chunk and S > chunk:
            n = S // chunk
            xs = jnp.moveaxis(seq_out.reshape(B, n, chunk, D), 1, 0)
            ls = jnp.moveaxis(labels.reshape(B, n, chunk), 1, 0)
            ck = jax.checkpoint(lambda xc, lc: _chunk_nll(params, xc, lc))

            def body(carry, xt):
                tot, cnt = carry
                t, c = ck(*xt)
                return (tot + t, cnt + c), None

            (total, count), _ = jax.lax.scan(
                body, (jnp.float32(0.0), jnp.int32(0)), (xs, ls)
            )
        else:
            total, count = _chunk_nll(params, seq_out, labels)
        return total / jnp.maximum(count, 1)

    def init_fn(rng):
        return init_params(rng, cfg)

    apply_fn.mlm_logits = mlm_logits
    return init_fn, apply_fn, mlm_loss_fn, param_specs(cfg)


def make_bert_qa(cfg: BertConfig, mesh=None):
    """SQuAD-class span-extraction fine-tuning (the reference's
    BingBertSquad leg: tests/model/BingBertSquad + the 1.5x fine-tune
    claim in docs/_posts/2020-05-28-fastest-bert-training.md:105-121).

    Returns (init_fn, apply_fn, qa_loss_fn, specs). The QA head is the
    standard 2-wide span projection; ``qa_loss_fn(params, batch, rng)``
    takes batch = (input_ids, start_positions, end_positions[,
    attention_mask]) and averages start/end cross-entropy, with the rng
    threading dropout through every layer (fine-tuning runs the 0.1
    dropout the MLM pretraining benches disable)."""
    init_fn, apply_fn, _, specs = make_bert(cfg, mesh=mesh)

    def qa_init_fn(rng):
        k1, k2 = jax.random.split(rng)
        params = init_fn(k1)
        D = cfg.d_model
        params["qa"] = {
            "w": jax.random.normal(k2, (D, 2), jnp.float32)
            * cfg.initializer_range,
            "b": jnp.zeros((2,), jnp.float32),
        }
        return params

    def qa_loss_fn(params, batch, rng=None):
        input_ids, start_pos, end_pos = batch[0], batch[1], batch[2]
        attention_mask = batch[3] if len(batch) > 3 else None
        seq_out, _ = apply_fn(params, input_ids,
                              attention_mask=attention_mask, rng=rng)
        cdt = cfg.dtype
        logits = (seq_out @ params["qa"]["w"].astype(cdt)
                  + params["qa"]["b"].astype(cdt)).astype(jnp.float32)
        if attention_mask is not None:
            logits = jnp.where(attention_mask[..., None] > 0, logits, -1e9)

        def span_nll(lg, pos):
            lse = jax.scipy.special.logsumexp(lg, axis=-1)
            tgt = jnp.take_along_axis(lg, pos[:, None], axis=-1)[:, 0]
            return jnp.mean(lse - tgt)

        return 0.5 * (span_nll(logits[..., 0], start_pos)
                      + span_nll(logits[..., 1], end_pos))

    qa_specs = dict(specs)
    from jax.sharding import PartitionSpec as P

    qa_specs["qa"] = {"w": P(), "b": P()}
    return qa_init_fn, apply_fn, qa_loss_fn, qa_specs


def params_from_hf(model, cfg: Optional[BertConfig] = None):
    """Import a huggingface BertModel/BertForMaskedLM checkpoint into the
    stacked param pytree (embeddings + all layers via module_inject)."""
    from ..module_inject import replace_transformer_layer

    bert = getattr(model, "bert", model)
    hf_cfg = model.config
    if cfg is None:
        cfg = BertConfig(
            vocab_size=hf_cfg.vocab_size,
            n_layer=hf_cfg.num_hidden_layers,
            n_head=hf_cfg.num_attention_heads,
            d_model=hf_cfg.hidden_size,
            d_ff=hf_cfg.intermediate_size,
            max_seq=hf_cfg.max_position_embeddings,
            type_vocab_size=hf_cfg.type_vocab_size,
            layernorm_eps=hf_cfg.layer_norm_eps,
            dtype=jnp.float32,
        )
    _, _, stacked = replace_transformer_layer(model=bert, fp16=False,
                                              attn_impl=cfg.attn_impl)
    emb = bert.embeddings
    params = init_params(jax.random.PRNGKey(0), cfg)
    params["layers"] = stacked
    params["embed"] = {
        "word": jnp.asarray(to_numpy_f32(emb.word_embeddings.weight)),
        "pos": jnp.asarray(to_numpy_f32(emb.position_embeddings.weight)),
        "type": jnp.asarray(to_numpy_f32(emb.token_type_embeddings.weight)),
        "ln_w": jnp.asarray(to_numpy_f32(emb.LayerNorm.weight)),
        "ln_b": jnp.asarray(to_numpy_f32(emb.LayerNorm.bias)),
    }
    if getattr(bert, "pooler", None) is not None:
        params["pooler"] = {
            "w": jnp.asarray(to_numpy_f32(bert.pooler.dense.weight).T),
            "b": jnp.asarray(to_numpy_f32(bert.pooler.dense.bias)),
        }
    # MLM head (BertForMaskedLM / BertForPreTraining: cls.predictions)
    cls = getattr(model, "cls", None)
    predictions = getattr(cls, "predictions", None) if cls is not None else None
    if predictions is not None:
        tr = predictions.transform
        params["mlm"] = {
            "w": jnp.asarray(to_numpy_f32(tr.dense.weight).T),
            "b": jnp.asarray(to_numpy_f32(tr.dense.bias)),
            "ln_w": jnp.asarray(to_numpy_f32(tr.LayerNorm.weight)),
            "ln_b": jnp.asarray(to_numpy_f32(tr.LayerNorm.bias)),
            "bias": jnp.asarray(to_numpy_f32(predictions.decoder.bias)),
        }
    return cfg, params
