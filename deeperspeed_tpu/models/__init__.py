from .gpt import GPTConfig, make_gpt, get_preset
from .bert import BertConfig, make_bert, params_from_hf
from .generation import make_generator, init_cache, apply_with_cache
from .speculative import make_speculative_generator
