"""KV-cache autoregressive generation for the GPT family.

The reference serves generation through the fork's
``PipelineEngine.inference_batch`` (reference runtime/pipe/engine.py:422 —
GPT-NeoX calls it per decoding step, recomputing the whole prefix each
time). The TPU rebuild keeps that API on the pipeline engine and adds the
design the hardware actually wants: a static-shape KV cache updated with
``dynamic_update_slice`` and a ``lax.scan`` over decode steps, so the whole
generate loop is ONE compiled program (no per-token dispatch, no prefix
recompute).

Usage::

    gen = make_generator(cfg)          # cfg: models.gpt.GPTConfig
    out = gen(params, prompt_ids, max_new_tokens=64,
              temperature=1.0, top_k=40, rng=key)   # (B, S+64) tokens

temperature=0 (default) is greedy argmax. The prompt is prefilled in one
pass; decode steps attend to the cache only.
"""

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from .gpt import GPTConfig, decoder_block, layer_norm


def init_cache(cfg: GPTConfig, batch: int, max_len: int):
    """Stacked per-layer KV cache: (L, B, max_len, Hkv, Dh) — GQA/MQA
    models cache only their n_kv_head heads (n_head/n_kv_head x smaller)."""
    shape = (cfg.n_layer, batch, max_len, cfg.kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
    }


def _cached_block(cfg: GPTConfig, x, layer_params, k_cache, v_cache,
                  offset, positions):
    """One decoder layer over S new tokens with a KV cache.

    x: (B, S, D); k/v_cache: (B, max_len, Hkv, Dh) — n_kv_head heads for
    GQA/MQA models; offset: scalar — number of tokens already cached.
    Returns (x_out, k_cache, v_cache). The layer math is gpt.decoder_block;
    only the attention core differs (cache update + absolute-position
    masking)."""
    cdt = cfg.dtype
    Dh = cfg.head_dim
    B_, S = x.shape[0], x.shape[1]

    vec = jnp.ndim(offset) == 1  # per-row offsets (batched speculative)

    def attend(q, k, v):
        if vec:
            # per-row write positions: scatter each row's S new entries at
            # its own offset
            rows = jnp.arange(B_, dtype=jnp.int32)[:, None]
            cols = offset[:, None] + jnp.arange(S, dtype=jnp.int32)[None]
            k_c = k_cache.at[rows, cols].set(k.astype(cdt))
            v_c = v_cache.at[rows, cols].set(v.astype(cdt))
        else:
            k_c = jax.lax.dynamic_update_slice(
                k_cache, k.astype(cdt), (0, offset, 0, 0)
            )
            v_c = jax.lax.dynamic_update_slice(
                v_cache, v.astype(cdt), (0, offset, 0, 0)
            )
        # grouped attention: q heads fold to (Hkv, rep) so the cached K/V
        # are read at their small Hkv width — no materialized repeat (the
        # HBM reads of K/V dominate decode cost)
        Hq = q.shape[2]
        rep = Hq // k_c.shape[2]
        qg = q.reshape(B_, S, k_c.shape[2], rep, Dh)
        scores = jnp.einsum("bqhrd,bkhd->bhrqk", qg, k_c,
                            preferred_element_type=jnp.float32)
        scores = scores / math.sqrt(Dh)
        key_pos = jnp.arange(k_c.shape[1])
        q_pos = (offset[:, None] if vec else offset) + jnp.arange(S)
        valid = key_pos[None, None, :] <= jnp.reshape(
            q_pos, (-1, S))[:, :, None]  # (B|1, S, max_len)
        scores = jnp.where(valid[:, None, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cdt)
        ctx = jnp.einsum("bhrqk,bkhd->bqhrd", probs, v_c)
        ctx = ctx.reshape(B_, S, Hq, Dh)
        return ctx, (k_c, v_c)

    moe_cfg = cfg.moe
    if moe_cfg is not None:
        from .moe import moe_ffn

        def mlp_fn(mlp_in):
            return moe_ffn(layer_params["moe"], mlp_in, moe_cfg)

        x, ((k_cache, v_cache), _) = decoder_block(
            cfg, None, x, layer_params, positions, attend, mlp_fn=mlp_fn
        )
    else:
        x, (k_cache, v_cache) = decoder_block(cfg, None, x, layer_params,
                                              positions, attend)
    return x, k_cache, v_cache


def apply_with_cache(cfg: GPTConfig, params, tokens, cache, offset):
    """Process S tokens given `offset` already-cached ones. Returns
    (logits (B, S, V), updated cache). ``offset`` is a scalar, or an (B,)
    int vector of PER-ROW offsets (batched speculative decoding, where
    rows accept different draft lengths and their caches desynchronize)."""
    cdt = cfg.dtype
    B, S = tokens.shape
    if (not cfg.rotary and isinstance(offset, int)
            and offset + S > cfg.max_seq):
        # (traced offsets are guarded at the generate() boundary instead)
        raise ValueError(
            f"offset ({offset}) + tokens ({S}) exceeds max_seq "
            f"({cfg.max_seq}): the learned-position table cannot extrapolate"
        )
    wte = params["embed"]["wte"].astype(cdt)
    x = jnp.take(wte, tokens, axis=0)
    if jnp.ndim(offset) == 1:
        positions = offset[:, None] + jnp.arange(S, dtype=jnp.int32)[None]
    else:
        positions = offset + jnp.arange(S, dtype=jnp.int32)
    if not cfg.rotary:
        x = x + jnp.take(params["embed"]["wpe"], positions, axis=0
                         ).astype(cdt).reshape((-1, S, cfg.d_model))

    def scan_body(carry, xs):
        x = carry
        layer_params, k_c, v_c = xs
        x, k_c, v_c = _cached_block(cfg, x, layer_params, k_c, v_c,
                                    offset, positions)
        return x, (k_c, v_c)

    x, (k_new, v_new) = jax.lax.scan(
        scan_body, x, (params["layers"], cache["k"], cache["v"])
    )
    x = layer_norm(x, params["final_ln"]["scale"], params["final_ln"]["bias"],
                   cfg.layernorm_eps)
    if cfg.tie_embeddings:
        logits = x @ params["embed"]["wte"].astype(cdt).T
    else:
        logits = x @ params["lm_head"].astype(cdt)
    return logits, {"k": k_new, "v": v_new}


def prep_sampling_logits(logits, temperature, top_k):
    """Shared sampling transform: fp32 temperature divide + top-k filter.
    One implementation serves make_generator AND the speculative decoder
    (whose draft/target distributions must be filtered identically)."""
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -1e30, logits)
    return logits


def _select_next(logits, temperature, top_k, rng):
    """logits (B, V) -> next token (B,). temperature<=0 = greedy."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = prep_sampling_logits(logits, temperature, top_k)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def make_generator(cfg: GPTConfig):
    """Build a jitted generate(params, prompt, max_new_tokens, ...) fn."""

    @partial(jax.jit, static_argnames=("max_new_tokens", "temperature", "top_k"))
    def generate(params, prompt, max_new_tokens: int, temperature: float = 0.0,
                 top_k: Optional[int] = None, rng=None):
        B, S = prompt.shape
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        max_len = S + max_new_tokens
        if not cfg.rotary and max_len > cfg.max_seq:
            raise ValueError(
                f"prompt ({S}) + max_new_tokens ({max_new_tokens}) exceeds "
                f"max_seq ({cfg.max_seq}) — learned position embeddings "
                "cannot extrapolate (the wpe slice would clamp silently)"
            )
        if rng is None:
            rng = jax.random.PRNGKey(0)
        cache = init_cache(cfg, B, max_len)
        logits, cache = apply_with_cache(cfg, params, prompt, cache, 0)
        rng, sub = jax.random.split(rng)
        next_tok = _select_next(logits[:, -1], temperature, top_k, sub)

        def body(carry, _):
            tok, cache, offset, rng = carry
            logits, cache = apply_with_cache(
                cfg, params, tok[:, None], cache, offset
            )
            rng, sub = jax.random.split(rng)
            nxt = _select_next(logits[:, -1], temperature, top_k, sub)
            return (nxt, cache, offset + 1, rng), tok

        (last, _, _, _), toks = jax.lax.scan(
            body, (next_tok, cache, jnp.int32(S), rng), None,
            length=max_new_tokens - 1,
        )
        generated = jnp.concatenate(
            [jnp.swapaxes(toks, 0, 1), last[:, None]], axis=1
        )
        return jnp.concatenate([prompt, generated], axis=1)

    return generate
