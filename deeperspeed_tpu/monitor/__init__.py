"""Unified telemetry: structured step tracing, a recompile watchdog, and
a Prometheus metrics endpoint.

One ``Monitor`` object owns the three legs:

  * ``tracer``   — thread-safe Chrome-trace recorder (bounded ring);
    installed as the process-global tracer so ``trace_span("fwd")``
    works from every subsystem (engine, pipeline, offload, serving).
  * ``watchdog`` — counts jit-cache growth per watched hot function and
    fires (warn or raise) when one recompiles after warmup.
  * ``registry`` — counters/gauges/histograms, served at ``/metrics``
    in Prometheus exposition format and exportable through
    ``TensorBoardMonitor``.

Lifecycle: ``init_monitor(config)`` builds + installs the process-global
monitor (engines pick it up automatically); ``shutdown_monitor()`` saves
the trace (if ``trace_path`` is set), stops the endpoint, and uninstalls.
An ``atexit`` hook guarantees the trace file exists even when a run
crashes. Everything is off by default: with no monitor installed,
``trace_span`` is a shared no-op and the engines' telemetry branches cost
one ``is None`` check.
"""

import atexit
import os
from typing import Optional, Union

from ..utils.logging import logger
from .config import MonitorConfig
from .flight import FlightRecorder
from .metrics import (
    MetricsRegistry,
    MetricsServer,
    export_to_tensorboard,
)
from .memwatch import MemWatch, aggregate_memory_stats, device_memory_stats
from .perf import (
    CompiledCostIndex,
    extract_cost_analysis,
    extract_memory_analysis,
    platform_peaks,
)
from .runctx import RunContext, current as current_run_context, ensure_run_id
from .tracer import (
    Tracer,
    get_tracer,
    set_tracer,
    trace_counter,
    trace_instant,
    trace_span,
)
from .validate import validate_events, validate_file
from .watchdog import RecompileError, RecompileWatchdog

__all__ = [
    "Monitor",
    "MonitorConfig",
    "MetricsRegistry",
    "MetricsServer",
    "Tracer",
    "FlightRecorder",
    "RunContext",
    "RecompileError",
    "RecompileWatchdog",
    "CompiledCostIndex",
    "MemWatch",
    "aggregate_memory_stats",
    "device_memory_stats",
    "extract_cost_analysis",
    "extract_memory_analysis",
    "platform_peaks",
    "current_run_context",
    "ensure_run_id",
    "export_to_tensorboard",
    "get_monitor",
    "init_monitor",
    "shutdown_monitor",
    "get_tracer",
    "set_tracer",
    "trace_span",
    "trace_instant",
    "trace_counter",
    "validate_events",
    "validate_file",
]


class Monitor:
    """Tracer + watchdog + metrics registry/endpoint under one config."""

    def __init__(self, config: Union[MonitorConfig, dict, None] = None):
        cfg = (config if isinstance(config, MonitorConfig)
               else MonitorConfig.from_dict(config))
        self.config = cfg
        self.run_context = current_run_context()
        trace_path, flight_path = cfg.trace_path, cfg.flight_path
        if cfg.obs_dir:
            # run-scoped layout: one static config serves every
            # incarnation of every role without files clobbering
            stem = (f"{self.run_context.role}"
                    f".i{self.run_context.incarnation}")
            if trace_path is None:
                trace_path = os.path.join(cfg.obs_dir,
                                          f"{stem}.trace.json")
            if flight_path is None:
                flight_path = os.path.join(cfg.obs_dir,
                                           f"{stem}.flight.bin")
        self.trace_path = trace_path
        self.registry = MetricsRegistry()
        self.flight: Optional[FlightRecorder] = None
        if cfg.trace_enabled and flight_path is not None:
            self.flight = FlightRecorder(
                flight_path, capacity=cfg.flight_records,
                slot_bytes=cfg.flight_slot_bytes)
        if cfg.trace_enabled:
            dropped = self.registry.counter(
                "monitor_dropped_events",
                "Trace events evicted unread by the bounded ring.")
            self.tracer: Optional[Tracer] = Tracer(
                ring_size=cfg.ring_size, flight=self.flight,
                run_context=self.run_context,
                on_drop=lambda n: dropped.inc(n))
        else:
            self.tracer = None
        self.watchdog = RecompileWatchdog(mode=cfg.watchdog)
        # perf doctor legs: compiled-cost index (opt-in — its live MFU
        # readout syncs the step inside the span) and the device-memory
        # watermark lane (near-free, defaults on with tracing)
        self.cost_index: Optional[CompiledCostIndex] = (
            CompiledCostIndex(registry=self.registry) if cfg.perf else None)
        self.memwatch: Optional[MemWatch] = (
            MemWatch(registry=self.registry,
                     near_oom_fraction=cfg.near_oom_fraction)
            if cfg.memwatch and cfg.trace_enabled else None)
        self.metrics_server: Optional[MetricsServer] = None
        if cfg.metrics_port is not None:
            self.metrics_server = MetricsServer(
                self.registry, port=cfg.metrics_port, host=cfg.metrics_host)
        self._prev_tracer = None
        self._started = False

    # -------------------------------------------------------------- #

    def start(self) -> "Monitor":
        if self._started:
            return self
        self._started = True
        if self.tracer is not None:
            self._prev_tracer = set_tracer(self.tracer)
        if self.metrics_server is not None:
            self.metrics_server.start()
            logger.info("monitor: metrics endpoint at %s",
                        self.metrics_server.url)
        atexit.register(self._atexit_save)
        return self

    def _atexit_save(self) -> None:
        # crash insurance: the trace survives a run that never reached
        # shutdown_monitor(); idempotent with an explicit save. (SIGKILL
        # skips this entirely — that is what the flight recorder is for.)
        try:
            if self.tracer is not None and self.trace_path:
                self.tracer.save(self.trace_path)
            if self.flight is not None:
                self.flight.flush()
        except Exception:  # pragma: no cover - interpreter teardown
            pass

    def save_trace(self, path: Optional[str] = None) -> Optional[str]:
        """Write the Chrome-trace JSON (to ``path`` or the configured
        ``trace_path``); returns the path written, or None."""
        if self.tracer is None:
            return None
        path = path or self.trace_path
        if not path:
            return None
        return self.tracer.save(path)

    def export_tensorboard(self, monitor, step: int) -> None:
        export_to_tensorboard(self.registry, monitor, step)

    def shutdown(self, save: bool = True) -> None:
        if not self._started:
            return
        self._started = False
        atexit.unregister(self._atexit_save)
        if save:
            self.save_trace()
        if self.metrics_server is not None:
            self.metrics_server.close()
        if self.flight is not None:
            self.flight.close()
        if self.tracer is not None and get_tracer() is self.tracer:
            set_tracer(self._prev_tracer)


# ------------------------------------------------------------------ #
# process-global monitor (what the engines pick up)
# ------------------------------------------------------------------ #

_MONITOR: Optional[Monitor] = None


def init_monitor(config: Union[MonitorConfig, dict, None]) -> Monitor:
    """Build + start + install the process-global Monitor. Re-initializing
    with a live monitor shuts the old one down first (its trace is
    saved)."""
    global _MONITOR
    if _MONITOR is not None:
        _MONITOR.shutdown()
    _MONITOR = Monitor(config).start()
    return _MONITOR


def get_monitor() -> Optional[Monitor]:
    return _MONITOR


def shutdown_monitor(save: bool = True) -> None:
    global _MONITOR
    if _MONITOR is not None:
        _MONITOR.shutdown(save=save)
        _MONITOR = None
