"""Structured step tracing: a thread-safe Chrome-trace event recorder.

Spans, counters, and instant events land in a bounded ring buffer (a
``deque(maxlen=ring_size)`` — memory stays fixed no matter how long the
run) and serialize to the Chrome Trace Event JSON format, loadable in
Perfetto (https://ui.perfetto.dev) or ``chrome://tracing``.

Spans are emitted as ``"X"`` (complete) events rather than ``"B"``/``"E"``
pairs so ring-buffer eviction can never orphan half a pair; the schema
validator (``monitor/validate.py``) still checks B/E balance for traces
that carry them (e.g. hand-merged ones).

The hot-path contract: when no tracer is installed, ``trace_span`` returns
a shared no-op context manager and ``trace_instant``/``trace_counter``
return immediately — observability off means a dict lookup and a branch,
nothing else. Engines therefore call the module-level helpers
unconditionally.

Timestamps are ``time.perf_counter()`` microseconds (monotonic); ``pid``
is the OS pid, ``tid`` is either the real thread id or a named logical
lane (``lane="serving"``) so Perfetto renders one track per subsystem
(engine / pipeline stages / offload / serving) instead of interleaving
everything on the main thread's track.

Two run-scoped extras feed the cross-process story (monitor/aggregate):

  * every tracer snapshots a ``(wall, perf)`` clock anchor at
    construction and stamps it — with the run context (run_id / role /
    incarnation, see runctx.py) — into the saved trace's ``otherData``
    and process metadata, so per-process traces can be rebased onto one
    shared timeline and labeled per incarnation;
  * an optional ``flight`` sink (monitor/flight.py) receives every
    event inline as it is recorded, so a SIGKILLed process still
    leaves its last events on disk.

Ring eviction is no longer silent: the tracer counts drops, notifies an
``on_drop`` hook (the Monitor wires it to the ``monitor_dropped_events``
counter), and emits a rate-limited ``trace/dropped`` instant so the
timeline itself shows where history was lost; the total also rides in
the trace footer (``otherData.dropped_events``).
"""

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from .runctx import RunContext, clock_anchor, current as current_run

__all__ = [
    "Tracer",
    "get_tracer",
    "set_tracer",
    "trace_span",
    "trace_instant",
    "trace_counter",
]


class _NullSpan:
    """Shared no-op context manager for the tracer-disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def note(self, **args):
        return self


_NULL_SPAN = _NullSpan()


class _Span:
    """Context manager emitting one "X" (complete) event on exit."""

    __slots__ = ("_tracer", "_name", "_tid", "_args", "_t0")

    def __init__(self, tracer, name, tid, args):
        self._tracer = tracer
        self._name = name
        self._tid = tid
        self._args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def note(self, **args):
        """Attach args discovered mid-span (MFU, HBM watermarks — values
        that only exist once the work ran); merged into the "X" event at
        exit. Returns self so call sites can chain."""
        if self._args:
            self._args.update(args)
        else:
            self._args = args
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        self._tracer._append({
            "name": self._name,
            "ph": "X",
            "ts": self._t0 * 1e6,
            "dur": (t1 - self._t0) * 1e6,
            "pid": self._tracer.pid,
            "tid": self._tid,
            **({"args": self._args} if self._args else {}),
        })
        return False


class Tracer:
    """Thread-safe span/counter/instant recorder with bounded memory."""

    # at most one trace/dropped instant per this many seconds
    DROP_NOTE_INTERVAL_S = 1.0

    def __init__(self, ring_size: int = 65536, pid: Optional[int] = None,
                 flight=None, run_context: Optional[RunContext] = None,
                 on_drop: Optional[Callable[[int], None]] = None):
        if ring_size < 1:
            raise ValueError(f"ring_size must be >= 1, got {ring_size}")
        self.ring_size = ring_size
        self.pid = os.getpid() if pid is None else pid
        self._events: deque = deque(maxlen=ring_size)
        self._lock = threading.Lock()
        self._lanes: Dict[str, int] = {}
        self.dropped = 0  # events evicted by the ring
        self.flight = flight            # inline crash-proof sink
        self.run_context = (run_context if run_context is not None
                            else current_run())
        self.on_drop = on_drop
        self.clock = clock_anchor()     # (wall, perf) for trace merging
        self._last_drop_note = float("-inf")
        self._extra_meta: Dict[str, object] = {}

    # -------------------------------------------------------------- #
    # recording
    # -------------------------------------------------------------- #

    def _append(self, ev: dict) -> None:
        note = None
        with self._lock:
            if len(self._events) == self.ring_size:
                evicted = 1
                now = time.perf_counter()
                if now - self._last_drop_note >= self.DROP_NOTE_INTERVAL_S:
                    self._last_drop_note = now
                    evicted += 1  # the note itself evicts one more
                self.dropped += evicted
                if evicted == 2:
                    note = {
                        "name": "trace/dropped",
                        "ph": "i",
                        "s": "p",  # process-scoped: loss affects every lane
                        "ts": now * 1e6,
                        "pid": self.pid,
                        "tid": 0,
                        "args": {"dropped": self.dropped},
                    }
                    self._events.append(note)
                if self.on_drop is not None:
                    try:
                        self.on_drop(evicted)
                    except Exception:  # pragma: no cover - hook is advisory
                        pass
            self._events.append(ev)
        if note is not None and self.flight is not None:
            self.flight.append(note)
        if self.flight is not None:
            # inline, outside the ring lock: the flight ring has its
            # own; this is what makes the record survive a SIGKILL that
            # lands one instruction later
            self.flight.append(ev)

    def _tid(self, lane: Optional[str]) -> int:
        if lane is None:
            return threading.get_ident() & 0x7FFFFFFF
        with self._lock:
            tid = self._lanes.get(lane)
            if tid is None:
                # small stable ids, separate from real thread idents
                tid = len(self._lanes) + 1
                self._lanes[lane] = tid
        return tid

    def span(self, name: str, lane: Optional[str] = None, **args) -> _Span:
        """``with tracer.span("fwd"): ...`` — one "X" event per exit."""
        return _Span(self, name, self._tid(lane), args)

    def instant(self, name: str, lane: Optional[str] = None, **args) -> None:
        self._append({
            "name": name,
            "ph": "i",
            "s": "t",  # thread-scoped instant
            "ts": time.perf_counter() * 1e6,
            "pid": self.pid,
            "tid": self._tid(lane),
            **({"args": args} if args else {}),
        })

    def counter(self, name: str, values, lane: Optional[str] = None) -> None:
        """Counter sample; ``values`` is a number or a dict of series."""
        if not isinstance(values, dict):
            values = {"value": values}
        self._append({
            "name": name,
            "ph": "C",
            "ts": time.perf_counter() * 1e6,
            "pid": self.pid,
            "tid": self._tid(lane),
            "args": {k: float(v) for k, v in values.items()},
        })

    # -------------------------------------------------------------- #
    # export
    # -------------------------------------------------------------- #

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0

    def _metadata(self) -> List[dict]:
        """Perfetto display names for the logical lanes."""
        with self._lock:
            lanes = dict(self._lanes)
        rc = self.run_context
        proc = "deeperspeed_tpu"
        if rc is not None and (rc.run_id or rc.role != "main"):
            proc = f"deeperspeed_tpu:{rc.role}#{rc.incarnation}"
        meta = [{
            "name": "process_name",
            "ph": "M",
            "pid": self.pid,
            "tid": 0,
            "args": {"name": proc},
        }]
        for lane, tid in sorted(lanes.items(), key=lambda kv: kv[1]):
            meta.append({
                "name": "thread_name",
                "ph": "M",
                "pid": self.pid,
                "tid": tid,
                "args": {"name": lane},
            })
        return meta

    def set_metadata(self, key: str, value) -> None:
        """Stamp a JSON-ready blob into the saved trace's ``otherData``
        (e.g. the perf layer's compiled-cost table); last write wins."""
        with self._lock:
            self._extra_meta[key] = value

    def to_dict(self) -> dict:
        other = {"dropped_events": self.dropped, "clock": dict(self.clock)}
        with self._lock:
            other.update(self._extra_meta)
        if self.run_context is not None:
            other["run"] = self.run_context.as_args()
        return {
            "traceEvents": self._metadata() + self.events(),
            "displayTimeUnit": "ms",
            "otherData": other,
        }

    def save(self, path: str) -> str:
        """Write the Perfetto-loadable JSON; returns ``path``."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_dict(), f)
            f.write("\n")
        return path


# ------------------------------------------------------------------ #
# module-level tracer (what the engines call)
# ------------------------------------------------------------------ #

_GLOBAL: Optional[Tracer] = None


def set_tracer(tracer: Optional[Tracer]) -> Optional[Tracer]:
    """Install (or remove, with None) the process-global tracer; returns
    the previous one so callers can restore it."""
    global _GLOBAL
    prev = _GLOBAL
    _GLOBAL = tracer
    return prev


def get_tracer() -> Optional[Tracer]:
    return _GLOBAL


def trace_span(name: str, lane: Optional[str] = None, **args):
    """Span against the global tracer; a shared no-op when tracing is off."""
    t = _GLOBAL
    if t is None:
        return _NULL_SPAN
    return t.span(name, lane, **args)


def trace_instant(name: str, lane: Optional[str] = None, **args) -> None:
    t = _GLOBAL
    if t is not None:
        t.instant(name, lane, **args)


def trace_counter(name: str, values, lane: Optional[str] = None) -> None:
    t = _GLOBAL
    if t is not None:
        t.counter(name, values, lane)
