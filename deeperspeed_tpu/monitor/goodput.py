"""Goodput ledger: where a run's wall-clock actually went.

The spot-pool story needs one headline number — the fraction of
wall-clock spent on productive steps versus everything a preemptible
fleet pays for the privilege: XLA compiles, checkpoint saves, restart +
reshard downtime, input-pipeline stalls, and replayed steps. This
module classifies a run's wall-clock into those buckets from two
sources that already exist:

  * the supervisor's **restart log** (JSONL launch/exit transitions,
    wall-clock stamped) — child lifetimes and the downtime gaps
    between an exit and the next launch;
  * each incarnation's **trace events** (from its trace file, or
    recovered from its flight.bin when it was SIGKILLed) — span
    intervals classified by name.

Bucket rules, applied as *interval arithmetic* so nested spans are
never double-counted (a compile inside the first ``engine/train_batch``
span is compile time, not productive time):

  ====================  =============================================
  ``compile``           ``xla_compile`` instants (duration in args)
  ``remesh``            ``lifecycle/remesh`` spans — live in-process
                        topology flips (the zero-restart elasticity
                        path pays a stall, not a relaunch)
  ``checkpoint``        ``resilience/write|snapshot|commit`` spans
  ``stall``             ``datapipe/wait`` spans
  ``rework``            train-step spans whose ``step`` arg was
                        already executed by an earlier incarnation —
                        the replay tax of checkpoint-interval resume
  ``productive``        remaining train/serving step span time
  ``restart``           gaps between a child's exit and the next
                        launch (supervisor backoff + spawn)
  ``other``             the remainder of each child's lifetime
                        (imports, engine build, resume/reshard)
  ====================  =============================================

Precedence within an incarnation: compile > remesh > checkpoint >
stall > rework > productive; each category is measured after subtracting the
higher ones, and ``other`` is the unclassified remainder, so the
buckets sum to measured wall-clock by construction — the drill audits
the sum against an independently measured wall time to within 5%.

``compute_goodput`` also exports ``goodput_fraction`` and
``goodput_seconds{bucket=...}`` gauges into a metrics registry and
emits a ``goodput/report`` trace instant, so dashboards and traces
carry the same number. CLI::

    python -m deeperspeed_tpu.monitor.goodput \
        --restart-log restarts.jsonl --out goodput.json \
        trainer.i0.trace.json trainer.i1.flight.bin trainer.i2.trace.json
"""

import argparse
import json
import os
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from . import flight as flight_mod
from .tracer import trace_instant

__all__ = [
    "BUCKETS",
    "interval_union",
    "interval_subtract",
    "interval_measure",
    "parse_restart_log",
    "classify_incarnation",
    "compute_goodput",
    "main",
]

BUCKETS = ("productive", "rework", "compile", "remesh", "checkpoint",
           "stall", "restart", "other")

# span names whose time is the run's actual point: training or serving
# forward progress
PRODUCTIVE_SPANS = frozenset({
    "engine/train_batch", "pipe/train_batch",
    "serving/prefill", "serving/decode",
})
CHECKPOINT_SPANS = frozenset({
    "resilience/write", "resilience/snapshot", "resilience/commit",
})
REMESH_SPANS = frozenset({"lifecycle/remesh"})
STALL_SPANS = frozenset({"datapipe/wait"})
COMPILE_INSTANT = "xla_compile"

Interval = Tuple[float, float]


# ------------------------------------------------------------------ #
# interval arithmetic (pure, unit-tested)
# ------------------------------------------------------------------ #


def interval_union(intervals: Iterable[Interval]) -> List[Interval]:
    """Sorted, disjoint union of (start, end) intervals."""
    ivs = sorted((a, b) for a, b in intervals if b > a)
    out: List[Interval] = []
    for a, b in ivs:
        if out and a <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], b))
        else:
            out.append((a, b))
    return out


def interval_subtract(a: Sequence[Interval],
                      b: Sequence[Interval]) -> List[Interval]:
    """``a - b`` where both are disjoint+sorted (use interval_union)."""
    out: List[Interval] = []
    j = 0
    for start, end in a:
        cur = start
        while j < len(b) and b[j][1] <= cur:
            j += 1
        k = j
        while k < len(b) and b[k][0] < end:
            bs, be = b[k]
            if bs > cur:
                out.append((cur, bs))
            cur = max(cur, be)
            if cur >= end:
                break
            k += 1
        if cur < end:
            out.append((cur, end))
    return out


def interval_measure(intervals: Iterable[Interval]) -> float:
    return sum(b - a for a, b in intervals)


# ------------------------------------------------------------------ #
# sources
# ------------------------------------------------------------------ #


def parse_restart_log(log) -> List[dict]:
    """Restart-log records from a path or an already-parsed list."""
    if isinstance(log, (list, tuple)):
        return list(log)
    records = []
    with open(log) as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    return records


def load_trace_events(path_or_events) -> List[dict]:
    """Events from a trace JSON path, a flight.bin path, a trace doc,
    or a raw event list — whatever an incarnation left behind."""
    if isinstance(path_or_events, list):
        return path_or_events
    if isinstance(path_or_events, dict):
        return path_or_events.get("traceEvents", [])
    path = path_or_events
    if flight_mod.is_flight_file(path):
        return flight_mod.recover(path).events
    with open(path) as f:
        doc = json.load(f)
    return doc.get("traceEvents", doc) if isinstance(doc, dict) else doc


def classify_incarnation(events: List[dict], prev_max_step: int,
                         ) -> Tuple[Dict[str, float], int]:
    """One incarnation's trace -> seconds per in-child bucket, plus the
    updated max step index seen (feeds the next incarnation's rework
    detection). Pure; the drill's synthetic-log test drives it."""
    compile_iv, remesh_iv, ckpt_iv, stall_iv = [], [], [], []
    prod_iv, rework_iv = [], []
    max_step = prev_max_step
    for ev in events:
        if not isinstance(ev, dict):
            continue
        name, ph, ts = ev.get("name"), ev.get("ph"), ev.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        if name == COMPILE_INSTANT:
            secs = (ev.get("args") or {}).get("seconds", 0.0)
            if isinstance(secs, (int, float)) and secs > 0:
                # the listener fires when the compile ENDS
                compile_iv.append((ts - secs * 1e6, ts))
            continue
        if ph != "X":
            continue
        dur = ev.get("dur")
        if not isinstance(dur, (int, float)) or dur <= 0:
            continue
        iv = (ts, ts + dur)
        if name in REMESH_SPANS:
            remesh_iv.append(iv)
        elif name in CHECKPOINT_SPANS:
            ckpt_iv.append(iv)
        elif name in STALL_SPANS:
            stall_iv.append(iv)
        elif name in PRODUCTIVE_SPANS:
            step = (ev.get("args") or {}).get("step")
            if isinstance(step, (int, float)) and step <= prev_max_step:
                rework_iv.append(iv)        # replaying already-done work
            else:
                prod_iv.append(iv)
            if isinstance(step, (int, float)):
                max_step = max(max_step, int(step))
    compile_u = interval_union(compile_iv)
    remesh_u = interval_subtract(interval_union(remesh_iv), compile_u)
    higher = interval_union(compile_u + remesh_u)
    ckpt_u = interval_subtract(interval_union(ckpt_iv), higher)
    higher = interval_union(higher + ckpt_u)
    stall_u = interval_subtract(interval_union(stall_iv), higher)
    higher = interval_union(higher + stall_u)
    rework_u = interval_subtract(interval_union(rework_iv), higher)
    higher = interval_union(higher + rework_u)
    prod_u = interval_subtract(interval_union(prod_iv), higher)
    to_s = 1e-6
    return {
        "productive": interval_measure(prod_u) * to_s,
        "rework": interval_measure(rework_u) * to_s,
        "compile": interval_measure(compile_u) * to_s,
        "remesh": interval_measure(remesh_u) * to_s,
        "checkpoint": interval_measure(ckpt_u) * to_s,
        "stall": interval_measure(stall_u) * to_s,
    }, max_step


def compute_goodput(restart_log, traces: Sequence,
                    wall_s: Optional[float] = None,
                    registry=None, emit_trace: bool = True) -> dict:
    """The ledger: classify a run's wall-clock into BUCKETS.

    ``restart_log`` — supervisor JSONL (path or record list); may be
    None for a single-incarnation run. ``traces`` — one entry per
    incarnation, in launch order: a trace/flight path, a trace doc, or
    an event list. ``wall_s`` — independently measured run wall time;
    defaults to the restart log's first-launch-to-last-exit span.
    """
    records = parse_restart_log(restart_log) if restart_log else []
    launches = [r for r in records if r.get("event") == "launch"]
    exits = [r for r in records if r.get("event") == "exit"]
    lives: List[Tuple[float, float]] = []
    for launch, exit_ in zip(launches, exits):
        if "ts" in launch and "ts" in exit_:
            lives.append((launch["ts"], exit_["ts"]))
    gaps = [max(0.0, launches[i + 1]["ts"] - exits[i]["ts"])
            for i in range(min(len(exits), len(launches) - 1))
            if "ts" in launches[i + 1] and "ts" in exits[i]]
    if wall_s is None:
        if lives:
            wall_s = lives[-1][1] - lives[0][0]
        else:
            raise ValueError(
                "compute_goodput needs wall_s when there is no "
                "restart log to measure it from")

    buckets = {b: 0.0 for b in BUCKETS}
    buckets["restart"] = sum(gaps)
    incarnations = []
    max_step = -1
    for i, trace in enumerate(traces):
        events = load_trace_events(trace)
        inc, max_step = classify_incarnation(events, max_step)
        child_wall = (lives[i][1] - lives[i][0]) if i < len(lives) \
            else wall_s - buckets["restart"]
        classified = sum(inc.values())
        inc["other"] = max(0.0, child_wall - classified)
        inc["child_wall_s"] = child_wall
        incarnations.append(inc)
        for b, v in inc.items():
            if b in buckets:
                buckets[b] += v
    # harness time outside any child lifetime (spawn overhead, the
    # drill's own bookkeeping) lands in "other" so the ledger still
    # covers the measured wall-clock
    in_children = sum(b - a for a, b in lives) if lives else \
        sum(i["child_wall_s"] for i in incarnations)
    buckets["other"] += max(0.0, wall_s - in_children - buckets["restart"])

    accounted = sum(buckets.values())
    goodput = buckets["productive"] / wall_s if wall_s > 0 else 0.0
    report = {
        "wall_s": wall_s,
        "buckets": {b: round(v, 6) for b, v in buckets.items()},
        "goodput": round(goodput, 6),
        "accounted_s": round(accounted, 6),
        "accounted_fraction": round(accounted / wall_s, 6)
        if wall_s > 0 else 0.0,
        "incarnations": [
            {k: round(v, 6) for k, v in inc.items()}
            for inc in incarnations],
        "restarts": max(0, len(launches) - 1),
    }
    if registry is None:
        from . import get_monitor
        mon = get_monitor()
        registry = mon.registry if mon is not None else None
    if registry is not None:
        registry.gauge("goodput_fraction",
                       "Fraction of wall-clock spent on productive "
                       "steps.").set(goodput)
        for b, v in buckets.items():
            registry.gauge("goodput_seconds",
                           "Run wall-clock per goodput bucket.",
                           labels={"bucket": b}).set(v)
    if emit_trace:
        trace_instant("goodput/report", lane="run",
                      wall_s=round(wall_s, 3), goodput=round(goodput, 4))
    return report


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeperspeed_tpu.monitor.goodput",
        description="Classify a run's wall-clock into goodput buckets "
                    "from its restart log and per-incarnation traces.")
    ap.add_argument("traces", nargs="+",
                    help="per-incarnation trace JSON / flight.bin, in "
                         "launch order")
    ap.add_argument("--restart-log", default=None,
                    help="supervisor --restart-log JSONL")
    ap.add_argument("--wall", type=float, default=None,
                    help="measured wall seconds (default: from the "
                         "restart log)")
    ap.add_argument("--out", default=None, help="write the JSON report")
    args = ap.parse_args(argv)
    report = compute_goodput(args.restart_log, args.traces,
                             wall_s=args.wall, emit_trace=False)
    for b in BUCKETS:
        v = report["buckets"][b]
        pct = 100.0 * v / report["wall_s"] if report["wall_s"] else 0.0
        print(f"  {b:<12} {v:>10.3f}s  {pct:5.1f}%")
    print(f"GOODPUT {report['goodput']:.4f} over {report['wall_s']:.2f}s "
          f"wall ({report['restarts']} restart(s), "
          f"{report['accounted_fraction']:.3f} accounted)")
    if args.out:
        parent = os.path.dirname(os.path.abspath(args.out))
        os.makedirs(parent, exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
        print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
