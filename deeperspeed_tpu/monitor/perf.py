"""Compiled-cost observability: where do the FLOPs and bytes go?

XLA already knows what every jitted entry point costs — the compiled
executable carries a cost model (``compiled.cost_analysis()``: flops,
bytes accessed, optimal seconds) and a memory breakdown
(``compiled.memory_analysis()``: argument / output / peak-temp bytes).
Until now that knowledge lived only in the offline flops profiler and
``scripts/mfu_decomposition.py``; this module makes it a live layer:

  * :func:`extract_cost_analysis` / :func:`extract_memory_analysis` —
    the ONE place the raw XLA structures are normalized (the CPU
    backend variously returns ``None``, a list of dicts, or a partial
    dict; the flops profiler shares these helpers instead of a second
    call-site);
  * :class:`CompiledCostIndex` — captures the cost/memory analysis of
    every registered jitted entry point (engine fused/imperative train
    step, serving prefill/decode, comm per-bucket reducers) by AOT
    re-lowering against the *abstract* shapes of the real call (so
    donated/deleted buffers are fine and the jit's own cache is never
    touched), stamps one ``perf/compiled`` instant + Prometheus gauges
    per capture, writes the table into the trace's process metadata,
    and answers the live questions: per-step MFU from measured flops
    over span wall time, and a roofline verdict (compute- / memory- /
    comm-bound) against a small platform peak table.

Capture keys off the same jit-cache counter the recompile watchdog
reads: ``observe(name, fn, args)`` is O(one int compare) while the
function stays warm and only re-captures when the cache grew (i.e. the
watchdog would have fired anyway).

The peak table reuses the MFU_DECOMP methodology: ``peak_tflops`` per
device generation (PALLAS_AXON_TPU_GEN overrides detection, exactly
like ``scripts/bert_sparse_bench.peak_tflops``), plus nominal HBM
bandwidth for the roofline ridge. CPU gets a deliberately nominal 0.5
TF so MFU numbers exist (and exercise the plumbing) without pretending
to mean anything.
"""

import dataclasses
import os
import threading
from typing import Any, Callable, Dict, Optional, Tuple

from ..utils.logging import logger
from .tracer import get_tracer, trace_instant

__all__ = [
    "PLATFORM_PEAKS",
    "CompiledCostIndex",
    "CostRecord",
    "extract_cost_analysis",
    "extract_memory_analysis",
    "platform_peaks",
]

# ------------------------------------------------------------------ #
# platform peak table (MFU_DECOMP.json peak_tflops lineage)
# ------------------------------------------------------------------ #

# peak_tflops: bf16 matmul peak per chip (the basis every MFU number in
# README/MFU_DECOMP.json uses); peak_gbps: nominal HBM bandwidth, the
# other roofline axis; hbm_gib: per-chip capacity (the autotuner's
# feasibility axis); ici_gbps: nominal per-chip interconnect bandwidth
# (the wire-model denominator). Keys are matched as prefixes against
# the lowered device_kind / PALLAS_AXON_TPU_GEN.
PLATFORM_PEAKS: Dict[str, Dict[str, float]] = {
    "v4": {"peak_tflops": 275.0, "peak_gbps": 1228.0,
           "hbm_gib": 32.0, "ici_gbps": 300.0},
    "v5p": {"peak_tflops": 459.0, "peak_gbps": 2765.0,
            "hbm_gib": 95.0, "ici_gbps": 600.0},
    "v5e": {"peak_tflops": 197.0, "peak_gbps": 819.0,
            "hbm_gib": 16.0, "ici_gbps": 160.0},
    "v5 lite": {"peak_tflops": 197.0, "peak_gbps": 819.0,
                "hbm_gib": 16.0, "ici_gbps": 160.0},
    "v6e": {"peak_tflops": 918.0, "peak_gbps": 1640.0,
            "hbm_gib": 32.0, "ici_gbps": 360.0},
    "v6 lite": {"peak_tflops": 918.0, "peak_gbps": 1640.0,
                "hbm_gib": 32.0, "ici_gbps": 360.0},
    # nominal: keeps CPU MFU numbers finite and the plumbing testable
    # (1 GiB "HBM" puts the serving pool frontier within CPU-test reach)
    "cpu": {"peak_tflops": 0.5, "peak_gbps": 50.0,
            "hbm_gib": 1.0, "ici_gbps": 10.0},
}


def platform_peaks(device=None) -> Dict[str, float]:
    """Peak table row for ``device`` (default: first local device).
    ``PALLAS_AXON_TPU_GEN`` overrides detection — same escape hatch the
    benches use when the tunnel misreports device_kind. Falls back to
    v5e on an unrecognized TPU and to the nominal CPU row elsewhere."""
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    kind, platform = "", "cpu"
    if device is None:
        try:
            import jax
            device = jax.local_devices()[0]
        except Exception:  # pragma: no cover - no backend at all
            device = None
    if device is not None:
        kind = getattr(device, "device_kind", "").lower()
        platform = getattr(device, "platform", "cpu")
    for key, row in PLATFORM_PEAKS.items():
        if gen.startswith(key) or (key in kind and key != "cpu"):
            return dict(row, source=key)
    if platform == "tpu":
        return dict(PLATFORM_PEAKS["v5e"], source="tpu-default")
    return dict(PLATFORM_PEAKS["cpu"], source="cpu")


# ------------------------------------------------------------------ #
# raw-structure normalization (shared with profiling/flops_profiler)
# ------------------------------------------------------------------ #


def extract_cost_analysis(compiled) -> Dict[str, float]:
    """Normalize ``compiled.cost_analysis()`` into a flat dict.

    Handles every shape the backends produce: ``None`` (CPU builds
    without a cost model), a list of per-computation dicts (older
    jaxlib), a single dict, and partial dicts missing keys. Returned
    keys (always present, 0.0 when the backend stayed silent):
    ``flops``, ``bytes_accessed``, ``optimal_seconds``."""
    out = {"flops": 0.0, "bytes_accessed": 0.0, "optimal_seconds": 0.0}
    try:
        ca = compiled.cost_analysis()
    except Exception:  # pragma: no cover - backend refuses entirely
        return out
    if ca is None:
        return out
    if isinstance(ca, (list, tuple)):
        ca = next((c for c in ca if isinstance(c, dict)), None)
        if ca is None:
            return out
    if not isinstance(ca, dict):
        return out

    def _num(key):
        v = ca.get(key)
        try:
            v = float(v)
        except (TypeError, ValueError):
            return 0.0
        return v if v > 0 else 0.0

    out["flops"] = _num("flops")
    out["bytes_accessed"] = _num("bytes accessed")
    out["optimal_seconds"] = _num("optimal_seconds")
    return out


def extract_memory_analysis(compiled) -> Dict[str, float]:
    """Normalize ``compiled.memory_analysis()`` into a flat dict; empty
    when the backend exposes nothing. Keys (when present):
    ``argument_bytes``, ``output_bytes``, ``temp_bytes``,
    ``alias_bytes``, ``code_bytes``, and ``peak_bytes`` (arguments +
    outputs + temporaries − aliased: the executable's HBM footprint
    while it runs — the number the sharding refactor needs per entry
    point before it moves anything)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:  # pragma: no cover - backend refuses entirely
        return {}
    if ma is None:
        return {}
    fields = {
        "argument_bytes": "argument_size_in_bytes",
        "output_bytes": "output_size_in_bytes",
        "temp_bytes": "temp_size_in_bytes",
        "alias_bytes": "alias_size_in_bytes",
        "code_bytes": "generated_code_size_in_bytes",
    }
    out: Dict[str, float] = {}
    for key, attr in fields.items():
        v = getattr(ma, attr, None)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[key] = float(v)
    if out:
        out["peak_bytes"] = (out.get("argument_bytes", 0.0)
                             + out.get("output_bytes", 0.0)
                             + out.get("temp_bytes", 0.0)
                             - out.get("alias_bytes", 0.0))
    return out


def _abstractify(args: Tuple, kwargs: Optional[dict]):
    """Replace every jax.Array leaf with a ShapeDtypeStruct so the AOT
    re-lower never touches device buffers (donated/deleted inputs from
    the real call still carry their aval)."""
    import jax

    def one(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return x

    return (jax.tree.map(one, args),
            jax.tree.map(one, kwargs if kwargs is not None else {}))


def _cache_size(fn) -> Optional[int]:
    get = getattr(fn, "_cache_size", None)
    if get is None:
        return None
    try:
        return int(get())
    except Exception:  # pragma: no cover - defensive
        return None


# ------------------------------------------------------------------ #
# the index
# ------------------------------------------------------------------ #


@dataclasses.dataclass
class CostRecord:
    """One captured entry point. ``flops``/``bytes_accessed`` are whole-
    program (all participating devices); ``peak_bytes`` is the
    executable's device-memory footprint estimate."""

    name: str
    flops: float = 0.0
    bytes_accessed: float = 0.0
    optimal_seconds: float = 0.0
    argument_bytes: float = 0.0
    output_bytes: float = 0.0
    temp_bytes: float = 0.0
    peak_bytes: float = 0.0
    cache_size: Optional[int] = None
    captures: int = 0
    error: Optional[str] = None

    def as_args(self) -> Dict[str, float]:
        return {
            "entry": self.name,
            "flops": self.flops,
            "bytes": self.bytes_accessed,
            "peak_hbm": self.peak_bytes,
            "optimal_s": self.optimal_seconds,
        }


class CompiledCostIndex:
    """Live table of what every jitted entry point costs.

    ``observe(name, fn, args)`` sits next to the recompile watchdog's
    ``watch``/``observe`` call sites: cheap while the function stays
    warm, re-captures (AOT lower + compile against abstract avals) when
    the jit cache grew. Every capture emits a ``perf/compiled`` instant,
    refreshes the ``perf_flops`` / ``perf_bytes_accessed`` /
    ``perf_peak_hbm_bytes`` gauges (labeled by entry), and stamps the
    whole table into the tracer's process metadata so a saved trace
    carries its own cost model."""

    def __init__(self, registry=None, peaks: Optional[Dict] = None,
                 emit: bool = True):
        self._lock = threading.Lock()
        self._records: Dict[str, CostRecord] = {}
        self._registry = registry
        self._peaks = peaks  # lazily resolved: jax may not be up yet
        self._devices: Optional[int] = None
        # emit=False sandboxes the index (autotune candidate sweeps):
        # no trace instants, no gauge refresh, no tracer-metadata stamp
        # — speculative captures must not pollute the live monitor
        self._emit = bool(emit)

    # -- platform ---------------------------------------------------- #

    @property
    def peaks(self) -> Dict[str, float]:
        if self._peaks is None:
            self._peaks = platform_peaks()
        return self._peaks

    @property
    def local_devices(self) -> int:
        if self._devices is None:
            try:
                import jax
                self._devices = max(1, jax.local_device_count())
            except Exception:  # pragma: no cover
                self._devices = 1
        return self._devices

    # -- capture ----------------------------------------------------- #

    def observe(self, name: str, fn: Callable, args: Tuple = (),
                kwargs: Optional[dict] = None) -> Optional[CostRecord]:
        """Record ``fn``'s compiled cost under ``name`` if it has not
        been captured yet (or recompiled since). Never raises: a backend
        that refuses to lower leaves a stub record with ``error`` set."""
        size = _cache_size(fn)
        with self._lock:
            rec = self._records.get(name)
        if rec is not None and rec.error is None and rec.cache_size == size:
            return rec
        return self._capture(name, fn, args, kwargs, size)

    def _capture(self, name, fn, args, kwargs, size) -> Optional[CostRecord]:
        rec = CostRecord(name=name, cache_size=size)
        try:
            a_args, a_kwargs = _abstractify(args, kwargs)
            lowered = fn.lower(*a_args, **a_kwargs)
            compiled = lowered.compile()
            rec_dict = extract_cost_analysis(compiled)
            mem = extract_memory_analysis(compiled)
            rec.flops = rec_dict["flops"]
            rec.bytes_accessed = rec_dict["bytes_accessed"]
            rec.optimal_seconds = rec_dict["optimal_seconds"]
            rec.argument_bytes = mem.get("argument_bytes", 0.0)
            rec.output_bytes = mem.get("output_bytes", 0.0)
            rec.temp_bytes = mem.get("temp_bytes", 0.0)
            rec.peak_bytes = mem.get("peak_bytes", 0.0)
        except Exception as e:  # noqa: BLE001 — observability must not kill
            rec.error = f"{type(e).__name__}: {e}"
            logger.debug("perf: cost capture for %r failed: %s", name,
                         rec.error)
        with self._lock:
            prev = self._records.get(name)
            rec.captures = (prev.captures if prev else 0) + 1
            self._records[name] = rec
        if rec.error is None and self._emit:
            trace_instant("perf/compiled", lane="perf", **rec.as_args())
            self._export_gauges(rec)
        if self._emit:
            self._stamp_metadata()
        return rec

    def _export_gauges(self, rec: CostRecord) -> None:
        if self._registry is None:
            return
        lab = {"entry": rec.name}
        self._registry.gauge(
            "perf_flops", "compiled cost model: flops per execution",
            labels=lab).set(rec.flops)
        self._registry.gauge(
            "perf_bytes_accessed", "compiled cost model: bytes accessed "
            "per execution", labels=lab).set(rec.bytes_accessed)
        self._registry.gauge(
            "perf_peak_hbm_bytes", "compiled executable memory footprint "
            "(args+outputs+temps-aliased)", labels=lab).set(rec.peak_bytes)

    def _stamp_metadata(self) -> None:
        t = get_tracer()
        if t is None or not hasattr(t, "set_metadata"):
            return
        t.set_metadata("perf", self.summary())

    # -- queries ------------------------------------------------------ #

    def get(self, name: str) -> Optional[CostRecord]:
        with self._lock:
            return self._records.get(name)

    def records(self) -> Dict[str, CostRecord]:
        with self._lock:
            return dict(self._records)

    def summary(self) -> Dict[str, Dict[str, float]]:
        """JSON-ready table (what the trace metadata / benches carry)."""
        with self._lock:
            recs = list(self._records.values())
        return {
            r.name: {
                "flops": r.flops,
                "bytes_accessed": r.bytes_accessed,
                "peak_hbm_bytes": r.peak_bytes,
                "optimal_seconds": r.optimal_seconds,
                "captures": r.captures,
                **({"error": r.error} if r.error else {}),
            }
            for r in recs
        }

    # -- live MFU / roofline ------------------------------------------ #

    def step_stats(self, name: str, wall_s: float,
                   comm_factor: float = 3.0) -> Optional[Dict[str, Any]]:
        """Measured-step verdict for entry ``name`` over ``wall_s``.

        MFU = measured flops / wall / (peak_tflops × local devices) —
        the same accounting MFU_DECOMP.json uses, with the compiled cost
        model supplying the flops. The roofline verdict compares the two
        floor estimates (flops/peak_flops vs bytes/peak_bw): the larger
        names the bound; a measured wall ``comm_factor``× past BOTH
        floors means the time went somewhere the single-program roofline
        cannot see — collectives on a multi-device mesh ("comm-bound"),
        host/dispatch overhead on one device ("host-bound")."""
        rec = self.get(name)
        if rec is None or rec.error is not None or wall_s <= 0:
            return None
        peaks = self.peaks
        ndev = self.local_devices
        peak_flops = peaks["peak_tflops"] * 1e12 * ndev
        peak_bw = peaks["peak_gbps"] * 1e9 * ndev
        tflops = rec.flops / wall_s / 1e12
        mfu = rec.flops / wall_s / peak_flops if peak_flops else 0.0
        est_compute = rec.flops / peak_flops if peak_flops else 0.0
        est_memory = rec.bytes_accessed / peak_bw if peak_bw else 0.0
        floor = max(est_compute, est_memory)
        if floor > 0 and wall_s > comm_factor * floor:
            verdict = "comm-bound" if ndev > 1 else "host-bound"
        elif est_compute >= est_memory:
            verdict = "compute-bound"
        else:
            verdict = "memory-bound"
        stats = {
            "entry": name,
            "wall_ms": wall_s * 1e3,
            "mfu": mfu,
            "tflops": tflops,
            "verdict": verdict,
            "est_compute_ms": est_compute * 1e3,
            "est_memory_ms": est_memory * 1e3,
        }
        if self._registry is not None:
            lab = {"entry": name}
            self._registry.gauge(
                "perf_mfu", "measured model-flops utilization per step",
                labels=lab).set(mfu)
            self._registry.gauge(
                "perf_step_tflops", "measured tflops per step",
                labels=lab).set(tflops)
        return stats

    def note_step(self, name: str, wall_s: float) -> Optional[Dict[str, Any]]:
        """step_stats + a ``perf/step`` trace instant (the live per-step
        MFU lane)."""
        stats = self.step_stats(name, wall_s)
        if stats is not None:
            trace_instant(
                "perf/step", lane="perf", entry=name,
                mfu=round(stats["mfu"], 6),
                wall_ms=round(stats["wall_ms"], 3),
                tflops=round(stats["tflops"], 4),
                verdict=stats["verdict"])
        return stats
