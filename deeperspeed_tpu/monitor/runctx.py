"""Run-scoped trace context: who is emitting, in which incarnation.

A *run* is one logical training/serving job across every process it
spawns and every restart it survives. Three environment variables carry
the context, chosen so the existing process trees propagate them for
free (the supervisor's child env, the fleet's replica env, plain
``subprocess`` inheritance):

  * ``DS_TPU_RUN_ID``       — one id per run, minted once by whoever is
    at the top of the tree (supervisor, drill script, or the first
    ``ensure_run_id()`` caller) and inherited by everything below.
  * ``DS_TPU_ROLE``         — what this process is: ``trainer``,
    ``router``, ``replica-r1``, ... Free-form, but stable across
    restarts of the same logical process.
  * ``DS_TPU_INCARNATION``  — how many times this logical process has
    been (re)launched; the supervisor and the fleet stamp it so a
    killed replica's events are distinguishable from its replacement's.

``current()`` is cheap (three env reads) and never raises: outside any
run the context is ``run_id=None, role="main", incarnation=0``. The
tracer stamps the context into the trace footer and process metadata,
the flight recorder into its header, and the replica protocol into its
``ready`` event, so the aggregator can label per-process lanes and join
a rid's journey across processes and incarnations.

``estimate_clock_offset`` is the handshake math the aggregator's
cross-process timeline alignment rests on: an NTP-style symmetric-delay
estimate from one request/response round trip.
"""

import dataclasses
import os
import time
import uuid
from typing import Dict, Optional

__all__ = [
    "RUN_ID_ENV",
    "ROLE_ENV",
    "INCARNATION_ENV",
    "RunContext",
    "current",
    "ensure_run_id",
    "child_env",
    "host_role",
    "clock_anchor",
    "estimate_clock_offset",
]

RUN_ID_ENV = "DS_TPU_RUN_ID"
ROLE_ENV = "DS_TPU_ROLE"
INCARNATION_ENV = "DS_TPU_INCARNATION"


@dataclasses.dataclass(frozen=True)
class RunContext:
    run_id: Optional[str]
    role: str = "main"
    incarnation: int = 0

    def as_args(self) -> Dict[str, object]:
        """The stamp events/headers carry (run_id normalized to "")."""
        return {"run_id": self.run_id or "", "role": self.role,
                "incarnation": self.incarnation}


def current() -> RunContext:
    """The process's run context from the environment; never raises."""
    try:
        inc = int(os.environ.get(INCARNATION_ENV, "0"))
    except ValueError:
        inc = 0
    return RunContext(
        run_id=os.environ.get(RUN_ID_ENV) or None,
        role=os.environ.get(ROLE_ENV, "main"),
        incarnation=inc,
    )


def ensure_run_id() -> str:
    """Return the run id, minting one (and exporting it, so child
    processes inherit it) when this process is the top of the tree."""
    rid = os.environ.get(RUN_ID_ENV)
    if not rid:
        rid = f"run-{uuid.uuid4().hex[:12]}"
        os.environ[RUN_ID_ENV] = rid
    return rid


def child_env(role: str, incarnation: int,
              base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Env overlay for a child process: same run, its own role and
    incarnation. ``base`` defaults to a copy of os.environ."""
    env = dict(os.environ if base is None else base)
    env[RUN_ID_ENV] = ensure_run_id()
    env[ROLE_ENV] = role
    env[INCARNATION_ENV] = str(int(incarnation))
    return env


def host_role(base: str, process_id: int, process_count: int) -> str:
    """The per-host role lane of a multi-process run: ``base.h<proc>``
    when the run spans processes, ``base`` unchanged when it doesn't.
    Because obs files are named ``<role>.i<inc>.*``, this suffix is what
    gives every host its own trace/flight files with zero plumbing —
    and the aggregator's offsets sidecar keys on the same string."""
    if int(process_count) <= 1:
        return base
    return f"{base}.h{int(process_id)}"


def clock_anchor() -> Dict[str, float]:
    """A (wall, perf) clock pair sampled back-to-back. The tracer's
    timestamps are perf_counter-based (monotonic, process-local); the
    anchor lets the aggregator rebase them onto the shared wall clock:
    ``wall_us = ts + (wall - perf) * 1e6``."""
    return {"wall": time.time(), "perf": time.perf_counter()}


def estimate_clock_offset(t_send: float, t_remote: float,
                          t_recv: float) -> float:
    """NTP-style one-round-trip offset estimate: how far the remote
    wall clock is AHEAD of the local one, assuming symmetric transit.
    The local side records ``t_send`` before the request and ``t_recv``
    after the response; the remote stamps ``t_remote`` in between. The
    error is bounded by half the round-trip time."""
    return t_remote - (t_send + t_recv) / 2.0
