"""Request-path doctor CLI: attribute serving tail latency from a trace.

Front-end over ``monitor/reqledger.py``: point it at a trace JSON, a
flight.bin, or a drill artifact directory and it prints, per latency
axis, the fleet percentiles, where the aggregate wall-clock went, the
p99 victim's own breakdown (with the blocker rid when head-of-line
blocking dominates), the top-K blocker requests fleet-wide, and the
per-replica / per-version cost-per-1k-tokens ledger::

    python -m deeperspeed_tpu.monitor.slo traces/serving_bench_trace.json
    python -m deeperspeed_tpu.monitor.slo --json doctor.json bench_obs/

Directory inputs pick the merged trace when one exists (the
``monitor/aggregate.py`` output is the richest view), else a single
trace/flight file; ambiguity is an error, not a guess.

``--max-residual`` turns the report into a gate: attribution must
explain at least ``1 - FRAC`` of every request's TTFT window (windows
shorter than ``--min-window-ms`` are exempt — a residual fraction of a
sub-millisecond window is noise, not a diagnosis). CI runs this over
the committed drill traces with ``--max-residual 0.05``: if the doctor
stops being able to account for where tail latency goes, the build
fails, not the postmortem. Exit 0 = report (and gate, if any) clean;
1 = gate violation; 2 = bad input.
"""

import argparse
import json
import os
import sys
from typing import List, Optional

from .reqledger import (
    ATTRIBUTION_BUCKETS,
    DEFAULT_EXCLUDE_PREFIXES,
    build_ledger,
)

__all__ = ["resolve_input", "format_report", "main"]


def resolve_input(path: str) -> str:
    """A trace file stays itself; a directory must resolve to exactly
    one trace (merged output preferred)."""
    if os.path.isfile(path):
        return path
    if not os.path.isdir(path):
        raise FileNotFoundError(f"no such trace or directory: {path}")
    cands: List[str] = []
    for root, _dirs, files in os.walk(path):
        for f in sorted(files):
            if f.endswith(".json") and "trace" in f.lower() \
                    or f.endswith("flight.bin"):
                cands.append(os.path.join(root, f))
    merged = [c for c in cands if "merged" in os.path.basename(c)]
    if len(merged) == 1:
        return merged[0]
    if len(cands) == 1:
        return cands[0]
    if not cands:
        raise FileNotFoundError(
            f"{path}: no trace JSON or flight.bin found")
    raise ValueError(
        f"{path}: ambiguous — {len(cands)} trace candidates and no "
        f"single merged trace; pass one explicitly: {cands}")


def format_report(report: dict, top: int = 5) -> str:
    lines: List[str] = []
    for axis in ("ttft", "e2e"):
        p = report[axis]
        lines.append(
            f"{axis.upper():<5} n={p['count']:<4} "
            f"p50={p['p50_ms']:.1f}ms  p90={p['p90_ms']:.1f}ms  "
            f"p99={p['p99_ms']:.1f}ms  max={p['max_ms']:.1f}ms")
    total = sum(report["buckets_total_ms"].values()) or 1.0
    lines.append("TTFT wall-clock by bucket (all requests):")
    for b in ATTRIBUTION_BUCKETS:
        v = report["buckets_total_ms"].get(b, 0.0)
        lines.append(f"  {b:<14} {v:>10.1f}ms  {100.0 * v / total:5.1f}%")
    victim = report.get("p99_victim")
    if victim:
        row = report["requests"][victim["rid"]]["ttft"]
        desc = f"p99 victim {victim['rid']}: " \
               f"{victim['ttft_ms']:.1f}ms TTFT, dominated by " \
               f"{victim['dominant_bucket']}"
        if victim["top_blocker"]:
            desc += f" (top blocker: {victim['top_blocker']})"
        lines.append(desc)
        for b in ATTRIBUTION_BUCKETS:
            v = row["buckets"].get(b, 0.0)
            if v > 0:
                lines.append(f"    {b:<14} {v:>8.1f}ms")
    if report["top_blockers"]:
        lines.append("top blockers (HOL time inflicted fleet-wide):")
        for blk in report["top_blockers"][:top]:
            lines.append(f"  {blk['rid']:<12} {blk['blocked_ms']:.1f}ms")
    lines.append(
        f"cost: {report['cost_per_1k_tokens']:.3f} device-s per 1k "
        f"tokens fleet-wide")
    for axis in ("replica", "version"):
        groups = report["economics"].get(axis, {})
        if len(groups) > 1 or (groups and axis == "replica"):
            for key, g in sorted(groups.items()):
                lines.append(
                    f"  {axis}={key}: {g['cost_per_1k_tokens']:.3f}/1k "
                    f"over {g['tokens']} tok, "
                    f"{g['retry_wasted_tokens']} wasted, "
                    f"kv {g['kv_block_s']:.2f} blk-s")
    lines.append(
        f"worst residual: "
        f"{100.0 * report['worst_residual_fraction']:.2f}% of a "
        f"request's TTFT window unattributed")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeperspeed_tpu.monitor.slo",
        description="Per-request tail-latency attribution + cost ledger "
                    "from a serving trace.")
    ap.add_argument("trace",
                    help="trace JSON / flight.bin / drill artifact dir")
    ap.add_argument("--json", dest="json_out", default=None,
                    help="write the full report as JSON")
    ap.add_argument("--top", type=int, default=5,
                    help="top-K blocker rids to print (default 5)")
    ap.add_argument("--max-residual", type=float, default=None,
                    help="gate: fail when any request's unattributed "
                         "TTFT fraction exceeds this (CI uses 0.05)")
    ap.add_argument("--min-window-ms", type=float, default=1.0,
                    help="exempt TTFT windows shorter than this from "
                         "the residual gate (default 1.0)")
    ap.add_argument("--include-warmup", action="store_true",
                    help="keep warm-* compile-warmup rids in the "
                         "doctored population (excluded by default)")
    args = ap.parse_args(argv)

    try:
        src = resolve_input(args.trace)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    report = build_ledger(
        src, top_blockers=args.top,
        exclude_prefixes=(() if args.include_warmup
                          else DEFAULT_EXCLUDE_PREFIXES))
    if not report["requests"]:
        print(f"error: {src}: no request-scoped events (req/submit / "
              f"serving/*) in trace", file=sys.stderr)
        return 2
    print(f"request-path doctor: {src}")
    print(format_report(report, top=args.top))
    if args.json_out:
        parent = os.path.dirname(os.path.abspath(args.json_out))
        os.makedirs(parent, exist_ok=True)
        with open(args.json_out, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
        print(f"wrote {args.json_out}")

    if args.max_residual is not None:
        floor_us = args.min_window_ms * 1e3
        bad = []
        for rid, row in sorted(report["requests"].items()):
            att = row.get("ttft")
            if att is None:
                continue
            window_us = row["ttft_ms"] * 1e3
            if window_us < floor_us:
                continue
            if att["residual_fraction"] > args.max_residual:
                bad.append((rid, att["residual_fraction"]))
        if bad:
            for rid, frac in bad:
                print(f"GATE: {rid}: {100.0 * frac:.2f}% of TTFT "
                      f"unattributed (> {100.0 * args.max_residual:.1f}%)",
                      file=sys.stderr)
            return 1
        print(f"gate OK: every TTFT >= {args.min_window_ms:g}ms is "
              f">= {100.0 * (1 - args.max_residual):.0f}% attributed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
