"""Recompile watchdog: catch silent XLA retraces after warmup.

On TPU the dominant invisible failure mode is a jitted hot function
quietly recompiling — a shape or dtype leaked into the trace, a python
scalar that should have been a traced array, a config knob that varies
per call. Wall-clock timers show a mysterious multi-second step; this
watchdog names the function that did it.

Two signals:

  * Per-function jit cache sizes (``fn._cache_size()`` on jitted
    callables — the same counter ``ServingEngine.decode_compile_count``
    already exposes). ``watch(name, fn)`` registers a function;
    ``observe(name)`` is called by the owning engine after each hot-path
    invocation. The first observation that finds a non-empty cache marks
    the function WARM and records the baseline; any growth past the
    baseline afterwards fires the watchdog.
  * ``jax.monitoring`` backend-compile duration events (when available)
    feed a process-global compile counter and a trace instant per
    compile, so even unwatched compiles show up on the timeline.

Firing emits a trace instant (``recompile!``) plus a rank-0 warning; in
``strict`` mode it raises :class:`RecompileError` instead — the mode the
serving tests run under, proving the decode step compiles exactly once
across a multi-request run.
"""

import threading
import time
from typing import Callable, Dict, List, Optional

from ..utils.logging import logger
from .runctx import current as current_run
from .tracer import trace_instant

__all__ = ["RecompileError", "RecompileWatchdog", "install_compile_listener"]

MODES = ("off", "warn", "strict")

# process-global compile-event counter fed by jax.monitoring (see
# install_compile_listener); None until the listener is installed
_compile_events = 0
_last_compile_t: Optional[float] = None  # perf_counter of the newest one
_listener_installed = False
_listener_lock = threading.Lock()
_COMPILE_EVENT_KEY = "backend_compile"


def _on_duration_event(event: str, duration: float, **kwargs) -> None:
    global _compile_events, _last_compile_t
    if _COMPILE_EVENT_KEY in event:
        _compile_events += 1
        _last_compile_t = time.perf_counter()
        trace_instant("xla_compile", lane="compile",
                      seconds=round(duration, 4))


def install_compile_listener() -> bool:
    """Register the jax.monitoring duration listener (once per process;
    jax offers no per-listener unregister so it stays installed). Returns
    True when the listener is active."""
    global _listener_installed
    with _listener_lock:
        if _listener_installed:
            return True
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(
                _on_duration_event)
        except Exception:  # pragma: no cover - very old jax
            return False
        _listener_installed = True
        return True


def global_compile_events() -> int:
    """Backend compiles observed process-wide since listener install."""
    return _compile_events


def _cache_size(fn) -> Optional[int]:
    get = getattr(fn, "_cache_size", None)
    if get is None:
        return None
    try:
        return int(get())
    except Exception:  # pragma: no cover - defensive
        return None


class RecompileError(RuntimeError):
    """Raised in strict mode when a watched function recompiles after
    warmup."""


class RecompileWatchdog:
    def __init__(self, mode: str = "warn"):
        if mode not in MODES:
            raise ValueError(f"watchdog mode must be one of {MODES}, "
                             f"got {mode!r}")
        self.mode = mode
        self._lock = threading.Lock()
        self._fns: Dict[str, Callable] = {}
        self._baseline: Dict[str, Optional[int]] = {}  # None until warm
        self.fired: List[dict] = []  # one record per detected recompile
        if mode != "off":
            install_compile_listener()

    @property
    def enabled(self) -> bool:
        return self.mode != "off"

    # -------------------------------------------------------------- #

    def watch(self, name: str, fn: Callable) -> None:
        """Register a jitted function under ``name`` (idempotent; re-
        registering a new fn object resets its warmup)."""
        with self._lock:
            if self._fns.get(name) is fn:
                return
            self._fns[name] = fn
            self._baseline[name] = None

    def watched(self) -> List[str]:
        with self._lock:
            return list(self._fns)

    def counts(self) -> Dict[str, Optional[int]]:
        """Current jit-cache entry count per watched function."""
        with self._lock:
            fns = dict(self._fns)
        return {name: _cache_size(fn) for name, fn in fns.items()}

    def mark_warm(self, name: Optional[str] = None) -> None:
        """Snapshot current cache sizes as the post-warmup baseline
        (``observe`` does this automatically on the first non-empty
        sighting; call this to warm explicitly, e.g. after a warmup
        batch)."""
        with self._lock:
            names = [name] if name is not None else list(self._fns)
            for n in names:
                self._baseline[n] = _cache_size(self._fns[n])

    def observe(self, name: Optional[str] = None,
                step: Optional[int] = None) -> List[str]:
        """Compare watched functions' cache sizes against their warm
        baselines; returns the names that recompiled (after firing the
        configured reaction for each). ``step`` is the caller's step
        counter, carried into the warning/instant so a firing is
        attributable to a specific point in the run."""
        if not self.enabled:
            return []
        with self._lock:
            items = ([(name, self._fns[name])] if name is not None
                     else list(self._fns.items()))
        recompiled = []
        for n, fn in items:
            size = _cache_size(fn)
            if size is None:
                continue
            base = self._baseline.get(n)
            if base is None:
                if size > 0:  # first compile = warmup, not a violation
                    with self._lock:
                        self._baseline[n] = size
                continue
            if size > base:
                with self._lock:
                    self._baseline[n] = size  # report each growth once
                recompiled.append(n)
                self._fire(n, base, size, step=step)
        return recompiled

    # -------------------------------------------------------------- #

    def _fire(self, name: str, baseline: int, size: int,
              step: Optional[int] = None) -> None:
        rc = current_run()
        since = (time.perf_counter() - _last_compile_t
                 if _last_compile_t is not None else None)
        record = {"name": name, "baseline": baseline, "cache_size": size,
                  "step": step, "run_id": rc.run_id,
                  "since_last_compile_s": since}
        self.fired.append(record)
        args = {"fn": name, "cache_size": size,
                "run_id": rc.run_id or "", "role": rc.role,
                "incarnation": rc.incarnation}
        if step is not None:
            args["step"] = step
        if since is not None:
            args["since_last_compile_s"] = round(since, 3)
        trace_instant("recompile!", lane="compile", **args)
        ctx = f" [run {rc.run_id}]" if rc.run_id else ""
        if step is not None:
            ctx += f" at step {step}"
        if since is not None:
            ctx += f", {since:.1f}s since the last backend compile"
        msg = (f"recompile watchdog: {name!r} recompiled after warmup "
               f"(jit cache {baseline} -> {size}){ctx}; a shape/dtype is "
               f"leaking into the trace")
        if self.mode == "strict":
            raise RecompileError(msg)
        try:
            import jax
            rank0 = jax.process_index() == 0
        except Exception:  # pragma: no cover
            rank0 = True
        if rank0:
            logger.warning(msg)
