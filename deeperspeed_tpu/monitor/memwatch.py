"""Device-memory watermark lane + near-OOM post-mortem.

TPU runtimes expose an allocator ledger per device
(``device.memory_stats()``: ``bytes_in_use`` / ``peak_bytes_in_use`` /
``bytes_limit``); CPU returns ``None``. Before this module the repo
read that ledger in two hand-rolled places (``runtime/utils.py`` and
``utils/timer.py``) and nowhere near the trace. Now:

  * :func:`device_memory_stats` / :func:`aggregate_memory_stats` are
    the one normalized reader (``{}`` on backends with no ledger) that
    both legacy call sites delegate to;
  * :class:`MemWatch` samples the ledger at phase boundaries — one
    ``mem/watermark`` instant plus gauges per sample, and a
    ``span.note(hbm_in_use=…, hbm_peak=…)`` helper so the fwd / bwd /
    step / prefill / decode spans carry their watermark;
  * when ``bytes_in_use`` crosses ``near_oom_fraction`` of
    ``bytes_limit`` it fires a post-mortem: the top-K live buffers
    (shape / dtype / nbytes / sharding, via ``jax.live_arrays()``)
    emitted as compact instants that ride the tracer's inline flight
    sink — so a process the allocator kills moments later still leaves
    an explanation in ``flight.bin``.

Everything degrades to near-free on CPU: stats are ``{}``, watermarks
are zeros (so the span args and trace schema stay identical across
backends, which is what keeps the CPU tests honest), and the
post-mortem only auto-fires where a ``bytes_limit`` exists.
"""

import threading
from typing import Any, Dict, List, Optional

from ..utils.logging import logger
from .tracer import trace_instant

__all__ = [
    "MemWatch",
    "aggregate_memory_stats",
    "device_memory_stats",
]

# the allocator ledger keys we normalize (ints, bytes)
_STAT_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
              "largest_free_block_bytes", "num_allocs")


def device_memory_stats(device=None) -> Dict[str, int]:
    """``device.memory_stats()`` normalized to ints; ``{}`` when the
    backend has no allocator ledger (CPU) or no device exists at all."""
    if device is None:
        try:
            import jax
            device = jax.local_devices()[0]
        except Exception:  # pragma: no cover - no backend
            return {}
    try:
        raw = device.memory_stats()
    except Exception:  # pragma: no cover - defensive
        return {}
    if not raw:
        return {}
    out: Dict[str, int] = {}
    for k in _STAT_KEYS:
        v = raw.get(k)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[k] = int(v)
    return out


def aggregate_memory_stats() -> Dict[str, int]:
    """Ledger summed across local devices; ``{}`` when every device is
    silent (so callers can distinguish "no ledger" from "zero bytes")."""
    try:
        import jax
        devices = jax.local_devices()
    except Exception:  # pragma: no cover - no backend
        return {}
    total: Dict[str, int] = {}
    backed = False
    for d in devices:
        s = device_memory_stats(d)
        if not s:
            continue
        backed = True
        for k, v in s.items():
            if k == "largest_free_block_bytes":
                total[k] = max(total.get(k, 0), v)
            else:
                total[k] = total.get(k, 0) + v
    return total if backed else {}


class MemWatch:
    """Watermark sampler + near-OOM post-mortem (see module docstring).

    ``sample(phase)`` is the phase-boundary hook: one ``mem/watermark``
    instant (zeros on CPU — the lane exists on every backend) plus the
    ``mem_bytes_in_use`` / ``mem_peak_bytes`` gauges, and the near-OOM
    trip check. ``annotate(span, phase)`` additionally stamps the
    enclosing span with ``hbm_in_use`` / ``hbm_peak`` args."""

    def __init__(self, registry=None, near_oom_fraction: float = 0.92,
                 top_k: int = 8):
        if not (0.0 < near_oom_fraction <= 1.0):
            raise ValueError(
                f"near_oom_fraction must be in (0, 1], got {near_oom_fraction}")
        self._registry = registry
        self.near_oom_fraction = near_oom_fraction
        self.top_k = top_k
        self._lock = threading.Lock()
        self._armed = True         # re-arms when usage falls back under
        self.postmortems = 0       # how many times the dump fired

    # -- sampling ----------------------------------------------------- #

    def sample(self, phase: str) -> Dict[str, int]:
        stats = aggregate_memory_stats()
        in_use = stats.get("bytes_in_use", 0)
        peak = stats.get("peak_bytes_in_use", 0)
        limit = stats.get("bytes_limit", 0)
        trace_instant("mem/watermark", lane="mem", phase=phase,
                      bytes_in_use=in_use, peak_bytes=peak,
                      **({"bytes_limit": limit} if limit else {}))
        if self._registry is not None:
            self._registry.gauge(
                "mem_bytes_in_use",
                "device allocator: live bytes across local devices",
            ).set(float(in_use))
            self._registry.gauge(
                "mem_peak_bytes",
                "device allocator: peak live bytes across local devices",
            ).set(float(peak))
        if limit > 0:
            frac = in_use / limit
            with self._lock:
                fire = self._armed and frac >= self.near_oom_fraction
                if fire:
                    self._armed = False
                elif frac < 0.75 * self.near_oom_fraction:
                    self._armed = True
            if fire:
                self.post_mortem(
                    reason=f"near-oom at {phase}: "
                           f"{frac:.1%} of bytes_limit", stats=stats)
        return stats

    def annotate(self, span, phase: str) -> Dict[str, int]:
        """sample() + watermark args on the enclosing span (works on the
        null span too — note() is a no-op there)."""
        stats = self.sample(phase)
        span.note(hbm_in_use=stats.get("bytes_in_use", 0),
                  hbm_peak=stats.get("peak_bytes_in_use", 0))
        return stats

    # -- post-mortem --------------------------------------------------- #

    def live_buffers(self, top_k: Optional[int] = None) -> List[Dict[str, Any]]:
        """Top-K live device buffers by size: shape / dtype / nbytes /
        sharding. Pure inspection — safe to call anywhere."""
        try:
            import jax
            arrays = jax.live_arrays()
        except Exception:  # pragma: no cover - no backend
            return []
        rows: List[Dict[str, Any]] = []
        for x in arrays:
            try:
                rows.append({
                    "shape": "x".join(str(s) for s in x.shape) or "scalar",
                    "dtype": str(x.dtype),
                    "nbytes": int(x.nbytes),
                    "sharding": str(getattr(x, "sharding", "?")),
                })
            except Exception:  # deleted/donated mid-iteration
                continue
        rows.sort(key=lambda r: r["nbytes"], reverse=True)
        return rows[: top_k if top_k is not None else self.top_k]

    def post_mortem(self, reason: str,
                    stats: Optional[Dict[str, int]] = None) -> Dict[str, Any]:
        """Dump the allocation picture into the trace. Each buffer is its
        own compact ``mem/buffer`` instant (small enough for one flight
        slot each — a 512 B slot cannot hold the whole table), headed by
        one ``mem/postmortem`` summary; the tracer's inline flight sink
        makes the dump SIGKILL-proof. Returns the payload for callers
        (tests, the OOM handler) that want it in hand."""
        if stats is None:
            stats = aggregate_memory_stats()
        buffers = self.live_buffers()
        payload = {
            "reason": reason,
            "bytes_in_use": stats.get("bytes_in_use", 0),
            "bytes_limit": stats.get("bytes_limit", 0),
            "live_buffers": len(buffers),
            "buffers": buffers,
        }
        trace_instant("mem/postmortem", lane="mem", reason=reason,
                      bytes_in_use=payload["bytes_in_use"],
                      bytes_limit=payload["bytes_limit"],
                      buffers=len(buffers))
        for rank, b in enumerate(buffers):
            trace_instant("mem/buffer", lane="mem", rank=rank,
                          shape=b["shape"], dtype=b["dtype"],
                          nbytes=b["nbytes"], sharding=b["sharding"])
        with self._lock:
            self.postmortems += 1
        logger.warning("memwatch: post-mortem (%s): %d live buffers, "
                       "%.2f GB in use", reason, len(buffers),
                       payload["bytes_in_use"] / 2**30)
        return payload
