"""Continuous perf-regression ledger: one schema, one gate.

The repo accumulates benchmark truth as loose ``BENCH_*.json`` files —
every drill writes its own shape and nothing ever compares two runs.
This module gives them a spine:

  * one record schema — ``{metric, value, direction, platform, source,
    git_rev, wall_time, run}`` (run context from runctx) — appended as
    JSON lines to ``PERF_LEDGER.jsonl``;
  * a tracked-metric table (:data:`METRIC_SPECS`) mapping each headline
    number in the BENCH corpus to its file, JSON path, direction
    (higher/lower-is-better), and per-metric tolerance;
  * a CLI gate::

        python -m deeperspeed_tpu.monitor.ledger append   # ingest corpus
        python -m deeperspeed_tpu.monitor.ledger check    # regression gate

    ``check`` compares each metric's current value (from the BENCH file,
    or ``--metric/--value`` for a live run) against the rolling baseline
    (median of the last N ledger records on the same platform) and exits
    non-zero when any tracked metric regresses beyond its tolerance —
    the gate every future perf PR (and the sharding refactor) benches
    against.

Design choices that keep the gate honest rather than noisy: tolerances
are per-metric (wall-clock numbers on the 1-core CPU host get wide
bands, counters like ``decode_compiles`` and ``strict_problems`` get
zero), missing BENCH files are *skipped with a note* (BENCH_elastic was
specced but never landed; absence is not a regression), and a first run
against an empty ledger seeds it and passes — the gate compares runs,
it does not invent a baseline.
"""

import argparse
import dataclasses
import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .runctx import current as current_run

__all__ = [
    "METRIC_SPECS",
    "MetricSpec",
    "PerfLedger",
    "collect_current",
    "main",
]

DEFAULT_LEDGER = "PERF_LEDGER.jsonl"
DEFAULT_BASELINE_N = 5


@dataclasses.dataclass(frozen=True)
class MetricSpec:
    """One tracked metric: where it lives and how much drift is noise."""

    name: str                 # ledger metric name, dotted
    file: str                 # BENCH file (repo-root-relative)
    path: Tuple[str, ...]     # JSON path inside the file
    direction: str            # "higher" | "lower" (which way is better)
    rel_tol: float = 0.25     # fractional drift allowed past baseline
    abs_tol: float = 0.0      # additive slack (units of the metric)
    note: str = ""

    def regressed(self, value: float, baseline: float) -> bool:
        if self.direction == "higher":
            return value < baseline * (1.0 - self.rel_tol) - self.abs_tol
        return value > baseline * (1.0 + self.rel_tol) + self.abs_tol


# The corpus gate. Wall-clock metrics measured on the 1-core CPU host
# carry wide rel_tol (the BENCH files themselves document the timing
# caveat); structural counters carry zero tolerance — one extra decode
# compile IS the regression.
METRIC_SPECS: Tuple[MetricSpec, ...] = (
    # comm (PR 6/10)
    MetricSpec("comm.int8.reduce_only_x", "BENCH_comm.json",
               ("modes", "int8", "reduce_only_x"), "higher", 0.10),
    MetricSpec("comm.int8.loss_delta_pct", "BENCH_comm.json",
               ("modes", "int8", "loss_delta_pct"), "lower", 0.50, 0.05),
    MetricSpec("comm.fp32.step_ms", "BENCH_comm.json",
               ("modes", "fp32", "step_ms"), "lower", 0.50,
               note="cpu wall clock: wide band"),
    MetricSpec("comm.overlap_fraction", "BENCH_comm.json",
               ("overlap", "overlap_fraction"), "higher", 0.05),
    # serving (PR 2/8)
    MetricSpec("serving.tokens_per_sec", "BENCH_serving.json",
               ("tokens_per_sec",), "higher", 0.30,
               note="cpu wall clock: wide band"),
    MetricSpec("serving.ttft_p99_s", "BENCH_serving.json",
               ("ttft_p99_s",), "lower", 0.50, 0.05),
    MetricSpec("serving.decode_compiles", "BENCH_serving.json",
               ("decode_compiles",), "lower", 0.0,
               note="one-compile decode is the invariant"),
    MetricSpec("serving.prefill_compiles", "BENCH_serving.json",
               ("prefill_compiles",), "lower", 0.0, 2.0,
               note="one compile per length bucket; --slo warms every "
                    "bucket (5) where the old bench warmed 3"),
    # request-path doctor (PR 17): attributed tail latency and unit
    # cost from the bench's --slo breakdown. Wall-clock on the CPU
    # host: wide bands; the attribution itself is gated by the slo CLI
    # in check.sh (residual < 5% is a hard failure there, not here)
    MetricSpec("serving.ttft_p99_ms", "BENCH_serving.json",
               ("slo", "ttft_p99_ms"), "lower", 0.50, 85.0,
               note="cpu wall clock: wide band; basis changed at the "
                    "--shared-prefix bench (slo pass now measures a "
                    "d_model=256 model, was 64) — abs band covers the "
                    "declared re-basis until the rolling median "
                    "catches up"),
    MetricSpec("serving.cost_per_1k_tokens", "BENCH_serving.json",
               ("slo", "cost_per_1k_tokens"), "lower", 0.50, 0.5,
               note="device-seconds per 1k tokens, cpu-host nominal"),
    # prefix-radix KV reuse (PR 18): the --shared-prefix traffic mix
    # must keep finding its system prompts in the radix cache — a
    # regression here means prompts are being re-prefilled fleet-wide
    MetricSpec("serving.prefill_tokens_saved_frac", "BENCH_serving.json",
               ("prefix_reuse", "tokens_saved_frac"), "higher", 0.15,
               note="fraction of prompt tokens served from the radix "
                    "cache under --shared-prefix traffic"),
    MetricSpec("serving.reuse_hit_rate", "BENCH_serving.json",
               ("prefix_reuse", "reuse_hit_rate"), "higher", 0.15),
    # speculative decoding (PR 19): the --speculative dual-pass bench.
    # Acceptance is a model/drafter property (tight band — a drop means
    # the verify contract or the drafter sync broke, not the host);
    # TPOT is cpu wall clock (wide band)
    MetricSpec("serving.spec_accept_rate", "BENCH_serving.json",
               ("speculative", "accept_rate"), "higher", 0.15,
               note="drafted tokens the target verified and kept"),
    MetricSpec("serving.tpot_ms", "BENCH_serving.json",
               ("speculative", "tpot_ms"), "lower", 0.50, 1.0,
               note="cpu wall clock: wide band; speculative pass of "
                    "the dual-pass bench"),
    # fleet (PR 8)
    MetricSpec("fleet.fault.accepted", "BENCH_fleet.json",
               ("failover", "fault", "accepted"), "higher", 0.0,
               note="kill drill must not lose accepted requests"),
    MetricSpec("fleet.fault.retries", "BENCH_fleet.json",
               ("failover", "fault", "retries"), "lower", 0.0, 2.0),
    MetricSpec("fleet.healthy.p99_ttft_s", "BENCH_fleet.json",
               ("failover", "healthy", "p99_ttft_s"), "lower", 0.50, 0.05),
    # observability (PR 9)
    MetricSpec("obs.strict_problems", "BENCH_obs.json",
               ("fleet_merge", "strict_problems"), "lower", 0.0),
    MetricSpec("obs.rids_traceable", "BENCH_obs.json",
               ("fleet_merge", "rids_traceable"), "higher", 0.0),
    MetricSpec("obs.goodput.accounting_error", "BENCH_obs.json",
               ("goodput", "accounting_error"), "lower", 0.0, 0.001),
    # datapipe (PR 5)
    MetricSpec("datapipe.host_blocked_mean_ms", "BENCH_datapipe.json",
               ("prefetch_on", "host_blocked_mean_ms"), "lower", 0.50, 0.5),
    MetricSpec("datapipe.stall_ratio", "BENCH_datapipe.json",
               ("stall_ratio",), "lower", 1.00, 0.10),
    # resilience (PR 4)
    MetricSpec("resilience.blocked_ratio", "BENCH_resilience.json",
               ("blocked_ratio",), "lower", 1.00, 0.01),
    MetricSpec("resilience.resume_latency_s", "BENCH_resilience.json",
               ("resume_latency_s",), "lower", 0.50, 0.2),
    # elastic (PR 7) — drill writes no BENCH file yet; specced so the
    # day it lands it is tracked, skipped-with-a-note until then
    MetricSpec("elastic.max_loss_delta", "BENCH_elastic.json",
               ("max_loss_delta",), "lower", 0.0, 1e-6,
               note="world-size resharding must stay bit-identical"),
    # hardware MFU (last real-TPU window)
    MetricSpec("mfu.1p3b.micro_step_floor_tflops", "MFU_DECOMP.json",
               ("1.3b", "micro_step_floor_tflops"), "higher", 0.10),
    # sharding substrate (PR 13): loss parity across layouts is an
    # exactness gate; step time per layout is wide-band (CPU-host noise)
    MetricSpec("mesh.parity.max_loss_delta", "BENCH_mesh.json",
               ("parity", "max_loss_delta"), "lower", 0.0, 1e-6,
               note="canonical mesh must reproduce the legacy loss curve"),
    MetricSpec("mesh.dp_fsdp.step_ms", "BENCH_mesh.json",
               ("layouts", "dp2_fsdp4", "step_ms"), "lower", 1.00, 5.0),
    MetricSpec("mesh.zero3.sharded_frac", "BENCH_mesh.json",
               ("layouts", "fsdp8_zero3", "param_sharded_frac"),
               "higher", 0.0, 0.01,
               note="ZeRO-3 on fsdp must actually shard the param bytes"),
    # lifecycle (PR 15): zero-downtime train→serve. Losing an accepted
    # request across a weight push, a non-bit-identical live re-mesh,
    # or restart downtime during a pool shrink are exactness gates; the
    # re-mesh stall itself is CPU wall clock and gets a wide band
    MetricSpec("lifecycle.lost_accepted", "BENCH_lifecycle.json",
               ("serving", "lost_accepted"), "lower", 0.0,
               note="weight pushes + pool shrink must not lose accepted "
                    "requests"),
    MetricSpec("lifecycle.max_loss_delta", "BENCH_lifecycle.json",
               ("remesh", "max_loss_delta"), "lower", 0.0, 1e-9,
               note="live re-mesh must match the kill-restart reshard "
                    "losses bit-for-bit"),
    MetricSpec("lifecycle.weight_pushes", "BENCH_lifecycle.json",
               ("weight_pushes",), "higher", 0.0),
    MetricSpec("lifecycle.goodput.restart_s", "BENCH_lifecycle.json",
               ("goodput", "restart_s"), "lower", 0.0, 0.5,
               note="the live path keeps the process up: shrink "
                    "downtime lands in `remesh`, not `restart`"),
    MetricSpec("lifecycle.remesh_stall_s", "BENCH_lifecycle.json",
               ("remesh", "stall_s"), "lower", 1.00, 5.0,
               note="cpu wall clock: wide band"),
    # static analysis (PR 14): the committed baseline findings file —
    # error count is an exactness gate (the CLI already fails CI on
    # errors; the ledger catches a quietly-committed regressed
    # baseline), warnings/suppressions get one entry of slack so a
    # deliberate new waiver doesn't read as a perf regression
    MetricSpec("analysis.errors", "ANALYSIS_BASELINE.json",
               ("counts", "error"), "lower", 0.0,
               note="python -m deeperspeed_tpu.analysis must stay clean"),
    MetricSpec("analysis.warnings", "ANALYSIS_BASELINE.json",
               ("counts", "warning"), "lower", 0.0, 1.0),
    MetricSpec("analysis.suppressed", "ANALYSIS_BASELINE.json",
               ("counts", "suppressed"), "lower", 0.0, 1.0,
               note="every new waiver needs a reason in "
                    "ANALYSIS_SUPPRESSIONS.json"),
    # autotune (PR 16): the cost model's honesty metric is rank
    # correlation between predicted and measured orderings over the
    # confirmed set (the acceptance floor is 0.6, so a baseline near
    # 1.0 minus the absolute band still gates there); the best
    # predicted cost itself is CPU-nominal and wide-band — it exists
    # so a cost-model change that doubles every prediction is seen
    MetricSpec("autotune.rank_correlation", "BENCH_autotune.json",
               ("confirm", "rank_correlation"), "higher", 0.0, 0.40,
               note="predicted order must keep tracking measured order"),
    MetricSpec("autotune.best_predicted_cost", "BENCH_autotune.json",
               ("best", "predicted_step_s"), "lower", 1.00,
               note="cpu-nominal roofline seconds: wide band"),
    # distributed (PR 20): the multi-host fleet drill. Cross-process
    # loss parity is an exactness gate (the canonical-slot reduction
    # must be independent of the device->process mapping AND the world
    # size); the SIGKILL->recovery wall time is CPU wall clock (two
    # jax.distributed rendezvous + recompile) and gets a wide band;
    # the cross-host wire bytes of the hierarchical int8 schedule are
    # a structural count priced by wiremodel.py
    MetricSpec("multihost.max_loss_delta", "BENCH_multihost.json",
               ("parity", "max_loss_delta"), "lower", 0.0, 1e-9,
               note="2-process fleet (and the grown 3-process fleet) "
                    "must match the single-process mesh bit-for-bit"),
    MetricSpec("multihost.crash_restarts_after_growth",
               "BENCH_multihost.json",
               ("growth", "crash_restarts_after_growth"), "lower", 0.0,
               note="pool growth is a planned re-mesh, never a crash "
                    "restart"),
    MetricSpec("multihost.restart_s", "BENCH_multihost.json",
               ("restart", "restart_s"), "lower", 1.00, 30.0,
               note="SIGKILL -> first post-barrier step: cpu wall "
                    "clock, wide band"),
    MetricSpec("multihost.int8_inter_bytes", "BENCH_multihost.json",
               ("wire", "int8", "inter_bytes"), "lower", 0.0,
               note="cross-host hop of the two-level int8 schedule "
                    "(wiremodel pricing, exact)"),
)

_SPECS_BY_NAME = {s.name: s for s in METRIC_SPECS}


# ------------------------------------------------------------------ #
# record plumbing
# ------------------------------------------------------------------ #


def _git_rev(root: str) -> str:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=root,
            capture_output=True, text=True, timeout=10)
        rev = out.stdout.strip()
        return rev if out.returncode == 0 and rev else "unknown"
    except Exception:
        return "unknown"


def _detect_platform() -> str:
    try:
        import jax
        return jax.local_devices()[0].platform
    except Exception:
        return "unknown"


def _dig(obj: Any, path: Sequence[str]) -> Optional[float]:
    for key in path:
        if not isinstance(obj, dict) or key not in obj:
            return None
        obj = obj[key]
    if isinstance(obj, bool) or not isinstance(obj, (int, float)):
        return None
    return float(obj)


def make_record(metric: str, value: float, platform: str, source: str,
                git_rev: str, wall_time: Optional[float] = None) -> Dict:
    rc = current_run()
    return {
        "metric": metric,
        "value": float(value),
        "platform": platform,
        "source": source,
        "git_rev": git_rev,
        "wall_time": time.time() if wall_time is None else wall_time,
        "run": rc.as_args(),
    }


def collect_current(root: str,
                    specs: Sequence[MetricSpec] = METRIC_SPECS,
                    ) -> Tuple[List[Dict], List[str]]:
    """Read every tracked metric's current value from the BENCH corpus
    under ``root``. Returns (records, notes) — notes name skipped files
    and missing paths, which are reported but never fail the gate."""
    records: List[Dict] = []
    notes: List[str] = []
    rev = _git_rev(root)
    cache: Dict[str, Any] = {}
    for spec in specs:
        fpath = os.path.join(root, spec.file)
        if spec.file not in cache:
            if not os.path.exists(fpath):
                cache[spec.file] = None
            else:
                try:
                    with open(fpath) as f:
                        cache[spec.file] = json.load(f)
                except (OSError, json.JSONDecodeError) as e:
                    cache[spec.file] = None
                    notes.append(f"skip {spec.file}: unreadable ({e})")
        blob = cache[spec.file]
        if blob is None:
            if not any(n.startswith(f"skip {spec.file}") for n in notes):
                notes.append(f"skip {spec.file}: missing")
            continue
        value = _dig(blob, spec.path)
        if value is None:
            notes.append(f"skip {spec.name}: no value at "
                         f"{'.'.join(spec.path)} in {spec.file}")
            continue
        platform = blob.get("platform") if isinstance(blob, dict) else None
        records.append(make_record(
            spec.name, value, platform or "cpu", spec.file, rev))
    return records, notes


class PerfLedger:
    """The JSONL file plus baseline/regression arithmetic."""

    def __init__(self, path: str, baseline_n: int = DEFAULT_BASELINE_N):
        self.path = path
        self.baseline_n = baseline_n

    def read(self) -> List[Dict]:
        if not os.path.exists(self.path):
            return []
        out: List[Dict] = []
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # half-written tail (crash) — records stand alone
                if isinstance(rec, dict) and "metric" in rec:
                    out.append(rec)
        return out

    def append(self, records: Sequence[Dict]) -> int:
        if not records:
            return 0
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        with open(self.path, "a") as f:
            for rec in records:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
        return len(records)

    def baseline(self, metric: str, platform: Optional[str] = None,
                 history: Optional[List[Dict]] = None) -> Optional[float]:
        """Rolling baseline: median of the last N records for ``metric``
        (same platform when given — a TPU number is not a CPU baseline)."""
        if history is None:
            history = self.read()
        vals = [r["value"] for r in history
                if r.get("metric") == metric
                and isinstance(r.get("value"), (int, float))
                and (platform is None or r.get("platform") == platform)]
        if not vals:
            return None
        tail = sorted(vals[-self.baseline_n:])
        mid = len(tail) // 2
        if len(tail) % 2:
            return float(tail[mid])
        return (tail[mid - 1] + tail[mid]) / 2.0

    def check(self, candidates: Sequence[Dict]) -> Tuple[List[str], List[str]]:
        """Compare candidate records against rolling baselines. Returns
        (failures, report_lines)."""
        history = self.read()
        failures: List[str] = []
        report: List[str] = []
        for rec in candidates:
            name = rec["metric"]
            spec = _SPECS_BY_NAME.get(name)
            base = self.baseline(name, rec.get("platform"), history)
            if base is None:
                # same metric, any platform — better a cross-platform
                # note than silence on a first TPU run
                base = self.baseline(name, None, history)
            if base is None:
                report.append(f"  NEW  {name} = {rec['value']:g} "
                              f"(no baseline yet)")
                continue
            if spec is None:
                report.append(f"  ??   {name} = {rec['value']:g} "
                              f"(untracked metric; baseline {base:g})")
                continue
            if spec.regressed(rec["value"], base):
                arrow = "<" if spec.direction == "higher" else ">"
                failures.append(
                    f"{name}: {rec['value']:g} {arrow} baseline {base:g} "
                    f"beyond tol (rel {spec.rel_tol:g}, abs {spec.abs_tol:g})"
                    + (f" — {spec.note}" if spec.note else ""))
                report.append(f"  FAIL {name} = {rec['value']:g} "
                              f"(baseline {base:g}, {spec.direction} is "
                              f"better)")
            else:
                report.append(f"  ok   {name} = {rec['value']:g} "
                              f"(baseline {base:g})")
        return failures, report


# ------------------------------------------------------------------ #
# CLI
# ------------------------------------------------------------------ #


def _live_records(args, root: str) -> List[Dict]:
    """One record from ``--metric/--value`` (a live run reporting in)."""
    if args.metric is None:
        return []
    if args.value is None:
        raise SystemExit("--metric requires --value")
    return [make_record(args.metric, args.value,
                        args.platform or _detect_platform(),
                        "live", _git_rev(root))]


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeperspeed_tpu.monitor.ledger",
        description="Perf-regression ledger over the BENCH_*.json corpus.")
    ap.add_argument("command", choices=("append", "check"))
    ap.add_argument("--root", default=".",
                    help="repo root holding the BENCH_*.json corpus")
    ap.add_argument("--ledger", default=None,
                    help=f"ledger path (default <root>/{DEFAULT_LEDGER})")
    ap.add_argument("--baseline-n", type=int, default=DEFAULT_BASELINE_N,
                    help="rolling-baseline window (median of last N)")
    ap.add_argument("--metric", default=None,
                    help="also include one live metric by name")
    ap.add_argument("--value", type=float, default=None,
                    help="value for --metric")
    ap.add_argument("--platform", default=None,
                    help="platform label for --metric (default: detected)")
    args = ap.parse_args(argv)

    root = args.root
    ledger = PerfLedger(args.ledger or os.path.join(root, DEFAULT_LEDGER),
                        baseline_n=args.baseline_n)
    corpus, notes = collect_current(root)
    live = _live_records(args, root)

    if args.command == "append":
        n = ledger.append(corpus + live)
        for note in notes:
            print(f"note: {note}")
        print(f"appended {n} records to {ledger.path}")
        return 0

    # check
    candidates = corpus + live
    if not ledger.read():
        n = ledger.append(candidates)
        for note in notes:
            print(f"note: {note}")
        print(f"ledger was empty: seeded {n} records to {ledger.path}; "
              "nothing to compare yet")
        return 0
    failures, report = ledger.check(candidates)
    print(f"perf ledger check: {len(candidates)} metrics vs {ledger.path}")
    for line in report:
        print(line)
    for note in notes:
        print(f"note: {note}")
    if failures:
        print(f"\n{len(failures)} regression(s):", file=sys.stderr)
        for f in failures:
            print(f"  {f}", file=sys.stderr)
        return 1
    print("no regressions")
    return 0


if __name__ == "__main__":
    sys.exit(main())
