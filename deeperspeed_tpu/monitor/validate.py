"""Chrome-trace schema validator.

Checks the invariants Perfetto/chrome://tracing rely on, so a bad trace
fails in CI instead of rendering as an empty timeline:

  * top level is a JSON event array or ``{"traceEvents": [...]}``;
  * every event is an object carrying ``ph``, ``pid``, ``tid`` (and
    ``name`` + numeric non-negative ``ts`` for non-metadata phases);
  * ``ph`` is a known phase; ``"X"`` events carry a numeric
    non-negative ``dur``;
  * ``"B"``/``"E"`` pairs balance per ``(pid, tid)`` track with proper
    LIFO nesting (an ``E`` must close the innermost open ``B`` of the
    same name);
  * named events with a registered arg schema (the serving fleet's
    ``serving/finish`` / ``serving/shed`` / ``serving/retry`` /
    ``serving/replica_down`` instants) carry their required args — a
    drill trace missing the rid/reason fields the zero-loss audit keys
    on fails here, not in a dashboard.

Strict mode adds name discipline: every non-metadata event must carry a
name under a registered subsystem prefix (``engine/``, ``serving/``,
``flight/``, ``goodput/``, ...) or be a known exact name
(``xla_compile``, ``recompile!``). Default (non-strict) keeps the
original behavior — unknown names pass, so ad-hoc spans in user code
stay legal; strict is what CI runs on merged drill traces, where an
unknown name means a producer and the schema drifted apart.

Used two ways: as a library (``validate_events`` / ``validate_file``,
the pytest round-trips a generated trace through it) and as a CLI::

    python -m deeperspeed_tpu.monitor.validate [--strict] trace.json

exit 0 = valid, exit 1 = problems (one per line on stderr).
"""

import json
import sys
from typing import List

__all__ = ["validate_events", "validate_file", "main"]

# phases from the Trace Event Format spec; "M" (metadata) and "C"
# (counter) are what the tracer emits beyond spans/instants
KNOWN_PHASES = set("BEXiICMPSTFsftbenO(N)D{}v")

# named-event arg schemas: when an event with one of these names appears,
# its "args" object must carry the listed keys. These are the events the
# fleet drill's zero-request-loss audit and the retry/shed accounting
# join on, so a rename or dropped field breaks CI, not the postmortem.
EVENT_ARG_SCHEMAS = {
    "serving/finish": ("rid", "reason"),
    "serving/shed": ("rid", "retry_after_s"),
    "serving/retry": ("rid", "attempt", "replica"),
    "serving/replica_down": ("replica", "cause", "inflight"),
    # run-scoped observability (flight recorder / aggregate / goodput)
    "serving/dispatch": ("rid", "replica", "attempt"),
    # request-path doctor (monitor/reqledger.py): the per-rid timeline
    # is reconstructed by joining exactly these events — a dropped rid
    # or ts breaks attribution, so the schemas are load-bearing
    "serving/admit": ("rid", "slot", "ctx_len", "admissions"),
    "serving/prefill": ("rid", "ctx_len"),
    "serving/preempt": ("rid", "slot", "blocks_freed"),
    # prefix-radix KV reuse + chunked prefill: reuse hits are the
    # aggregator's flow-arrow source per rid, CoW splits audit the
    # exactly-once divergence invariant, and chunk spans are what the
    # reqledger splits across its prefill/hol_blocking buckets
    "kv/reuse": ("rid", "matched_tokens", "shared_blocks"),
    "kv/cow_split": ("rid", "block", "rows"),
    "serving/prefill_chunk": ("rid", "chunk", "tokens"),
    "req/submit": ("rid", "prompt_len"),
    "req/accept": ("rid", "cost_tokens"),
    "req/requeue": ("rid", "backoff_s"),
    "slo/violation": ("slo", "value_ms", "target_ms"),
    "trace/dropped": ("dropped",),
    "flight/recovered": ("count", "torn", "source"),
    "run/start": ("run_id", "role", "incarnation"),
    "run/preempt": ("signum",),
    "goodput/report": ("wall_s", "goodput"),
    # comm overlap scheduling: per-bucket reduce launches must say
    # whether they were overlapped, and every drain must say how many
    # buckets it waited on — overlap_fraction in BENCH_comm.json joins
    # on exactly these spans
    "comm/reduce": ("bucket", "mode"),
    "comm/overlap_window": ("buckets",),
    # perf doctor: compiled-cost captures, live per-step MFU, and the
    # device-memory watermark lane — PERF_LEDGER tooling and the
    # roofline readout join on these
    "perf/compiled": ("entry", "flops", "bytes", "peak_hbm"),
    "perf/step": ("entry", "mfu", "wall_ms", "verdict"),
    "mem/watermark": ("phase", "bytes_in_use", "peak_bytes"),
    "mem/postmortem": ("reason", "bytes_in_use", "buffers"),
    "mem/buffer": ("rank", "shape", "dtype", "nbytes", "sharding"),
    # sharding substrate: every mesh build announces its layout, and the
    # bench's placement audits record what actually sharded — BENCH_mesh
    # and post-hoc layout debugging join on these
    "mesh/build": ("axes", "devices"),
    "mesh/audit": ("tree", "sharded_frac", "digest"),
    # lifecycle control plane: every live re-mesh span names both
    # topologies (the goodput `remesh` bucket and the drill's audit
    # join on it); publishes/rollouts/repins carry the version so
    # mixed-version routing is reconstructible from the trace alone
    "lifecycle/remesh": ("world_from", "world_to"),
    "lifecycle/publish": ("version", "tag", "step"),
    "lifecycle/rollout": ("replica", "version"),
    "lifecycle/repin": ("rid", "version"),
    # speculative decoding (serving/spec): per-round draft/verify
    # dispatches carry their device-seconds so the reqledger can split
    # decode attribution into draft vs verify cost, and per-rid accept
    # instants are what acceptance-rate accounting joins on
    "spec/draft": ("n_active", "k", "dur_us"),
    "spec/verify": ("n_active", "k", "dur_us"),
    "spec/accept": ("rid", "accepted", "k", "emitted"),
    # multi-host runtime (distributed/): every process stamps its
    # topology at jax.distributed init (the merged fleet timeline and
    # BENCH_multihost join per-host lanes on these). Fleet-side
    # coordination — rendezvous, restart barriers, pool growth — is
    # recorded in the supervisor's restart JSONL and the rendezvous
    # records, not as trace events (the supervisor owns no trace lane)
    "dist/init": ("process", "processes", "local_devices",
                  "global_devices"),
}

# strict-mode name discipline: one prefix per subsystem that emits
# events, plus the exact names outside any subsystem
KNOWN_EVENT_PREFIXES = (
    "engine/", "pipe/", "offload/", "comm/", "kernels/", "datapipe/",
    "resilience/", "serving/", "flight/", "run/", "goodput/", "trace/",
    "perf/", "mem/", "mesh/", "ablation/", "lifecycle/", "req/", "slo/",
    "kv/", "spec/", "dist/",
)
KNOWN_EVENT_NAMES = frozenset({
    "xla_compile", "recompile!", "process_name", "thread_name",
})


def _known_name(name) -> bool:
    return (isinstance(name, str)
            and (name in KNOWN_EVENT_NAMES
                 or name.startswith(KNOWN_EVENT_PREFIXES)))

_NUM = (int, float)


def _is_num(v) -> bool:
    return isinstance(v, _NUM) and not isinstance(v, bool)


def validate_events(events, strict: bool = False) -> List[str]:
    """Returns a list of problems; empty means the trace is valid.
    ``strict`` additionally rejects event names outside the registered
    subsystem prefixes / known exact names."""
    if not isinstance(events, list):
        return [f"traceEvents must be a list, got {type(events).__name__}"]
    errors: List[str] = []
    open_stacks = {}  # (pid, tid) -> [names of open B events]
    for i, ev in enumerate(events):
        where = f"event[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object "
                          f"({type(ev).__name__})")
            continue
        ph = ev.get("ph")
        if ph is None:
            errors.append(f"{where}: missing required field 'ph'")
            continue
        if not isinstance(ph, str) or ph not in KNOWN_PHASES:
            errors.append(f"{where}: unknown phase {ph!r}")
            continue
        for field in ("pid", "tid"):
            if field not in ev:
                errors.append(f"{where} (ph={ph}): missing required "
                              f"field {field!r}")
        if ph == "M":
            continue  # metadata: no ts/name requirements
        if "name" not in ev:
            errors.append(f"{where} (ph={ph}): missing required field "
                          f"'name'")
        elif strict and not _known_name(ev["name"]):
            errors.append(
                f"{where} (ph={ph}): unknown event name {ev['name']!r} "
                f"(strict mode requires a registered subsystem prefix)")
        ts = ev.get("ts")
        if ts is None:
            errors.append(f"{where} (ph={ph}): missing required field 'ts'")
        elif not _is_num(ts) or ts < 0:
            errors.append(f"{where} (ph={ph}): 'ts' must be a "
                          f"non-negative number, got {ts!r}")
        schema = EVENT_ARG_SCHEMAS.get(ev.get("name"))
        if schema is not None:
            args = ev.get("args")
            if not isinstance(args, dict):
                errors.append(f"{where}: {ev.get('name')!r} requires an "
                              f"'args' object with {sorted(schema)}")
            else:
                missing = [k for k in schema if k not in args]
                if missing:
                    errors.append(f"{where}: {ev.get('name')!r} args "
                                  f"missing {missing}")
        if ph == "X":
            dur = ev.get("dur")
            if dur is None:
                errors.append(f"{where}: 'X' event missing 'dur'")
            elif not _is_num(dur) or dur < 0:
                errors.append(f"{where}: 'dur' must be a non-negative "
                              f"number, got {dur!r}")
        if ph in ("B", "E"):
            track = (ev.get("pid"), ev.get("tid"))
            stack = open_stacks.setdefault(track, [])
            name = ev.get("name")
            if ph == "B":
                stack.append(name)
            else:
                if not stack:
                    errors.append(f"{where}: 'E' with no open 'B' on "
                                  f"track pid={track[0]} tid={track[1]}")
                elif stack[-1] != name:
                    errors.append(
                        f"{where}: 'E' for {name!r} does not close the "
                        f"innermost open 'B' ({stack[-1]!r}) on track "
                        f"pid={track[0]} tid={track[1]}")
                    stack.pop()
                else:
                    stack.pop()
    for (pid, tid), stack in open_stacks.items():
        for name in stack:
            errors.append(f"unbalanced 'B' event {name!r} never closed "
                          f"on track pid={pid} tid={tid}")
    return errors


def validate_file(path: str, strict: bool = False) -> List[str]:
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError as e:
        return [f"cannot read {path}: {e}"]
    except json.JSONDecodeError as e:
        return [f"{path} is not valid JSON: {e}"]
    if isinstance(doc, dict):
        if "traceEvents" not in doc:
            return [f"{path}: object form must carry 'traceEvents'"]
        doc = doc["traceEvents"]
    return validate_events(doc, strict=strict)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    strict = False
    if "--strict" in argv:
        strict = True
        argv = [a for a in argv if a != "--strict"]
    if len(argv) != 1 or argv[0] in ("-h", "--help"):
        print(__doc__, file=sys.stderr)
        return 2
    errors = validate_file(argv[0], strict=strict)
    if errors:
        for e in errors:
            print(e, file=sys.stderr)
        print(f"{argv[0]}: INVALID ({len(errors)} problem(s))",
              file=sys.stderr)
        return 1
    print(f"{argv[0]}: OK{' (strict)' if strict else ''}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
