"""Cross-process trace merge: one timeline for a whole run.

A run leaves telemetry scattered across processes and incarnations —
the router's trace, each replica worker's trace, the supervisor's
trainer traces, and ``flight.bin`` tails recovered from SIGKILLed
processes. This module merges them into ONE validator-clean Chrome
trace with:

  * **per-process lanes** — every source gets a synthetic pid and a
    ``process_name`` built from its run context
    (``router#0``, ``replica-r1#0 (flight)``, ``trainer#2``), so
    Perfetto shows one labeled track group per incarnation;
  * **clock alignment** — tracer timestamps are process-local
    ``perf_counter`` microseconds. Each trace/flight carries a
    ``(wall, perf)`` anchor pair (runctx.clock_anchor); events are
    rebased onto the shared wall clock, with optional per-source
    offsets from the fleet's NTP-style handshake
    (``runctx.estimate_clock_offset``) for hosts whose wall clocks
    disagree;
  * **flow arrows across hops** — for every request the router
    dispatched (``serving/dispatch`` instants, args rid/replica/
    attempt) the merger finds the matching replica-side admission
    (``serving/admit``) and emits a Chrome flow ``s``/``f`` pair, so a
    rid's journey — admit at the router, prefill/decode on a replica,
    retry on another after a kill — renders as arrows across lanes;
  * **flight recovery markers** — events recovered from a flight file
    join the timeline as first-class events, plus one
    ``flight/recovered`` instant summarizing what the post-mortem got
    back (count, torn records, source file).

Library surface: ``merge_files(paths, ...) -> (doc, stats)``. CLI::

    python -m deeperspeed_tpu.monitor.aggregate --out merged.json \
        router.trace.json replica-r1.i0.flight.bin replica-r0.i0.trace.json

Sources are auto-detected (flight magic vs JSON). A source that is a
DIRECTORY expands to every ``*.trace.json`` / ``*.flight.bin`` inside
it — the multi-host shape, where each host's ``trainer.h<k>`` role
writes its own obs files into one shared directory — and an
``offsets.json`` sidecar in that directory (the fleet supervisor's
clock-offset ledger, keyed by host role) is applied automatically.
``--strict`` runs the schema validator in strict mode on the merged
result and exits non-zero on problems; ``--offsets offsets.json`` maps
source basenames OR host roles to handshake-measured clock offsets in
seconds (explicit values win over directory sidecars).
"""

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

from . import flight as flight_mod
from .validate import validate_events

__all__ = ["expand_sources", "load_source", "merge_sources",
           "merge_files", "main"]

OFFSETS_SIDECAR = "offsets.json"


def expand_sources(paths: List[str]) -> List[str]:
    """Expand directory sources into their obs files, sorted by name so
    per-host lanes come out in host order. Non-directories pass through
    unchanged (missing files fail later, loudly, in load_source)."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            names = sorted(os.listdir(p))
            out.extend(os.path.join(p, n) for n in names
                       if n.endswith(".trace.json")
                       or n.endswith(".flight.bin"))
        else:
            out.append(p)
    return out


def _sidecar_offsets(paths: List[str]) -> Dict[str, float]:
    """Clock offsets from offsets.json sidecars of directory sources
    (the fleet supervisor's handshake ledger, keyed by host role)."""
    out: Dict[str, float] = {}
    for p in paths:
        if not os.path.isdir(p):
            continue
        sidecar = os.path.join(p, OFFSETS_SIDECAR)
        try:
            with open(sidecar) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        for k, v in doc.items():
            if isinstance(v, (int, float)):
                out[str(k)] = float(v)
    return out

# events on these names seed flow arrows: dispatch is the source side,
# admit the target side, matched per (rid, attempt) ordering
_FLOW_SRC = "serving/dispatch"
_FLOW_DST = "serving/admit"


class Source:
    """One per-process input: parsed events + run/clock metadata."""

    def __init__(self, path: str, kind: str, events: List[dict],
                 run: Optional[dict], clock: Optional[dict],
                 torn: int = 0, recovered: int = 0):
        self.path = path
        self.kind = kind                      # "trace" | "flight"
        self.events = events
        self.run = run or {}
        self.clock = clock                    # {"wall": s, "perf": s}
        self.torn = torn
        self.recovered = recovered
        self.offset_us = 0.0                  # handshake adjustment

    @property
    def label(self) -> str:
        role = self.run.get("role") or os.path.basename(self.path)
        inc = self.run.get("incarnation", 0)
        tag = f"{role}#{inc}"
        return f"{tag} (flight)" if self.kind == "flight" else tag


def load_source(path: str) -> Source:
    """Parse one input file, auto-detecting flight vs Chrome-trace."""
    if flight_mod.is_flight_file(path):
        snap = flight_mod.recover(path)
        run = {k: snap.meta.get(k) for k in
               ("run_id", "role", "incarnation") if k in snap.meta}
        return Source(path, "flight", snap.events, run,
                      snap.meta.get("clock"), torn=snap.torn,
                      recovered=len(snap.events))
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        events = doc.get("traceEvents", [])
        other = doc.get("otherData", {})
        return Source(path, "trace", events, other.get("run"),
                      other.get("clock"))
    return Source(path, "trace", doc, None, None)


def _source_offset_us(src: Source) -> Optional[float]:
    """Rebase term turning a source's perf-us timestamps into wall-us:
    ``wall_us = ts + offset``. None when the source has no anchor."""
    if not src.clock or "wall" not in src.clock or "perf" not in src.clock:
        return None
    return ((src.clock["wall"] - src.clock["perf"]) * 1e6
            + src.offset_us)


def _stitch_flows(events: List[dict]) -> List[dict]:
    """Chrome flow s/f pairs from router dispatches to replica admits.

    Match key is (rid, attempt-order): the k-th dispatch of a rid pairs
    with the k-th admit of that rid at a LATER (aligned) timestamp on a
    DIFFERENT pid — retries therefore get their own arrow to the
    replica that actually served them."""
    dispatches: Dict[str, List[dict]] = {}
    admits: Dict[str, List[dict]] = {}
    for ev in events:
        name = ev.get("name")
        rid = (ev.get("args") or {}).get("rid")
        if rid is None:
            continue
        if name == _FLOW_SRC:
            dispatches.setdefault(str(rid), []).append(ev)
        elif name == _FLOW_DST:
            admits.setdefault(str(rid), []).append(ev)
    flows: List[dict] = []
    flow_id = 0
    for rid, srcs in sorted(dispatches.items()):
        cands = sorted(admits.get(rid, []), key=lambda e: e.get("ts", 0))
        used = [False] * len(cands)
        for src in sorted(srcs, key=lambda e: e.get("ts", 0)):
            match = None
            for i, dst in enumerate(cands):
                if used[i] or dst.get("pid") == src.get("pid"):
                    continue
                if dst.get("ts", 0) >= src.get("ts", 0):
                    match = i
                    break
            if match is None:
                continue
            used[match] = True
            dst = cands[match]
            flow_id += 1
            common = {"name": "run/rid_hop", "cat": "rid", "id": flow_id}
            flows.append({**common, "ph": "s", "ts": src["ts"],
                          "pid": src["pid"], "tid": src["tid"],
                          "args": {"rid": rid}})
            flows.append({**common, "ph": "f", "bp": "e", "ts": dst["ts"],
                          "pid": dst["pid"], "tid": dst["tid"],
                          "args": {"rid": rid}})
    return flows


def merge_sources(sources: List[Source]) -> Tuple[dict, dict]:
    """Merge parsed sources into one Chrome-trace doc. Returns
    ``(doc, stats)``; stats carries per-source event counts, recovery
    numbers, alignment info, and the flow-arrow count."""
    merged: List[dict] = []
    stats = {"sources": [], "flow_arrows": 0, "events": 0,
             "recovered_events": 0, "unaligned_sources": 0}
    offsets = [_source_offset_us(s) for s in sources]
    for pid, (src, off) in enumerate(zip(sources, offsets), start=1):
        aligned = off is not None
        if not aligned:
            stats["unaligned_sources"] += 1
        kept = 0
        last_ts = 0.0
        for ev in src.events:
            if not isinstance(ev, dict):
                continue
            if ev.get("ph") == "M":
                if ev.get("name") == "process_name":
                    continue       # replaced by the merged label below
                ev = dict(ev)
                ev["pid"] = pid
                merged.append(ev)
                continue
            ev = dict(ev)
            ev["pid"] = pid
            if aligned and isinstance(ev.get("ts"), (int, float)):
                ev["ts"] = ev["ts"] + off
            if isinstance(ev.get("ts"), (int, float)):
                last_ts = max(last_ts, ev["ts"])
            run_id = src.run.get("run_id")
            if run_id:
                args = dict(ev.get("args") or {})
                args.setdefault("run_id", run_id)
                ev["args"] = args
            merged.append(ev)
            kept += 1
        merged.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": src.label}})
        if src.kind == "flight":
            merged.append({
                "name": "flight/recovered", "ph": "i", "s": "p",
                "ts": last_ts, "pid": pid, "tid": 0,
                "args": {"count": src.recovered, "torn": src.torn,
                         "source": os.path.basename(src.path)},
            })
            stats["recovered_events"] += src.recovered
        stats["sources"].append({
            "path": src.path, "kind": src.kind, "label": src.label,
            "events": kept, "aligned": aligned, "torn": src.torn,
        })
        stats["events"] += kept
    # rebase the whole merged timeline to zero: wall-epoch microseconds
    # overflow Perfetto's niceties and the validator requires ts >= 0
    t0 = min((ev["ts"] for ev in merged
              if ev.get("ph") != "M"
              and isinstance(ev.get("ts"), (int, float))), default=0.0)
    for ev in merged:
        if ev.get("ph") != "M" and isinstance(ev.get("ts"), (int, float)):
            ev["ts"] = max(0.0, ev["ts"] - t0)
    flows = _stitch_flows(merged)
    stats["flow_arrows"] = len(flows) // 2
    merged.extend(flows)
    doc = {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "otherData": {
            "merged_from": [os.path.basename(s.path) for s in sources],
            "run": next((s.run for s in sources if s.run.get("run_id")),
                        {}),
        },
    }
    return doc, stats


def merge_files(paths: List[str], out: Optional[str] = None,
                offsets_s: Optional[Dict[str, float]] = None,
                ) -> Tuple[dict, dict]:
    """Load, align, merge, and optionally write. ``offsets_s`` maps a
    source basename OR its run-context role to its handshake-measured
    wall-clock offset in seconds (how far that host's clock runs
    ahead). Directory entries in ``paths`` expand to their obs files,
    and their offsets.json sidecars merge in under explicit values."""
    offsets = _sidecar_offsets(paths)
    offsets.update(offsets_s or {})
    sources = [load_source(p) for p in expand_sources(paths)]
    for src in sources:
        if offsets:
            off = offsets.get(os.path.basename(src.path))
            if off is None:
                # multi-host ledgers key by role (trainer.h1), which
                # survives the per-incarnation file renames
                role = (src.run or {}).get("role")
                off = offsets.get(str(role or ""))
            if off is not None:
                # the source's clock runs `off` ahead: subtract to land
                # its events on the reference timeline
                src.offset_us = -off * 1e6
    doc, stats = merge_sources(sources)
    if out is not None:
        parent = os.path.dirname(os.path.abspath(out))
        os.makedirs(parent, exist_ok=True)
        with open(out, "w") as f:
            json.dump(doc, f)
            f.write("\n")
    return doc, stats


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeperspeed_tpu.monitor.aggregate",
        description="Merge per-process Chrome traces and recovered "
                    "flight snapshots into one aligned timeline.")
    ap.add_argument("sources", nargs="+",
                    help="trace JSON and/or flight.bin files "
                         "(auto-detected), or obs directories that "
                         "expand to every trace/flight file inside")
    ap.add_argument("--out", required=True, help="merged trace path")
    ap.add_argument("--offsets", default=None, metavar="JSON",
                    help="file mapping source basename or host role -> "
                         "clock offset seconds (from the fleet clock "
                         "handshake)")
    ap.add_argument("--strict", action="store_true",
                    help="validate the merged trace in strict mode; "
                         "non-zero exit on problems")
    args = ap.parse_args(argv)
    offsets = None
    if args.offsets:
        with open(args.offsets) as f:
            offsets = {k: float(v) for k, v in json.load(f).items()}
    doc, stats = merge_files(args.sources, out=args.out,
                             offsets_s=offsets)
    for s in stats["sources"]:
        extras = "" if s["aligned"] else ", unaligned"
        if s["torn"]:
            extras += f", torn={s['torn']}"
        print(f"  {s['label']:<24} {s['events']:>6} events "
              f"[{s['kind']}{extras}]")
    print(f"wrote {args.out}: {stats['events']} events from "
          f"{len(stats['sources'])} sources, "
          f"{stats['recovered_events']} recovered from flight, "
          f"{stats['flow_arrows']} flow arrows")
    problems = validate_events(doc["traceEvents"], strict=args.strict)
    if problems:
        for p in problems:
            print(f"merged trace: {p}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
