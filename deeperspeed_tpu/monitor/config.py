"""Monitor-block configuration.

The telemetry counterpart of the ``"serving"`` block: a ``"monitor"``
block in the master JSON config (or a plain dict) builds a
``MonitorConfig``. Everything is off by default — tracing, the recompile
watchdog, and the metrics endpoint only exist when the block asks for
them, so the hot path pays nothing otherwise.

::

    "monitor": {
        "trace_path": "/tmp/step.trace.json",  # null = keep in memory
        "ring_size": 65536,                    # bounded event memory
        "watchdog": "warn",                    # off | warn | strict
        "metrics_port": 9184,                  # null = no endpoint; 0 = ephemeral
        "metrics_host": "127.0.0.1",
        "tb_export_interval": 0,               # steps; 0 = no TB export
        "flight_path": "/tmp/flight.bin",      # null = no flight recorder
        "flight_records": 2048,                # flight ring capacity
        "flight_slot_bytes": 512,              # fixed record size
        "obs_dir": null                        # derive per-incarnation paths
    }

``obs_dir`` is the run-scoped form: when set, ``trace_path`` and
``flight_path`` default to ``<obs_dir>/<role>.i<incarnation>.trace.json``
/ ``...flight.bin`` (role/incarnation from the DS_TPU_* run context), so
one static config block works across supervisor restarts and replica
fleets without incarnations overwriting each other's files.
"""

import dataclasses
from typing import Optional

from .watchdog import MODES

_KNOWN_KEYS = frozenset({
    "enabled", "trace_enabled", "trace_path", "ring_size", "watchdog",
    "metrics_port", "metrics_host", "tb_export_interval",
    "flight_path", "flight_records", "flight_slot_bytes", "obs_dir",
    "perf", "memwatch", "near_oom_fraction",
})


@dataclasses.dataclass(frozen=True)
class MonitorConfig:
    # master switch; runtime/config.py treats block presence as enabled
    # unless {"enabled": false}
    enabled: bool = True
    # span/counter/instant tracing into the ring buffer
    trace_enabled: bool = True
    # where Monitor.save_trace()/shutdown() write the Chrome-trace JSON;
    # None keeps events in memory for the caller to export
    trace_path: Optional[str] = None
    # ring-buffer capacity (events); memory stays bounded at ~200B/event
    ring_size: int = 65536
    # recompile watchdog mode: "off", "warn" (rank-0 warning + trace
    # instant), "strict" (raise RecompileError)
    watchdog: str = "warn"
    # Prometheus endpoint port; None disables the server, 0 binds an
    # ephemeral port (exposed as Monitor.metrics_server.port)
    metrics_port: Optional[int] = None
    metrics_host: str = "127.0.0.1"
    # export the metrics registry through TensorBoardMonitor every N
    # steps; 0 disables
    tb_export_interval: int = 0
    # crash-proof flight recorder (monitor/flight.py): None disables
    flight_path: Optional[str] = None
    flight_records: int = 2048
    flight_slot_bytes: int = 512
    # run-scoped output directory: derives trace_path/flight_path from
    # the process's role + incarnation when they are not set explicitly
    obs_dir: Optional[str] = None
    # perf doctor (monitor/perf.py): compiled-cost captures + live MFU
    # span args. Opt-in: the MFU readout syncs the step result inside
    # the train-batch span, an observer effect the default must not pay
    perf: bool = False
    # device-memory watermark lane (monitor/memwatch.py): ~free (CPU
    # reads {}; TPU reads the allocator ledger), so on by default
    # wherever tracing is on
    memwatch: bool = True
    # bytes_in_use/bytes_limit fraction that trips the near-OOM
    # post-mortem (top-K live buffers through the flight recorder)
    near_oom_fraction: float = 0.92

    def __post_init__(self):
        if self.ring_size < 1:
            raise ValueError(f"ring_size must be >= 1, got {self.ring_size}")
        if self.flight_records < 1:
            raise ValueError(
                f"flight_records must be >= 1, got {self.flight_records}")
        if self.flight_slot_bytes < 48:
            raise ValueError(
                f"flight_slot_bytes must be >= 48, got "
                f"{self.flight_slot_bytes}")
        if self.watchdog not in MODES:
            raise ValueError(
                f"watchdog must be one of {MODES}, got {self.watchdog!r}")
        if self.metrics_port is not None and not (
                0 <= int(self.metrics_port) <= 65535):
            raise ValueError(
                f"metrics_port must be 0..65535 or null, got "
                f"{self.metrics_port}")
        if self.tb_export_interval < 0:
            raise ValueError(
                f"tb_export_interval must be >= 0, got "
                f"{self.tb_export_interval}")
        if not (0.0 < self.near_oom_fraction <= 1.0):
            raise ValueError(
                f"near_oom_fraction must be in (0, 1], got "
                f"{self.near_oom_fraction}")

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "MonitorConfig":
        """Build from a ``"monitor"`` config block; unknown keys raise
        (same typo discipline as ServingConfig.from_dict)."""
        if d is None:
            return cls()
        unknown = set(d) - _KNOWN_KEYS
        if unknown:
            raise ValueError(
                f"unknown monitor config keys {sorted(unknown)}; known "
                f"keys are {sorted(_KNOWN_KEYS)}")
        return cls(**d)
