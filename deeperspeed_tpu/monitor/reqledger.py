"""Request-path doctor: per-request timelines, tail-latency attribution,
and a per-request cost ledger, reconstructed from trace events alone.

A serving p99 is useless without knowing *which* requests were slow and
*where* their time went. This module joins the request-scoped events the
fleet already emits — ``req/submit`` / ``req/accept`` (clock zero),
``serving/dispatch`` / ``req/requeue`` (router hops), ``serving/admit``
/ ``serving/prefill`` / ``serving/preempt`` (engine lifecycle),
``serving/decode`` (batch participation via the ``rids`` arg),
``xla_compile`` (duration in args), and ``serving/finish`` (token and
KV-occupancy totals) — into one ``RequestTimeline`` per rid, then
decomposes each request's TTFT and E2E wall-clock with the same
interval arithmetic ``monitor/goodput.py`` uses for run-level goodput.

Attribution is precedence-ordered so the buckets sum to the measured
wall by construction (each bucket is measured after subtracting every
higher one; the remainder is an explicit ``residual``, never silently
dropped):

  ====================  ===========================================
  ``compile``           ``xla_compile`` inside the window — split
                        out of the rid's own prefill first, then
                        whatever else fires on its serving process
  ``prefill``           the rid's own ``serving/prefill`` +
                        ``serving/prefill_chunk`` spans, compile time
                        removed (chunked prefill is own prefill,
                        spread across steps; other rids' chunks land
                        in ``hol_blocking`` like any other prefill)
  ``retry_backoff``     ``req/requeue`` -> next dispatch (failover
                        penalty holds + shed retry-after)
  ``router_queue``      ``req/accept`` -> first dispatch (admission
                        queueing at the router)
  ``preempt_gap``       ``serving/preempt`` -> next own admit (KV
                        pressure evicted the rid mid-decode)
  ``hol_blocking``      OTHER rids' prefill spans on the rid's
                        serving process — head-of-line blocking,
                        attributed per blocker rid
  ``decode``            ``serving/decode`` spans on the serving
                        process (own steps after admission; the
                        batch running ahead of you before it)
  ``sched_queue``       engine-side queue residency (submit ->
                        admit), dispatch -> replica-submit transit,
                        and ``serving/step`` span time not covered
                        by any of the above (scheduler bookkeeping,
                        backpressure polls)
  ``residual``          window time outside every bucket — host
                        gaps between steps; CI gates this < 5%
  ====================  ===========================================

The cost ledger counts what each request *consumed*, not just waited
on: prefill context tokens, generated tokens per dispatch attempt
(retry-wasted tokens are exact because failover replays are
token-identical — every token generated in a non-final attempt is
waste), device-time share (own prefill spans + ``dur/n_active`` of
each decode span the rid rode in), and KV block-seconds from the
scheduler's accrual (``serving/finish`` args). Costs aggregate per
replica and per lifecycle weight-version (``lifecycle/repin`` /
``lifecycle/rollout``) into ``cost_per_1k_tokens`` gauges.

Works on single-engine traces (scripts/serving_bench.py) and on merged
multi-source fleet traces (monitor/aggregate.py output, flight-recorder
recoveries included) — serving-side spans are matched per process id,
so one engine's decode is never charged to a request served elsewhere.
CLI: ``python -m deeperspeed_tpu.monitor.slo``.
"""

import dataclasses
import math
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .goodput import (
    Interval,
    interval_measure,
    interval_subtract,
    interval_union,
    load_trace_events,
)

__all__ = [
    "ATTRIBUTION_BUCKETS",
    "DEFAULT_EXCLUDE_PREFIXES",
    "RequestTimeline",
    "TraceIndex",
    "interval_intersect",
    "build_index",
    "attribute_window",
    "request_cost",
    "build_ledger",
    "export_cost_gauges",
    "percentile",
]

# precedence order (highest first); "residual" is the explicit remainder
ATTRIBUTION_BUCKETS = (
    "compile", "prefill", "retry_backoff", "router_queue", "preempt_gap",
    "hol_blocking", "decode", "sched_queue", "residual",
)

_US = 1e-6  # trace ts/dur are microseconds


def interval_intersect(a: Sequence[Interval],
                       b: Sequence[Interval]) -> List[Interval]:
    """``a ∩ b`` for disjoint+sorted interval lists (interval_union
    both). Complements goodput's union/subtract/measure trio."""
    out: List[Interval] = []
    j = 0
    for s, e in a:
        while j < len(b) and b[j][1] <= s:
            j += 1
        k = j
        while k < len(b) and b[k][0] < e:
            lo, hi = max(s, b[k][0]), min(e, b[k][1])
            if hi > lo:
                out.append((lo, hi))
            if b[k][1] >= e:
                break
            k += 1
    return out


def _clip(ivs: Iterable[Interval], window: Interval) -> List[Interval]:
    return interval_intersect(interval_union(ivs), [window])


# ------------------------------------------------------------------ #
# timeline reconstruction
# ------------------------------------------------------------------ #


@dataclasses.dataclass
class RequestTimeline:
    """Every trace event that names one rid, in one place (ts in µs of
    the merged/rebased timeline)."""

    rid: str
    submit_ts: List[float] = dataclasses.field(default_factory=list)
    accept_ts: Optional[float] = None
    # (ts, replica, attempt) from the router; empty for single engines
    dispatches: List[Tuple[float, str, int]] = \
        dataclasses.field(default_factory=list)
    requeues: List[Tuple[float, float]] = \
        dataclasses.field(default_factory=list)      # (ts, backoff_s)
    admits: List[Tuple[float, object]] = \
        dataclasses.field(default_factory=list)      # (ts, pid)
    preempts: List[Tuple[float, object]] = \
        dataclasses.field(default_factory=list)      # (ts, pid)
    # (start, end, pid, ctx_len) own prefill spans. With chunked
    # prefill the engine emits one ``serving/prefill`` span only for the
    # FINAL chunk (the one that emits token 0), so first_token_ts and
    # the one-token-per-prefill-span cost invariant survive chunking
    prefills: List[Tuple[float, float, object, int]] = \
        dataclasses.field(default_factory=list)
    # (start, end, pid, tokens) own non-final ``serving/prefill_chunk``
    # spans — the rid's own prefill work, spread over engine steps
    chunks: List[Tuple[float, float, object, int]] = \
        dataclasses.field(default_factory=list)
    # (start, end, pid, n_active) decode spans the rid rode in
    decodes: List[Tuple[float, float, object, int]] = \
        dataclasses.field(default_factory=list)
    # (ts, reason, args) — engine finishes carry tokens/kv_block_s,
    # router finishes only (rid, reason)
    finishes: List[Tuple[float, str, dict]] = \
        dataclasses.field(default_factory=list)
    # (ts, accepted, k, emitted) ``spec/accept`` instants — one per
    # speculative round the rid rode in; ``emitted`` counts the tokens
    # the round actually appended (accepted drafts + bonus, truncated
    # at EOS/length), which is what keeps token accounting exact when
    # decode emits more than one token per span
    spec_accepts: List[Tuple[float, int, int, int]] = \
        dataclasses.field(default_factory=list)

    # -- derived ----------------------------------------------------- #

    @property
    def t0(self) -> Optional[float]:
        """Clock zero: the earliest submit/accept the trace saw."""
        cands = list(self.submit_ts)
        if self.accept_ts is not None:
            cands.append(self.accept_ts)
        return min(cands) if cands else None

    @property
    def first_token_ts(self) -> Optional[float]:
        """End of the first own prefill span — when token 0 existed."""
        return min((end for _s, end, _p, _c in self.prefills),
                   default=None)

    @property
    def end_ts(self) -> Optional[float]:
        return max((ts for ts, _r, _a in self.finishes), default=None)

    @property
    def engine_finish(self) -> Optional[dict]:
        """Args of the last engine-side finish (the one carrying
        ``tokens`` / ``kv_block_s``); None when only the router saw the
        request end (e.g. shed before admission)."""
        eng = [a for _ts, _r, a in self.finishes if "tokens" in a]
        return eng[-1] if eng else None

    @property
    def serving_pids(self) -> List[object]:
        """Processes that actually served the rid (admitted or
        prefilled it) — the only tracks whose decode/step/compile time
        can be charged to this request."""
        pids = {p for _ts, p in self.admits}
        pids.update(p for _s, _e, p, _c in self.prefills)
        pids.update(p for _s, _e, p, _c in self.chunks)
        return sorted(pids, key=repr)

    def ttft_window(self) -> Optional[Interval]:
        t0, t1 = self.t0, self.first_token_ts
        return (t0, t1) if t0 is not None and t1 is not None \
            and t1 > t0 else None

    def e2e_window(self) -> Optional[Interval]:
        t0, t1 = self.t0, self.end_ts
        return (t0, t1) if t0 is not None and t1 is not None \
            and t1 > t0 else None


@dataclasses.dataclass
class TraceIndex:
    """Per-pid span pools shared across all requests' attributions."""

    timelines: Dict[str, RequestTimeline]
    # pid -> [(start, end, rid)] every prefill span (HOL candidates)
    prefills_by_pid: Dict[object, List[Tuple[float, float, str]]]
    compiles_by_pid: Dict[object, List[Interval]]
    decodes_by_pid: Dict[object, List[Interval]]
    steps_by_pid: Dict[object, List[Interval]]
    # lifecycle joins for the cost ledger's per-version axis
    rollouts: List[Tuple[float, str, object]]    # (ts, replica, version)
    repins: Dict[str, object]                    # rid -> version
    # speculative decoding: per-round ``spec/draft`` / ``spec/verify``
    # instants as (ts, n_active, dur_us) — the draft-vs-verify split of
    # the decode bucket's device time
    spec_drafts: List[Tuple[float, int, float]] = \
        dataclasses.field(default_factory=list)
    spec_verifies: List[Tuple[float, int, float]] = \
        dataclasses.field(default_factory=list)


def _args(ev: dict) -> dict:
    a = ev.get("args")
    return a if isinstance(a, dict) else {}


def build_index(events: List[dict]) -> TraceIndex:
    """One pass over a (merged) event list -> TraceIndex."""
    tls: Dict[str, RequestTimeline] = {}
    prefills_by_pid: Dict[object, list] = {}
    compiles_by_pid: Dict[object, list] = {}
    decodes_by_pid: Dict[object, list] = {}
    steps_by_pid: Dict[object, list] = {}
    rollouts: List[Tuple[float, str, object]] = []
    repins: Dict[str, object] = {}
    spec_drafts: List[Tuple[float, int, float]] = []
    spec_verifies: List[Tuple[float, int, float]] = []

    def tl(rid) -> RequestTimeline:
        rid = str(rid)
        if rid not in tls:
            tls[rid] = RequestTimeline(rid=rid)
        return tls[rid]

    for ev in events:
        if not isinstance(ev, dict):
            continue
        name, ph, ts = ev.get("name"), ev.get("ph"), ev.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        pid = ev.get("pid")
        args = _args(ev)
        rid = args.get("rid")
        if name == "xla_compile":
            secs = args.get("seconds", 0.0)
            if isinstance(secs, (int, float)) and secs > 0:
                # the compile listener fires at compile END
                compiles_by_pid.setdefault(pid, []).append(
                    (ts - secs * 1e6, ts))
            continue
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur <= 0:
                continue
            start, end = ts, ts + dur
            if name == "serving/prefill" and rid is not None:
                tl(rid).prefills.append(
                    (start, end, pid, int(args.get("ctx_len", 0))))
                prefills_by_pid.setdefault(pid, []).append(
                    (start, end, str(rid)))
            elif name == "serving/prefill_chunk" and rid is not None:
                # a chunk forward is the rid's OWN prefill work and,
                # symmetrically, head-of-line blocking for everyone
                # else on the same track — so it joins the per-pid
                # prefill pool HOL attribution draws from
                tl(rid).chunks.append(
                    (start, end, pid, int(args.get("tokens", 0))))
                prefills_by_pid.setdefault(pid, []).append(
                    (start, end, str(rid)))
            elif name == "serving/decode":
                decodes_by_pid.setdefault(pid, []).append((start, end))
                riders = [r for r in
                          str(args.get("rids", "")).split(",") if r]
                n = int(args.get("n_active", len(riders)) or 1)
                for r in riders:
                    tl(r).decodes.append((start, end, pid, n))
            elif name == "serving/step":
                steps_by_pid.setdefault(pid, []).append((start, end))
            continue
        # instants
        if name == "req/submit" and rid is not None:
            tl(rid).submit_ts.append(ts)
        elif name == "req/accept" and rid is not None:
            t = tl(rid)
            t.accept_ts = ts if t.accept_ts is None \
                else min(t.accept_ts, ts)
        elif name == "serving/dispatch" and rid is not None:
            tl(rid).dispatches.append(
                (ts, str(args.get("replica", "?")),
                 int(args.get("attempt", 0))))
        elif name == "req/requeue" and rid is not None:
            tl(rid).requeues.append(
                (ts, float(args.get("backoff_s", 0.0) or 0.0)))
        elif name == "serving/admit" and rid is not None:
            tl(rid).admits.append((ts, pid))
        elif name == "serving/preempt" and rid is not None:
            tl(rid).preempts.append((ts, pid))
        elif name == "serving/finish" and rid is not None:
            tl(rid).finishes.append(
                (ts, str(args.get("reason", "?")), args))
        elif name == "lifecycle/rollout":
            rollouts.append((ts, str(args.get("replica", "?")),
                             args.get("version")))
        elif name == "lifecycle/repin" and rid is not None:
            repins[str(rid)] = args.get("version")
        elif name == "spec/draft":
            spec_drafts.append((ts, int(args.get("n_active", 0) or 0),
                                float(args.get("dur_us", 0.0) or 0.0)))
        elif name == "spec/verify":
            spec_verifies.append((ts, int(args.get("n_active", 0) or 0),
                                  float(args.get("dur_us", 0.0) or 0.0)))
        elif name == "spec/accept" and rid is not None:
            acc = int(args.get("accepted", 0) or 0)
            tl(rid).spec_accepts.append(
                (ts, acc, int(args.get("k", 0) or 0),
                 int(args.get("emitted", acc + 1) or (acc + 1))))

    for tline in tls.values():
        tline.dispatches.sort()
        tline.prefills.sort()
        tline.chunks.sort()
        tline.decodes.sort()
        tline.finishes.sort()
        tline.spec_accepts.sort()
    rollouts.sort()
    spec_drafts.sort()
    spec_verifies.sort()
    return TraceIndex(
        timelines=tls,
        prefills_by_pid=prefills_by_pid,
        compiles_by_pid=compiles_by_pid,
        decodes_by_pid=decodes_by_pid,
        steps_by_pid=steps_by_pid,
        rollouts=rollouts,
        repins=repins,
        spec_drafts=spec_drafts,
        spec_verifies=spec_verifies,
    )


# ------------------------------------------------------------------ #
# attribution
# ------------------------------------------------------------------ #


def _serving_pids(idx: TraceIndex, tline: RequestTimeline) -> List[object]:
    pids = tline.serving_pids
    if pids:
        return pids
    # never admitted anywhere (shed, or still queued at trace end):
    # charge engine-side time from every serving track, so a fleet-wide
    # stall still shows up instead of landing in residual
    return sorted(idx.steps_by_pid.keys(), key=repr)


def attribute_window(idx: TraceIndex, tline: RequestTimeline,
                     window: Interval) -> dict:
    """Decompose one request's window into ATTRIBUTION_BUCKETS (µs).

    Returns ``{"window_us", "buckets": {bucket: µs}, "blockers":
    {rid: µs}, "residual_fraction"}``; buckets + residual sum to the
    window by construction.
    """
    pids = _serving_pids(idx, tline)

    # own prefill = the final-chunk serving/prefill span(s) plus any
    # earlier serving/prefill_chunk spans: chunked prefill is still the
    # rid's own prefill time, just spread across engine steps instead
    # of one contiguous stall
    own_prefill = _clip([(s, e) for s, e, _p, _c in tline.prefills]
                        + [(s, e) for s, e, _p, _c in tline.chunks],
                        window)
    compile_all = _clip(
        [iv for p in pids for iv in idx.compiles_by_pid.get(p, [])],
        window)
    # compile inside the rid's own prefill is the cold-bucket tax the
    # request itself paid; it outranks "prefill" so warm and cold
    # prefills are distinguishable in the breakdown
    compile_u = interval_intersect(compile_all, own_prefill)
    prefill_u = interval_subtract(own_prefill, compile_u)
    higher = interval_union(own_prefill)

    def take(ivs: List[Interval]) -> List[Interval]:
        nonlocal higher
        got = interval_subtract(_clip(ivs, window), higher)
        higher = interval_union(higher + got)
        return got

    # requeue -> next dispatch: failover penalty hold / shed backoff
    retry_iv = []
    for ts, _backoff in tline.requeues:
        nxt = min((d for d, _r, _a in tline.dispatches if d > ts),
                  default=window[1])
        retry_iv.append((ts, nxt))
    retry_u = take(retry_iv)

    # router admission queueing: accept -> first dispatch
    router_u = take(
        [(tline.accept_ts, tline.dispatches[0][0])]
        if tline.accept_ts is not None and tline.dispatches else [])

    preempt_iv = []
    for ts, _pid in tline.preempts:
        nxt = min((a for a, _p in tline.admits if a > ts),
                  default=window[1])
        preempt_iv.append((ts, nxt))
    preempt_u = take(preempt_iv)

    # head-of-line: OTHER rids' prefills on this rid's serving tracks.
    # The union is exact; the per-blocker split re-intersects each
    # blocker's own spans, so concurrent blockers on different tracks
    # can jointly over-claim the union (noted, not hidden).
    remaining_before_hol = interval_subtract([window], higher)
    hol_spans = [(s, e, r) for p in pids
                 for s, e, r in idx.prefills_by_pid.get(p, [])
                 if r != tline.rid]
    hol_u = take([(s, e) for s, e, _r in hol_spans])
    blockers: Dict[str, float] = {}
    for s, e, r in hol_spans:
        got = interval_intersect(_clip([(s, e)], window),
                                 remaining_before_hol)
        if got:
            blockers[r] = blockers.get(r, 0.0) + interval_measure(got)

    compile_rest = take(
        [iv for p in pids for iv in idx.compiles_by_pid.get(p, [])])
    decode_u = take(
        [iv for p in pids for iv in idx.decodes_by_pid.get(p, [])])
    # scheduler queue: engine-side queue residency (submit -> first
    # admit — the wait for the next step to pick the request up),
    # dispatch -> replica-submit IPC transit, and serving/step span
    # time no higher bucket claimed (admission polls, backpressure
    # checks, bookkeeping). Lowest precedence: it mops up only what
    # nothing more specific explains — a replica prefilling someone
    # else during these windows already counted as hol_blocking.
    queue_iv = []
    if tline.submit_ts:
        first_admit = min((a for a, _p in tline.admits),
                          default=window[1])
        queue_iv.append((min(tline.submit_ts), first_admit))
    for d_ts, _rep, _att in tline.dispatches:
        landed = [s for s in tline.submit_ts if s > d_ts]
        landed += [a for a, _p in tline.admits if a > d_ts]
        queue_iv.append((d_ts, min(landed, default=window[1])))
    step_u = take(
        queue_iv
        + [iv for p in pids for iv in idx.steps_by_pid.get(p, [])])

    wall = window[1] - window[0]
    buckets = {
        "compile": interval_measure(compile_u)
        + interval_measure(compile_rest),
        "prefill": interval_measure(prefill_u),
        "retry_backoff": interval_measure(retry_u),
        "router_queue": interval_measure(router_u),
        "preempt_gap": interval_measure(preempt_u),
        "hol_blocking": interval_measure(hol_u),
        "decode": interval_measure(decode_u),
        "sched_queue": interval_measure(step_u),
    }
    buckets["residual"] = max(0.0, wall - sum(buckets.values()))
    return {
        "window_us": wall,
        "buckets": buckets,
        "blockers": dict(sorted(blockers.items(),
                                key=lambda kv: -kv[1])),
        "residual_fraction": (buckets["residual"] / wall
                              if wall > 0 else 0.0),
    }


# ------------------------------------------------------------------ #
# cost ledger
# ------------------------------------------------------------------ #


def request_cost(idx: TraceIndex, tline: RequestTimeline) -> dict:
    """What the request consumed, split by dispatch attempt.

    Token counting is exact, not sampled: every own prefill span emits
    one generated token (the scheduler prefills once per admission) and
    every decode participation emits one, so tokens-per-attempt is a
    pure event count; the final attempt must equal the engine finish's
    ``tokens`` arg. Failover replays are token-identical, so everything
    generated in a non-final attempt is retry waste.
    """
    if tline.dispatches:
        bounds = [d for d, _r, _a in tline.dispatches]
    else:
        bounds = [tline.t0 if tline.t0 is not None else 0.0]

    def attempt_of(ts: float) -> int:
        i = 0
        for k, b in enumerate(bounds):
            if ts >= b:
                i = k
        return i

    n_attempts = len(bounds)
    tokens = [0] * n_attempts
    prefill_ctx = [0] * n_attempts
    device_us = [0.0] * n_attempts
    for _s, end, _pid, ctx in tline.prefills:
        a = attempt_of(end)
        tokens[a] += 1
        prefill_ctx[a] += ctx
        device_us[a] += end - _s
    for s, e, _pid, _tok in tline.chunks:
        # non-final chunks consume device time but emit no token (the
        # final chunk's serving/prefill span carries that), and their
        # context tokens are already inside the final span's ctx_len
        device_us[attempt_of(e)] += e - s
    for s, e, _pid, n in tline.decodes:
        a = attempt_of(e)
        tokens[a] += 1
        device_us[a] += (e - s) / max(1, n)   # fair share of the batch
    # speculative rounds append more than one token per decode span:
    # the +1 above is the round's floor, spec/accept's ``emitted``
    # carries the rest, so spec-on attempts stay exactly counted
    for ts, _acc, _k, emitted in tline.spec_accepts:
        tokens[attempt_of(ts)] += max(0, emitted - 1)

    fin = tline.engine_finish or {}
    final_tokens = tokens[-1]
    total = sum(tokens)
    replica = tline.dispatches[-1][1] if tline.dispatches else "local"
    spec_drafted = sum(k for _ts, _a, k, _e in tline.spec_accepts)
    spec_accepted = sum(a for _ts, a, _k, _e in tline.spec_accepts)
    return {
        "spec_rounds": len(tline.spec_accepts),
        "spec_accept_rate": round(spec_accepted / spec_drafted, 6)
        if spec_drafted else 0.0,
        "attempts": n_attempts,
        "tokens_final": final_tokens,
        "tokens_total": total,
        "retry_wasted_tokens": total - final_tokens,
        "prefill_ctx_tokens": sum(prefill_ctx),
        "device_s": round(sum(device_us) * _US, 6),
        "kv_block_s": float(fin.get("kv_block_s", 0.0) or 0.0),
        "admissions": int(fin.get("admissions", len(tline.admits))
                          or len(tline.admits)),
        "preemptions": len(tline.preempts),
        "replica": replica,
        "version": _version_of(idx, tline, replica),
        "finish_tokens_reported": fin.get("tokens"),
        "finish_reason": (tline.finishes[-1][1]
                          if tline.finishes else None),
    }


def _version_of(idx: TraceIndex, tline: RequestTimeline,
                replica: str) -> str:
    """Weight-version axis: an explicit ``lifecycle/repin`` wins, else
    the latest rollout the serving replica had taken by dispatch time."""
    v = idx.repins.get(tline.rid)
    if v is not None:
        return str(v)
    t_ref = tline.dispatches[-1][0] if tline.dispatches else float("inf")
    best = None
    for ts, rep, ver in idx.rollouts:
        if rep == replica and ts <= t_ref:
            best = ver
    return str(best) if best is not None else "unversioned"


def percentile(values: Sequence[float], q: float) -> float:
    """Nearest-rank percentile (q in [0, 100]); 0.0 on empty input —
    matches how the bench summarizes TTFT."""
    vs = sorted(values)
    if not vs:
        return 0.0
    k = max(0, min(len(vs) - 1,
                   math.ceil(q / 100.0 * len(vs)) - 1))
    return vs[k]


# ------------------------------------------------------------------ #
# the full report
# ------------------------------------------------------------------ #


DEFAULT_EXCLUDE_PREFIXES = ("warm-", "_warm")


def build_ledger(events_or_path, top_blockers: int = 5,
                 exclude_prefixes: Tuple[str, ...] =
                 DEFAULT_EXCLUDE_PREFIXES) -> dict:
    """Events (list / trace doc / path, flight.bin included) -> the
    request-path doctor report: per-rid attribution + cost, fleet
    percentiles, aggregate bucket totals, the p99 victim's breakdown,
    and per-replica / per-version unit economics.

    Rids under ``exclude_prefixes`` (by default the bench's ``warm-*``
    and the replica worker's ``_warm*`` compile-warmup requests) are
    dropped from the doctored population — but their prefill spans
    still count as HOL blockers, because a warmup prefill in front of
    real traffic is real blocking.
    """
    events = load_trace_events(events_or_path)
    idx = build_index(events)

    requests: Dict[str, dict] = {}
    ttfts: List[Tuple[float, str]] = []
    e2es: List[Tuple[float, str]] = []
    agg = {b: 0.0 for b in ATTRIBUTION_BUCKETS}
    blocker_totals: Dict[str, float] = {}

    for rid in sorted(idx.timelines):
        if any(rid.startswith(p) for p in exclude_prefixes):
            continue
        tline = idx.timelines[rid]
        row = {"rid": rid, "cost": request_cost(idx, tline)}
        w = tline.ttft_window()
        if w is not None:
            att = attribute_window(idx, tline, w)
            row["ttft_ms"] = round(att["window_us"] * 1e-3, 3)
            row["ttft"] = _ms_view(att)
            ttfts.append((att["window_us"], rid))
            for b, v in att["buckets"].items():
                agg[b] += v
            for r, v in att["blockers"].items():
                blocker_totals[r] = blocker_totals.get(r, 0.0) + v
        w = tline.e2e_window()
        if w is not None:
            att = attribute_window(idx, tline, w)
            row["e2e_ms"] = round(att["window_us"] * 1e-3, 3)
            row["e2e"] = _ms_view(att)
            e2es.append((att["window_us"], rid))
        requests[rid] = row

    def pct_block(samples: List[Tuple[float, str]]) -> dict:
        vals = [v * 1e-3 for v, _ in samples]
        return {"count": len(vals),
                "p50_ms": round(percentile(vals, 50), 3),
                "p90_ms": round(percentile(vals, 90), 3),
                "p99_ms": round(percentile(vals, 99), 3),
                "max_ms": round(max(vals), 3) if vals else 0.0}

    p99_victim = None
    if ttfts:
        # nearest-rank p99 of a bench-sized sample IS the max; name the
        # slowest request and say where its time went
        v_us, v_rid = max(ttfts)
        vb = requests[v_rid]["ttft"]["buckets"]
        dominant = max(vb, key=lambda b: 0.0 if b == "residual"
                       else vb[b])
        blk = requests[v_rid]["ttft"]["blockers"]
        p99_victim = {
            "rid": v_rid,
            "ttft_ms": round(v_us * 1e-3, 3),
            "dominant_bucket": dominant,
            "top_blocker": next(iter(blk), None),
        }

    # per-replica / per-version unit economics over completed requests
    econ: Dict[str, Dict[str, dict]] = {"replica": {}, "version": {}}
    total_dev_s = total_tok = 0
    for row in requests.values():
        c = row["cost"]
        if not c["tokens_final"]:
            continue
        total_dev_s += c["device_s"]
        total_tok += c["tokens_final"]
        for axis, key in (("replica", c["replica"]),
                          ("version", c["version"])):
            g = econ[axis].setdefault(
                key, {"requests": 0, "tokens": 0, "device_s": 0.0,
                      "retry_wasted_tokens": 0, "kv_block_s": 0.0})
            g["requests"] += 1
            g["tokens"] += c["tokens_final"]
            g["device_s"] = round(g["device_s"] + c["device_s"], 6)
            g["retry_wasted_tokens"] += c["retry_wasted_tokens"]
            g["kv_block_s"] = round(g["kv_block_s"] + c["kv_block_s"], 6)
    for axis in econ.values():
        for g in axis.values():
            g["cost_per_1k_tokens"] = round(
                1000.0 * g["device_s"] / g["tokens"], 6) \
                if g["tokens"] else 0.0

    worst_residual = max(
        (requests[r].get("ttft", {}).get("residual_fraction", 0.0)
         for r in requests), default=0.0)

    # speculative decoding: the draft-vs-verify split of decode device
    # time plus fleet and per-rid acceptance — accept_rate is what the
    # spec-on/spec-off routing decision and the bench's TPOT claim key
    # on, so it lives in the doctored report, not just engine metrics
    spec_drafted = spec_accepted = 0
    spec_per_rid: Dict[str, dict] = {}
    for rid, row in requests.items():
        tline = idx.timelines[rid]
        if not tline.spec_accepts:
            continue
        d = sum(k for _ts, _a, k, _e in tline.spec_accepts)
        a = sum(acc for _ts, acc, _k, _e in tline.spec_accepts)
        spec_drafted += d
        spec_accepted += a
        spec_per_rid[rid] = {
            "rounds": len(tline.spec_accepts),
            "accept_rate": round(a / d, 6) if d else 0.0,
        }
    speculative = {
        "rounds": len(idx.spec_drafts),
        "draft_ms": round(
            sum(d for _t, _n, d in idx.spec_drafts) * 1e-3, 3),
        "verify_ms": round(
            sum(d for _t, _n, d in idx.spec_verifies) * 1e-3, 3),
        "drafted": spec_drafted,
        "accepted": spec_accepted,
        "accept_rate": round(spec_accepted / spec_drafted, 6)
        if spec_drafted else 0.0,
        "per_rid": spec_per_rid,
    }
    return {
        "requests": requests,
        "ttft": pct_block(ttfts),
        "e2e": pct_block(e2es),
        "p99_victim": p99_victim,
        "buckets_total_ms": {b: round(v * 1e-3, 3)
                             for b, v in agg.items()},
        "top_blockers": [
            {"rid": r, "blocked_ms": round(v * 1e-3, 3)}
            for r, v in sorted(blocker_totals.items(),
                               key=lambda kv: -kv[1])[:top_blockers]],
        "worst_residual_fraction": round(worst_residual, 6),
        "cost_per_1k_tokens": round(
            1000.0 * total_dev_s / total_tok, 6) if total_tok else 0.0,
        "economics": econ,
        "speculative": speculative,
    }


def _ms_view(att: dict) -> dict:
    return {
        "buckets": {b: round(v * 1e-3, 3)
                    for b, v in att["buckets"].items()},
        "blockers": {r: round(v * 1e-3, 3)
                     for r, v in att["blockers"].items()},
        "residual_fraction": round(att["residual_fraction"], 6),
    }


def export_cost_gauges(report: dict, registry) -> None:
    """Push the ledger's unit-economics axes into a MetricsRegistry:
    ``cost_per_1k_tokens{replica=...}`` / ``{version=...}`` plus the
    fleet-wide value — the scrape-side face of the cost ledger."""
    if registry is None:
        return
    help_ = "Device-seconds consumed per 1k delivered tokens."
    registry.gauge("cost_per_1k_tokens", help_).set(
        report.get("cost_per_1k_tokens", 0.0))
    for axis in ("replica", "version"):
        for key, g in report.get("economics", {}).get(axis, {}).items():
            registry.gauge("cost_per_1k_tokens", help_,
                           labels={axis: key}).set(
                g["cost_per_1k_tokens"])
