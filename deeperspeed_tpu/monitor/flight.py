"""Crash-proof flight recorder: the trace tail that survives SIGKILL.

The ring tracer keeps events in process memory, so a SIGKILLed trainer
or replica takes its last seconds of telemetry with it — exactly the
window a post-mortem needs. The flight recorder closes that gap: a
fixed-size file-backed mmap ring of CRC'd records, written inline by
the tracer on every event. mmap writes land in the kernel page cache
immediately, so even an abrupt SIGKILL (no atexit, no flush) leaves a
readable ``flight.bin`` holding the process's final events; only a
whole-machine power loss can take them.

Layout (little-endian)::

    header (4096 B): magic "DSFL" | version u32 | slot_size u32 |
                     capacity u32 | meta_len u32 | meta JSON
    slots  (capacity x slot_size):
                     seq u64 | payload_len u32 | crc32 u32 | payload

The header's meta JSON carries the run context (run_id / role /
incarnation, see runctx.py) and a (wall, perf) clock anchor so the
aggregator can place recovered events on the shared timeline. Each
record's payload is one Chrome-trace event as compact JSON. The seq
field is written LAST: a record torn mid-write (killed between bytes)
either keeps its old seq — stale but intact — or fails the CRC; either
way ``recover()`` never yields garbage. Recovery scans every slot,
drops CRC failures (reported as ``torn``), and returns the survivors
in append order.

Capacity is a ring: record N+capacity overwrites record N. The default
(2048 records x 512 B = 1 MiB) holds the last few thousand events —
minutes of steady-state tracing, which is the window that matters when
a process dies.
"""

import json
import mmap
import os
import struct
import threading
import zlib
from typing import List, Optional

from .runctx import clock_anchor, current

__all__ = ["FlightRecorder", "FlightSnapshot", "recover", "is_flight_file"]

MAGIC = b"DSFL"
VERSION = 1
HEADER_BYTES = 4096
_HEADER = struct.Struct("<4sIIII")          # magic, version, slot, cap, meta
_SLOT = struct.Struct("<QII")               # seq, payload_len, crc32
_SLOT_OVERHEAD = _SLOT.size

DEFAULT_RECORDS = 2048
DEFAULT_SLOT_BYTES = 512


class FlightSnapshot:
    """What ``recover()`` returns: the readable tail of a flight file."""

    def __init__(self, path: str, meta: dict, events: List[dict],
                 torn: int, last_seq: int):
        self.path = path
        self.meta = meta
        self.events = events
        self.torn = torn            # slots whose CRC failed (mid-write kill)
        self.last_seq = last_seq    # total records ever appended
        # records lost to ring overwrite (distinct from torn)
        self.overwritten = max(0, last_seq - len(events) - torn)


class FlightRecorder:
    """Bounded mmap ring of CRC'd trace events; safe under SIGKILL."""

    def __init__(self, path: str, capacity: int = DEFAULT_RECORDS,
                 slot_bytes: int = DEFAULT_SLOT_BYTES,
                 meta: Optional[dict] = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if slot_bytes < _SLOT_OVERHEAD + 32:
            raise ValueError(f"slot_bytes must be >= {_SLOT_OVERHEAD + 32}, "
                             f"got {slot_bytes}")
        self.path = path
        self.capacity = capacity
        self.slot_bytes = slot_bytes
        self._lock = threading.Lock()
        self._seq = 0
        full_meta = {**current().as_args(), "pid": os.getpid(),
                     "clock": clock_anchor(), **(meta or {})}
        meta_blob = json.dumps(full_meta).encode("utf-8")
        if _HEADER.size + len(meta_blob) > HEADER_BYTES:
            meta_blob = json.dumps(current().as_args()).encode("utf-8")
        header = _HEADER.pack(MAGIC, VERSION, slot_bytes, capacity,
                              len(meta_blob)) + meta_blob
        header = header.ljust(HEADER_BYTES, b"\0")
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        size = HEADER_BYTES + capacity * slot_bytes
        # recreate from scratch: a flight file is per-(process,
        # incarnation); stale records from a previous life must not
        # masquerade as this one's
        self._fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_TRUNC)
        os.ftruncate(self._fd, size)
        self._mm = mmap.mmap(self._fd, size)
        self._mm[:HEADER_BYTES] = header
        self._closed = False

    # -------------------------------------------------------------- #

    def append(self, ev: dict) -> None:
        """Record one event inline. Never raises into the hot path: an
        oversized event is shrunk to its envelope rather than dropped."""
        if self._closed:
            return
        payload = json.dumps(ev, separators=(",", ":"),
                             default=str).encode("utf-8")
        limit = self.slot_bytes - _SLOT_OVERHEAD
        if len(payload) > limit:
            slim = {k: ev[k] for k in
                    ("name", "ph", "ts", "dur", "pid", "tid") if k in ev}
            slim["args"] = {"truncated": True}
            payload = json.dumps(slim, separators=(",", ":"),
                                 default=str).encode("utf-8")[:limit]
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        with self._lock:
            if self._closed:
                return
            self._seq += 1
            seq = self._seq
            off = HEADER_BYTES + ((seq - 1) % self.capacity) * self.slot_bytes
            mm = self._mm
            # payload + envelope first, seq LAST: a kill mid-write leaves
            # either the old (intact) record or a CRC failure, never a
            # plausible-looking hybrid
            mm[off + 8:off + _SLOT_OVERHEAD] = struct.pack(
                "<II", len(payload), crc)
            mm[off + _SLOT_OVERHEAD:off + _SLOT_OVERHEAD + len(payload)] = \
                payload
            mm[off:off + 8] = struct.pack("<Q", seq)

    @property
    def appended(self) -> int:
        return self._seq

    def flush(self) -> None:
        with self._lock:
            if not self._closed:
                self._mm.flush()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                self._mm.flush()
                self._mm.close()
            finally:
                os.close(self._fd)


# ------------------------------------------------------------------ #
# recovery
# ------------------------------------------------------------------ #


def is_flight_file(path: str) -> bool:
    try:
        with open(path, "rb") as f:
            return f.read(4) == MAGIC
    except OSError:
        return False


def recover(path: str) -> FlightSnapshot:
    """Read back whatever a (possibly SIGKILLed) process left behind.
    Tolerates a torn final record and a truncated file; raises only on
    a missing/garbled header."""
    with open(path, "rb") as f:
        raw = f.read()
    if len(raw) < HEADER_BYTES:
        raise ValueError(f"{path}: too short to be a flight file")
    magic, version, slot_bytes, capacity, meta_len = _HEADER.unpack(
        raw[:_HEADER.size])
    if magic != MAGIC:
        raise ValueError(f"{path}: not a flight file (bad magic)")
    if version != VERSION:
        raise ValueError(f"{path}: unsupported flight version {version}")
    try:
        meta = json.loads(
            raw[_HEADER.size:_HEADER.size + meta_len].decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        meta = {}
    records = []
    torn = 0
    last_seq = 0
    for i in range(capacity):
        off = HEADER_BYTES + i * slot_bytes
        slot = raw[off:off + slot_bytes]
        if len(slot) < _SLOT_OVERHEAD:
            break  # file truncated mid-slot: everything past here is gone
        seq, plen, crc = _SLOT.unpack(slot[:_SLOT_OVERHEAD])
        if seq == 0:
            continue  # never written
        last_seq = max(last_seq, seq)
        payload = slot[_SLOT_OVERHEAD:_SLOT_OVERHEAD + plen]
        if (plen > slot_bytes - _SLOT_OVERHEAD or len(payload) < plen
                or (zlib.crc32(payload) & 0xFFFFFFFF) != crc):
            torn += 1
            continue
        try:
            ev = json.loads(payload.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            torn += 1
            continue
        if isinstance(ev, dict):
            records.append((seq, ev))
        else:
            torn += 1
    records.sort(key=lambda r: r[0])
    return FlightSnapshot(path, meta, [ev for _, ev in records],
                          torn, last_seq)
