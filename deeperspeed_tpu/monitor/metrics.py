"""Metrics registry (counters / gauges / histograms) with a Prometheus
text-exposition endpoint.

The registry is the single collection surface: ``serving/metrics.py``
records into it, the stdlib ``http.server`` thread serves it at
``/metrics`` in Prometheus exposition format 0.0.4 (scrapeable by any
Prometheus/Grafana-agent), and the same snapshot exports periodically
through the existing ``TensorBoardMonitor`` so serving dashboards and
training dashboards stay one system.

Instruments follow the Prometheus data model:

  * ``Counter`` — monotone; rendered as ``name_total``-style samples.
  * ``Gauge``   — last-write-wins scalar.
  * ``Histogram`` — FIXED bucket bounds chosen at creation (cumulative
    ``le`` buckets + ``_sum`` + ``_count``); fixed buckets keep the
    per-observation cost to a bisect + two adds, no allocation.

Labels are static per child: ``registry.counter("finished_total",
labels={"reason": "eos"})`` returns the child for that label set; render
groups children under one ``# TYPE`` header, as the format requires.

Everything is stdlib-only and thread-safe (one lock per registry; the
GIL makes the instrument fast paths near-free).
"""

import bisect
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "MetricsServer",
    "export_to_tensorboard",
    "DEFAULT_LATENCY_BUCKETS",
    "DEFAULT_SAVE_BUCKETS",
    "DEFAULT_STALL_BUCKETS",
]

# seconds; spans sub-ms decode steps to multi-second TTFT tails. The
# 0.1–10 s range is deliberately dense: that is where serving TTFT/E2E
# tails live (cold prefill buckets, HOL blocking, retry backoff), and a
# p99 estimated from histogram buckets is only as sharp as the bucket
# walls around it.
DEFAULT_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.15, 0.25, 0.35,
    0.5, 0.75, 1.0, 1.5, 2.5, 3.5, 5.0, 7.5, 10.0, 15.0, 30.0,
)

# seconds; checkpoint write+commit wall time — tiny CPU-test saves up to
# multi-minute full-model writes on a slow disk
DEFAULT_SAVE_BUCKETS = (
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0, 30.0, 60.0, 120.0,
)

# seconds; per-step host-blocked input wait (datapipe) — a healthy
# prefetched pipe sits in the sub-ms buckets, a host-bound one in the
# tens/hundreds of ms
DEFAULT_STALL_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _sanitize(name: str) -> str:
    out = []
    for i, ch in enumerate(name):
        ok = ch.isalnum() or ch in "_:"
        if ch.isdigit() and i == 0:
            out.append("_")
        out.append(ch if ok else "_")
    return "".join(out)


def _fmt_labels(labels: Optional[Dict[str, str]]) -> str:
    if not labels:
        return ""
    parts = []
    for k in sorted(labels):
        v = str(labels[k]).replace("\\", r"\\").replace('"', r"\"") \
            .replace("\n", r"\n")
        parts.append(f'{_sanitize(k)}="{v}"')
    return "{" + ",".join(parts) + "}"


def _fmt_value(v: float) -> str:
    f = float(v)
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class Counter:
    __slots__ = ("labels", "_value", "_lock")

    def __init__(self, labels=None):
        self.labels = labels
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value

    def _samples(self, name):
        return [(name, self.labels, self._value)]


class Gauge:
    __slots__ = ("labels", "_value", "_lock")

    def __init__(self, labels=None):
        self.labels = labels
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        return self._value

    def _samples(self, name):
        return [(name, self.labels, self._value)]


class Histogram:
    __slots__ = ("labels", "buckets", "_counts", "_sum", "_count", "_lock")

    def __init__(self, buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
                 labels=None):
        b = sorted(float(x) for x in buckets)
        if not b:
            raise ValueError("histogram needs at least one bucket bound")
        if sorted(set(b)) != b:
            raise ValueError(f"duplicate histogram bucket bounds: {b}")
        self.labels = labels
        self.buckets = tuple(b)
        self._counts = [0] * (len(b) + 1)  # +1 for +Inf
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self.buckets, float(value))
        with self._lock:
            self._counts[i] += 1
            self._sum += float(value)
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def _samples(self, name):
        out = []
        cum = 0
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        for bound, c in zip(self.buckets, counts):
            cum += c
            le = dict(self.labels or {}, le=_fmt_value(bound))
            out.append((f"{name}_bucket", le, cum))
        out.append((f"{name}_bucket", dict(self.labels or {}, le="+Inf"),
                    total))
        out.append((f"{name}_sum", self.labels, s))
        out.append((f"{name}_count", self.labels, total))
        return out


_TYPES = {Counter: "counter", Gauge: "gauge", Histogram: "histogram"}


class MetricsRegistry:
    """Name -> instrument family; families with labels hold one child per
    label set."""

    def __init__(self):
        self._lock = threading.Lock()
        # name -> (kind, help, {label_key: instrument})
        self._families: Dict[str, Tuple[type, str, Dict]] = {}

    def _get(self, cls, name: str, help: str, labels, **kw):
        name = _sanitize(name)
        key = tuple(sorted((labels or {}).items()))
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = (cls, help, {})
                self._families[name] = fam
            if fam[0] is not cls:
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{_TYPES[fam[0]]}, not {_TYPES[cls]}")
            inst = fam[2].get(key)
            if inst is None:
                inst = cls(labels=dict(labels) if labels else None, **kw)
                fam[2][key] = inst
        return inst

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
                  labels: Optional[Dict[str, str]] = None) -> Histogram:
        return self._get(Histogram, name, help, labels, buckets=buckets)

    # -------------------------------------------------------------- #

    def collect(self) -> Dict[str, Tuple[str, str, List]]:
        """name -> (type, help, [(sample_name, labels, value), ...])."""
        with self._lock:
            families = {n: (f[0], f[1], list(f[2].values()))
                        for n, f in self._families.items()}
        out = {}
        for name, (cls, help, children) in families.items():
            samples = []
            for child in children:
                samples.extend(child._samples(name))
            out[name] = (_TYPES[cls], help, samples)
        return out

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines = []
        for name, (typ, help, samples) in sorted(self.collect().items()):
            if help:
                esc = help.replace("\\", r"\\").replace("\n", r"\n")
                lines.append(f"# HELP {name} {esc}")
            lines.append(f"# TYPE {name} {typ}")
            for sname, labels, value in samples:
                lines.append(f"{sname}{_fmt_labels(labels)} "
                             f"{_fmt_value(value)}")
        return "\n".join(lines) + "\n"

    def snapshot_scalars(self, prefix: str = "") -> Dict[str, float]:
        """Flat scalar view (histograms as mean/count) for TensorBoard."""
        out = {}
        for name, (typ, _help, samples) in self.collect().items():
            if typ == "histogram":
                by_suffix = {}
                for sname, labels, value in samples:
                    by_suffix.setdefault(sname, []).append((labels, value))
                for (labels_c, count), (labels_s, total) in zip(
                        by_suffix.get(f"{name}_count", []),
                        by_suffix.get(f"{name}_sum", [])):
                    tag = prefix + name + _fmt_labels(labels_c)
                    out[tag + "_count"] = float(count)
                    if count:
                        out[tag + "_mean"] = float(total) / count
            else:
                for sname, labels, value in samples:
                    out[prefix + sname + _fmt_labels(labels)] = float(value)
        return out


def export_to_tensorboard(registry: MetricsRegistry, monitor,
                          step: int, prefix: str = "Monitor/") -> None:
    """Push the registry snapshot through a TensorBoardMonitor (the same
    scalar surface the training engine writes to)."""
    if monitor is None:
        return
    monitor.write_scalars(registry.snapshot_scalars(prefix), step)


# ------------------------------------------------------------------ #
# the /metrics endpoint
# ------------------------------------------------------------------ #


class MetricsServer:
    """Prometheus scrape endpoint on a daemon ``http.server`` thread.

    Port 0 binds an ephemeral port (see ``.port`` after ``start()``) —
    what the tests use; production configs pin one. The default host is
    loopback; set ``host="0.0.0.0"`` explicitly to expose beyond the pod.
    """

    def __init__(self, registry: MetricsRegistry, port: int = 0,
                 host: str = "127.0.0.1"):
        self.registry = registry
        self.host = host
        self.port = port
        self._httpd = None
        self._thread = None

    def start(self) -> "MetricsServer":
        registry = self.registry

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API name)
                if self.path.split("?")[0] not in ("/", "/metrics"):
                    self.send_error(404)
                    return
                body = registry.render().encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # keep scrapes out of stderr
                pass

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="metrics-endpoint",
            daemon=True)
        self._thread.start()
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
