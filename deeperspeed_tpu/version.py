__version__ = "0.1.0"
__version_info__ = tuple(int(x) for x in __version__.split("."))
