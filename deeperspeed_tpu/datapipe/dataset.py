"""Streaming memory-mapped token-shard dataset + deterministic ordering.

``TokenShardDataset`` indexes fixed ``seq_len + 1``-token windows over a
memory-mapped token corpus — a single ``.npy`` file (the bundled
``data/corpus_tokens.npy``) or a directory of ``*.npy`` shards. Nothing
is read until a window is fetched, so a multi-TB corpus costs a few
mmap handles, and the page cache does the streaming.

Epoch order is a **counter-based** permutation: ``epoch_order(seed,
epoch, n)`` derives the whole epoch's order from the Philox counter RNG
keyed by ``(seed, epoch)``. There is no mutable RNG object whose state
must be serialized — any ``(seed, epoch, cursor)`` triple reconstructs
the exact remaining sample sequence, which is what makes mid-epoch
resume bit-identical. ``order_fingerprint`` condenses the order into a
short hash the checkpoint carries so a resume against a changed corpus
or seed is detected instead of silently replaying different data.
"""

import hashlib
import os
from typing import List, Optional

import numpy as np

__all__ = [
    "TokenShardDataset",
    "epoch_order",
    "order_fingerprint",
]


def _load_shard(path: str):
    arr = np.load(path, mmap_mode="r")
    if arr.ndim != 1:
        raise ValueError(
            f"token shard {path} must be a 1-D token array, got shape "
            f"{arr.shape}")
    return arr


class TokenShardDataset:
    """Indexable windows of ``seq_len + 1`` tokens over mmap'd shards.

    Windows never straddle a shard boundary (each shard's ragged tail is
    dropped), so shard files can be produced independently and
    concatenated logically in sorted-filename order — the order is part
    of the deterministic-iteration contract.
    """

    def __init__(self, source, seq_len: int, dtype=np.int32):
        self.seq_len = int(seq_len)
        if self.seq_len < 1:
            raise ValueError(f"seq_len must be >= 1, got {seq_len}")
        self.dtype = np.dtype(dtype)
        self._window = self.seq_len + 1
        if isinstance(source, np.ndarray):
            shards: List[np.ndarray] = [source]
            self.paths = ["<in-memory>"]
        else:
            source = str(source)
            if os.path.isdir(source):
                self.paths = sorted(
                    os.path.join(source, f) for f in os.listdir(source)
                    if f.endswith(".npy"))
                if not self.paths:
                    raise FileNotFoundError(
                        f"no .npy token shards in directory {source}")
            elif os.path.isfile(source):
                self.paths = [source]
            else:
                raise FileNotFoundError(f"token source {source} not found")
            shards = [_load_shard(p) for p in self.paths]
        self._shards = shards
        per_shard = [s.size // self._window for s in shards]
        if sum(per_shard) == 0:
            raise ValueError(
                f"token source holds no full window of {self._window} "
                f"tokens (sizes: {[s.size for s in shards]})")
        # windows[i] lives in shard bisect(cum, i); cum is exclusive
        self._cum = np.cumsum([0] + per_shard)
        self._len = int(self._cum[-1])

    def __len__(self) -> int:
        return self._len

    def __getitem__(self, i: int) -> np.ndarray:
        i = int(i)
        if not 0 <= i < self._len:
            raise IndexError(f"window {i} out of range [0, {self._len})")
        s = int(np.searchsorted(self._cum, i, side="right")) - 1
        local = i - int(self._cum[s])
        w = self._window
        chunk = self._shards[s][local * w:(local + 1) * w]
        return np.asarray(chunk, dtype=self.dtype)

    def identity(self) -> dict:
        """What the checkpoint fingerprint binds to: the shard layout."""
        return {
            "n_windows": self._len,
            "seq_len": self.seq_len,
            "shards": [os.path.basename(p) for p in self.paths],
        }


def epoch_order(seed: int, epoch: int, n: int,
                shuffle: bool = True) -> np.ndarray:
    """The epoch's sample order — a pure function of (seed, epoch, n).

    Philox is a counter-based generator: keying it with (seed, epoch)
    gives independent streams per epoch with nothing to carry between
    them, so the permutation can be recomputed identically at resume
    from just the integers in the checkpoint.
    """
    if not shuffle:
        return np.arange(n, dtype=np.int64)
    key = (int(seed) & (2**64 - 1)) << 64 | (int(epoch) & (2**64 - 1))
    rng = np.random.Generator(np.random.Philox(key=key))
    return rng.permutation(n).astype(np.int64)


def order_fingerprint(seed: int, epoch: int, n: int,
                      shuffle: bool = True,
                      identity: Optional[dict] = None) -> str:
    """Short stable hash naming the epoch order (plus the dataset
    identity) for the resume sanity check. The order is a pure function
    of ``(seed, epoch, n, shuffle)``, so hashing those parameters binds
    the fingerprint to the order exactly — without materializing the
    O(n) permutation, which matters on billion-window corpora."""
    h = hashlib.sha256()
    h.update(
        f"{int(seed)}:{int(epoch)}:{int(n)}:{int(bool(shuffle))}".encode())
    if identity:
        h.update(repr(sorted(identity.items())).encode())
    return h.hexdigest()[:16]
