"""Async double-buffered prefetcher.

One daemon producer thread runs ``produce()`` (index gather + collation
+ curriculum masking + optional device staging) and parks finished
global batches in a bounded queue. The step loop's only host work per
step is a queue pop — the ``wait`` it reports is exactly the host time
the device sat starved for input, which the pipeline exports as
``datapipe_host_stall_seconds``.

The same overlap principle the engine applies to compute/collectives
applies here one level up: input staging is tracked (the queue) and
triggered (the producer) asynchronously so the device never waits on
the host. ``jax.device_put`` is safe to call off-thread — dispatch is
thread-safe and the transfer overlaps the running step.

Error contract: a producer exception is caught, parked, and re-raised
on the consumer's next ``get()`` — never swallowed by the thread.
"""

import queue
import threading
import time
from typing import Any, Callable, Tuple

__all__ = ["AsyncPrefetcher"]

_OK, _ERR = 0, 1


class AsyncPrefetcher:
    def __init__(self, produce: Callable[[], Any], depth: int = 2,
                 name: str = "datapipe-prefetch"):
        self._produce = produce
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(depth)))
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._thread.start()

    # ---- producer side ---------------------------------------------- #

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                item = self._produce()
            except BaseException as e:  # noqa: BLE001 - parked for consumer
                self._put((_ERR, e))
                return
            if not self._put((_OK, item)):
                return

    def _put(self, item) -> bool:
        """Bounded put that stays responsive to close()."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    # ---- consumer side ---------------------------------------------- #

    def get(self) -> Tuple[Any, float]:
        """(next item, seconds the caller blocked waiting for it)."""
        if self._stop.is_set():
            raise RuntimeError("prefetcher is closed")
        t0 = time.perf_counter()
        kind, item = self._q.get()
        wait = time.perf_counter() - t0
        if kind == _ERR:
            self._stop.set()
            raise item
        return item, wait

    @property
    def queued(self) -> int:
        return self._q.qsize()

    def close(self) -> None:
        """Stop the producer and drop staged batches. Safe to call
        twice; used on restore (staged batches predate the restored
        cursor and must not be consumed) and at preemption."""
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5)
