"""DataState: the explicit, checkpointable iteration cursor.

Everything needed to reproduce the remaining batch stream after a
restart — including a mid-epoch SIGKILL — is six integers and a
fingerprint:

  * ``epoch``      — which counter-based permutation is in effect;
  * ``cursor``     — samples already consumed from this epoch's order;
  * ``offset``     — tokens already consumed from the (EOS-augmented)
    document AT the cursor, when sequence packing split that document
    at a batch boundary; 0 otherwise. The next packed batch resumes the
    document there, so long documents lose nothing across batches;
  * ``step``       — global batches produced (drives the curriculum and
    the batch-size schedule composition, so prefetched batches are
    shaped for the step that will consume them);
  * ``samples``    — lifetime samples consumed (bookkeeping/metrics);
  * ``seed``       — the shuffle seed the stream was built with;
  * ``fingerprint``— hash of the CURRENT epoch's order + dataset
    identity, verified at restore so a changed corpus/seed is loud.

The state advances only when a batch is **handed to the step loop**,
never when the prefetcher merely produces it — so a checkpoint taken at
a step boundary always points at exactly the first batch the resumed
run must consume, regardless of how many batches sat staged in the
queue when the process died.
"""

import dataclasses

__all__ = ["DataState"]


@dataclasses.dataclass(frozen=True)
class DataState:
    epoch: int = 0
    cursor: int = 0
    step: int = 0
    samples: int = 0
    seed: int = 0
    fingerprint: str = ""
    offset: int = 0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "DataState":
        # unknown keys are dropped and missing keys default, so
        # checkpoints written before a field existed (e.g. ``offset``)
        # restore cleanly
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in (d or {}).items() if k in known})
