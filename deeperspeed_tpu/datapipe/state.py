"""DataState: the explicit, checkpointable iteration cursor.

Everything needed to reproduce the remaining batch stream after a
restart — including a mid-epoch SIGKILL — is five integers and a
fingerprint:

  * ``epoch``      — which counter-based permutation is in effect;
  * ``cursor``     — samples already consumed from this epoch's order;
  * ``step``       — global batches produced (drives the curriculum and
    the batch-size schedule composition, so prefetched batches are
    shaped for the step that will consume them);
  * ``samples``    — lifetime samples consumed (bookkeeping/metrics);
  * ``seed``       — the shuffle seed the stream was built with;
  * ``fingerprint``— hash of the CURRENT epoch's order + dataset
    identity, verified at restore so a changed corpus/seed is loud.

The state advances only when a batch is **handed to the step loop**,
never when the prefetcher merely produces it — so a checkpoint taken at
a step boundary always points at exactly the first batch the resumed
run must consume, regardless of how many batches sat staged in the
queue when the process died.
"""

import dataclasses

__all__ = ["DataState"]


@dataclasses.dataclass(frozen=True)
class DataState:
    epoch: int = 0
    cursor: int = 0
    step: int = 0
    samples: int = 0
    seed: int = 0
    fingerprint: str = ""

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "DataState":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in (d or {}).items() if k in known})
