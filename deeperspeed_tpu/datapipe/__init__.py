"""datapipe: streaming, prefetching, checkpointable host input pipeline.

Enabled by a ``"datapipe"`` block in the DeepSpeed-style config (same
convention as ``"monitor"`` and ``"resilience"``: presence enables
unless ``"enabled": false``). The engine builds one :class:`DataPipe`
at init, pulls global batches from it in ``train_batch``, carries its
:class:`DataState` inside every checkpoint, and restores it on
``load_checkpoint`` — giving bit-identical batch order across resumes,
including a mid-epoch SIGKILL with batches staged in the prefetch queue.
"""

from .collator import SequencePacker, stack_collate
from .config import DataPipeConfig
from .curriculum import CurriculumStage, SeqLenCurriculum, batch_size_at
from .dataset import TokenShardDataset, epoch_order, order_fingerprint
from .pipeline import DataPipe, build_datapipe
from .prefetcher import AsyncPrefetcher
from .state import DataState

__all__ = [
    "AsyncPrefetcher",
    "CurriculumStage",
    "DataPipe",
    "DataPipeConfig",
    "DataState",
    "SeqLenCurriculum",
    "SequencePacker",
    "TokenShardDataset",
    "batch_size_at",
    "build_datapipe",
    "epoch_order",
    "order_fingerprint",
    "stack_collate",
]
