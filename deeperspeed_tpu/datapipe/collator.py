"""Collation: fixed-window stacking and ragged-document packing.

``stack_collate`` is the fixed-shape fast path (re-exported from the
legacy loader so the two cannot diverge). ``SequencePacker`` handles
ragged documents: tokens from consecutive documents are packed greedily,
in order, into fixed ``(rows, seq_len + 1)`` batches with an optional
EOS separator and per-token segment ids, so short documents stop
wasting the padded tail of every row. Packing is deterministic — same
document stream, same packed batches — which keeps it compatible with
the checkpointable cursor: the cursor counts documents consumed
completely, and a document split by a batch boundary is named by its
``(cursor, tail offset)`` pair so the next batch resumes its remainder
instead of dropping it. No token is ever lost to packing.
"""

from typing import Iterable, Optional, Tuple

import numpy as np

from ..runtime.dataloader import _default_collate as stack_collate

__all__ = ["SequencePacker", "stack_collate"]


class SequencePacker:
    """Greedy in-order packer of 1-D token arrays into fixed rows.

    A document longer than the space left in a row spills into the next
    row, where its continuation becomes that row's segment 1. Segment
    ids are 1-based per row; 0 marks padding — usable directly as an
    attention-mask key or a loss mask.
    """

    def __init__(self, seq_len: int, pad_id: int = 0,
                 eos_id: Optional[int] = None, dtype=np.int32):
        self.row_len = int(seq_len) + 1
        self.pad_id = int(pad_id)
        self.eos_id = eos_id
        self.dtype = np.dtype(dtype)

    def doc_tokens(self, doc) -> np.ndarray:
        doc = np.asarray(doc).reshape(-1)
        if self.eos_id is not None:
            doc = np.concatenate(
                [doc, np.array([self.eos_id], dtype=doc.dtype)])
        return doc

    def pack(self, docs: Iterable, rows: int,
             first_offset: int = 0
             ) -> Tuple[np.ndarray, np.ndarray, int, int]:
        """Pack ``docs`` into ``(tokens, segment_ids, used, tail_offset)``.

        ``docs`` may be any iterable — including a lazy generator over
        the remaining epoch — and is consumed only until the ``rows``
        rows of ``seq_len + 1`` tokens are full, so per-batch cost is
        bounded by the batch size, never by the epoch remainder.

        ``used`` counts documents consumed COMPLETELY; the caller
        advances its cursor by that count. A document cut off by the end
        of the batch is not counted — instead ``tail_offset`` reports
        how far into its (EOS-augmented) token stream the batch reached,
        and the caller stores it so the next batch resumes the remainder
        via ``first_offset``. Unstarted documents are simply re-read
        next batch. Either way no hidden carry state escapes the
        checkpoint and no token is ever dropped.
        """
        tokens = np.full((rows, self.row_len), self.pad_id, self.dtype)
        segs = np.zeros((rows, self.row_len), np.int32)
        r, col, seg = 0, 0, 0
        used = 0
        first = True
        for doc in docs:
            flat = self.doc_tokens(doc)
            start = 0
            if first:
                start = min(int(first_offset), flat.size)
                first = False
            if r >= rows:
                break
            # a doc that cannot start in the remaining space of the
            # LAST row is left for the next batch; mid-batch it spills
            # into the next row instead
            if col >= self.row_len:
                r, col, seg = r + 1, 0, 0
                if r >= rows:
                    break
            seg += 1
            pos = start
            while pos < flat.size and r < rows:
                space = self.row_len - col
                take = min(space, flat.size - pos)
                tokens[r, col:col + take] = flat[pos:pos + take]
                segs[r, col:col + take] = seg
                col += take
                pos += take
                if col >= self.row_len and pos < flat.size:
                    r, col = r + 1, 0
                    seg = 1  # new row restarts segment numbering
            if pos < flat.size:
                # ran out of rows mid-document: hand the split point
                # back so the next batch resumes this document at pos
                return tokens, segs, used, pos
            used += 1
        return tokens, segs, used, 0
