"""Datapipe-block configuration.

The host-input counterpart of the ``"serving"``/``"monitor"``/
``"resilience"`` blocks: a ``"datapipe"`` block in the master JSON
config (or a plain dict) builds a ``DataPipeConfig``. Block presence
enables the subsystem unless ``{"enabled": false}``; without it the
engine keeps the legacy synchronous ``DeepSpeedDataLoader`` path.

::

    "datapipe": {
        "source": "data/corpus_tokens.npy",  # .npy file or dir of shards
        "seq_len": 1024,          # window length (tokens per sample - 1)
        "seed": 0,                # epoch-shuffle seed
        "shuffle": true,          # deterministic per-epoch permutation
        "prefetch": true,         # async double-buffered producer thread
        "prefetch_depth": 2,      # bounded staging queue (global batches)
        "stage_to_device": true,  # place batches on the mesh off-thread
        "pack_sequences": false,  # greedy packing for ragged documents
        "pad_id": 0,
        "eos_id": null,           # separator appended between packed docs
        "curriculum": {           # optional seq-len warmup stage
            "start_seq_len": 64,
            "warmup_steps": 1000,
            "num_intervals": 4
        }
    }

Every knob that shapes the batch stream (seed, shuffle, packing,
curriculum) is part of the checkpointable iteration contract: a resumed
run with the same block replays the exact same remaining batches.
"""

import dataclasses
from typing import Optional

_KNOWN_KEYS = frozenset({
    "enabled", "source", "seq_len", "seed", "shuffle", "prefetch",
    "prefetch_depth", "stage_to_device", "pack_sequences", "pad_id",
    "eos_id", "curriculum",
})

# curriculum sub-block keys, declared as constants so the static
# config-key audit can enumerate them (analysis config-key-undeclared)
CURRICULUM_START_SEQ_LEN = "start_seq_len"
CURRICULUM_WARMUP_STEPS = "warmup_steps"
CURRICULUM_NUM_INTERVALS = "num_intervals"

_CURRICULUM_KEYS = frozenset({
    CURRICULUM_START_SEQ_LEN, CURRICULUM_WARMUP_STEPS,
    CURRICULUM_NUM_INTERVALS,
})


@dataclasses.dataclass(frozen=True)
class DataPipeConfig:
    # master switch; runtime/config.py treats block presence as enabled
    # unless {"enabled": false}
    enabled: bool = True
    # token source: a .npy file of token ids or a directory of *.npy
    # shards; None means the dataset comes from initialize()'s
    # training_data argument instead
    source: Optional[str] = None
    # tokens per model input; each dataset sample is seq_len + 1 tokens
    # (inputs + shifted targets), matching the corpus window convention
    seq_len: int = 1024
    # seed of the counter-based per-epoch permutation; the order for
    # (seed, epoch) is a pure function — no mutable RNG state to persist
    seed: int = 0
    shuffle: bool = True
    # run collation + device staging on a background thread so the next
    # global batch is ready before the current step retires
    prefetch: bool = True
    # bounded queue of finished global batches (backpressure, not
    # unbounded host-memory growth)
    prefetch_depth: int = 2
    # stage prefetched batches onto the mesh (P('data') leading-dim
    # sharding via the engine's placement path) from the producer thread
    stage_to_device: bool = True
    # greedy in-order sequence packing for ragged document datasets;
    # requires samples to be 1-D token arrays
    pack_sequences: bool = False
    pad_id: int = 0
    # separator token appended after each packed document (None = none)
    eos_id: Optional[int] = None
    # optional seq-len warmup: {"start_seq_len": S, "warmup_steps": N,
    # "num_intervals": K} — piecewise-constant stages like
    # bs_schedules.BatchSizeScheduler, keyed off the DataState step so
    # prefetched batches are curriculum-consistent and resumable
    curriculum: Optional[dict] = None

    def __post_init__(self):
        if self.seq_len < 1:
            raise ValueError(f"seq_len must be >= 1, got {self.seq_len}")
        if self.prefetch_depth < 1:
            raise ValueError(
                f"prefetch_depth must be >= 1, got {self.prefetch_depth}")
        if self.curriculum is not None:
            if not isinstance(self.curriculum, dict):
                raise ValueError('"curriculum" must be a dict '
                                 '(start_seq_len/warmup_steps/num_intervals)'
                                 ' or null')
            unknown = set(self.curriculum) - _CURRICULUM_KEYS
            if unknown:
                raise ValueError(
                    f"unknown curriculum keys {sorted(unknown)}; valid "
                    f"keys: {sorted(_CURRICULUM_KEYS)}")
            start = self.curriculum.get(CURRICULUM_START_SEQ_LEN,
                                        self.seq_len)
            if not (1 <= int(start) <= self.seq_len):
                raise ValueError(
                    f"curriculum.start_seq_len must be in 1..seq_len "
                    f"({self.seq_len}), got {start}")
            if int(self.curriculum.get(CURRICULUM_WARMUP_STEPS, 0)) < 0:
                raise ValueError("curriculum.warmup_steps must be >= 0")

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "DataPipeConfig":
        d = dict(d or {})
        unknown = set(d) - _KNOWN_KEYS
        if unknown:
            raise ValueError(
                f"unknown datapipe config keys {sorted(unknown)}; "
                f"valid keys: {sorted(_KNOWN_KEYS)}")
        return cls(**d)
