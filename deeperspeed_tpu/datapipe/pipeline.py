"""DataPipe: the engine-facing composition of the input subsystem.

One pipe per engine binds the pieces together:

  * a sample source — ``TokenShardDataset`` built from
    ``datapipe.source``, or any indexable dataset handed to
    ``initialize(training_data=...)``;
  * the counter-based epoch order (``dataset.epoch_order``) and the
    explicit ``DataState`` cursor over it;
  * the curriculum stage (seq-len warmup composed with the engine's
    ``bs_schedules`` batch-size schedule) and the collator (stacking or
    ragged-document packing);
  * the async prefetcher, which also **stages the batch onto the mesh**
    (the engine's ``P('data')`` placement path) from the producer
    thread while the current step runs;
  * monitor wiring: ``datapipe/wait`` trace spans plus the
    ``datapipe_host_stall_seconds`` histogram/gauge and
    ``datapipe_queue_depth`` gauge so input starvation is visible in
    traces and on ``/metrics``.

Determinism contract: ``_make_batch`` is a pure function of
``(DataState, dataset, config)``. The pipe's public state advances only
when the step loop consumes a batch, so the state a checkpoint captures
at a step boundary names exactly the next batch a resumed run will
produce — staged-but-unconsumed batches are recomputed after restore,
bit-identically, from the same counters.
"""

import time
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from ..monitor import get_monitor, trace_span
from ..utils.logging import logger
from .collator import SequencePacker, stack_collate
from .config import (
    CURRICULUM_NUM_INTERVALS,
    CURRICULUM_START_SEQ_LEN,
    CURRICULUM_WARMUP_STEPS,
    DataPipeConfig,
)
from .curriculum import CurriculumStage, SeqLenCurriculum
from .dataset import TokenShardDataset, epoch_order, order_fingerprint
from .prefetcher import AsyncPrefetcher
from .state import DataState

__all__ = ["DataPipe", "build_datapipe"]


class DataPipe:
    def __init__(
        self,
        dataset,
        cfg: DataPipeConfig,
        global_rows: int,
        place_fn: Optional[Callable[[Any], Any]] = None,
        bs_schedule: Optional[List[Tuple[int, int]]] = None,
        collate_fn: Optional[Callable] = None,
    ):
        if global_rows < 1:
            raise ValueError(f"global_rows must be >= 1, got {global_rows}")
        n = len(dataset)
        if not cfg.pack_sequences and global_rows > n:
            raise ValueError(
                f"global batch of {global_rows} rows exceeds the dataset "
                f"({n} samples); shrink the batch or add data")
        self.dataset = dataset
        self.cfg = cfg
        self.global_rows = int(global_rows)
        if cfg.stage_to_device and place_fn is None:
            # standalone use (no engine supplying its _place_batch):
            # stage through the shared sharding substrate — batch axes of
            # the default data mesh
            from ..sharding import default_mesh, place_batch

            _mesh = default_mesh()
            place_fn = lambda b: place_batch(_mesh, b)  # noqa: E731
        self.place_fn = place_fn if cfg.stage_to_device else None
        self.collate_fn = collate_fn or stack_collate
        self.packer = (
            SequencePacker(cfg.seq_len, pad_id=cfg.pad_id, eos_id=cfg.eos_id)
            if cfg.pack_sequences else None)
        curriculum = None
        if cfg.curriculum is not None:
            cur = dict(cfg.curriculum)
            curriculum = SeqLenCurriculum(
                final_seq_len=cfg.seq_len,
                start_seq_len=int(cur.get(CURRICULUM_START_SEQ_LEN,
                                          cfg.seq_len)),
                warmup_steps=int(cur.get(CURRICULUM_WARMUP_STEPS, 1000)),
                num_intervals=int(cur.get(CURRICULUM_NUM_INTERVALS, 4)))
        self.stage = CurriculumStage(curriculum, bs_schedule=bs_schedule,
                                     pad_id=cfg.pad_id)
        self.state = DataState(
            seed=cfg.seed,
            fingerprint=self._fingerprint(cfg.seed, 0))
        self._order_cache: Tuple[Optional[tuple], Optional[np.ndarray]] = (
            None, None)
        self._prefetcher: Optional[AsyncPrefetcher] = None
        self._prod_state: DataState = self.state
        self.last_stall_seconds = 0.0
        if cfg.prefetch:
            self._start_prefetcher()

    # ---------------------------------------------------------------- #
    # deterministic production
    # ---------------------------------------------------------------- #

    def _identity(self) -> Optional[dict]:
        ident = getattr(self.dataset, "identity", None)
        return ident() if callable(ident) else None

    def _fingerprint(self, seed: int, epoch: int) -> str:
        return order_fingerprint(seed, epoch, len(self.dataset),
                                 shuffle=self.cfg.shuffle,
                                 identity=self._identity())

    def _order_for(self, seed: int, epoch: int) -> np.ndarray:
        # keyed by the STATE's seed, not the config's: a checkpoint
        # restored under a different configured seed must still replay
        # the stream it was saved from (checkpoint wins)
        cached_key, order = self._order_cache
        if cached_key != (seed, epoch) or order is None:
            order = epoch_order(seed, epoch, len(self.dataset),
                                shuffle=self.cfg.shuffle)
            self._order_cache = ((seed, epoch), order)
        return order

    def _wrap_epoch(self, st: DataState) -> DataState:
        return DataState(
            epoch=st.epoch + 1, cursor=0, step=st.step,
            samples=st.samples, seed=st.seed,
            fingerprint=self._fingerprint(st.seed, st.epoch + 1))

    def _make_batch(self, st: DataState) -> Tuple[Any, DataState]:
        """Pure: (state) -> (collated+masked batch, state after it)."""
        rows = self.global_rows
        n = len(self.dataset)
        if self.packer is None and st.cursor + rows > n:
            st = self._wrap_epoch(st)  # drop the ragged tail
        order = self._order_for(st.seed, st.epoch)
        if self.packer is not None:
            # lazy: the packer pulls only as many documents as the batch
            # consumes, so per-batch cost is bounded by the batch size —
            # never by the epoch remainder (which on a multi-TB corpus
            # would mean O(n) reads per batch)
            docs = (self.dataset[int(i)] for i in order[st.cursor:])
            tokens, segs, used, offset = self.packer.pack(
                docs, rows, first_offset=st.offset)
            tokens, segs = self.stage.apply(tokens, st.step,
                                            segment_ids=segs)
            batch = {"tokens": tokens, "segment_ids": segs}
            next_st = DataState(
                epoch=st.epoch, cursor=st.cursor + used, step=st.step + 1,
                samples=st.samples + used, seed=st.seed,
                fingerprint=st.fingerprint, offset=offset)
            if next_st.cursor >= n:
                next_st = self._wrap_epoch(next_st)
            return batch, next_st
        idx = order[st.cursor:st.cursor + rows]
        samples = [self.dataset[int(i)] for i in idx]
        batch = self.stage.apply(self.collate_fn(samples), st.step)
        next_st = DataState(
            epoch=st.epoch, cursor=st.cursor + rows, step=st.step + 1,
            samples=st.samples + rows, seed=st.seed,
            fingerprint=st.fingerprint)
        return batch, next_st

    def _produce(self):
        """Producer-thread body: build the next batch from the producer
        cursor and stage it on the mesh while the current step runs."""
        batch, next_st = self._make_batch(self._prod_state)
        self._prod_state = next_st
        placed = False
        if self.place_fn is not None:
            batch = self.place_fn(batch)
            placed = True
        return batch, next_st, placed

    # ---------------------------------------------------------------- #
    # the step loop's view
    # ---------------------------------------------------------------- #

    def _start_prefetcher(self) -> None:
        self._prod_state = self.state
        self._prefetcher = AsyncPrefetcher(
            self._produce, depth=self.cfg.prefetch_depth)

    def next_global_batch(self) -> Tuple[Any, bool]:
        """The next global batch and whether it is already placed on the
        mesh. Blocks only while the host is genuinely behind; the wait is
        recorded as the step's host stall."""
        with trace_span("datapipe/wait", lane="datapipe",
                        step=self.state.step):
            if self._prefetcher is not None:
                (batch, next_st, placed), wait = self._prefetcher.get()
            else:
                t0 = time.perf_counter()
                batch, next_st, placed = self._produce()
                wait = time.perf_counter() - t0
        self.state = next_st
        self.last_stall_seconds = wait
        self._record_metrics(wait)
        return batch, placed

    def __iter__(self):
        return self

    def __next__(self):
        return self.next_global_batch()[0]

    def _record_metrics(self, wait: float) -> None:
        mon = get_monitor()
        if mon is None:
            return
        from ..monitor.metrics import DEFAULT_STALL_BUCKETS

        reg = mon.registry
        reg.counter("datapipe_batches_total",
                    "global batches handed to the step loop").inc()
        reg.gauge("datapipe_host_stall_seconds",
                  "host time the last step blocked waiting on input"
                  ).set(wait)
        reg.histogram("datapipe_host_stall_seconds_hist",
                      "host-blocked time per step waiting on input",
                      buckets=DEFAULT_STALL_BUCKETS).observe(wait)
        reg.gauge("datapipe_queue_depth",
                  "staged global batches ready for the step loop").set(
            self._prefetcher.queued if self._prefetcher is not None else 0)
        reg.gauge("datapipe_epoch", "current dataset epoch").set(
            self.state.epoch)

    # ---------------------------------------------------------------- #
    # checkpointable state
    # ---------------------------------------------------------------- #

    def state_dict(self) -> dict:
        return self.state.to_dict()

    def load_state_dict(self, sd: dict) -> None:
        """Restore the iteration cursor. Any staged batches are dropped
        and re-produced from the restored counters — that recomputation
        is what makes resume bit-identical even after a mid-epoch kill
        with batches in flight."""
        st = DataState.from_dict(sd)
        expect = self._fingerprint(st.seed, st.epoch)
        if st.fingerprint and st.fingerprint != expect:
            logger.warning(
                "datapipe: restored DataState fingerprint %s does not "
                "match this dataset/seed (%s) — the corpus, seed, or "
                "shuffle setting changed since the checkpoint; the "
                "resumed batch stream will NOT replay the original run",
                st.fingerprint, expect)
        self.state = DataState(
            epoch=st.epoch, cursor=st.cursor, step=st.step,
            samples=st.samples, seed=st.seed, fingerprint=expect,
            offset=st.offset)
        self._restart_production()

    def seed_step(self, step: int) -> None:
        """Align the curriculum/batch-size step with the engine's
        ``global_steps`` when a restored checkpoint carries no datapipe
        state (a pre-datapipe save). The batch stream still restarts
        from epoch 0 — only the schedules stay consistent."""
        self.state = DataState(
            epoch=self.state.epoch, cursor=self.state.cursor,
            step=int(step), samples=self.state.samples,
            seed=self.state.seed, fingerprint=self.state.fingerprint,
            offset=self.state.offset)
        self._restart_production()

    def _restart_production(self) -> None:
        """Drop staged batches and re-produce from the current state."""
        if self._prefetcher is not None:
            self._prefetcher.close()
            self._start_prefetcher()
        else:
            self._prod_state = self.state

    def close(self) -> None:
        if self._prefetcher is not None:
            self._prefetcher.close()
            self._prefetcher = None


def build_datapipe(
    cfg: DataPipeConfig,
    dataset=None,
    global_rows: int = 1,
    place_fn=None,
    bs_schedule=None,
    collate_fn=None,
) -> DataPipe:
    """Build a DataPipe from the config block. ``dataset`` (an indexable
    of samples, e.g. ``initialize(training_data=...)``) wins over
    ``cfg.source``; with neither there is nothing to iterate."""
    if dataset is None:
        if cfg.source is None:
            raise ValueError(
                'the "datapipe" block needs a "source" (token .npy file '
                "or shard directory) when initialize() gets no "
                "training_data")
        dataset = TokenShardDataset(cfg.source, cfg.seq_len)
    return DataPipe(dataset, cfg, global_rows, place_fn=place_fn,
                    bs_schedule=bs_schedule, collate_fn=collate_fn)
