"""Curriculum stage: seq-len warmup composed with the batch-size warmup.

``SeqLenCurriculum`` mirrors the shape of
``runtime/bs_schedules.BatchSizeScheduler``: piecewise-constant stages
spread linearly over ``warmup_steps``, growing from ``start_seq_len``
to the full ``seq_len``. ``CurriculumStage`` applies both warmups to a
produced batch **without changing its array shape** (the TPU rule: one
compiled step, masked inactive work, no retrace per stage):

  * columns past the scheduled seq-len are overwritten with ``pad_id``;
  * rows past the scheduled batch size (read off an attached
    ``BatchSizeScheduler``'s static schedule) are overwritten with
    ``pad_id``.

Both reads are **pure functions of the DataState step**, not of live
scheduler objects — a prefetched batch produced two steps ahead is
shaped for the step that will consume it, and a resumed run reproduces
the identical masking because the step rides in the checkpoint.
"""

from typing import List, Optional, Tuple

import numpy as np

__all__ = ["SeqLenCurriculum", "CurriculumStage", "batch_size_at"]


def batch_size_at(schedule: List[Tuple[int, int]], step: int) -> int:
    """Scheduled batch size at ``step`` from a BatchSizeScheduler's
    static ``schedule`` — the pure counterpart of its stateful
    ``get_current_batch_size`` (which reads ``last_batch_iteration``)."""
    bs = schedule[0][1]
    for start, stage_bs in schedule:
        if step >= start:
            bs = stage_bs
    return bs


class SeqLenCurriculum:
    def __init__(self, final_seq_len: int, start_seq_len: int,
                 warmup_steps: int = 1000, num_intervals: int = 4):
        self.final_seq_len = int(final_seq_len)
        self.start_seq_len = int(start_seq_len)
        self.warmup_steps = int(warmup_steps)
        self.schedule = self._build(max(int(num_intervals), 1))

    def _build(self, n: int) -> List[Tuple[int, int]]:
        stages: List[Tuple[int, int]] = []
        for i in range(n):
            frac = i / (n - 1) if n > 1 else 1.0
            step = round(frac * self.warmup_steps)
            sl = round(self.start_seq_len
                       + frac * (self.final_seq_len - self.start_seq_len))
            if not stages or stages[-1][1] != sl:
                stages.append((step, sl))
        return stages

    def seq_len_at(self, step: int) -> int:
        return batch_size_at(self.schedule, step)


class CurriculumStage:
    """Applies the seq-len and batch-size warmups to one token batch."""

    def __init__(self, curriculum: Optional[SeqLenCurriculum],
                 bs_schedule: Optional[List[Tuple[int, int]]] = None,
                 pad_id: int = 0):
        self.curriculum = curriculum
        self.bs_schedule = bs_schedule
        self.pad_id = int(pad_id)

    @property
    def active(self) -> bool:
        return self.curriculum is not None or self.bs_schedule is not None

    def plan(self, step: int, rows: int, seq_len: int) -> Tuple[int, int]:
        """(active_rows, active_seq_len) scheduled for ``step``."""
        active_rows = rows
        if self.bs_schedule:
            active_rows = min(rows, batch_size_at(self.bs_schedule, step))
        active_seq = seq_len
        if self.curriculum is not None:
            active_seq = min(seq_len, self.curriculum.seq_len_at(step))
        return active_rows, active_seq

    def apply(self, tokens: np.ndarray, step: int,
              segment_ids: Optional[np.ndarray] = None):
        """Mask inactive rows/columns to pad_id, shape unchanged. Only
        plain 2-D token batches are maskable; anything else (tuple/dict
        pytrees from user collate_fns) passes through untouched.

        When the batch is packed, pass its ``segment_ids`` too: every
        position masked to pad_id also gets segment id 0, so the
        attention/loss mask agrees that the padded tokens are not real
        data. With ``segment_ids`` given the return is the
        ``(tokens, segment_ids)`` pair."""
        maskable = (self.active and isinstance(tokens, np.ndarray)
                    and tokens.ndim == 2)
        if maskable:
            rows, width = tokens.shape
            active_rows, active_seq = self.plan(step, rows, width - 1)
            maskable = active_rows < rows or active_seq < width - 1
        if not maskable:
            return tokens if segment_ids is None else (tokens, segment_ids)
        out = np.array(tokens, copy=True)
        segs = (np.array(segment_ids, copy=True)
                if segment_ids is not None else None)
        if active_seq < width - 1:
            # width is seq_len + 1 (inputs + shifted targets): keep
            # active_seq + 1 tokens so the last target survives
            out[:, active_seq + 1:] = self.pad_id
            if segs is not None:
                segs[:, active_seq + 1:] = 0
        if active_rows < rows:
            out[active_rows:, :] = self.pad_id
            if segs is not None:
                segs[active_rows:, :] = 0
        return out if segs is None else (out, segs)
