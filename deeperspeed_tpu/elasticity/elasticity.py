"""Elastic batch-size / chip-count co-design.

Capability parity with /root/reference/deepspeed/elasticity/elasticity.py:240
(`compute_elastic_config`, `_get_compatible_gpus_v01`): statically choose a
final train batch size whose set of compatible accelerator counts is maximal,
so a scheduler can restart the job at a different chip count without changing
convergence behavior. Re-implemented for the TPU mesh world (a "gpu" here is
one chip / one data-parallel worker slot).
"""

from ..utils.logging import logger
from . import constants as ec


class ElasticityError(Exception):
    pass


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


class ElasticityConfig:
    def __init__(self, param_dict):
        self.enabled = param_dict.get(ec.ENABLED, ec.ENABLED_DEFAULT)
        if self.enabled:
            if ec.MAX_ACCEPTABLE_BATCH_SIZE not in param_dict:
                raise ElasticityConfigError(
                    f"Elasticity config missing {ec.MAX_ACCEPTABLE_BATCH_SIZE}"
                )
            if ec.MICRO_BATCHES not in param_dict:
                raise ElasticityConfigError(f"Elasticity config missing {ec.MICRO_BATCHES}")
        self.max_acceptable_batch_size = param_dict.get(
            ec.MAX_ACCEPTABLE_BATCH_SIZE, ec.MAX_ACCEPTABLE_BATCH_SIZE_DEFAULT
        )
        self.micro_batches = param_dict.get(ec.MICRO_BATCHES, ec.MICRO_BATCHES_DEFAULT)
        if not isinstance(self.micro_batches, list) or not self.micro_batches:
            raise ElasticityConfigError(
                f"{ec.MICRO_BATCHES} must be a non-empty list, got {self.micro_batches}"
            )
        if any((not isinstance(m, int)) or m <= 0 for m in self.micro_batches):
            raise ElasticityConfigError(
                f"{ec.MICRO_BATCHES} values must be positive ints, got {self.micro_batches}"
            )
        self.min_gpus = param_dict.get(ec.MIN_GPUS, ec.MIN_GPUS_DEFAULT)
        self.max_gpus = param_dict.get(ec.MAX_GPUS, ec.MAX_GPUS_DEFAULT)
        if self.min_gpus < 1 or self.max_gpus < self.min_gpus:
            raise ElasticityConfigError(
                f"invalid gpu range [{self.min_gpus}, {self.max_gpus}]"
            )
        self.min_time = param_dict.get(ec.MIN_TIME, ec.MIN_TIME_DEFAULT)
        self.version = param_dict.get(ec.VERSION, ec.VERSION_DEFAULT)
        self.prefer_larger_batch_size = param_dict.get(
            ec.PREFER_LARGER_BATCH, ec.PREFER_LARGER_BATCH_DEFAULT
        )
        self.ignore_non_elastic_batch_info = param_dict.get(
            ec.IGNORE_NON_ELASTIC_BATCH_INFO, ec.IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT
        )

    def repr(self):
        return self.__dict__


def _get_candidate_batch_sizes(base_list, max_acceptable_batch_size):
    candidates = set()
    for base in base_list:
        batch = base
        while batch <= max_acceptable_batch_size:
            candidates.add(batch)
            batch += base
    return sorted(candidates)


def _get_compatible_gpus_v01(micro_batches, final_batch_size, min_gpus, max_gpus):
    """All accelerator counts g in [min, max] such that some micro batch m
    satisfies final_batch_size % (m * g) == 0 (i.e. grad-accum steps integral)."""
    valid = set()
    for m in micro_batches:
        if final_batch_size % m != 0:
            continue
        max_slots = final_batch_size // m
        for g in range(min_gpus, min(max_gpus, max_slots) + 1):
            if max_slots % g == 0:
                valid.add(g)
    return sorted(valid)


def get_best_candidate_batch_size(
    micro_batches, max_acceptable_batch_size, min_gpus, max_gpus, prefer_larger=True
):
    candidates = _get_candidate_batch_sizes(micro_batches, max_acceptable_batch_size)
    best = None
    best_gpus = []
    for batch in candidates:
        valid = _get_compatible_gpus_v01(micro_batches, batch, min_gpus, max_gpus)
        better = len(valid) > len(best_gpus) or (
            len(valid) == len(best_gpus)
            and best is not None
            and (batch > best if prefer_larger else batch < best)
        )
        if best is None or better:
            best, best_gpus = batch, valid
    if best is None or not best_gpus:
        raise ElasticityError(
            "no valid batch size found for "
            f"micro_batches={micro_batches}, max={max_acceptable_batch_size}"
        )
    return best, best_gpus


def compute_elastic_config(ds_config, target_deepspeed_version=None, world_size=0):
    """Returns (final_batch_size, valid_gpus[, micro_batch]) — with world_size>0
    also resolves the per-chip micro batch size for that world size."""
    if isinstance(ds_config, dict):
        elastic_dict = ds_config.get(ec.ELASTICITY, {})
    else:
        elastic_dict = ds_config
    cfg = ElasticityConfig(elastic_dict)
    if cfg.version > ec.LATEST_ELASTICITY_VERSION:
        raise ElasticityConfigError(
            f"Unsupported elasticity version {cfg.version}; latest is "
            f"{ec.LATEST_ELASTICITY_VERSION}"
        )

    final_batch_size, valid_gpus = get_best_candidate_batch_size(
        cfg.micro_batches,
        cfg.max_acceptable_batch_size,
        cfg.min_gpus,
        cfg.max_gpus,
        prefer_larger=cfg.prefer_larger_batch_size,
    )
    logger.info(
        "elasticity: final_batch_size=%d valid world sizes=%s", final_batch_size, valid_gpus
    )
    if world_size > 0:
        if world_size not in valid_gpus:
            raise ElasticityIncompatibleWorldSize(
                f"world size {world_size} not in valid set {valid_gpus} for "
                f"batch size {final_batch_size}"
            )
        # pick the largest compatible micro batch for throughput
        micro = None
        for m in sorted(cfg.micro_batches, reverse=cfg.prefer_larger_batch_size):
            if final_batch_size % (m * world_size) == 0:
                micro = m
                break
        assert micro is not None
        return final_batch_size, valid_gpus, micro
    return final_batch_size, valid_gpus


def elastic_world_sizes(ds_config):
    """Valid world sizes for a config with an elasticity block, [] when
    the block is absent/disabled or unsatisfiable. The resilience
    supervisor exports these to restarted children so a resume on a
    shrunken TPU pool can pick a compatible chip count without
    re-deriving the elastic schedule."""
    if not isinstance(ds_config, dict):
        return []
    elastic_dict = ds_config.get(ec.ELASTICITY, {})
    if not elastic_dict.get(ec.ENABLED, ec.ENABLED_DEFAULT):
        return []
    try:
        _batch, valid_gpus = compute_elastic_config(ds_config)
    except ElasticityError:
        return []
    return sorted(valid_gpus)


def ensure_immutable_elastic_config(runtime_elastic_config_dict):
    """Guard that scheduler-time and runtime elastic configs agree
    (parity with elasticity/elasticity.py:207)."""
    import json
    import os

    env_key = "DEEPSPEED_ELASTICITY_CONFIG"
    if env_key in os.environ:
        scheduler_config = json.loads(os.environ[env_key])
        scheduler = ElasticityConfig(scheduler_config)
        runtime = ElasticityConfig(runtime_elastic_config_dict)
        err = (
            "Elastic config '{}' seen by scheduler ({}) != runtime ({}); "
            "elastic config cannot change after scheduling"
        )
        for field in ("max_acceptable_batch_size", "micro_batches", "version"):
            if getattr(scheduler, field) != getattr(runtime, field):
                raise ElasticityConfigError(
                    err.format(field, getattr(scheduler, field), getattr(runtime, field))
                )
