"""`python -m deeperspeed_tpu.elasticity` — the `ds_elastic` CLI
(reference /root/reference/bin/ds_elastic): print a config's elasticity
block and, given a world size, the resolved batch configuration."""

import argparse
import json

from ..version import __version__
from .elasticity import compute_elastic_config


def main(argv=None):
    parser = argparse.ArgumentParser(prog="ds_elastic")
    parser.add_argument("-c", "--config", type=str, required=True,
                        help="DeepSpeed config json")
    parser.add_argument("-w", "--world-size", type=int, default=0,
                        help="Intended/current world size")
    args = parser.parse_args(argv)
    with open(args.config) as f:
        ds_config = json.load(f)

    sep = "-" * 42
    print(sep)
    print("Elasticity config:")
    print(sep)
    print(json.dumps(ds_config["elasticity"], indent=4, sort_keys=True))

    if args.world_size > 0:
        final_batch, valid_chips, micro = compute_elastic_config(
            ds_config=ds_config, target_deepspeed_version=__version__,
            world_size=args.world_size,
        )
        print(sep)
        print(f"Calculated results for world size {args.world_size}:")
        print(sep)
        print(f"final_batch_size .... {final_batch}")
        print(f"valid_chips ......... {valid_chips}")
        print(f"micro_batch_size .... {micro}")
    else:
        final_batch, valid_chips = compute_elastic_config(
            ds_config=ds_config, target_deepspeed_version=__version__,
        )
        print(sep)
        print("Calculated results:")
        print(sep)
        print(f"final_batch_size .... {final_batch}")
        print(f"valid_chips ......... {valid_chips}")


if __name__ == "__main__":
    main()
