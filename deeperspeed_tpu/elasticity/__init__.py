from .elasticity import (
    ElasticityConfig,
    ElasticityError,
    ElasticityConfigError,
    ElasticityIncompatibleWorldSize,
    compute_elastic_config,
    ensure_immutable_elastic_config,
)
from . import constants
