from .elasticity import (
    ElasticityConfig,
    ElasticityError,
    ElasticityConfigError,
    ElasticityIncompatibleWorldSize,
    compute_elastic_config,
    elastic_world_sizes,
    ensure_immutable_elastic_config,
)
from . import constants
