"""Multi-host job runner: the ``deeperspeed`` CLI front-end.

TPU-native analog of the reference launcher (deepspeed/launcher/runner.py):
parses an MPI-style hostfile ("worker-0 slots=4"), applies include/exclude
resource filters with the same NODE_SPEC grammar, encodes the active
resources as a base64 world-info blob, and fans out one per-node
``deeperspeed_tpu.launcher.launch`` invocation via pdsh / plain ssh /
mpirun / ``gcloud compute tpus tpu-vm ssh`` — or runs locally when no
hostfile is given.

Differences from the reference are deliberate and TPU-shaped:
- "slots" are TPU chips; by default ONE JAX process per host drives all of
  its chips (JAX's process model), instead of one process per device.
- rendezvous env is jax.distributed (coordinator address + process count),
  with RANK/WORLD_SIZE/MASTER_ADDR also set for porting convenience.
"""

from __future__ import annotations

import argparse
import base64
import collections
import json
import os
import shutil
import subprocess
import sys
from copy import deepcopy

from ..utils.logging import logger
from .constants import (
    DEFAULT_HOSTFILE,
    DISTRIBUTED_DEFAULT_PORT,
    ENVIRONMENT_FILE,
    EXPORT_ENVS,
    GCLOUD_LAUNCHER,
    OPENMPI_LAUNCHER,
    PDSH_LAUNCHER,
    SSH_LAUNCHER,
)
from .multinode_runner import (
    GCloudRunner,
    OpenMPIRunner,
    PDSHRunner,
    SSHRunner,
    launch_module_args,
)


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        prog="deeperspeed",
        description="DeeperSpeed-TPU runner: launch multi-host training jobs "
        "across a TPU pod slice or any ssh-reachable cluster.",
    )
    parser.add_argument(
        "-H",
        "--hostfile",
        type=str,
        default=DEFAULT_HOSTFILE,
        help="MPI-style hostfile defining the resource pool "
        "(e.g. 'worker-0 slots=4', slots = TPU chips).",
    )
    parser.add_argument(
        "-i",
        "--include",
        type=str,
        default="",
        help="Resources to use: NODE_SPEC[@NODE_SPEC ...] where "
        "NODE_SPEC=NAME[:SLOT[,SLOT ...]]. Omitting :SLOT takes every slot.",
    )
    parser.add_argument(
        "-e",
        "--exclude",
        type=str,
        default="",
        help="Resources NOT to use; same grammar as --include, mutually "
        "exclusive with it.",
    )
    parser.add_argument(
        "--num_nodes",
        type=int,
        default=-1,
        help="Use only the first N hosts of the hostfile.",
    )
    parser.add_argument(
        "--num_chips",
        "--num_gpus",
        dest="num_chips",
        type=int,
        default=-1,
        help="Max chips per node; uses chip ids [0, N).",
    )
    parser.add_argument(
        "--master_port",
        default=DISTRIBUTED_DEFAULT_PORT,
        type=int,
        help="Port for the jax.distributed coordinator service.",
    )
    parser.add_argument(
        "--master_addr",
        default="",
        type=str,
        help="Address of node 0; inferred via 'hostname -I' over ssh if unset.",
    )
    parser.add_argument(
        "--launcher",
        default=PDSH_LAUNCHER,
        type=str,
        help="Multi-node backend: pdsh, ssh, openmpi, or gcloud "
        "(gcloud compute tpus tpu-vm ssh --worker=all).",
    )
    parser.add_argument(
        "--launcher_args",
        default="",
        type=str,
        help="Extra args passed through to the launcher backend.",
    )
    parser.add_argument(
        "--force_multi",
        action="store_true",
        help="Force multi-node launch even for a single host.",
    )
    parser.add_argument(
        "--procs_per_node",
        type=int,
        default=1,
        help="JAX processes per host (default 1: one process drives all "
        "local chips; raise for per-chip process layouts).",
    )
    parser.add_argument(
        "--tpu_name",
        type=str,
        default="",
        help="(gcloud launcher) TPU VM name for 'gcloud compute tpus tpu-vm ssh'.",
    )
    parser.add_argument(
        "--zone",
        type=str,
        default="",
        help="(gcloud launcher) GCP zone of the TPU VM.",
    )
    parser.add_argument(
        "user_script",
        type=str,
        help="User training script, followed by its arguments.",
    )
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def fetch_hostfile(hostfile_path):
    """Parse 'hostname slots=N' lines into an ordered {host: slot_count}.

    Mirrors reference launcher/runner.py:122 semantics: empty lines skipped,
    malformed lines and duplicate hosts raise ValueError, order preserved.
    """
    if not os.path.isfile(hostfile_path):
        logger.warning(
            "Unable to find hostfile %s, proceeding with local resources only.",
            hostfile_path,
        )
        return None

    resource_pool = collections.OrderedDict()
    with open(hostfile_path, "r") as fd:
        for line in fd.readlines():
            line = line.strip()
            if line == "" or line.startswith("#"):
                continue
            try:
                hostname, slots = line.split()
                key, slot_count = slots.split("=")
                if key != "slots":
                    raise ValueError(key)
                slot_count = int(slot_count)
            except ValueError:
                raise ValueError(
                    f"Hostfile is not formatted correctly: {line!r} "
                    "(expected 'hostname slots=N')"
                )
            if hostname in resource_pool:
                raise ValueError(f"host {hostname} is already defined")
            resource_pool[hostname] = slot_count
    return resource_pool


def parse_resource_filter(host_info, include_str="", exclude_str=""):
    """Filter {host: [slot ids]} by an include or exclude NODE_SPEC string.

    Grammar (reference launcher/runner.py:155): NODE_SPEC[@NODE_SPEC ...],
    NODE_SPEC = NAME[:SLOT[,SLOT ...]]; bare NAME means every slot.
    include and exclude are mutually exclusive; host order is preserved.
    """
    NODE_SEP = "@"
    SLOT_LIST_START = ":"
    SLOT_SEP = ","

    if include_str and exclude_str:
        raise ValueError("include_str and exclude_str are mutually exclusive.")
    if not include_str and not exclude_str:
        return host_info

    filtered_hosts = dict()
    if include_str:
        parse_str = include_str
    else:
        filtered_hosts = deepcopy(host_info)
        parse_str = exclude_str

    for node_config in parse_str.split(NODE_SEP):
        if SLOT_LIST_START in node_config:
            hostname, slots = node_config.split(SLOT_LIST_START)
            slots = [int(x) for x in slots.split(SLOT_SEP)]
            if hostname not in host_info:
                raise ValueError(f"Hostname '{hostname}' not found in hostfile")
            for s in slots:
                if s not in host_info[hostname]:
                    raise ValueError(
                        f"No slot '{s}' specified on host '{hostname}'"
                    )
            if include_str:
                filtered_hosts[hostname] = slots
            else:
                for s in slots:
                    filtered_hosts[hostname].remove(s)
        else:
            hostname = node_config
            if hostname not in host_info:
                raise ValueError(f"Hostname '{hostname}' not found in hostfile")
            if include_str:
                filtered_hosts[hostname] = host_info[hostname]
            else:
                filtered_hosts[hostname] = []

    ordered_hosts = collections.OrderedDict()
    for host in host_info:
        if host not in filtered_hosts:
            continue
        slots = sorted(set(filtered_hosts[host]))
        if slots:
            ordered_hosts[host] = slots
    return ordered_hosts


def parse_inclusion_exclusion(resource_pool, inclusion, exclusion):
    active_resources = collections.OrderedDict()
    for hostname, slots in resource_pool.items():
        active_resources[hostname] = list(range(slots))
    return parse_resource_filter(
        active_resources, include_str=inclusion, exclude_str=exclusion
    )


def encode_world_info(world_info):
    world_info_json = json.dumps(world_info).encode("utf-8")
    return base64.urlsafe_b64encode(world_info_json).decode("utf-8")


def _local_chip_count() -> int:
    """Best-effort local accelerator count without initializing jax."""
    visible = os.environ.get("TPU_VISIBLE_CHIPS")
    if visible:
        return len(visible.split(","))
    try:
        import jax

        return jax.local_device_count()
    except Exception:
        return 1


def _build_launch_cmd(args, world_info_base64, node_rank=None):
    cmd = launch_module_args(
        world_info_base64,
        args.master_addr,
        args.master_port,
        args.procs_per_node,
        node_rank_token=node_rank,
    )
    return cmd + [args.user_script] + args.user_args


def main(args=None):
    args = parse_args(args)

    if (args.num_nodes >= 0 or args.num_chips >= 0) and (
        args.include != "" or args.exclude != ""
    ):
        raise ValueError("Cannot specify num_nodes/chips with include/exclude")

    resource_pool = fetch_hostfile(args.hostfile)
    multi_node_exec = resource_pool is not None
    if resource_pool is None:
        resource_pool = collections.OrderedDict(localhost=_local_chip_count())
        args.master_addr = "127.0.0.1"

    if not multi_node_exec and args.num_nodes > 1:
        raise ValueError("num_nodes > 1 but no extra nodes in hostfile")

    active_resources = parse_inclusion_exclusion(
        resource_pool, args.include, args.exclude
    )

    env = os.environ.copy()

    # env fills in the coordinator only when the CLI flag was left unset —
    # an explicit --master_addr wins over an inherited MASTER_ADDR
    if not args.master_addr and "MASTER_ADDR" in os.environ:
        args.master_addr = os.environ["MASTER_ADDR"]
        args.master_port = int(os.environ.get("MASTER_PORT", args.master_port))
    if not args.master_addr:
        first_host = list(active_resources.keys())[0]
        result = subprocess.check_output(
            [f"ssh {first_host} hostname -I"], shell=True
        )
        args.master_addr = result.decode("utf-8").split()[0]
        logger.info("Using IP %s for node %s", args.master_addr, first_host)

    if args.num_nodes > 0:
        active_resources = collections.OrderedDict(
            list(active_resources.items())[: args.num_nodes]
        )
    if args.num_chips > 0:
        for hostname in active_resources:
            n = min(args.num_chips, len(active_resources[hostname]))
            active_resources[hostname] = list(range(n))

    world_info_base64 = encode_world_info(active_resources)
    multi_node_exec = args.force_multi or len(active_resources) > 1

    if not multi_node_exec:
        # single-node world_info always has exactly one node; never inherit
        # a stale RANK from the shell as a node rank
        cmd = _build_launch_cmd(args, world_info_base64, node_rank=None)
    else:
        launcher = args.launcher.lower()
        if launcher == PDSH_LAUNCHER:
            runner = PDSHRunner(args, world_info_base64)
        elif launcher == SSH_LAUNCHER:
            runner = SSHRunner(args, world_info_base64)
        elif launcher == OPENMPI_LAUNCHER:
            runner = OpenMPIRunner(args, world_info_base64, resource_pool)
        elif launcher == GCLOUD_LAUNCHER:
            runner = GCloudRunner(args, world_info_base64)
        else:
            raise NotImplementedError(f"Unknown launcher {args.launcher}")

        if not runner.backend_exists():
            raise RuntimeError(f"launcher '{launcher}' is not installed.")

        curr_path = os.path.abspath(".")
        env["PYTHONPATH"] = (
            curr_path + ":" + env["PYTHONPATH"] if "PYTHONPATH" in env else curr_path
        )
        for var in env:
            if any(var.startswith(name) for name in EXPORT_ENVS):
                runner.add_export(var, env[var])
        for environ_path in (os.path.expanduser("~"), "."):
            environ_file = os.path.join(environ_path, ENVIRONMENT_FILE)
            if os.path.isfile(environ_file):
                with open(environ_file, "r") as fd:
                    for var in fd.readlines():
                        var = var.strip()
                        if not var or var.startswith("#") or "=" not in var:
                            continue
                        key, val = var.split("=", 1)
                        runner.add_export(key, val)
        cmd = runner.get_cmd(env, active_resources)

    logger.info("cmd = %s", " ".join(cmd))
    result = subprocess.Popen(cmd, env=env)
    result.wait()
    if result.returncode != 0:
        # negative returncode = killed by signal; surface as failure too
        sys.exit(result.returncode if result.returncode > 0 else 1)


if __name__ == "__main__":
    main()
