"""Multi-node launch backends.

Analog of reference deepspeed/launcher/multinode_runner.py (PDSHRunner :35,
OpenMPIRunner :80, MVAPICHRunner :123), re-targeted at TPU fleets: pdsh and
plain-ssh fan-out for generic clusters, mpirun for MPI sites, and a
``gcloud compute tpus tpu-vm ssh --worker=all`` backend for Cloud TPU pods
(the TPU-native replacement for MVAPICH).
"""

from __future__ import annotations

import os
import shlex
import shutil
import sys
from abc import ABC, abstractmethod

from .constants import PDSH_MAX_FAN_OUT


def launch_module_args(
    world_info_base64, master_addr, master_port, procs_per_node, node_rank_token=None
):
    """The shared ``python -m deeperspeed_tpu.launcher.launch`` arg list —
    single source of truth for local and multi-node launch paths."""
    cmd = [
        sys.executable,
        "-u",
        "-m",
        "deeperspeed_tpu.launcher.launch",
        f"--world_info={world_info_base64}",
        f"--master_addr={master_addr}",
        f"--master_port={master_port}",
        f"--procs_per_node={procs_per_node}",
    ]
    if node_rank_token is not None:
        cmd.append(f"--node_rank={node_rank_token}")
    return cmd


class MultiNodeRunner(ABC):
    def __init__(self, args, world_info_base64):
        self.args = args
        self.user_arguments = self.parse_user_args()
        self.user_script = args.user_script
        self.world_info_base64 = world_info_base64
        self.exports = {}

    @abstractmethod
    def backend_exists(self):
        ...

    @abstractmethod
    def get_cmd(self, environment, active_resources):
        ...

    def add_export(self, key, var):
        self.exports[key.strip()] = var.strip()

    def parse_user_args(self):
        return self.args.user_args

    def _launch_module_args(self, node_rank_token):
        return launch_module_args(
            self.world_info_base64,
            self.args.master_addr,
            self.args.master_port,
            self.args.procs_per_node,
            node_rank_token=node_rank_token,
        )

    def _exports_prefix(self) -> str:
        return "".join(
            f"export {key}={shlex.quote(val)}; "
            for key, val in self.exports.items()
        )


class PDSHRunner(MultiNodeRunner):
    """pdsh fan-out: %n expands to the per-host index (node rank)."""

    def backend_exists(self):
        return shutil.which("pdsh")

    def parse_user_args(self):
        return [
            x if x.startswith("-") else f"'{x}'" for x in self.args.user_args
        ]

    def get_cmd(self, environment, active_resources):
        environment["PDSH_RCMD_TYPE"] = "ssh"
        active_workers = ",".join(active_resources.keys())
        pdsh_cmd_args = ["pdsh", "-f", str(PDSH_MAX_FAN_OUT), "-w", active_workers]
        if self.args.launcher_args:
            pdsh_cmd_args += self.args.launcher_args.split()

        launch = (
            [self._exports_prefix(), f"cd {os.path.abspath('.')};"]
            + self._launch_module_args("%n")
        )
        return pdsh_cmd_args + launch + [self.user_script] + self.user_arguments


class SSHRunner(MultiNodeRunner):
    """Plain-ssh fan-out via a generated bash command: one ssh per host,
    backgrounded, with 'wait' to propagate failures. No pdsh dependency."""

    def backend_exists(self):
        return shutil.which("ssh")

    def get_cmd(self, environment, active_resources):
        exports = self._exports_prefix()
        workdir = os.path.abspath(".")
        per_host = []
        for node_rank, host in enumerate(active_resources.keys()):
            launch = " ".join(
                shlex.quote(a)
                for a in self._launch_module_args(node_rank)
                + [self.user_script]
                + self.user_arguments
            )
            remote = f"{exports}cd {workdir}; {launch}"
            ssh_args = self.args.launcher_args or ""
            per_host.append(f"ssh {ssh_args} {host} {shlex.quote(remote)} &")
            per_host.append("pids+=($!)")
        # collect each child's status so a failing node fails the launch
        script = "\n".join(
            ["pids=()"]
            + per_host
            + ["rc=0", 'for p in "${pids[@]}"; do wait "$p" || rc=$?; done', "exit $rc"]
        )
        return ["bash", "-c", script]


class OpenMPIRunner(MultiNodeRunner):
    """mpirun -n <procs> with one rank per (host, slot): ranks discover
    their ids via the OMPI env (utils/distributed.mpi_discovery)."""

    def __init__(self, args, world_info_base64, resource_pool):
        super().__init__(args, world_info_base64)
        self.resource_pool = resource_pool
        self.add_export("UCX_TLS", "tcp")

    def backend_exists(self):
        return shutil.which("ompi_info")

    def get_cmd(self, environment, active_resources):
        if self.args.include or self.args.exclude:
            raise ValueError(
                "openmpi backend does not support worker include/exclusion"
            )
        if self.args.num_nodes != -1 or self.args.num_chips != -1:
            raise ValueError(
                "openmpi backend does not support limiting num nodes/chips"
            )
        # every rank needs the coordinator address for jax.distributed
        # rendezvous (mpi_discovery reads MASTER_ADDR/MASTER_PORT)
        self.add_export("MASTER_ADDR", str(self.args.master_addr))
        self.add_export("MASTER_PORT", str(self.args.master_port))
        total_process_count = sum(self.resource_pool.values())
        mpirun_cmd = [
            "mpirun",
            "-n",
            str(total_process_count),
            "-hostfile",
            self.args.hostfile,
            "--mca",
            "btl",
            "^openib",
            "--mca",
            "btl_tcp_if_include",
            "eth0",
        ]
        if self.args.launcher_args:
            mpirun_cmd += self.args.launcher_args.split()
        export_cmd = []
        for key, val in self.exports.items():
            export_cmd += ["-x", f"{key}={val}"]
        return (
            mpirun_cmd
            + export_cmd
            + [sys.executable, "-u", self.user_script]
            + self.user_arguments
        )


class GCloudRunner(MultiNodeRunner):
    """Cloud TPU pod launch: a single gcloud invocation fans the per-node
    command out to every TPU-VM worker; the worker index comes from the
    TPU metadata env (TPU_WORKER_ID) at runtime."""

    def backend_exists(self):
        return shutil.which("gcloud")

    def get_cmd(self, environment, active_resources):
        if not self.args.tpu_name:
            raise ValueError("gcloud launcher requires --tpu_name")
        exports = self._exports_prefix()
        launch = " ".join(
            shlex.quote(a)
            # node_rank resolved on-worker from TPU_WORKER_ID
            for a in self._launch_module_args("env")
            + [self.user_script]
            + self.user_arguments
        )
        command = f"{exports}cd {os.path.abspath('.')}; {launch}"
        cmd = [
            "gcloud",
            "compute",
            "tpus",
            "tpu-vm",
            "ssh",
            self.args.tpu_name,
            "--worker=all",
            f"--command={command}",
        ]
        if self.args.zone:
            cmd.append(f"--zone={self.args.zone}")
        if self.args.launcher_args:
            cmd += self.args.launcher_args.split()
        return cmd
