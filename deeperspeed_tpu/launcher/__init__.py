"""Multi-host launcher: ``python -m deeperspeed_tpu.launcher <script>``.

TPU-native analog of the reference deepspeed CLI (bin/deepspeed ->
deepspeed/launcher/runner.py): hostfile + include/exclude resource
selection, pdsh/ssh/mpirun/gcloud fan-out, per-node process spawn with
jax.distributed rendezvous env.
"""

from .runner import (
    encode_world_info,
    fetch_hostfile,
    main,
    parse_args,
    parse_inclusion_exclusion,
    parse_resource_filter,
)
from .launch import plan_node_processes
