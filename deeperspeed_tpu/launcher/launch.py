"""Per-node process launcher.

Analog of reference deepspeed/launcher/launch.py: decodes the base64
world-info dict, computes this node's process ids, and spawns the user
script. TPU-native differences:

- Default is ONE JAX process per host driving all local chips (JAX's
  process model); ``--procs_per_node`` > 1 splits the node's chips across
  several processes (chip visibility via TPU_VISIBLE_CHIPS), the analog of
  the reference's one-process-per-GPU with CUDA_VISIBLE_DEVICES.
- Rendezvous env is jax.distributed: DS_COORDINATOR_ADDRESS /
  DS_NUM_PROCESSES / DS_PROCESS_ID consumed by
  deeperspeed_tpu.utils.distributed.init_distributed; RANK / LOCAL_RANK /
  WORLD_SIZE / MASTER_ADDR / MASTER_PORT are also set so reference-style
  user scripts port unchanged.
- Node rank may be given as an integer or the literal string "env", which
  resolves from TPU_WORKER_ID (gcloud --worker=all launches every worker
  with the same command line).

Signals: SIGINT/SIGTERM are forwarded to children; the first non-zero
child exit code is propagated (reference launch.py sig_handler/poll loop).
"""

from __future__ import annotations

import base64
import json
import os
import signal
import subprocess
import sys
import time
from argparse import REMAINDER, ArgumentParser
from collections import defaultdict

from ..utils.logging import logger
from .constants import DISTRIBUTED_DEFAULT_PORT


def parse_args(args=None):
    parser = ArgumentParser(
        description="DeeperSpeed-TPU per-node launcher: spawns this node's "
        "JAX processes for a distributed job."
    )
    parser.add_argument(
        "--node_rank",
        type=str,
        default="0",
        help="Rank of this node, or 'env' to read TPU_WORKER_ID/RANK.",
    )
    parser.add_argument("--master_addr", default="127.0.0.1", type=str)
    parser.add_argument(
        "--master_port", default=DISTRIBUTED_DEFAULT_PORT, type=int
    )
    parser.add_argument(
        "--world_info", default="None", type=str, help="base64 world-info dict"
    )
    parser.add_argument("--procs_per_node", type=int, default=1)
    parser.add_argument("training_script", type=str)
    parser.add_argument("training_script_args", nargs=REMAINDER)
    return parser.parse_args(args=args)


def _resolve_node_rank(token: str) -> int:
    if token != "env":
        return int(token)
    for var in ("TPU_WORKER_ID", "NODE_RANK", "RANK"):
        if var in os.environ:
            return int(os.environ[var])
    raise RuntimeError(
        "--node_rank=env but none of TPU_WORKER_ID/NODE_RANK/RANK is set"
    )


def plan_node_processes(world_info, node_rank, procs_per_node):
    """Compute the per-process env layout for this node.

    Returns a list of dicts, one per local process, with keys:
    process_id (global), local_rank, chips (list of local chip ids),
    num_processes (global process count), world_size (global chip count).
    Slots are divided round-robin-contiguously across procs_per_node.
    """
    node_list = list(world_info.keys())
    if node_rank >= len(node_list):
        raise ValueError(
            f"node_rank {node_rank} out of range for {len(node_list)} nodes"
        )

    world_size = sum(len(v) for v in world_info.values())
    num_processes = 0
    first_pid_by_node = {}
    for node_id in node_list:
        first_pid_by_node[node_id] = num_processes
        n_slots = len(world_info[node_id])
        num_processes += min(procs_per_node, n_slots) if n_slots else 0

    local_node = node_list[node_rank]
    local_slots = world_info[local_node]
    n_procs = min(procs_per_node, len(local_slots))
    base = first_pid_by_node[local_node]

    plans = []
    per = defaultdict(list)
    for i, slot in enumerate(local_slots):
        per[i % n_procs].append(slot)
    for local_rank in range(n_procs):
        plans.append(
            dict(
                process_id=base + local_rank,
                local_rank=local_rank,
                chips=sorted(per[local_rank]),
                num_processes=num_processes,
                world_size=world_size,
            )
        )
    return plans


def main(args=None):
    args = parse_args(args)
    assert args.world_info != "None", "must provide world info dict"
    world_info = json.loads(base64.urlsafe_b64decode(args.world_info))
    logger.info("WORLD INFO DICT: %s", world_info)

    node_rank = _resolve_node_rank(args.node_rank)
    plans = plan_node_processes(world_info, node_rank, args.procs_per_node)
    logger.info(
        "nnodes=%d, node_rank=%d, local procs=%d",
        len(world_info),
        node_rank,
        len(plans),
    )

    current_env = os.environ.copy()
    processes = []
    for plan in plans:
        env = current_env.copy()
        env["DS_COORDINATOR_ADDRESS"] = f"{args.master_addr}:{args.master_port}"
        env["DS_NUM_PROCESSES"] = str(plan["num_processes"])
        env["DS_PROCESS_ID"] = str(plan["process_id"])
        # chip visibility (libtpu infers the per-process topology from the
        # visible-chip list); always set so slot filters (--num_chips,
        # --exclude, include slot lists) restrict the chips actually used
        env["TPU_VISIBLE_CHIPS"] = ",".join(map(str, plan["chips"]))
        # reference-compatible env (launch.py sets RANK/LOCAL_RANK/...)
        env["RANK"] = str(plan["process_id"])
        env["LOCAL_RANK"] = str(plan["local_rank"])
        env["WORLD_SIZE"] = str(plan["num_processes"])
        env["MASTER_ADDR"] = args.master_addr
        env["MASTER_PORT"] = str(args.master_port)

        cmd = [sys.executable, "-u", args.training_script] + args.training_script_args
        processes.append(subprocess.Popen(cmd, env=env))

    def sig_handler(signum, frame):
        for p in processes:
            if p.poll() is None:
                p.send_signal(signum)

    signal.signal(signal.SIGINT, sig_handler)
    signal.signal(signal.SIGTERM, sig_handler)

    exit_code = 0
    alive = list(processes)
    while alive:
        for p in list(alive):
            rc = p.poll()
            if rc is None:
                continue
            alive.remove(p)
            if rc != 0 and exit_code == 0:
                exit_code = rc
                # one process failed: bring the rest down (reference
                # behavior is to terminate the job on first failure)
                for q in alive:
                    if q.poll() is None:
                        q.terminate()
        time.sleep(0.1)
    sys.exit(exit_code)


if __name__ == "__main__":
    main()
