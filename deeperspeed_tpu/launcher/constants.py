"""Launcher constants (reference: deepspeed/launcher/constants.py)."""

PDSH_LAUNCHER = "pdsh"
OPENMPI_LAUNCHER = "openmpi"
GCLOUD_LAUNCHER = "gcloud"
SSH_LAUNCHER = "ssh"

PDSH_MAX_FAN_OUT = 1024

# Default coordinator port for jax.distributed (analog of
# TORCH_DISTRIBUTED_DEFAULT_PORT=29500 in reference deepspeed/constants.py).
DISTRIBUTED_DEFAULT_PORT = 29500

DEFAULT_HOSTFILE = "/job/hostfile"

# Env prefixes forwarded to remote workers (reference launcher/runner.py:27
# exports NCCL/PYTHON/MV2/UCX; on TPU the relevant knobs are JAX/XLA/TPU/
# LIBTPU plus the python environment).
EXPORT_ENVS = ["JAX", "XLA", "TPU", "LIBTPU", "PYTHON", "PALLAS", "DS_TPU"]

ENVIRONMENT_FILE = ".deeperspeed_env"
