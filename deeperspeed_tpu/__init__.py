"""DeeperSpeed-TPU: a TPU-native large-model training framework.

Re-creates the capabilities of zhuzilin/DeeperSpeed (DeepSpeed v0.3.15) on
JAX/XLA/Pallas: engine + config, ZeRO 1/2/3 via sharding, pipeline/tensor/
sequence parallelism over an ICI mesh, bf16/fp16 mixed precision, compressed
communication, fused kernels, checkpointing, elasticity, profiling, and a
multi-host launcher. API names mirror the reference
(/root/reference/deepspeed/__init__.py) so callers can port directly.
"""

from .version import __version__, __version_info__

from .runtime.config import TrainingConfig, DeepSpeedConfig, ConfigError
from .runtime import zero
from .runtime.engine import Engine, initialize
from .runtime import lr_schedules
from .parallel.topology import (
    ProcessTopology,
    PipeDataParallelTopology,
    PipeModelDataParallelTopology,
    PipelineParallelGrid,
    build_mesh,
)
from .runtime.activation_checkpointing import checkpointing
from .runtime.pipe.engine import PipelineEngine
from .pipe import LayerSpec, PipelineModule, TiedLayerSpec
from .ops.transformer import DeepSpeedTransformerLayer, DeepSpeedTransformerConfig
from .module_inject import replace_transformer_layer, module_inject
from .utils import logger, log_dist
from .utils.distributed import init_distributed
from .serving import PipelineServingBridge, ServingConfig, ServingEngine
from .resilience import (
    ResilienceConfig,
    ResilienceManager,
    get_resilience_manager,
    init_resilience,
    shutdown_resilience,
)


def add_config_arguments(parser):
    """Argparse flags matching reference deepspeed/__init__.py:199."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument(
        "--deepspeed",
        default=False,
        action="store_true",
        help="Enable DeepSpeed (helper flag for user code, no impact on library)",
    )
    group.add_argument(
        "--deepspeed_config", default=None, type=str, help="DeepSpeed json config file."
    )
    group.add_argument(
        "--deepscale",
        default=False,
        action="store_true",
        help="Deprecated enable DeepSpeed (helper flag for user code)",
    )
    group.add_argument(
        "--deepscale_config", default=None, type=str, help="Deprecated json config file."
    )
    group.add_argument(
        "--deepspeed_mpi",
        default=False,
        action="store_true",
        help="Run via MPI; discover ranks from the MPI environment.",
    )
    return parser
