"""Native tensor (model) parallelism.

The reference framework did NOT implement TP — it consumed an external
Megatron-style ``mpu`` object (/root/reference/deepspeed/__init__.py:80,
runtime/engine.py:630-641) that supplied model-parallel rank/group queries,
while Megatron supplied ColumnParallelLinear / RowParallelLinear /
VocabParallelEmbedding. A TPU-native rebuild must provide the real thing
(SURVEY §7 phase 8): here TP is expressed as PartitionSpecs over the
``'model'`` mesh axis and XLA inserts the collectives — the all-reduce that
Megatron issues by hand at the end of RowParallelLinear appears automatically
when the sharded contraction's output is constrained to be replicated.

Two surfaces:

  * Functional/pjit surface — ``column_parallel_spec`` / ``row_parallel_spec``
    PartitionSpecs plus ``ColumnParallelLinear`` / ``RowParallelLinear`` /
    ``VocabParallelEmbedding`` Layer classes (pipeline-compatible; see
    runtime/pipe/module.py Layer protocol) carrying their own specs.
  * ``ModelParallelUnit`` — the mpu-compatible adapter object GPT-NeoX-style
    callers pass to ``initialize(mpu=...)``: get_model_parallel_rank/
    world_size/group etc., answered from a Mesh instead of torch process
    groups.
"""

from typing import Any, Optional

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .topology import DATA_AXIS, MODEL_AXIS, PIPE_AXIS, SEQ_AXIS


# ------------------------------------------------------------------ #
# PartitionSpec builders (the TP "layout algebra")
# ------------------------------------------------------------------ #


def column_parallel_spec(stacked: bool = False) -> P:
    """Weight (in, out) split on the OUTPUT dim — Megatron column parallel.

    ``stacked=True`` prepends a layer axis (scan-stacked models)."""
    return P(None, None, MODEL_AXIS) if stacked else P(None, MODEL_AXIS)


def row_parallel_spec(stacked: bool = False) -> P:
    """Weight (in, out) split on the INPUT dim — Megatron row parallel."""
    return P(None, MODEL_AXIS, None) if stacked else P(MODEL_AXIS, None)


def vocab_parallel_spec() -> P:
    """Embedding table (vocab, dim) layout for TP.

    NOTE: shards the embedding DIM, not vocab rows. XLA's SPMD partitioner
    handles a vocab-row-sharded gather by replicating the whole table, so the
    Megatron row split is an anti-layout on TPU; the column split keeps the
    gather local (see VocabParallelEmbedding)."""
    return P(None, MODEL_AXIS)


def constrain(x, spec: P, mesh: Optional[Mesh]):
    """with_sharding_constraint that tolerates meshes lacking some axes.

    Entries may be axis names, None (force replicated on that dim) or
    ``P.UNCONSTRAINED`` (let the partitioner keep whatever sharding — e.g.
    the data-parallel batch sharding — it already picked). Axis names are
    resolved through the sharding rule table, so the legacy 'model'/'seq'
    specs emitted by this module place correctly on a canonical
    dp×fsdp×tp×sp mesh (and vice versa)."""
    if mesh is None:
        return x
    from ..sharding.rules import translate_spec

    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, translate_spec(spec, mesh))
    )


def _model_last_spec(ndim: int, last) -> P:
    """Spec constraining only the LAST dim (to `last`); every other dim is
    left unconstrained so batch/sequence shardings survive the TP layers."""
    parts = [P.UNCONSTRAINED] * ndim
    parts[-1] = last
    return P(*parts)


# Megatron mappings re-expressed as sharding constraints. Under pjit these
# compile to the same collectives Megatron issues by hand
# (copy_to / reduce_from / scatter_to / gather_from _model_parallel_region).


# --------------------------------------------------------------------- #
# shard_map-mode megatron f/g operators
# --------------------------------------------------------------------- #
#
# The region helpers below this block are pjit-style (sharding-constraint
# driven). INSIDE `shard_map` the collectives must be explicit — and a bare
# `lax.psum` is a gradient trap there: with replication checking disabled
# (check_rep/check_vma False, which ring attention and the SPMD pipeline
# need), psum's transpose is psum, so the backward double-counts. These
# custom-vjp pairs pin Megatron's exact semantics:
#   f: identity forward,  psum backward   (input of a column-parallel layer)
#   g: psum forward,      identity backward (output of a row-parallel layer)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tp_region(x, axis_name=MODEL_AXIS):
    """Megatron f for shard_map code: identity fwd, psum-over-axis bwd."""
    return x


def _copy_tp_fwd(x, axis_name):
    return x, None


def _copy_tp_bwd(axis_name, _, g):
    return (jax.lax.psum(g, axis_name),)


copy_to_tp_region.defvjp(_copy_tp_fwd, _copy_tp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tp_region(x, axis_name=MODEL_AXIS):
    """Megatron g for shard_map code: psum fwd, identity bwd. Use this, not
    a bare lax.psum, to complete a row-parallel matmul."""
    return jax.lax.psum(x, axis_name)


def _reduce_tp_fwd(x, axis_name):
    return jax.lax.psum(x, axis_name), None


def _reduce_tp_bwd(axis_name, _, g):
    return (g,)


reduce_from_tp_region.defvjp(_reduce_tp_fwd, _reduce_tp_bwd)


def copy_to_model_parallel_region(x, mesh=None):
    """Identity fwd, all-reduce bwd in Megatron; a no-op layout-wise."""
    return x


def reduce_from_model_parallel_region(x, mesh=None):
    """Partial-sum -> model-replicated: constraining the output of a
    row-parallel contraction to 'no model axis on the feature dim' makes XLA
    emit the psum. Batch dims stay unconstrained (DP sharding survives)."""
    return constrain(x, _model_last_spec(x.ndim, None), mesh)


def scatter_to_model_parallel_region(x, mesh=None):
    """-> sharded on last dim over the model axis."""
    return constrain(x, _model_last_spec(x.ndim, MODEL_AXIS), mesh)


def gather_from_model_parallel_region(x, mesh=None):
    """Sharded on last dim -> model-replicated (all-gather)."""
    return constrain(x, _model_last_spec(x.ndim, None), mesh)


# ------------------------------------------------------------------ #
# TP layers (pipeline-module compatible)
# ------------------------------------------------------------------ #


from ..runtime.pipe.module import Layer as _PipeLayer


class _TPLayerBase(_PipeLayer):
    """Pipeline-protocol Layer (runtime/pipe/module.py) carrying TP
    PartitionSpecs in .specs so PipelineModule / LayerSpec accept TP layers
    directly."""

    specs: Any = None


class ColumnParallelLinear(_TPLayerBase):
    """Y = X W + b with W (in, out) sharded on out.

    ``gather_output=True`` replicates Y afterwards (Megatron semantics);
    default False keeps Y column-sharded for a following RowParallelLinear.
    """

    def __init__(self, in_dim: int, out_dim: int, bias: bool = True,
                 gather_output: bool = False, mesh: Optional[Mesh] = None,
                 init_scale: float = 0.02):
        self.in_dim, self.out_dim, self.bias = in_dim, out_dim, bias
        self.gather_output = gather_output
        self.mesh = mesh
        self.init_scale = init_scale
        self.specs = {"w": column_parallel_spec()}
        if bias:
            self.specs["b"] = P(MODEL_AXIS)

    def init(self, rng):
        w = jax.random.normal(rng, (self.in_dim, self.out_dim), jnp.float32)
        p = {"w": w * self.init_scale}
        if self.bias:
            p["b"] = jnp.zeros((self.out_dim,), jnp.float32)
        return p

    def apply(self, params, x, rng=None):
        w = params["w"].astype(x.dtype)
        y = x @ w
        if self.bias:
            y = y + params["b"].astype(x.dtype)
        if self.gather_output:
            y = gather_from_model_parallel_region(y, self.mesh)
        else:
            y = constrain(y, _model_last_spec(y.ndim, MODEL_AXIS), self.mesh)
        return y


class RowParallelLinear(_TPLayerBase):
    """Y = X W + b with W (in, out) sharded on in.

    ``input_is_parallel=True`` means X arrives column-sharded from a
    ColumnParallelLinear; the contraction over the sharded dim produces
    partial sums which the output constraint turns into an XLA psum —
    the automatic analog of Megatron's explicit all_reduce.
    """

    def __init__(self, in_dim: int, out_dim: int, bias: bool = True,
                 input_is_parallel: bool = True, mesh: Optional[Mesh] = None,
                 init_scale: float = 0.02):
        self.in_dim, self.out_dim, self.bias = in_dim, out_dim, bias
        self.input_is_parallel = input_is_parallel
        self.mesh = mesh
        self.init_scale = init_scale
        self.specs = {"w": row_parallel_spec()}
        if bias:
            self.specs["b"] = P(None)

    def init(self, rng):
        w = jax.random.normal(rng, (self.in_dim, self.out_dim), jnp.float32)
        p = {"w": w * self.init_scale}
        if self.bias:
            p["b"] = jnp.zeros((self.out_dim,), jnp.float32)
        return p

    def apply(self, params, x, rng=None):
        if not self.input_is_parallel:
            x = scatter_to_model_parallel_region(x, self.mesh)
        w = params["w"].astype(x.dtype)
        y = x @ w
        y = reduce_from_model_parallel_region(y, self.mesh)
        if self.bias:
            y = y + params["b"].astype(x.dtype)
        return y


class VocabParallelEmbedding(_TPLayerBase):
    """Embedding with the table sharded over d_model columns.

    Megatron shards over vocab rows and masks+psums; XLA's SPMD partitioner
    handles a vocab-sharded gather by replicating the table, so the TPU-native
    layout shards the embedding DIM instead — the gather is then local and the
    output comes out column-sharded (same layout a column-parallel layer
    produces). See also models/gpt.py param_specs.
    """

    def __init__(self, vocab: int, dim: int, mesh: Optional[Mesh] = None):
        self.vocab, self.dim, self.mesh = vocab, dim, mesh
        self.specs = {"w": P(None, MODEL_AXIS)}

    def init(self, rng):
        return {"w": jax.random.normal(rng, (self.vocab, self.dim), jnp.float32) * 0.02}

    def apply(self, params, x, rng=None):
        y = jnp.take(params["w"], x, axis=0)
        return constrain(y, _model_last_spec(y.ndim, MODEL_AXIS), self.mesh)


class ParallelMLP(_TPLayerBase):
    """Column-parallel up-proj + gelu + row-parallel down-proj: one model-axis
    psum per MLP, the canonical Megatron pairing."""

    def __init__(self, d_model: int, d_ff: int, mesh: Optional[Mesh] = None):
        self.up = ColumnParallelLinear(d_model, d_ff, mesh=mesh)
        self.down = RowParallelLinear(d_ff, d_model, mesh=mesh)
        self.specs = {"up": self.up.specs, "down": self.down.specs}

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        return {"up": self.up.init(k1), "down": self.down.init(k2)}

    def apply(self, params, x, rng=None):
        h = self.up.apply(params["up"], x)
        h = jax.nn.gelu(h, approximate=True)
        return self.down.apply(params["down"], h)


# ------------------------------------------------------------------ #
# mpu-compatible adapter
# ------------------------------------------------------------------ #


class ModelParallelUnit:
    """Megatron-mpu-compatible facade over a jax Mesh.

    The reference engine calls get_model_parallel_rank/world_size/group and
    get_data_parallel_* on whatever object the user passes as ``mpu``
    (runtime/engine.py:630-641). Group queries return the mesh axis NAME —
    under XLA, collectives address axes by name, so the name is the group.
    """

    def __init__(self, mesh: Mesh, process_index: Optional[int] = None):
        self.mesh = mesh
        self._pidx = jax.process_index() if process_index is None else process_index
        shape = dict(mesh.shape)
        self._mp = int(shape.get(MODEL_AXIS, 1))
        self._dp = int(shape.get(DATA_AXIS, 1))
        self._pp = int(shape.get(PIPE_AXIS, 1))
        self._sp = int(shape.get(SEQ_AXIS, 1))

    # --- coords of this *process* (multi-host). On one host all ranks are 0.
    def _coord(self, axis: str) -> int:
        if axis not in self.mesh.shape:
            return 0
        # first local device's coordinate along the axis
        axis_idx = list(self.mesh.axis_names).index(axis)
        local = jax.local_devices()[0]
        pos = np.argwhere(self.mesh.devices == local)
        if pos.size == 0:
            return 0
        return int(pos[0][axis_idx])

    def get_model_parallel_rank(self) -> int:
        return self._coord(MODEL_AXIS)

    def get_model_parallel_world_size(self) -> int:
        return self._mp

    def get_model_parallel_group(self) -> str:
        return MODEL_AXIS

    def get_data_parallel_rank(self) -> int:
        return self._coord(DATA_AXIS)

    def get_data_parallel_world_size(self) -> int:
        return self._dp

    def get_data_parallel_group(self) -> str:
        return DATA_AXIS

    def get_pipe_parallel_rank(self) -> int:
        return self._coord(PIPE_AXIS)

    def get_pipe_parallel_world_size(self) -> int:
        return self._pp

    def get_sequence_parallel_world_size(self) -> int:
        return self._sp

    def get_sequence_parallel_group(self) -> str:
        return SEQ_AXIS
