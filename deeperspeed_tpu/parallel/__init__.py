from .topology import (
    DATA_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    SEQ_AXIS,
    EXPERT_AXIS,
    ProcessTopology,
    PipeDataParallelTopology,
    PipeModelDataParallelTopology,
    PipelineParallelGrid,
    build_mesh,
    single_device_mesh,
)
