"""Process/mesh topology.

Capability parity with /root/reference/deepspeed/runtime/pipe/topology.py
(`ProcessTopology` :13, `PipeDataParallelTopology` :238,
`PipeModelDataParallelTopology` :250, `PipelineParallelGrid` :257), redesigned
around `jax.sharding.Mesh`: instead of building torch.distributed process
groups per axis, we build one named device mesh and express per-axis
communication as collectives over mesh axis names. The pure coordinate math
(rank <-> coord mapping, axis slicing) is kept because the pipeline engine and
checkpoint layout still need it.
"""

from collections import namedtuple
from itertools import product
from typing import Dict, List, Optional, Sequence

import numpy as np

# Canonical mesh axis names. 'seq' (context/sequence parallel) and 'expert'
# (MoE) are first-class here even though the reference lacks them (SURVEY §2.3).
PIPE_AXIS = "pipe"
DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"


class ProcessTopology:
    """Cartesian rank <-> coordinate mapping over named axes.

    Axes are ordered major to minor: the last axis has stride 1.
    """

    def __init__(self, axes: Sequence[str], dims: Sequence[int]):
        assert len(axes) == len(dims)
        self.axes = list(axes)
        self.dims = list(dims)
        self.ProcessCoord = namedtuple("ProcessCoord", self.axes)
        self.mapping = {}
        ranges = [range(d) for d in self.dims]
        for global_rank, coord in enumerate(product(*ranges)):
            key = dict(zip(self.axes, coord))
            self.mapping[self.ProcessCoord(**key)] = global_rank

    def get_rank(self, **coord_kwargs) -> int:
        if len(coord_kwargs) != len(self.axes):
            raise ValueError(f"get_rank() needs all axes {self.axes}")
        return self.mapping[self.ProcessCoord(**coord_kwargs)]

    def get_axis_names(self) -> List[str]:
        return self.axes

    def get_rank_repr(self, rank, omit_axes=("data", "pipe"), inner_sep="_", outer_sep="-"):
        omit_axes = list(omit_axes)
        axes = [a for a in self.axes if a not in omit_axes]
        names = []
        for ax in axes:
            ax_rank = getattr(self.get_coord(rank=rank), ax)
            names.append(f"{ax}{inner_sep}{ax_rank:02d}")
        return outer_sep.join(names)

    def get_dim(self, axis: str) -> int:
        if axis not in self.axes:
            return 0
        return self.dims[self.axes.index(axis)]

    def get_coord(self, rank: int):
        for coord, idx in self.mapping.items():
            if idx == rank:
                return coord
        raise ValueError(f"rank {rank} not found in topology")

    def get_axis_comm_lists(self, axis: str) -> List[List[int]]:
        """Groups of ranks that communicate along `axis` (all other coords equal)."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        ranges = [range(self.get_dim(a)) for a in other_axes]
        for other in product(*ranges):
            other_keys = dict(zip(other_axes, other))
            group = [
                self.get_rank(**{axis: ax_idx, **other_keys})
                for ax_idx in range(self.get_dim(axis))
            ]
            lists.append(group)
        return lists

    def filter_match(self, **filter_kwargs) -> List[int]:
        def criterion(x):
            for key, val in filter_kwargs.items():
                if getattr(x, key) != val:
                    return False
            return True

        return sorted(idx for coord, idx in self.mapping.items() if criterion(coord))

    def get_axis_list(self, axis: str, idx: int) -> List[int]:
        return sorted(
            rank for coord, rank in self.mapping.items() if getattr(coord, axis) == idx
        )

    def world_size(self) -> int:
        return int(np.prod(self.dims)) if self.dims else 1

    def __str__(self):
        return str(self.mapping)


class PipeDataParallelTopology(ProcessTopology):
    """Pipeline-major hybrid PP+DP (reference topology.py:238)."""

    def __init__(self, num_pp, num_dp):
        super().__init__(axes=[PIPE_AXIS, DATA_AXIS], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """3D PP x DP x TP (reference topology.py:250)."""

    def __init__(self, num_pp, num_mp, num_dp):
        super().__init__(
            axes=[PIPE_AXIS, DATA_AXIS, MODEL_AXIS], dims=[num_pp, num_dp, num_mp]
        )


class PipelineParallelGrid:
    """Axis-rank bookkeeping for a topology (reference topology.py:257).

    Under XLA there are no explicit process groups — collectives name mesh
    axes — so this class only answers "who am I on each axis" questions for
    the pipeline engine, checkpoint naming, and mpu-compatible callers.
    """

    def __init__(self, topology: ProcessTopology, global_rank: int = 0):
        self._topo = topology
        self.global_rank = global_rank
        self.world_size = topology.world_size()
        self.data_parallel_size = max(1, topology.get_dim(DATA_AXIS))
        self.pipe_parallel_size = max(1, topology.get_dim(PIPE_AXIS))
        self.model_parallel_size = max(1, topology.get_dim(MODEL_AXIS))
        self.seq_parallel_size = max(1, topology.get_dim(SEQ_AXIS))
        self.expert_parallel_size = max(1, topology.get_dim(EXPERT_AXIS))
        coord = topology.get_coord(global_rank)
        self.stage_id = getattr(coord, PIPE_AXIS, 0) if PIPE_AXIS in topology.axes else 0
        self.data_parallel_id = (
            getattr(coord, DATA_AXIS, 0) if DATA_AXIS in topology.axes else 0
        )
        self.model_parallel_id = (
            getattr(coord, MODEL_AXIS, 0) if MODEL_AXIS in topology.axes else 0
        )
        # p2p neighbours on the pipe axis
        self.stage_to_global = {}
        if PIPE_AXIS in topology.axes:
            kwargs = {a: getattr(coord, a) for a in topology.axes if a != PIPE_AXIS}
            for s in range(self.pipe_parallel_size):
                self.stage_to_global[s] = topology.get_rank(**{PIPE_AXIS: s, **kwargs})

    def get_stage_id(self):
        return self.stage_id

    def get_data_parallel_id(self):
        return self.data_parallel_id

    def get_model_parallel_id(self):
        return self.model_parallel_id

    def get_pipe_parallel_rank(self):
        return self.stage_id

    def get_pipe_parallel_world_size(self):
        return self.pipe_parallel_size

    def get_data_parallel_rank(self):
        return self.data_parallel_id

    def get_data_parallel_world_size(self):
        return self.data_parallel_size

    def get_model_parallel_rank(self):
        return self.model_parallel_id

    def get_model_parallel_world_size(self):
        return self.model_parallel_size

    def get_global_rank(self):
        return self.global_rank

    def is_first_stage(self):
        return self.stage_id == 0

    def is_last_stage(self):
        return self.stage_id == self.pipe_parallel_size - 1

    def stage_to_global_rank(self, stage_id):
        return self.stage_to_global[stage_id]

    @property
    def topology(self):
        return self._topo


# ---------------------------------------------------------------------- #
# jax Mesh construction
# ---------------------------------------------------------------------- #


def build_mesh(
    axis_dims: Dict[str, int],
    devices: Optional[Sequence] = None,
    allow_split_physical_axes: bool = True,
):
    """Build a `jax.sharding.Mesh` with named axes from an {axis: dim} dict.

    Axis order follows the dict order (put the axis with the heaviest
    communication last so it lands on the innermost ICI ring). Dims of -1 are
    inferred from the device count. Uses `mesh_utils.create_device_mesh` for
    ICI-topology-aware device ordering on real TPU slices, falling back to a
    simple reshape on CPU meshes.
    """
    import jax

    if devices is None:
        devices = jax.devices()
    n = len(devices)
    dims = dict(axis_dims)
    unknown = [a for a, d in dims.items() if d in (-1, None)]
    known = int(np.prod([d for d in dims.values() if d not in (-1, None)])) or 1
    if len(unknown) > 1:
        raise ValueError("at most one axis dim may be -1")
    if unknown:
        if n % known != 0:
            raise ValueError(f"{n} devices not divisible by {known}")
        dims[unknown[0]] = n // known
    total = int(np.prod(list(dims.values())))
    if total != n:
        raise ValueError(
            f"mesh dims {dims} require {total} devices but {n} are available"
        )

    shape = tuple(dims.values())
    try:
        from jax.experimental import mesh_utils

        mesh_devices = mesh_utils.create_device_mesh(
            shape,
            devices=devices,
            allow_split_physical_axes=allow_split_physical_axes,
        )
    except Exception:
        mesh_devices = np.asarray(devices).reshape(shape)
    # all Mesh objects are constructed through the sharding factory (lazy
    # import: sharding.mesh.from_config calls back into build_mesh)
    from ..sharding.mesh import make_mesh

    return make_mesh(mesh_devices, tuple(dims.keys()))


def filter_spec(spec, mesh):
    """Drop PartitionSpec axis names a mesh doesn't carry (or carries at
    size 1), so a model's canonical specs (naming e.g. 'model'/'seq') work on
    any mesh shape. Entries may be axis names, tuples of names, None, or
    ``P.UNCONSTRAINED``. The single source of truth for this rule — used by
    ZeRO spec derivation, TP layers, and model sharding constraints."""
    if spec is None or mesh is None:
        return spec
    from jax.sharding import PartitionSpec as P

    def keep(a):
        return a in mesh.shape and mesh.shape[a] > 1

    parts = []
    for a in tuple(spec):
        if a is None or a is P.UNCONSTRAINED:
            parts.append(a)
        elif isinstance(a, tuple):
            kept = tuple(x for x in a if keep(x))
            parts.append(kept if len(kept) > 1 else (kept[0] if kept else None))
        else:
            parts.append(a if keep(a) else None)
    return P(*parts)


def single_device_mesh(axis_names=(DATA_AXIS,)):
    """A trivial mesh over one device (useful for tests / single chip)."""
    import jax

    from ..sharding.mesh import make_mesh

    dev = np.asarray(jax.devices()[:1]).reshape((1,) * len(axis_names))
    return make_mesh(dev, tuple(axis_names))
