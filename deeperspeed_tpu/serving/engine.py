"""ServingEngine: continuous-batching inference over a fixed slot pool.

The request lifecycle::

    engine = ServingEngine(cfg, params, {"num_slots": 8, "num_blocks": 128})
    rid = engine.submit([1, 2, 3], max_new_tokens=32)
    while engine.has_work():
        for req in engine.step():
            print(req.rid, req.output)
    # or: outputs = engine.run()

One ``step()`` is: expire timeouts -> admit+prefill queued requests into
free slots (length-bucketed, backpressure when the block pool is dry) ->
grow block tables for the next write (preempting the youngest slot when
the pool is exhausted) -> ONE jitted decode step over ALL slots -> append
tokens, evict finished requests.

Static-shape discipline: the decode step closes over (num_slots,
blocks_per_slot) and always runs the full slot array — idle slots carry
token 0 / length 0 / an all-null block table and their garbage lane is
ignored on the host. Requests joining and leaving change only the DATA
fed to the same compiled program, never its shapes, so the decode step
compiles exactly once per engine (asserted in tests via the jit cache
counter). Prefill compiles once per length bucket.

Decode math reuses ``models/gpt.decoder_block`` (the same layer the
training forward and ``models/generation`` use) with a paged-cache
``attend`` (serving/kv_cache.paged_attend), which is what makes greedy
serving outputs token-identical to per-request ``make_generator`` calls.

``PipelineServingBridge`` gives pipelined models (PipelineModule over a
'pipe' mesh) the same submit/step/run surface by driving
``PipelineEngine.inference_batch`` with full-prefix recompute per token —
the reference fork's serving mode, kept as the compatibility path until
pipelined KV caching lands.
"""

import itertools
import time
import zlib
from functools import partial
from typing import Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..models.generation import apply_with_cache, init_cache, \
    prep_sampling_logits
from ..models.gpt import GPTConfig, decoder_block, layer_norm
from ..models.speculative import engine_sample_key
from ..monitor import get_monitor, init_monitor
from ..monitor.tracer import trace_counter, trace_instant, trace_span
from ..utils.logging import logger
from .config import ServingConfig
from .kv_cache import NULL_BLOCK, PagedKVCache, blocks_needed, paged_attend
from .metrics import DECODE_TIMER, PREFILL_TIMER, ServingMetrics
from .scheduler import Request, Scheduler


class EngineDrainingError(RuntimeError):
    """Raised by ``submit()`` while the engine is draining: it is
    finishing its in-flight requests and admits nothing new. Callers
    owning more than one engine (the fleet router) catch this and fail
    the request over to another replica instead of stranding it in a
    queue that will never be served."""


# ------------------------------------------------------------------ #
# deterministic per-request sampling
# ------------------------------------------------------------------ #


def derive_request_seed(base_seed: int, rid: str) -> int:
    """Stable per-request sampling seed: a pure function of the engine
    seed and the request id (crc32, NOT Python hash(), which is
    randomized per process) so every replica — and every retry of the
    same rid on a different replica — derives the same stream."""
    return (zlib.crc32(rid.encode("utf-8")) ^ (base_seed * 0x9E3779B1)) \
        & 0x7FFFFFFF


def request_sample_key(seed: int, count: int):
    """PRNG key for a request's ``count``-th sampled token. Sampling is
    a pure function of (seed, token index): no engine-global key stream,
    so a retried request replays token-identically anywhere. Delegates
    to models/speculative.engine_sample_key — the single definition of
    the key contract that plain decode, the spec draft/verify programs,
    and make_matched_speculative_generator all share."""
    return engine_sample_key(seed, count)


# ------------------------------------------------------------------ #
# the jitted decode step
# ------------------------------------------------------------------ #


def _paged_block(cfg: GPTConfig, x, layer_params, k_l, v_l, tables,
                 lengths, wblk, woff, positions):
    """One decoder layer over all slots' single new tokens, reading and
    writing the paged pool. The layer math is gpt.decoder_block — only
    the attention core differs (mirrors generation._cached_block)."""

    def attend(q, k, v):
        ctx, k2, v2 = paged_attend(k_l, v_l, q, k, v, tables, lengths,
                                   wblk, woff)
        return ctx, (k2, v2)

    moe_cfg = cfg.moe
    if moe_cfg is not None:
        from ..models.moe import moe_ffn

        def mlp_fn(mlp_in):
            return moe_ffn(layer_params["moe"], mlp_in, moe_cfg)

        x, ((k_l, v_l), _) = decoder_block(
            cfg, None, x, layer_params, positions, attend, mlp_fn=mlp_fn
        )
    else:
        x, (k_l, v_l) = decoder_block(cfg, None, x, layer_params,
                                      positions, attend)
    return x, k_l, v_l


def make_decode_step(cfg: GPTConfig, scfg: ServingConfig):
    """Build the jitted all-slots decode step.

    decode_step(params, k_pool, v_pool, tables, lengths, tokens, temps,
    seeds, counts) -> (next_tokens (N,), k_pool', v_pool'). Pools are
    donated — the caller's old handles die each step (no second pool in
    HBM). temps[i] <= 0 selects greedy argmax for slot i; > 0 samples at
    that temperature under the config's static top_k, keyed by
    ``request_sample_key(seeds[i], counts[i])`` so the sampled stream is
    a pure per-request function — retries and cross-replica failovers
    replay it token-identically.
    """
    top_k = scfg.top_k
    if top_k is not None and top_k >= cfg.vocab_size:
        top_k = None  # full-vocab top-k is a no-op filter

    @partial(jax.jit, donate_argnums=(1, 2))
    def decode_step(params, k_pool, v_pool, tables, lengths, tokens,
                    temps, seeds, counts):
        cdt = cfg.dtype
        N = tokens.shape[0]
        wte = params["embed"]["wte"].astype(cdt)
        x = jnp.take(wte, tokens, axis=0)[:, None, :]       # (N, 1, D)
        positions = lengths[:, None]                        # (N, 1)
        if not cfg.rotary:
            x = x + jnp.take(params["embed"]["wpe"], positions,
                             axis=0).astype(cdt)
        wblk = tables[jnp.arange(N), lengths // scfg.block_size]
        woff = lengths % scfg.block_size

        def scan_body(carry, xs):
            x = carry
            layer_params, k_l, v_l = xs
            x, k_l, v_l = _paged_block(cfg, x, layer_params, k_l, v_l,
                                       tables, lengths, wblk, woff,
                                       positions)
            return x, (k_l, v_l)

        x, (k_new, v_new) = jax.lax.scan(
            scan_body, x, (params["layers"], k_pool, v_pool)
        )
        x = layer_norm(x, params["final_ln"]["scale"],
                       params["final_ln"]["bias"], cfg.layernorm_eps)
        if cfg.tie_embeddings:
            logits = x @ params["embed"]["wte"].astype(cdt).T
        else:
            logits = x @ params["lm_head"].astype(cdt)
        logits = logits[:, 0]                               # (N, V)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        l32 = logits.astype(jnp.float32) / jnp.maximum(
            temps, 1e-6)[:, None]
        if top_k is not None:
            kth = jax.lax.top_k(l32, top_k)[0][..., -1:]
            l32 = jnp.where(l32 < kth, -1e30, l32)
        keys = jax.vmap(request_sample_key)(seeds, counts)
        sampled = jax.vmap(
            lambda k, row: jax.random.categorical(k, row)
        )(keys, l32).astype(jnp.int32)
        nxt = jnp.where(temps > 0.0, sampled, greedy)
        return nxt, k_new, v_new

    return decode_step


# ------------------------------------------------------------------ #
# shared submit/run surface
# ------------------------------------------------------------------ #


class _ServingBase:
    """submit/step/run/metrics shared by ServingEngine and the pipeline
    bridge; subclasses implement _admit_one (prefill) and _decode_all."""

    def __init__(self, scfg: ServingConfig, scheduler: Scheduler,
                 clock, monitor, monitor_config=None):
        self.scfg = scfg
        self.sched = scheduler
        self.clock = clock
        # telemetry facade (monitor/ package): own it when a config is
        # passed, else adopt a process-global one if installed
        if monitor_config is not None:
            self.telemetry = init_monitor(monitor_config)
        else:
            self.telemetry = get_monitor()
        registry = (self.telemetry.registry
                    if self.telemetry is not None else None)
        self.metrics = ServingMetrics(scfg.num_slots, clock, monitor,
                                      registry, slo=scfg.slo)
        self._rid_counter = itertools.count()
        self._requests: Dict[str, Request] = {}
        self._step_i = 0
        # preemption drain: while set, step() admits nothing new and only
        # finishes the requests already holding slots
        self._draining = False
        from ..resilience import get_resilience_manager

        mgr = get_resilience_manager()
        if mgr is not None:
            mgr.attach_serving(self)

    # -- queue surface ------------------------------------------------ #

    def submit(self, prompt: Union[Sequence[int], np.ndarray],
               max_new_tokens: Optional[int] = None,
               temperature: float = 0.0,
               request_id: Optional[str] = None,
               arrival_t: Optional[float] = None,
               seed: Optional[int] = None) -> str:
        """Queue one request; returns its id. Raises when the request
        could never fit (context cap / pool footprint) or while the
        engine is draining (``EngineDrainingError`` — the caller must
        fail over, not wait) — everything else is handled by scheduling,
        not by the caller."""
        if self._draining:
            raise EngineDrainingError(
                "engine is draining (preemption/restart in progress); "
                "admits nothing new — resubmit on another replica")
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        rid = request_id if request_id is not None else \
            f"req-{next(self._rid_counter)}"
        if rid in self._requests:
            raise ValueError(f"duplicate request id {rid!r}")
        req = Request(
            rid=rid,
            prompt=prompt,
            max_new_tokens=(self.scfg.max_new_tokens
                            if max_new_tokens is None else max_new_tokens),
            temperature=float(temperature),
            arrival_t=self.clock() if arrival_t is None else arrival_t,
            seed=(derive_request_seed(self.scfg.seed, rid)
                  if seed is None else int(seed)),
        )
        self.sched.submit(req)
        self._requests[rid] = req
        # the request ledger's clock-zero: every downstream wait bucket
        # (scheduler queue, HOL blocking, compile, prefill) is measured
        # against this instant
        trace_instant("req/submit", lane="serving", rid=rid,
                      prompt_len=len(prompt),
                      max_new=req.max_new_tokens)
        return rid

    def get(self, rid: str) -> Request:
        return self._requests[rid]

    def has_work(self) -> bool:
        return self.sched.has_work()

    def cancel(self, rid: str, reason: str = "timeout") -> bool:
        """Terminate one request wherever it is (queued or active),
        releasing its slot/blocks; partial output is kept. Returns False
        when the rid is unknown or already finished. The router's
        deadline enforcement lands here."""
        req = self._requests.get(rid)
        if req is None or req.state == "finished":
            return False
        self.sched.finish(req, reason)
        self.metrics.record_finish(req, self.clock())
        return True

    # -- the scheduler loop ------------------------------------------- #

    def step(self) -> List[Request]:
        """One scheduler iteration; returns requests finished by it."""
        n_done = len(self.sched.finished)
        with trace_span("serving/step", lane="serving", step=self._step_i):
            now = self.clock()
            for req in self.sched.expire_timeouts(now):
                self.metrics.record_finish(req, now)
            self._prefill_phase()
            for _ in self.sched.ensure_decode_capacity(
                    self._decode_window()):
                self.metrics.record_preemption()
            trace_counter("serving/load", {
                "queued": len(self.sched.queue),
                "active": self.sched.num_active,
            }, lane="serving")
            if self._has_decodable():
                self._decode_all()
        self._step_i += 1
        self.metrics.export(self._step_i)
        return self.sched.finished[n_done:]

    def run(self, max_steps: Optional[int] = None) -> Dict[str, List[int]]:
        """Drive step() until idle (or max_steps); returns {rid: tokens}
        for every finished request."""
        steps = 0
        while self.has_work():
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return {r.rid: r.output for r in self.sched.finished}

    def drain(self, max_steps: Optional[int] = None) -> List[str]:
        """Preemption drain: stop admitting, run decode until every
        in-flight (slot-holding) request finishes, and return the rids
        left queued — the caller (the resilience preemption protocol, or
        an external LB) is expected to re-submit those elsewhere."""
        self._draining = True
        steps = 0
        while self.sched.num_active:
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return [r.rid for r in self.sched.queue]

    # -- helpers ------------------------------------------------------ #

    def _prefill_phase(self) -> None:
        """Admit + prefill queued requests into free slots. Subclasses
        with chunked prefill override this to pump in-flight prompt
        chunks under the per-step token budget before admitting more —
        chunk pumping must keep running while draining (those requests
        hold slots), only NEW admissions stop."""
        if self._draining:
            return
        while (adm := self.sched.pop_admissible()) is not None:
            self._admit_one(*adm)

    def _has_decodable(self) -> bool:
        """Whether any slot has a pending token to decode this step
        (chunk-prefilling slots don't, until their final chunk lands)."""
        return self.sched.num_active > 0

    def _decode_window(self) -> int:
        """Tokens of KV headroom each active slot needs for the next
        decode phase (1 for plain decode; draft_k + 1 with speculation
        on, so a round's window of writes always has rows)."""
        return 1

    def _record_emitted(self, req: Request, prefill: bool) -> None:
        now = self.clock()
        req.last_token_t = now    # progress clock for expire_timeouts
        if prefill:
            ttft = None
            if req.first_token_t is None:
                req.first_token_t = now
                ttft = now - req.arrival_t
            self.metrics.record_prefill(now, ttft)
        if self.sched.check_finished(req, now):
            self.metrics.record_finish(req, now)


class ServingEngine(_ServingBase):
    """Continuous batching with the slot-based paged KV cache (module
    docstring has the architecture)."""

    def __init__(self, cfg: GPTConfig, params,
                 serving_config: Union[ServingConfig, dict, None] = None,
                 clock=time.monotonic, monitor=None, monitor_config=None,
                 mesh=None, param_specs=None, drafter_params=None):
        scfg = (serving_config if isinstance(serving_config, ServingConfig)
                else ServingConfig.from_dict(serving_config))
        if not cfg.rotary and scfg.max_seq_len > cfg.max_seq:
            raise ValueError(
                f"serving max_seq_len ({scfg.max_seq_len}) exceeds the "
                f"model's learned-position table ({cfg.max_seq})"
            )
        self.cfg = cfg
        # dp×tp serving: with a mesh, params place by their TP specs
        # (sharding rule table translates the model's legacy 'model'
        # specs onto a canonical tp axis), the paged KV pools shard
        # their heads dim over tp, and decode inputs shard the slot dim
        # over the batch axes — all through the one sharding/ module.
        self.mesh = mesh
        if mesh is not None:
            params = self._place_params(params, param_specs)
        self.params = params
        self.kv = PagedKVCache(cfg, scfg)
        if mesh is not None:
            self._place_kv_pools()
        super().__init__(scfg, Scheduler(scfg, self.kv.allocator, clock),
                         clock, monitor, monitor_config)
        self._decode_step = make_decode_step(cfg, scfg)
        # retraces once per prefill bucket (toks.shape[1] varies)
        self._prefill_step = jax.jit(
            lambda params, toks: apply_with_cache(
                cfg, params, toks,
                init_cache(cfg, toks.shape[0], toks.shape[1]), 0))
        # suffix/chunked prefill over a gathered staging cache: the write
        # offset is TRACED, so one compile serves every (matched, chunk)
        # position and it retraces only per (chunk len, staging len)
        # shape pair; staging buffers are donated chunk to chunk
        self._suffix_prefill = jax.jit(
            lambda params, toks, kc, vc, offset: apply_with_cache(
                cfg, params, toks, {"k": kc, "v": vc}, offset),
            donate_argnums=(2, 3))
        # slot -> in-flight chunked-prefill state (staging cache, cursor)
        self._chunking: Dict[int, dict] = {}
        self._prefill_spent = 0   # prompt tokens prefilled this step
        if self.telemetry is not None:
            # decode must stay one-compile forever; prefill legitimately
            # retraces per length bucket, so it is deliberately unwatched
            self.telemetry.watchdog.watch("serving/decode_step",
                                          self._decode_step)
        # speculative decoding: a SpecRuntime owns the drafter (params,
        # paged pool, draft/verify programs) and takes over the decode
        # phase; the decode step above stays as the fallback program for
        # slots that cannot speculate a given round
        self._spec = None
        if scfg.speculative is not None:
            from .spec.runtime import SpecRuntime

            self._spec = SpecRuntime(self, scfg.speculative,
                                     drafter_params)

    # -- mesh placement (dp×tp serving) -------------------------------- #

    def _place_params(self, params, param_specs):
        from .. import sharding as shd

        if param_specs is None:
            from ..models.gpt import param_specs as gpt_param_specs

            try:
                param_specs = gpt_param_specs(self.cfg)
                jax.tree.flatten(params)  # sanity touch
                shardings = shd.named_shardings(self.mesh, param_specs)
                return jax.tree.map(jax.device_put, params, shardings)
            except Exception:
                # unknown param structure: replicate rather than refuse
                logger.warning(
                    "serving: params do not match the GPT spec tree; "
                    "replicating them over the mesh")
                import jax.sharding as js

                rep = js.NamedSharding(self.mesh, js.PartitionSpec())
                return jax.tree.map(lambda x: jax.device_put(x, rep), params)
        shardings = shd.named_shardings(self.mesh, param_specs)
        return jax.tree.map(jax.device_put, params, shardings)

    def _place_kv_pools(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .. import sharding as shd

        tp = shd.tp_axis(self.mesh)
        tps = shd.tp_size(self.mesh)
        n_kv = int(self.kv.k.shape[3])  # (layer, blocks, blk, Hkv, Dh)
        head_entry = tp if (tps > 1 and n_kv % tps == 0) else None
        # no trailing None: the decode jit returns pools with the
        # canonicalized spec, and a trailing-None mismatch would cost a
        # one-time retrace when the round-tripped pools feed back in
        sh = NamedSharding(self.mesh, P(None, None, None, head_entry))
        self.kv.k = jax.device_put(self.kv.k, sh)
        self.kv.v = jax.device_put(self.kv.v, sh)

    def _place_slot_array(self, x):
        """Shard a per-slot decode input over the mesh's batch axes (the
        slot dim is the serving analogue of the batch dim)."""
        if self.mesh is None:
            return x
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .. import sharding as shd

        n = int(x.shape[0])
        dp = shd.data_parallel_size(self.mesh)
        spec = (shd.batch_spec(self.mesh, x.ndim)
                if dp > 1 and n % dp == 0 else P())
        return jax.device_put(x, NamedSharding(self.mesh, spec))

    # compile counters (tests assert decode compiles exactly once)
    @property
    def decode_compile_count(self) -> int:
        return getattr(self._decode_step, "_cache_size", lambda: -1)()

    @property
    def prefill_compile_count(self) -> int:
        return getattr(self._prefill_step, "_cache_size", lambda: -1)()

    @property
    def chunk_prefill_compile_count(self) -> int:
        return getattr(self._suffix_prefill, "_cache_size", lambda: -1)()

    @property
    def draft_compile_count(self) -> int:
        return self._spec.draft_compile_count if self._spec else -1

    @property
    def verify_compile_count(self) -> int:
        return self._spec.verify_compile_count if self._spec else -1

    def _decode_window(self) -> int:
        return self._spec.K + 1 if self._spec is not None else 1

    def set_drafter_params(self, drafter_params) -> None:
        """Swap the drafter's weights in place (same drafter config —
        shapes must match, so the compiled draft program is reused).
        The lifecycle rollout path: a (target, drafter) version pair
        restarts the engine for the target side but can hot-swap the
        drafter, whose KV is rebuilt lazily. No-op guard when
        speculative decoding is off."""
        if self._spec is None:
            raise RuntimeError(
                "set_drafter_params: speculative decoding is not enabled "
                "on this engine")
        self._spec.set_drafter_params(drafter_params)

    def _pick_token(self, logits_1d, req: Request) -> int:
        """Prefill-time next-token selection (one request, host-driven).
        Greedy path is the same raw argmax make_generator uses; sampling
        keys off (req.seed, token index) exactly like the decode step,
        so a re-prefill after preemption or retry replays the stream."""
        if req.temperature <= 0.0:
            return int(jnp.argmax(logits_1d))
        top_k = self.scfg.top_k
        if top_k is not None and top_k >= self.cfg.vocab_size:
            top_k = None
        filtered = prep_sampling_logits(logits_1d[None], req.temperature,
                                        top_k)
        key = request_sample_key(req.seed, len(req.generated))
        return int(jax.random.categorical(key, filtered, axis=-1)[0])

    # -- admission: full, suffix, and chunked prefill ------------------ #

    def _budget_ok(self) -> bool:
        b = self.scfg.prefill_token_budget
        return b is None or self._prefill_spent < b

    def _prefill_phase(self) -> None:
        """Chunk-aware prefill phase: pump in-flight prompt chunks, then
        admit queued requests, all under ``prefill_token_budget`` prompt
        tokens per step (budget is a high-water mark, not a hard cap —
        the launch that crosses it still runs, so progress is guaranteed
        and a prompt longer than the budget cannot starve)."""
        self._prefill_spent = 0
        self._sweep_chunk_states()
        for slot in sorted(self._chunking):
            if not self._budget_ok():
                break
            self._pump_slot(slot, self._chunking[slot])
        if self._draining:
            return
        while self._budget_ok() and \
                (adm := self.sched.pop_admissible()) is not None:
            self._admit_one(*adm)

    def _has_decodable(self) -> bool:
        return any(req is not None and s not in self._chunking
                   for s, req in enumerate(self.sched.slots))

    def _sweep_chunk_states(self) -> None:
        """Drop chunk states whose request no longer holds the slot
        (preempted or expired mid-prefill). Nothing to undo: chunked
        prefill stages into a private dense cache and touches the pool
        only at finalize, so abandoning the state abandons nothing."""
        for slot in list(self._chunking):
            if self.sched.slots[slot] is not self._chunking[slot]["req"]:
                del self._chunking[slot]

    def _admit_one(self, slot: int, req: Request, blocks: List[int]) -> None:
        """Prefill the request's context into its allocated blocks.

        Three paths: (1) no cached prefix, prompt within one chunk —
        the original full bucketed prefill; (2) cached prefix — gather
        shared pages into a staging cache, forward only the suffix at
        the matched offset, scatter back the private pages (the matched
        boundary page's re-scatter is the CoW split); (3) long suffix —
        same staging, but forwarded ``prefill_chunk`` tokens per engine
        step so active decodes interleave instead of stalling behind one
        long prompt."""
        ctx = req.context
        L = len(ctx)
        plan = (self.scfg.prefill_plan(L, req.prefix_matched)
                if (req.prefix_matched > 0
                    or self.scfg.prefill_chunk is not None) else None)
        if plan is None or (req.prefix_matched == 0 and plan[0] == 1):
            self._prefill_full(slot, req, blocks)
            self._prefill_spent += L
            return
        n_chunks, chunk, cache_len = plan
        m = req.prefix_matched
        bs = self.scfg.block_size
        page_to_block = [NULL_BLOCK] * (cache_len // bs)
        for i in range(req.prefix_shared_blocks):
            page_to_block[i] = blocks[i]
        if req.prefix_src is not None:
            page_to_block[req.prefix_shared_blocks] = req.prefix_src[0]
        k_stage, v_stage = self.kv.gather_pages(page_to_block)
        state = {
            "req": req, "blocks": blocks, "m": m, "L": L,
            "suffix": ctx[m:], "n": n_chunks, "chunk": chunk,
            "cache_len": cache_len, "k": k_stage, "v": v_stage,
            "next": 0,
        }
        self._chunking[slot] = state
        self._pump_slot(slot, state)

    def _pump_slot(self, slot: int, state: dict) -> None:
        """Forward staged prompt chunks for one slot while the step
        budget allows; the final chunk scatters the staging cache into
        the pool and emits the request's first token."""
        req = state["req"]
        chunk = state["chunk"]
        suffix = state["suffix"]
        while state["next"] < state["n"] and self._budget_ok():
            c = state["next"]
            lo = c * chunk
            hi = min(lo + chunk, len(suffix))
            final = (c + 1) == state["n"]
            if final:
                cm = trace_span("serving/prefill", lane="serving",
                                rid=req.rid, slot=slot,
                                ctx_len=state["L"],
                                bucket=state["cache_len"])
            else:
                cm = trace_span("serving/prefill_chunk", lane="serving",
                                rid=req.rid, chunk=c, tokens=hi - lo)
            with cm as _sp:
                timer = self.metrics.timers(PREFILL_TIMER)
                timer.safe_start()
                toks = np.zeros((1, chunk), np.int32)
                toks[0, :hi - lo] = suffix[lo:hi]
                _pargs = (self.params, jnp.asarray(toks), state["k"],
                          state["v"], state["m"] + lo)
                logits, cache = self._suffix_prefill(*_pargs)
                state["k"], state["v"] = cache["k"], cache["v"]
                if final:
                    self._finish_staged(req, state)
                    tok = self._pick_token(logits[0, hi - lo - 1], req)
                    req.generated.append(tok)
                timer.stop(sync_with=self.kv.k if final else state["k"])
                tel = self.telemetry
                if tel is not None:
                    if tel.cost_index is not None:
                        # one compile per (chunk len, staging len) pair;
                        # the traced offset keeps every chunk position
                        # on the same program
                        tel.cost_index.observe(
                            f"serving/suffix_prefill"
                            f"[s{chunk}c{state['cache_len']}]",
                            self._suffix_prefill, _pargs)
                    if tel.memwatch is not None:
                        tel.memwatch.annotate(_sp, "prefill")
            self._prefill_spent += hi - lo
            self.metrics.record_prefill_chunk(hi - lo)
            state["next"] += 1
            if final:
                del self._chunking[slot]
                logger.debug(
                    "serving: admitted %s to slot %d (ctx=%d matched=%d "
                    "chunks=%d)", req.rid, slot, state["L"], state["m"],
                    state["n"])
                self._record_emitted(req, prefill=True)

    def _finish_staged(self, req: Request, state: dict) -> None:
        """Scatter the staged suffix into the slot's private blocks.
        Pages fully covered by shared blocks stay mapped read-only (their
        scatter target is the null block); the matched boundary page —
        gathered shared rows plus freshly forwarded suffix rows — lands
        in a private block, which IS the copy-on-write split. Then index
        the prompt in the radix cache for the next request."""
        bs = self.scfg.block_size
        m, L, blocks = state["m"], state["L"], state["blocks"]
        first = m // bs
        page_to_block = [NULL_BLOCK] * (state["cache_len"] // bs)
        for p in range(first, blocks_needed(L, bs)):
            page_to_block[p] = blocks[p]
        self.kv.write_pages(state["k"], state["v"], page_to_block)
        if req.prefix_src is not None:
            trace_instant("kv/cow_split", lane="serving", rid=req.rid,
                          block=blocks[first], rows=req.prefix_src[1])
            self.metrics.record_cow_split()
        self.sched.release_prefix_src(req)
        self.metrics.record_reuse(m, L)
        self._index_prompt(req, blocks)

    def _index_prompt(self, req: Request, blocks: List[int]) -> None:
        if self.sched.prefix_cache is None:
            return
        n = blocks_needed(len(req.prompt), self.scfg.block_size)
        self.sched.prefix_cache.insert(req.prompt, blocks[:n])

    def _prefill_full(self, slot: int, req: Request,
                      blocks: List[int]) -> None:
        """Length-bucketed prefill of the request's whole context into
        its allocated blocks; emits the request's next token."""
        ctx = req.context
        L = len(ctx)
        bucket = self.scfg.bucket_for(L)
        with trace_span("serving/prefill", lane="serving", rid=req.rid,
                        slot=slot, ctx_len=L, bucket=bucket) as _sp:
            timer = self.metrics.timers(PREFILL_TIMER)
            timer.safe_start()
            toks = np.zeros((1, bucket), np.int32)
            toks[0, :L] = ctx
            _pargs = (self.params, jnp.asarray(toks))
            logits, cache = self._prefill_step(*_pargs)
            # admission allocated headroom for the first decode write;
            # only the context's own pages carry prefill data
            data_blocks = blocks[:blocks_needed(L, self.scfg.block_size)]
            self.kv.write_prefill(cache["k"], cache["v"], data_blocks, L)
            tok = self._pick_token(logits[0, L - 1], req)
            req.generated.append(tok)
            timer.stop(sync_with=self.kv.k)
            tel = self.telemetry
            if tel is not None:
                if tel.cost_index is not None:
                    # per-bucket: the prefill jit legitimately holds one
                    # compile per context-length bucket
                    tel.cost_index.observe(
                        f"serving/prefill_step[b{bucket}]",
                        self._prefill_step, _pargs)
                if tel.memwatch is not None:
                    tel.memwatch.annotate(_sp, "prefill")
        logger.debug("serving: admitted %s to slot %d (ctx=%d bucket=%d)",
                     req.rid, slot, L, bucket)
        self.metrics.record_reuse(0, L)
        self._index_prompt(req, blocks)
        self._record_emitted(req, prefill=True)

    def _active_decodable(self):
        """(slot, request) pairs with a pending token this step.
        Chunk-prefilling slots have no pending token yet: their lane
        stays idle (all-null table, length 0), so the decode programs'
        shapes — and their single compiles — are untouched by
        chunking."""
        return [(s, req) for s, req in enumerate(self.sched.slots)
                if req is not None and s not in self._chunking]

    def _dispatch_plain(self, active) -> np.ndarray:
        """Run the plain decode program with ``active`` lanes populated
        (the rest idle); returns the host-synced next-token array (N,).
        The caller owns the surrounding span/metrics — this is both the
        whole decode phase (speculation off) and the fallback program
        for non-speculating slots (speculation on)."""
        N = self.scfg.num_slots
        tables = np.zeros((N, self.scfg.blocks_per_slot), np.int32)
        lengths = np.zeros(N, np.int32)
        tokens = np.zeros(N, np.int32)
        temps = np.zeros(N, np.float32)
        seeds = np.zeros(N, np.int32)
        counts = np.zeros(N, np.int32)
        for s, req in active:
            tables[s] = self.sched.slot_table_row(s)
            lengths[s] = req.cached_len
            tokens[s] = req.pending_token
            temps[s] = req.temperature
            seeds[s] = req.seed
            counts[s] = len(req.generated)
        _place = (self._place_slot_array if self.mesh is not None
                  else jnp.asarray)
        _dargs = (self.params, self.kv.k, self.kv.v, _place(tables),
                  _place(lengths), _place(tokens),
                  _place(temps), _place(seeds),
                  _place(counts))
        nxt, self.kv.k, self.kv.v = self._decode_step(*_dargs)
        nxt = np.asarray(nxt)                   # device sync
        self._last_dargs = _dargs
        return nxt

    def _decode_all(self) -> None:
        """One decode phase over the full slot array: the speculative
        round when enabled, else one jitted plain decode step."""
        if self._spec is not None:
            self._spec.decode_round()
            return
        active = self._active_decodable()
        with trace_span("serving/decode", lane="serving",
                        n_active=len(active),
                        rids=",".join(r.rid for _, r in active)) as _sp:
            _t0 = time.perf_counter()
            timer = self.metrics.timers(DECODE_TIMER)
            timer.safe_start()
            nxt = self._dispatch_plain(active)
            timer.stop()
            tel = self.telemetry
            if tel is not None:
                if tel.cost_index is not None:
                    # the sync above already happened, so this wall time
                    # is real; the AOT re-lower never touches the decode
                    # jit's cache (one-compile decode stays one-compile)
                    tel.cost_index.observe("serving/decode_step",
                                           self._decode_step,
                                           self._last_dargs)
                    _stats = tel.cost_index.note_step(
                        "serving/decode_step", time.perf_counter() - _t0)
                    if _stats is not None:
                        _sp.note(mfu=round(_stats["mfu"], 6),
                                 verdict=_stats["verdict"])
                if tel.memwatch is not None:
                    tel.memwatch.annotate(_sp, "decode")
        if self.telemetry is not None:
            self.telemetry.watchdog.observe("serving/decode_step",
                                            step=self._step_i)
        self.metrics.record_decode_step(len(active), len(self.sched.queue),
                                        self.clock())
        for s, req in active:
            req.cached_len += 1
            req.generated.append(int(nxt[s]))
            self._record_emitted(req, prefill=False)


# ------------------------------------------------------------------ #
# pipelined-model bridge
# ------------------------------------------------------------------ #


class PipelineServingBridge(_ServingBase):
    """The same submit/step/run surface for models served through a
    full-prefix logits function — in particular a pipelined model's
    ``PipelineEngine.inference_batch`` (the reference's per-token
    recompute serving mode).

    ``logits_fn(tokens (1, S) int32) -> logits (1, S, V)`` runs once per
    active request per step (pipelined stages can't batch mixed-length
    prefixes without an attention mask), so this path is for
    compatibility, not throughput; the paged ServingEngine is the fast
    path for non-pipelined models.
    """

    def __init__(self, logits_fn,
                 serving_config: Union[ServingConfig, dict, None] = None,
                 clock=time.monotonic, monitor=None, monitor_config=None):
        scfg = (serving_config if isinstance(serving_config, ServingConfig)
                else ServingConfig.from_dict(serving_config))
        self.logits_fn = logits_fn
        # no KV pool: a throwaway allocator sized so block accounting
        # never backpressures — slots are the only admission limit here
        from .kv_cache import BlockAllocator

        alloc = BlockAllocator(1 + scfg.num_slots * scfg.blocks_per_slot)
        super().__init__(scfg, Scheduler(scfg, alloc, clock), clock,
                         monitor, monitor_config)

    @classmethod
    def from_pipeline_engine(cls, engine, serving_config=None, **kw):
        """Serve a PipelineEngine (see runtime/pipe/engine.py
        ``serving_logits_fn``)."""
        return cls(engine.serving_logits_fn(), serving_config, **kw)

    def _pick(self, logits_1d, req: Request) -> int:
        if req.temperature <= 0.0:
            return int(np.asarray(jnp.argmax(logits_1d)))
        top_k = self.scfg.top_k
        filtered = prep_sampling_logits(jnp.asarray(logits_1d)[None],
                                        req.temperature, top_k)
        key = request_sample_key(req.seed, len(req.generated))
        return int(jax.random.categorical(key, filtered, axis=-1)[0])

    def _emit_next(self, req: Request, prefill: bool) -> None:
        ctx = np.asarray(req.context, np.int32)[None]
        logits = self.logits_fn(ctx)
        req.generated.append(self._pick(logits[0, -1], req))
        req.cached_len = ctx.shape[1]   # bookkeeping only (no real cache)
        self._record_emitted(req, prefill=prefill)

    def _admit_one(self, slot: int, req: Request, blocks) -> None:
        with trace_span("serving/prefill", lane="serving", rid=req.rid,
                        slot=slot, ctx_len=len(req.context)):
            timer = self.metrics.timers(PREFILL_TIMER)
            timer.safe_start()
            self._emit_next(req, prefill=True)
            timer.stop()

    def _decode_all(self) -> None:
        active = list(self.sched.active)
        with trace_span("serving/decode", lane="serving",
                        n_active=len(active),
                        rids=",".join(r.rid for r in active)):
            timer = self.metrics.timers(DECODE_TIMER)
            timer.safe_start()
            for req in active:
                self._emit_next(req, prefill=False)
            timer.stop()
        self.metrics.record_decode_step(len(active),
                                        len(self.sched.queue),
                                        self.clock())
