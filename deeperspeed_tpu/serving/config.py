"""Serving-engine configuration.

The inference counterpart of ``runtime/config.py``'s training blocks: a
``"serving"`` block in the master JSON config (or a plain dict) builds a
``ServingConfig``. All sizes here are STATIC — they fix the shapes of the
jitted decode step (slot count, block-table width) and of the paged KV
pool, so requests can join and leave without ever recompiling.

Geometry:

  * ``num_slots`` decode slots — the fixed batch dimension of the decode
    step. A request occupies one slot from admission to eviction.
  * The KV pool holds ``num_blocks`` blocks of ``block_size`` tokens each
    (block 0 is reserved as the null block that idle slots and padding
    point at). Long and short requests draw from the SAME pool — no
    per-request max-length reservation, which is the whole point of
    paging (vLLM's PagedAttention insight).
  * Prefill pads prompts up to a length bucket (multiples of
    ``block_size``, doubling), so prefill compiles once per bucket rather
    than once per prompt length.
"""

import dataclasses
import math
from typing import Optional, Tuple

_KNOWN_KEYS = frozenset({
    "enabled", "num_slots", "block_size", "num_blocks", "max_seq_len",
    "max_new_tokens", "eos_token_id", "top_k", "request_timeout_s",
    "prefill_buckets", "seed",
})


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    # slot pool: batch dimension of the one jitted decode step
    num_slots: int = 8
    # paged KV cache geometry; block 0 is the reserved null block
    block_size: int = 16
    num_blocks: int = 128
    # hard cap on prompt_len + max_new_tokens per request (bounds the
    # block-table width: ceil(max_seq_len / block_size) entries per slot)
    max_seq_len: int = 512
    # default per-request generation budget (requests may pass their own)
    max_new_tokens: int = 64
    # stop token; None disables EOS eviction
    eos_token_id: Optional[int] = None
    # static top-k for sampled (temperature > 0) slots; None = full vocab.
    # Static because it shapes the decode step's lax.top_k — per-request
    # top_k would recompile per value.
    top_k: Optional[int] = None
    # evict requests (queued or running) older than this; None = never
    request_timeout_s: Optional[float] = None
    # prefill length buckets; () derives doubling multiples of block_size
    prefill_buckets: Tuple[int, ...] = ()
    # base PRNG seed for sampled slots
    seed: int = 0

    def __post_init__(self):
        if self.num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {self.num_slots}")
        if self.block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {self.block_size}")
        if self.max_seq_len < 1:
            raise ValueError(f"max_seq_len must be >= 1, got {self.max_seq_len}")
        # block 0 is the null block — at least one usable block is needed
        if self.num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is the reserved null "
                f"block), got {self.num_blocks}"
            )
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}"
            )
        if self.top_k is not None and self.top_k < 1:
            raise ValueError(f"top_k must be >= 1 or None, got {self.top_k}")
        buckets = self.prefill_buckets or self._default_buckets()
        buckets = tuple(sorted(set(int(b) for b in buckets)))
        for b in buckets:
            if b < 1 or b % self.block_size:
                raise ValueError(
                    f"prefill bucket {b} must be a positive multiple of "
                    f"block_size ({self.block_size})"
                )
        if buckets[-1] < self.max_seq_len:
            raise ValueError(
                f"largest prefill bucket ({buckets[-1]}) must cover "
                f"max_seq_len ({self.max_seq_len})"
            )
        object.__setattr__(self, "prefill_buckets", buckets)

    def _default_buckets(self):
        buckets, b = [], self.block_size
        while b < self.max_seq_len:
            buckets.append(b)
            b *= 2
        buckets.append(self.blocks_per_slot * self.block_size)
        return tuple(buckets)

    @property
    def blocks_per_slot(self) -> int:
        """Block-table width: blocks a maximally long request occupies."""
        return math.ceil(self.max_seq_len / self.block_size)

    @property
    def usable_blocks(self) -> int:
        """Allocatable blocks (the pool minus the null block)."""
        return self.num_blocks - 1

    def bucket_for(self, length: int) -> int:
        """Smallest prefill bucket covering ``length``."""
        for b in self.prefill_buckets:
            if b >= length:
                return b
        raise ValueError(
            f"prompt length {length} exceeds the largest prefill bucket "
            f"({self.prefill_buckets[-1]}); raise max_seq_len"
        )

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "ServingConfig":
        """Build from a ``"serving"`` config block. Unknown keys raise —
        a typo'd knob silently falling back to its default is the classic
        serving-config footgun."""
        if d is None:
            return cls()
        unknown = set(d) - _KNOWN_KEYS
        if unknown:
            raise ValueError(
                f"unknown serving config keys {sorted(unknown)}; known keys "
                f"are {sorted(_KNOWN_KEYS)}"
            )
        kw = {k: v for k, v in d.items() if k != "enabled"}
        if "prefill_buckets" in kw and kw["prefill_buckets"] is not None:
            kw["prefill_buckets"] = tuple(kw["prefill_buckets"])
        return cls(**kw)
