"""Serving-engine configuration.

The inference counterpart of ``runtime/config.py``'s training blocks: a
``"serving"`` block in the master JSON config (or a plain dict) builds a
``ServingConfig``. All sizes here are STATIC — they fix the shapes of the
jitted decode step (slot count, block-table width) and of the paged KV
pool, so requests can join and leave without ever recompiling.

Geometry:

  * ``num_slots`` decode slots — the fixed batch dimension of the decode
    step. A request occupies one slot from admission to eviction.
  * The KV pool holds ``num_blocks`` blocks of ``block_size`` tokens each
    (block 0 is reserved as the null block that idle slots and padding
    point at). Long and short requests draw from the SAME pool — no
    per-request max-length reservation, which is the whole point of
    paging (vLLM's PagedAttention insight).
  * Prefill pads prompts up to a length bucket (multiples of
    ``block_size``, doubling), so prefill compiles once per bucket rather
    than once per prompt length.
"""

import dataclasses
import math
from typing import Optional, Tuple

_KNOWN_KEYS = frozenset({
    "enabled", "num_slots", "block_size", "num_blocks", "max_seq_len",
    "max_new_tokens", "eos_token_id", "top_k", "request_timeout_s",
    "prefill_buckets", "seed", "fleet", "slo",
    "prefix_caching", "prefill_chunk", "prefill_token_budget",
    "speculative",
})

_SPEC_KNOWN_KEYS = frozenset({
    "enabled", "draft_k", "drafter", "drafter_checkpoint", "num_blocks",
})

_SLO_KNOWN_KEYS = frozenset({
    "ttft_p99_ms", "tpot_p99_ms", "e2e_p99_ms", "error_budget",
})

_ROUTER_KNOWN_KEYS = frozenset({
    "num_replicas", "max_queue_depth", "max_inflight_tokens",
    "default_deadline_s", "retry_max", "retry_backoff_base_s",
    "retry_backoff_max_s", "heartbeat_timeout_s", "progress_timeout_s",
    "replica_restart", "replica_max_restarts", "poll_interval_s",
    "prefix_affinity", "affinity_prefix_len", "affinity_load_slack",
})


@dataclasses.dataclass(frozen=True)
class SLOConfig:
    """The ``"slo"`` sub-block of the serving config: tail-latency
    targets the fleet promises its clients. Each target is a p99 bound
    in milliseconds; None leaves that axis unpromised. Targets drive
    live burn-rate gauges and ``slo/violation`` trace instants
    (serving/metrics.SLOTracker) and the offline doctor's verdicts
    (``python -m deeperspeed_tpu.monitor.slo``).

    ``burn_rate = violating_fraction / error_budget`` — at 1.0 the
    request stream is violating exactly as fast as a p99 target allows
    (1% of requests for the default budget); above 1.0 the budget is
    burning down and the pager should care."""

    ttft_p99_ms: Optional[float] = None   # time to first token
    tpot_p99_ms: Optional[float] = None   # time per output token
    e2e_p99_ms: Optional[float] = None    # submit/accept -> terminal
    error_budget: float = 0.01            # allowed violating fraction

    def __post_init__(self):
        for key in ("ttft_p99_ms", "tpot_p99_ms", "e2e_p99_ms"):
            v = getattr(self, key)
            if v is not None and v <= 0:
                raise ValueError(f"{key} must be > 0 or None, got {v}")
        if not 0.0 < self.error_budget < 1.0:
            raise ValueError(
                f"error_budget must be in (0, 1), got {self.error_budget}")

    def targets(self) -> dict:
        """Non-None targets: ``{"ttft": ms, ...}`` keyed by axis."""
        out = {}
        for axis in ("ttft", "tpot", "e2e"):
            v = getattr(self, f"{axis}_p99_ms")
            if v is not None:
                out[axis] = float(v)
        return out

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "SLOConfig":
        if d is None:
            return cls()
        unknown = set(d) - _SLO_KNOWN_KEYS
        if unknown:
            raise ValueError(
                f"unknown slo config keys {sorted(unknown)}; known keys "
                f"are {sorted(_SLO_KNOWN_KEYS)}")
        return cls(**d)


@dataclasses.dataclass(frozen=True)
class SpeculativeConfig:
    """The ``"speculative"`` sub-block of the serving config: drafter-
    backed speculative decoding (serving/spec/). Off unless the block is
    present — the plain one-compile decode path is bit-for-bit untouched
    without it.

    The drafter is a second, smaller model sharing the target's
    vocabulary. It proposes ``draft_k`` tokens per round from its own
    paged KV pool; the target then scores all ``draft_k + 1`` positions
    in one batched verify step and keeps the longest agreeing prefix
    plus one bonus token. Greedy output is bit-identical to plain greedy
    decode for ANY drafter — the drafter only changes how many target
    forwards a token costs, never which token is emitted."""

    # tokens drafted per speculative round (the verify step scores
    # draft_k + 1 positions; static — it shapes the compiled programs)
    draft_k: int = 4
    # drafter model config (GPTConfig kwargs, e.g. {"n_layer": 1, ...});
    # None means the engine derives a layer-truncated drafter from the
    # target (serving/spec.truncated_drafter) unless explicit drafter
    # params are passed to the engine
    drafter: Optional[dict] = None
    # checkpoint tag/path the drafter's weights load from (subprocess
    # replicas; in-process engines usually pass drafter_params directly)
    drafter_checkpoint: Optional[str] = None
    # drafter KV pool size in blocks (its own BlockAllocator; block 0
    # reserved exactly like the target pool); None = target num_blocks
    num_blocks: Optional[int] = None

    def __post_init__(self):
        if self.draft_k < 1:
            raise ValueError(
                f"draft_k must be >= 1, got {self.draft_k}")
        if self.num_blocks is not None and self.num_blocks < 2:
            raise ValueError(
                f"speculative num_blocks must be >= 2 (block 0 is the "
                f"reserved null block), got {self.num_blocks}")
        if self.drafter is not None and not isinstance(self.drafter, dict):
            raise ValueError(
                f"drafter must be a GPTConfig kwargs dict or None, got "
                f"{type(self.drafter).__name__}")

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "SpeculativeConfig":
        if d is None:
            return cls()
        unknown = set(d) - _SPEC_KNOWN_KEYS
        if unknown:
            raise ValueError(
                f"unknown speculative config keys {sorted(unknown)}; "
                f"known keys are {sorted(_SPEC_KNOWN_KEYS)}")
        return cls(**{k: v for k, v in d.items() if k != "enabled"})


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """The ``"fleet"`` sub-block of the serving config: the front-end
    router's admission, deadline, retry, and health policy
    (serving/router.py). Every limit is explicit — the router sheds
    rather than queue unboundedly, and a replica that stops heartbeating
    or stops emitting tokens is failed over, not waited on."""

    # replicas the fleet builder spawns (a pre-built replica list wins)
    num_replicas: int = 2
    # admission control: accepted-but-unfinished request cap ...
    max_queue_depth: int = 64
    # ... and in-flight token budget (sum of prompt + max_new_tokens
    # over accepted requests); None disables the token gate
    max_inflight_tokens: Optional[int] = None
    # wall-clock budget per request, checked AT THE ROUTER (distinct
    # from the engine's progress-based request_timeout_s); submit may
    # override per request; None = no deadline
    default_deadline_s: Optional[float] = None
    # bounded failover: re-dispatches allowed per request after replica
    # failures, with exponential backoff between attempts
    retry_max: int = 2
    retry_backoff_base_s: float = 0.05
    retry_backoff_max_s: float = 2.0
    # health watchdogs: a replica is DEAD when its heartbeat is older
    # than this ...
    heartbeat_timeout_s: float = 10.0
    # ... and STALLED when it holds in-flight work but its decode
    # progress counter has not moved for this long
    progress_timeout_s: float = 30.0
    # lifecycle: restart failed replicas (supervisor-style backoff),
    # capped per replica
    replica_restart: bool = True
    replica_max_restarts: int = 2
    # router run()/drive loop sleep when idle
    poll_interval_s: float = 0.01
    # prefix affinity: hash each request's first affinity_prefix_len
    # prompt tokens and prefer the replica that last served that prefix
    # (its radix cache is warm), as long as that replica's assigned
    # count is within affinity_load_slack of the least-loaded one —
    # affinity never overrides health, and never builds hot spots
    prefix_affinity: bool = False
    affinity_prefix_len: int = 64
    affinity_load_slack: int = 2

    def __post_init__(self):
        if self.affinity_prefix_len < 1:
            raise ValueError(
                f"affinity_prefix_len must be >= 1, got "
                f"{self.affinity_prefix_len}")
        if self.affinity_load_slack < 0:
            raise ValueError(
                f"affinity_load_slack must be >= 0, got "
                f"{self.affinity_load_slack}")
        if self.num_replicas < 1:
            raise ValueError(
                f"num_replicas must be >= 1, got {self.num_replicas}")
        if self.max_queue_depth < 1:
            raise ValueError(
                f"max_queue_depth must be >= 1, got {self.max_queue_depth}")
        if (self.max_inflight_tokens is not None
                and self.max_inflight_tokens < 1):
            raise ValueError(
                f"max_inflight_tokens must be >= 1 or None, got "
                f"{self.max_inflight_tokens}")
        if (self.default_deadline_s is not None
                and self.default_deadline_s <= 0):
            raise ValueError(
                f"default_deadline_s must be > 0 or None, got "
                f"{self.default_deadline_s}")
        if self.retry_max < 0:
            raise ValueError(
                f"retry_max must be >= 0, got {self.retry_max}")
        for key in ("retry_backoff_base_s", "retry_backoff_max_s",
                    "heartbeat_timeout_s", "progress_timeout_s",
                    "poll_interval_s"):
            if getattr(self, key) <= 0:
                raise ValueError(
                    f"{key} must be > 0, got {getattr(self, key)}")
        if self.replica_max_restarts < 0:
            raise ValueError(
                f"replica_max_restarts must be >= 0, got "
                f"{self.replica_max_restarts}")

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "RouterConfig":
        if d is None:
            return cls()
        unknown = set(d) - _ROUTER_KNOWN_KEYS
        if unknown:
            raise ValueError(
                f"unknown fleet config keys {sorted(unknown)}; known keys "
                f"are {sorted(_ROUTER_KNOWN_KEYS)}")
        return cls(**d)


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    # slot pool: batch dimension of the one jitted decode step
    num_slots: int = 8
    # paged KV cache geometry; block 0 is the reserved null block
    block_size: int = 16
    num_blocks: int = 128
    # hard cap on prompt_len + max_new_tokens per request (bounds the
    # block-table width: ceil(max_seq_len / block_size) entries per slot)
    max_seq_len: int = 512
    # default per-request generation budget (requests may pass their own)
    max_new_tokens: int = 64
    # stop token; None disables EOS eviction
    eos_token_id: Optional[int] = None
    # static top-k for sampled (temperature > 0) slots; None = full vocab.
    # Static because it shapes the decode step's lax.top_k — per-request
    # top_k would recompile per value.
    top_k: Optional[int] = None
    # evict requests (queued or running) older than this; None = never
    request_timeout_s: Optional[float] = None
    # prefill length buckets; () derives doubling multiples of block_size
    prefill_buckets: Tuple[int, ...] = ()
    # base PRNG seed for sampled slots (per-request seeds derive from it)
    seed: int = 0
    # prefix-radix KV reuse: index prefilled prompts in a radix trie and
    # admit new requests by longest cached prefix, mapping shared blocks
    # read-only and prefilling only the suffix. Off by default — the
    # exact-ownership block accounting stays bit-for-bit what it was.
    prefix_caching: bool = False
    # chunked prefill: prompts longer than this prefill in fixed-size
    # chunks interleaved with decode steps (one extra compile per
    # (chunk, cache-bucket) pair; the decode jit never retraces). None
    # disables chunking (one-shot prefill, the original behavior).
    prefill_chunk: Optional[int] = None
    # per-step prefill token budget: one scheduler step runs at most
    # this many prefill tokens (admissions + chunks) before decoding,
    # so a wave of long prompts cannot stall active decodes for more
    # than ~budget tokens of prefill compute. None = unbounded.
    prefill_token_budget: Optional[int] = None
    # multi-replica front-end router policy (serving/router.py); None =
    # single-engine serving, no fleet layer
    fleet: Optional[RouterConfig] = None
    # tail-latency promises (burn-rate gauges + slo/violation instants);
    # None = no SLO accounting
    slo: Optional[SLOConfig] = None
    # drafter-backed speculative decoding (serving/spec/); None = plain
    # one-program decode, the default path, untouched
    speculative: Optional[SpeculativeConfig] = None

    def __post_init__(self):
        if isinstance(self.fleet, dict):
            object.__setattr__(self, "fleet",
                               RouterConfig.from_dict(self.fleet))
        if isinstance(self.slo, dict):
            object.__setattr__(self, "slo",
                               SLOConfig.from_dict(self.slo))
        if isinstance(self.speculative, dict):
            object.__setattr__(self, "speculative",
                               SpeculativeConfig.from_dict(self.speculative))
        if self.num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {self.num_slots}")
        if self.block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {self.block_size}")
        if self.max_seq_len < 1:
            raise ValueError(f"max_seq_len must be >= 1, got {self.max_seq_len}")
        # block 0 is the null block — at least one usable block is needed
        if self.num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is the reserved null "
                f"block), got {self.num_blocks}"
            )
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}"
            )
        if self.top_k is not None and self.top_k < 1:
            raise ValueError(f"top_k must be >= 1 or None, got {self.top_k}")
        buckets = self.prefill_buckets or self._default_buckets()
        buckets = tuple(sorted(set(int(b) for b in buckets)))
        for b in buckets:
            if b < 1 or b % self.block_size:
                raise ValueError(
                    f"prefill bucket {b} must be a positive multiple of "
                    f"block_size ({self.block_size})"
                )
        if buckets[-1] < self.max_seq_len:
            raise ValueError(
                f"largest prefill bucket ({buckets[-1]}) must cover "
                f"max_seq_len ({self.max_seq_len})"
            )
        object.__setattr__(self, "prefill_buckets", buckets)
        if self.prefill_chunk is not None and self.prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1 or None, got "
                f"{self.prefill_chunk}")
        if (self.prefill_token_budget is not None
                and self.prefill_token_budget < 1):
            raise ValueError(
                f"prefill_token_budget must be >= 1 or None, got "
                f"{self.prefill_token_budget}")

    def _default_buckets(self):
        buckets, b = [], self.block_size
        while b < self.max_seq_len:
            buckets.append(b)
            b *= 2
        buckets.append(self.blocks_per_slot * self.block_size)
        return tuple(buckets)

    @property
    def blocks_per_slot(self) -> int:
        """Block-table width: blocks a maximally long request occupies."""
        return math.ceil(self.max_seq_len / self.block_size)

    @property
    def usable_blocks(self) -> int:
        """Allocatable blocks (the pool minus the null block)."""
        return self.num_blocks - 1

    def bucket_for(self, length: int) -> int:
        """Smallest prefill bucket covering ``length``."""
        for b in self.prefill_buckets:
            if b >= length:
                return b
        raise ValueError(
            f"prompt length {length} exceeds the largest prefill bucket "
            f"({self.prefill_buckets[-1]}); raise max_seq_len"
        )

    def prefill_plan(self, ctx_len: int,
                     matched: int = 0) -> Optional[Tuple[int, int, int]]:
        """Shape plan for a (possibly suffix-only, possibly chunked)
        staging-cache prefill of ``ctx_len`` context tokens of which
        ``matched`` are already cached: ``(n_chunks, chunk_tokens,
        cache_len)``. The forward runs n_chunks times over
        (1, chunk_tokens) token slabs against a (1, cache_len) staging
        cache at a TRACED offset, so compiles are bounded by
        (chunk size, cache bucket) pairs, never by matched/offset values.
        None when no bucket combination covers the request — the caller
        falls back to the one-shot full prefill (correct, just unshared).
        """
        suffix = ctx_len - matched
        if suffix < 1:
            return None
        try:
            if (self.prefill_chunk is not None
                    and suffix > self.prefill_chunk):
                chunk = self.prefill_chunk
                n = math.ceil(suffix / chunk)
                return n, chunk, self.bucket_for(matched + n * chunk)
            s_pad = self.bucket_for(suffix)
            cache_len = (self.bucket_for(matched + s_pad) if matched
                         else s_pad)
            return 1, s_pad, cache_len
        except ValueError:
            return None

    def kv_pool_bytes(self, n_layer: int, kv_heads: int, head_dim: int,
                      dtype_bytes: int = 2) -> int:
        """Bytes the paged KV pool pins in HBM for a given model shape:
        K and V for every layer, every block — the serving half of the
        autotuner's HBM-feasibility axis."""
        per_token = 2 * n_layer * kv_heads * head_dim
        return self.num_blocks * self.block_size * per_token * dtype_bytes

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "ServingConfig":
        """Build from a ``"serving"`` config block. Unknown keys raise —
        a typo'd knob silently falling back to its default is the classic
        serving-config footgun."""
        if d is None:
            return cls()
        unknown = set(d) - _KNOWN_KEYS
        if unknown:
            raise ValueError(
                f"unknown serving config keys {sorted(unknown)}; known keys "
                f"are {sorted(_KNOWN_KEYS)}"
            )
        kw = {k: v for k, v in d.items() if k != "enabled"}
        if "prefill_buckets" in kw and kw["prefill_buckets"] is not None:
            kw["prefill_buckets"] = tuple(kw["prefill_buckets"])
        return cls(**kw)
