"""Fleet front-end: admission control, deadlines, health-checked
dispatch, and bounded failover over N replica workers (serving/fleet.py).

The router is the layer that turns "a replica died" into "the client
never noticed". Requests flow through four gates:

  1. **Admission** — ``submit()`` either accepts or raises ``ShedError``
     with a retry-after hint. Two caps, both explicit: accepted-but-
     unfinished depth (``max_queue_depth``) and an in-flight token
     budget (``max_inflight_tokens``). The router NEVER queues
     unboundedly; overload is shed at the door, visible in
     ``serving_shed_total`` and ``serving/shed`` trace instants.
  2. **Deadlines** — wall-clock, enforced at the router against its own
     clock (``default_deadline_s`` or a per-request override). Distinct
     from the engine's progress-based ``request_timeout_s``: the engine
     protects itself from wedged requests, the router keeps promises to
     clients.
  3. **Health-checked dispatch** — each step the router runs two
     watchdogs per replica: a heartbeat age check (process/thread dead)
     and a decode-progress check (alive but wedged — the stall fault).
     An unhealthy replica's in-flight requests are requeued by rid and
     re-dispatched to healthy replicas with bounded retries and
     exponential backoff (``resilience.supervisor.compute_backoff``).
     Because a request's sampling seed rides in its dispatch spec (and
     every replica holds identical weights), the retried request
     regenerates token-identical output — greedy trivially, sampled via
     the per-(seed, position) key derivation in serving/engine.py.
  4. **Lifecycle** — ``drain_replica`` (stop dispatching, finish
     in-flight, requeue leftovers without retry penalty),
     ``rolling_restart`` (drain + restart one replica at a time; the
     fleet keeps serving), and supervisor-style crash restarts capped
     by ``replica_max_restarts``.

Terminal outcomes per accepted rid land in ``results()``; the invariant
the kill drill audits is that every accepted rid reaches one — finished
(length/eos), deadline ``timeout``, or ``failed`` after the retry
budget. Nothing is silently lost.
"""

import dataclasses
import time
import zlib
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

from ..monitor import get_monitor
from ..monitor.tracer import trace_instant, trace_span
from ..resilience.supervisor import compute_backoff
from .config import RouterConfig
from .engine import derive_request_seed
from .fleet import ReplicaUnavailableError
from .metrics import FleetMetrics
from .scheduler import FINISH_FAILED, FINISH_TIMEOUT

__all__ = ["ShedError", "FleetRouter", "RouterRequest"]

_TRACE_LANE = "router"


class ShedError(RuntimeError):
    """Structured admission rejection: the fleet is at capacity and the
    client should retry after ``retry_after_s`` rather than pile on."""

    def __init__(self, rid: str, reason: str, retry_after_s: float):
        super().__init__(
            f"request {rid} shed ({reason}); retry after "
            f"{retry_after_s:.3f}s")
        self.rid = rid
        self.reason = reason
        self.retry_after_s = retry_after_s


@dataclasses.dataclass
class RouterRequest:
    """Router-side record: the authoritative copy of a request. Survives
    any number of replica deaths — replicas only ever hold a copy."""

    rid: str
    spec: dict                       # the dispatch spec (incl. seed)
    cost_tokens: int                 # admission token-budget charge
    submit_t: float
    deadline_t: Optional[float]
    # crc32 of the prompt's head tokens; same-prefix requests share it,
    # and dispatch prefers the replica whose radix cache is warm for it
    prefix_key: Optional[int] = None
    attempts: int = 0                # dispatches so far
    not_before: float = 0.0          # backoff gate for re-dispatch
    assigned: Optional[str] = None   # replica name, while in flight
    # weight-version pin: set from the FIRST replica that serves the
    # request; failover retries only target replicas on the same
    # version, so the regenerated stream is token-identical. None =
    # unpinned (versionless replicas, or re-pinned after the version
    # lost its last replica).
    version: Optional[int] = None
    repins: int = 0                  # version pins abandoned (rare)
    first_t: Optional[float] = None
    finish_t: Optional[float] = None
    tokens: Optional[List[int]] = None
    finish_reason: Optional[str] = None

    @property
    def done(self) -> bool:
        return self.finish_reason is not None


class _ReplicaState:
    """Router-side view of one replica: health verdict, progress
    tracker, restart budget."""

    def __init__(self, replica, now: float):
        self.replica = replica
        self.healthy = True
        self.assigned: set = set()           # rids dispatched, unfinished
        self.last_progress = replica.progress
        self.progress_t = now                # when progress last moved
        self.failure_restarts = 0
        self.restart_at: Optional[float] = None   # pending crash restart

    @property
    def name(self) -> str:
        return self.replica.name


class FleetRouter:
    def __init__(self, replicas: Sequence[object],
                 rcfg: Optional[RouterConfig] = None,
                 clock: Callable[[], float] = time.monotonic,
                 registry=None, base_seed: int = 0, slo=None):
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        self.rcfg = rcfg or RouterConfig()
        self.clock = clock
        self.base_seed = base_seed
        now = clock()
        self._states = [_ReplicaState(r, now) for r in replicas]
        self._reqs: Dict[str, RouterRequest] = {}
        self._pending: "deque[str]" = deque()
        self._inflight_tokens = 0
        self._next_rid = 0
        # prefix_key -> replica name that last served it (warm cache)
        self._affinity: Dict[int, str] = {}
        if registry is None:
            mon = get_monitor()
            registry = mon.registry if mon is not None else None
        # slo: an SLOConfig (serving/config.py) — router-observed TTFT
        # and E2E latencies feed its burn-rate gauges
        self.metrics = FleetMetrics(clock=clock, registry=registry,
                                    slo=slo)

    # -- client surface ----------------------------------------------

    def submit(self, prompt: Sequence[int],
               max_new_tokens: Optional[int] = None,
               temperature: float = 0.0,
               request_id: Optional[str] = None,
               deadline_s: Optional[float] = None,
               seed: Optional[int] = None) -> str:
        """Admit or shed. Raises ``ShedError`` at capacity; otherwise
        returns the rid (dispatch happens on the next ``step()``)."""
        rid = request_id if request_id is not None \
            else f"fleet-{self._next_rid}"
        self._next_rid += 1
        if rid in self._reqs:
            raise ValueError(f"duplicate request id {rid!r}")
        now = self.clock()
        depth = self._accepted_unfinished()
        if depth >= self.rcfg.max_queue_depth:
            self._shed(rid, "queue_depth", depth)
        cost = len(prompt) + int(max_new_tokens or 0)
        if (self.rcfg.max_inflight_tokens is not None
                and self._inflight_tokens + cost
                > self.rcfg.max_inflight_tokens):
            self._shed(rid, "token_budget", depth)
        if deadline_s is None:
            deadline_s = self.rcfg.default_deadline_s
        # the seed is fixed HERE, not on the replica, so a failover
        # re-dispatch replays the identical sampling stream
        if seed is None:
            seed = derive_request_seed(self.base_seed, rid)
        spec = {"rid": rid, "prompt": list(int(t) for t in prompt),
                "max_new_tokens": max_new_tokens,
                "temperature": float(temperature), "seed": int(seed)}
        prefix_key = None
        if self.rcfg.prefix_affinity:
            head = spec["prompt"][:self.rcfg.affinity_prefix_len]
            prefix_key = zlib.crc32(
                ",".join(str(t) for t in head).encode("ascii"))
        self._reqs[rid] = RouterRequest(
            rid=rid, spec=spec, cost_tokens=cost, submit_t=now,
            deadline_t=(now + deadline_s) if deadline_s else None,
            prefix_key=prefix_key)
        self._pending.append(rid)
        self._inflight_tokens += cost
        self.metrics.record_accept()
        # router-side clock-zero for the request ledger (the engine-side
        # counterpart is req/submit, emitted at replica admission)
        trace_instant("req/accept", _TRACE_LANE, rid=rid,
                      cost_tokens=cost)
        return rid

    def result(self, rid: str) -> RouterRequest:
        return self._reqs[rid]

    def results(self) -> Dict[str, RouterRequest]:
        return dict(self._reqs)

    def outcomes(self) -> Dict[str, str]:
        """rid -> terminal reason, for finished requests only. The kill
        drill's zero-loss audit checks every accepted rid shows up."""
        return {rid: r.finish_reason for rid, r in self._reqs.items()
                if r.done}

    def unfinished(self) -> List[str]:
        return [rid for rid, r in self._reqs.items() if not r.done]

    # -- drive loop --------------------------------------------------

    def step(self) -> None:
        """One router turn: collect events, run watchdogs, enforce
        deadlines, dispatch. Non-blocking; call from a loop or use
        ``run_until_idle``."""
        now = self.clock()
        self._collect_events(now)
        self._check_health(now)
        self._enforce_deadlines(now)
        self._dispatch(now)
        self._export_gauges()

    def run_until_idle(self, timeout_s: float = 120.0) -> Dict[str, str]:
        """Step until every accepted request is terminal (or timeout —
        then remaining requests fail with ``failed`` so the audit still
        sees a terminal outcome, and the timeout is loud in metrics)."""
        deadline = time.monotonic() + timeout_s
        while self.unfinished():
            if time.monotonic() > deadline:
                for rid in self.unfinished():
                    self._finish_local(
                        self._reqs[rid], FINISH_FAILED, self.clock(),
                        note="router run_until_idle timeout")
                break
            self.step()
            time.sleep(self.rcfg.poll_interval_s)
        return self.outcomes()

    # -- lifecycle ---------------------------------------------------

    def drain_replica(self, name: str, timeout_s: float = 60.0) -> None:
        """Graceful: stop dispatching to the replica, let it finish its
        in-flight work, requeue whatever remains WITHOUT charging the
        retry budget (draining is not the request's fault)."""
        st = self._state(name)
        st.healthy = False   # no new dispatches
        with trace_span("serving/drain_replica", _TRACE_LANE,
                        replica=name):
            leftovers = st.replica.drain(timeout_s)
            self._collect_events(self.clock())
            for rid in list(st.assigned):
                if rid in leftovers or not self._reqs[rid].done:
                    self._requeue(self._reqs[rid], penalize=False)
            st.assigned.clear()

    def rolling_restart(self, timeout_s: float = 120.0) -> None:
        """Restart every replica one at a time; the rest of the fleet
        keeps serving throughout. Loses nothing: drained leftovers are
        requeued, and dispatch only ever targets healthy replicas."""
        for st in self._states:
            self.drain_replica(st.name, timeout_s)
            with trace_span("serving/rolling_restart", _TRACE_LANE,
                            replica=st.name):
                st.replica.restart()
            self._mark_restarted(st)

    def rolling_update(self, version: int, weights: Optional[dict] = None,
                       timeout_s: float = 120.0) -> None:
        """Roll the fleet onto a new weight version, one replica at a
        time. During the transition the fleet is MIXED-version: new
        requests pin to whichever version first serves them, and
        failover retries stay inside the pinned version — no request
        ever sees tokens from two weight sets. ``weights`` is the
        replica-side load payload (e.g. ``{"load_dir": ..., "tag":
        ...}``); replicas without a ``set_weights`` method are restarted
        as-is (version label only)."""
        for st in self._states:
            self.drain_replica(st.name, timeout_s)
            with trace_span("serving/rolling_restart", _TRACE_LANE,
                            replica=st.name):
                set_weights = getattr(st.replica, "set_weights", None)
                if set_weights is not None:
                    set_weights(weights, version)
                elif hasattr(st.replica, "version"):
                    st.replica.version = version
                st.replica.restart()
            self._mark_restarted(st)
            trace_instant("lifecycle/rollout", "lifecycle",
                          replica=st.name, version=int(version))
        if self.metrics.registry is not None:
            self.metrics.registry.counter(
                "lifecycle_rollout_total",
                "replica weight-version rollouts completed").inc()
            self.metrics.registry.gauge(
                "lifecycle_fleet_version",
                "newest weight version the fleet was rolled onto",
            ).set(float(version))

    def shutdown(self) -> None:
        for st in self._states:
            try:
                st.replica.stop()
            except Exception:  # noqa: BLE001 - teardown is best-effort
                pass

    # -- internals ---------------------------------------------------

    def _state(self, name: str) -> _ReplicaState:
        for st in self._states:
            if st.name == name:
                return st
        raise KeyError(f"no replica named {name!r}")

    def _accepted_unfinished(self) -> int:
        return sum(1 for r in self._reqs.values() if not r.done)

    def _shed(self, rid: str, reason: str, depth: int) -> None:
        # hint grows with how far over capacity we are, so retrying
        # clients naturally spread out instead of hammering in sync
        retry_after_s = round(
            self.rcfg.poll_interval_s
            * max(1.0, depth / max(1, len(self._states))), 3)
        self.metrics.record_shed()
        trace_instant("serving/shed", _TRACE_LANE, rid=rid,
                      retry_after_s=retry_after_s)
        raise ShedError(rid, reason, retry_after_s)

    def _collect_events(self, now: float) -> None:
        for st in self._states:
            for ev in st.replica.poll_events():
                rid = ev.get("rid")
                rec = self._reqs.get(rid)
                if rec is None:
                    continue
                kind = ev.get("ev")
                if kind == "first":
                    if rec.first_t is None and not rec.done:
                        rec.first_t = now
                        self.metrics.record_ttft(now - rec.submit_t)
                elif kind == "fin":
                    if not rec.done:
                        rec.tokens = list(ev.get("tokens") or [])
                        self._finish_local(rec, ev.get("reason"), now)
                    st.assigned.discard(rid)
                elif kind == "err":
                    # submit bounced (draining race, bad spec): treat
                    # as a dispatch failure and retry elsewhere
                    st.assigned.discard(rid)
                    if not rec.done:
                        self._requeue(rec, penalize=True)

    def _check_health(self, now: float) -> None:
        for st in self._states:
            if st.restart_at is not None:
                if now >= st.restart_at:
                    self._crash_restart(st)
                continue
            if not st.healthy:
                continue   # draining — lifecycle owns this replica
            rep = st.replica
            if rep.progress != st.last_progress:
                st.last_progress = rep.progress
                st.progress_t = now
            cause = None
            if not rep.alive:
                cause = "dead"
            elif now - rep.heartbeat_t > self.rcfg.heartbeat_timeout_s:
                cause = "heartbeat"
            elif (st.assigned
                  and now - st.progress_t > self.rcfg.progress_timeout_s):
                cause = "stalled"
            if cause is not None:
                self._mark_down(st, cause, now)

    def _mark_down(self, st: _ReplicaState, cause: str,
                   now: float) -> None:
        st.healthy = False
        inflight = sorted(st.assigned)
        self.metrics.record_replica_down(st.name, cause, len(inflight))
        trace_instant("serving/replica_down", _TRACE_LANE,
                      replica=st.name, cause=cause,
                      inflight=len(inflight))
        # a stalled/heartbeat-lost replica may still hold the process —
        # kill it so the restart starts from a clean slate
        try:
            st.replica.kill()
        except Exception:  # noqa: BLE001 - it may already be gone
            pass
        for rid in inflight:
            rec = self._reqs[rid]
            if not rec.done:
                self._requeue(rec, penalize=True)
        st.assigned.clear()
        if (self.rcfg.replica_restart
                and st.failure_restarts < self.rcfg.replica_max_restarts):
            st.failure_restarts += 1
            delay = compute_backoff(
                st.failure_restarts, self.rcfg.retry_backoff_base_s,
                2.0, self.rcfg.retry_backoff_max_s)
            st.restart_at = now + delay
        # else: the replica stays down; dispatch routes around it

    def _crash_restart(self, st: _ReplicaState) -> None:
        with trace_span("serving/replica_restart", _TRACE_LANE,
                        replica=st.name):
            try:
                st.replica.restart()
            except Exception:  # noqa: BLE001 - retry on a later step
                if st.failure_restarts < self.rcfg.replica_max_restarts:
                    st.failure_restarts += 1
                    st.restart_at = self.clock() + compute_backoff(
                        st.failure_restarts,
                        self.rcfg.retry_backoff_base_s, 2.0,
                        self.rcfg.retry_backoff_max_s)
                else:
                    st.restart_at = None
                return
        self._mark_restarted(st)

    def _mark_restarted(self, st: _ReplicaState) -> None:
        now = self.clock()
        st.healthy = True
        st.restart_at = None
        st.last_progress = st.replica.progress
        st.progress_t = now

    def _requeue(self, rec: RouterRequest, penalize: bool) -> None:
        """Put an in-flight request back on the dispatch queue after its
        replica failed (penalize=True, charges the retry budget and
        backs off) or drained (penalize=False, immediate)."""
        now = self.clock()
        if penalize and rec.attempts > self.rcfg.retry_max:
            self._finish_local(rec, FINISH_FAILED, now,
                               note="retry budget exhausted")
            return
        if penalize:
            rec.not_before = now + compute_backoff(
                max(1, rec.attempts), self.rcfg.retry_backoff_base_s,
                2.0, self.rcfg.retry_backoff_max_s)
        else:
            rec.not_before = 0.0
        rec.assigned = None
        if rec.rid not in self._pending:
            self._pending.appendleft(rec.rid)
        # the ledger's retry-backoff bucket: [this instant -> the rid's
        # next serving/dispatch] is time the request sat out on purpose
        trace_instant("req/requeue", _TRACE_LANE, rid=rec.rid,
                      backoff_s=round(max(0.0, rec.not_before - now), 6),
                      penalize=bool(penalize))

    def _enforce_deadlines(self, now: float) -> None:
        for rec in self._reqs.values():
            if rec.done or rec.deadline_t is None or now < rec.deadline_t:
                continue
            if rec.assigned is not None:
                try:
                    self._state(rec.assigned).replica.cancel(
                        rec.rid, FINISH_TIMEOUT)
                except (ReplicaUnavailableError, KeyError):
                    pass
                self._state(rec.assigned).assigned.discard(rec.rid)
            if rec.rid in self._pending:
                self._pending.remove(rec.rid)
            self._finish_local(rec, FINISH_TIMEOUT, now,
                               note="router deadline")

    @staticmethod
    def _replica_version(st: _ReplicaState) -> Optional[int]:
        v = getattr(st.replica, "version", None)
        return int(v) if v is not None else None

    def _dispatch(self, now: float) -> None:
        healthy = [st for st in self._states if st.healthy
                   and st.replica.alive]
        if not healthy:
            return
        deferred = []
        while self._pending:
            rid = self._pending.popleft()
            rec = self._reqs[rid]
            if rec.done:
                continue
            if now < rec.not_before:
                deferred.append(rid)
                continue
            pool = healthy
            if rec.version is not None:
                pinned = [st for st in healthy
                          if self._replica_version(st) == rec.version]
                if pinned:
                    pool = pinned
                else:
                    # the pinned version lost its last healthy replica
                    # (rollout completed mid-retry): re-pin and
                    # REGENERATE — every token the client sees comes
                    # from one weight set, never a spliced stream
                    rec.repins += 1
                    trace_instant("lifecycle/repin", "lifecycle",
                                  rid=rid, version=rec.version)
                    if self.metrics.registry is not None:
                        self.metrics.registry.counter(
                            "lifecycle_repin_total",
                            "requests re-pinned after their weight "
                            "version lost its last replica").inc()
                    rec.version = None
            target = min(pool, key=lambda st: len(st.assigned))
            # prefix affinity: same-prefix traffic goes back to the
            # replica whose radix cache is warm for it, unless that
            # replica is more than affinity_load_slack requests above
            # the least-loaded choice (affinity must not build hot
            # spots, and never overrides health — it only picks WITHIN
            # the healthy pool)
            if rec.prefix_key is not None:
                warm_name = self._affinity.get(rec.prefix_key)
                if warm_name is not None and warm_name != target.name:
                    warm = next((st for st in pool
                                 if st.name == warm_name), None)
                    if warm is not None and (
                            len(warm.assigned) <= len(target.assigned)
                            + self.rcfg.affinity_load_slack):
                        target = warm
            try:
                target.replica.submit(rec.spec)
            except ReplicaUnavailableError:
                # replica died between the health check and the submit;
                # the next step's watchdog will mark it down
                deferred.append(rid)
                break
            rec.attempts += 1
            rec.assigned = target.name
            if rec.version is None:
                rec.version = self._replica_version(target)
            if rec.prefix_key is not None:
                self._affinity[rec.prefix_key] = target.name
            target.assigned.add(rid)
            # the flow-arrow source: the aggregator pairs this with the
            # replica-side serving/admit carrying the same rid
            trace_instant("serving/dispatch", _TRACE_LANE, rid=rid,
                          replica=target.name, attempt=rec.attempts)
            if rec.attempts > 1:
                self.metrics.record_retry()
                trace_instant("serving/retry", _TRACE_LANE, rid=rid,
                              attempt=rec.attempts, replica=target.name)
        for rid in reversed(deferred):
            self._pending.appendleft(rid)

    def _finish_local(self, rec: RouterRequest, reason: str, now: float,
                      note: Optional[str] = None) -> None:
        rec.finish_reason = reason
        rec.finish_t = now
        if rec.tokens is None:
            rec.tokens = []
        self._inflight_tokens -= rec.cost_tokens
        self.metrics.record_outcome(reason, now - rec.submit_t)
        args = {"rid": rec.rid, "reason": reason}
        if note:
            args["note"] = note
        trace_instant("serving/finish", _TRACE_LANE, **args)

    def _export_gauges(self) -> None:
        for st in self._states:
            self.metrics.set_replica_gauges(
                st.name, st.healthy and st.replica.alive,
                len(st.assigned))
        self.metrics.set_load_gauges(self._accepted_unfinished(),
                                     self._inflight_tokens)
