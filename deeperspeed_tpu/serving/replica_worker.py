"""Subprocess replica runner: one ServingEngine behind a line-JSON pipe.

``python -m deeperspeed_tpu.serving.replica_worker --spec spec.json``
builds a GPT from the spec (config kwargs + init seed — every replica of
a fleet derives IDENTICAL weights from the same spec, which is what
makes cross-replica retries token-identical) and serves requests over a
newline-delimited JSON protocol:

parent -> child (stdin)::

    {"op": "submit", "rid": ..., "prompt": [...],
     "max_new_tokens": N, "temperature": T, "seed": S}
    {"op": "cancel", "rid": ..., "reason": "timeout"}
    {"op": "drain"}          # reject new work, finish what's in flight
    {"op": "stop"}           # graceful exit

child -> parent (stdout; logs go to stderr, stdout is protocol-only)::

    {"ev": "ready"}                                  # engine warm
    {"ev": "hb", "progress": N, "inflight": [...],
     "draining": bool}                               # every loop turn
    {"ev": "first", "rid": ...}                      # first token out
    {"ev": "fin", "rid": ..., "tokens": [...], "reason": ...}
    {"ev": "err", "rid": ..., "error": ...}          # submit rejected

The worker is where the fleet drill's faults land: it calls
``FaultInjector.on_decode_step`` once per engine step, so
``DS_TPU_FAULTS='{"replica_sigkill_at_decode": 12}'`` kills THIS replica
mid-decode and ``replica_stall_at_decode`` wedges it (alive and
heartbeating, emitting no tokens) — the two failure modes the router's
watchdogs must distinguish.
"""

import argparse
import json
import os
import queue
import sys
import threading
import time
from typing import Optional, Sequence

# the worker always serves on the host platform unless told otherwise —
# replicas are CPU-testable by design (same rationale as serving_bench)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

WARM_RID = "_warm"   # internal warmup request, never reported


def _emit(obj: dict) -> None:
    sys.stdout.write(json.dumps(obj) + "\n")
    sys.stdout.flush()


def _stdin_reader(q: "queue.Queue[Optional[dict]]") -> None:
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            q.put(json.loads(line))
        except json.JSONDecodeError:
            print(f"replica_worker: bad op line {line!r}", file=sys.stderr)
    q.put(None)   # EOF: parent is gone -> orderly exit


def _load_weights(params, weights: dict):
    """Replace init params with a published checkpoint's module tree.

    ``weights`` is the pointer the lifecycle controller pushes through
    ``SubprocessReplica.set_weights``: ``{"load_dir", "tag"}`` naming a
    trainer checkpoint (legacy single-file layout). Every replica pinned
    to the same WeightVersion loads the same bytes, which is what keeps
    version-pinned failover retries token-identical."""
    import os as _os

    from flax import serialization as _ser

    from ..checkpoint.serialization import load_tree, model_state_filename

    path = _os.path.join(str(weights["load_dir"]), str(weights["tag"]),
                         model_state_filename())
    model_states = load_tree(path)
    return _ser.from_state_dict(params, model_states["module"])


def build_engine(spec: dict):
    """GPT + ServingEngine from a replica spec: deterministic init from
    ``init_seed`` so every replica holds the same weights. A ``weights``
    block (``{"load_dir", "tag"}``) swaps in a published checkpoint —
    same determinism, now anchored to the checkpoint bytes instead of
    the init PRNG."""
    import jax
    import jax.numpy as jnp

    from ..models.gpt import GPTConfig, make_gpt
    from .config import ServingConfig
    from .engine import ServingEngine

    gpt_kwargs = dict(spec.get("gpt") or {})
    gpt_kwargs.setdefault("dtype", jnp.float32)
    cfg = GPTConfig(**gpt_kwargs)
    init_fn, _, _, _ = make_gpt(cfg)
    params = init_fn(jax.random.PRNGKey(int(spec.get("init_seed", 0))))
    if spec.get("weights"):
        params = _load_weights(params, spec["weights"])
    scfg = ServingConfig.from_dict(
        {k: v for k, v in (spec.get("serving") or {}).items()
         if k != "fleet"})
    return ServingEngine(cfg, params, scfg)


def serve(spec: dict, injector=None) -> int:
    from ..monitor import init_monitor, shutdown_monitor
    from ..monitor.runctx import current as current_run
    from .engine import EngineDrainingError

    run_ctx = current_run()
    if spec.get("monitor"):
        # before build_engine so warmup compiles and admits are traced;
        # with an obs_dir the paths derive from DS_TPU_ROLE/INCARNATION
        # set by the parent fleet, and the flight recorder makes this
        # worker's tail survive the drill's SIGKILL
        init_monitor(spec["monitor"])

    eng = build_engine(spec)
    if injector is None:
        from ..resilience.faults import FaultInjector, \
            plan_from_config_and_env

        injector = FaultInjector(plan_from_config_and_env(
            spec.get("faults")))

    if spec.get("warm", True):
        # compile the decode program + smallest prefill bucket up front
        # so fault step counts and health timings hit a warm engine; the
        # sampled (temperature > 0) host path compiles separately, so
        # warm both
        rid = eng.submit([1, 2, 3], max_new_tokens=2, request_id=WARM_RID)
        eng.submit([4, 5, 6], max_new_tokens=2, temperature=0.5,
                   request_id=WARM_RID + "2")
        eng.run()
        assert eng.get(rid).state == "finished"

    ops: "queue.Queue[Optional[dict]]" = queue.Queue()
    threading.Thread(target=_stdin_reader, args=(ops,), daemon=True).start()
    _emit({"ev": "ready", "run_id": run_ctx.run_id, "role": run_ctx.role,
           "incarnation": run_ctx.incarnation, "wall_t": time.time()})

    poll_s = float(spec.get("poll_interval_s", 0.002))
    decode_i = 0
    stalled = False
    draining = False
    stopping = False
    first_sent = set()
    reported = set()
    tracked = []   # rids in submission order, for first/fin scans

    while True:
        while True:
            try:
                op = ops.get_nowait()
            except queue.Empty:
                break
            if op is None or op.get("op") == "stop":
                stopping = True
                break
            kind = op.get("op")
            if kind == "submit":
                try:
                    if draining:
                        raise EngineDrainingError("replica draining")
                    eng.submit(op["prompt"],
                               max_new_tokens=op.get("max_new_tokens"),
                               temperature=op.get("temperature", 0.0),
                               request_id=op["rid"],
                               seed=op.get("seed"))
                    tracked.append(op["rid"])
                except Exception as e:  # noqa: BLE001 - reported upstream
                    _emit({"ev": "err", "rid": op.get("rid"),
                           "error": f"{type(e).__name__}: {e}"})
            elif kind == "cancel":
                eng.cancel(op["rid"], op.get("reason", "timeout"))
            elif kind == "drain":
                draining = True
            elif kind == "clock":
                # NTP-style handshake leg: echo the parent's t0 with our
                # wall time so it can estimate this host's clock offset
                _emit({"ev": "clock", "t0": op.get("t0"),
                       "t_child": time.time()})
            else:
                print(f"replica_worker: unknown op {op!r}", file=sys.stderr)
        if stopping:
            break

        if eng.has_work() and not stalled:
            decode_i += 1
            verdict = injector.on_decode_step(decode_i)
            if verdict == "stall":
                stalled = True
            else:
                eng.step()
        else:
            time.sleep(poll_s)

        # report first tokens and finishes in submission order
        for rid in tracked:
            req = eng.get(rid)
            if rid not in first_sent and req.first_token_t is not None:
                first_sent.add(rid)
                _emit({"ev": "first", "rid": rid})
            if rid not in reported and req.state == "finished":
                reported.add(rid)
                _emit({"ev": "fin", "rid": rid, "tokens": req.output,
                       "reason": req.finish_reason})
        inflight = [r for r in tracked if r not in reported]
        _emit({"ev": "hb", "progress": int(eng.metrics.total_generated),
               "inflight": inflight, "draining": draining})
        if draining and not inflight and not eng.has_work():
            break

    shutdown_monitor(save=True)   # graceful exits write the full trace
    _emit({"ev": "bye"})
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeperspeed_tpu.serving.replica_worker")
    ap.add_argument("--spec", required=True,
                    help="JSON replica spec: {gpt: {...GPTConfig kwargs}, "
                         "init_seed, serving: {...ServingConfig}, warm, "
                         "poll_interval_s, faults}")
    args = ap.parse_args(argv)
    with open(args.spec) as f:
        spec = json.load(f)
    return serve(spec)


if __name__ == "__main__":
    sys.exit(main())
