"""Replica workers for the serving fleet: the layer the router drives.

Two interchangeable replica kinds share one duck-typed surface:

  * ``ThreadReplica`` — a ``ServingEngine`` stepped by a daemon thread in
    this process. Cheap enough that CPU tests run 2-4 of them; failure
    modes (``kill()``, ``inject_stall()``) are simulated, so watchdog
    logic is testable without subprocesses.
  * ``SubprocessReplica`` — spawns ``serving.replica_worker`` and talks
    the line-JSON protocol over its stdio. The real thing for kill
    drills: ``kill()`` is an actual SIGKILL, and fault injection
    (``resilience.faults``) fires inside the child.

The shared surface the router (serving/router.py) relies on:

  ``start() / stop() / kill() / restart() / drain(timeout_s)``
  ``submit(spec) / cancel(rid, reason) / poll_events()``
  ``alive`` (bool), ``heartbeat_t`` (router-clock stamp of the last sign
  of life), ``progress`` (monotone decode-token counter), ``restarts``,
  ``inflight_rids()``.

Events from ``poll_events()`` use the worker protocol's shapes:
``{"ev": "first", "rid"}``, ``{"ev": "fin", "rid", "tokens", "reason"}``,
``{"ev": "err", "rid", "error"}``.

Submit specs are plain dicts — ``{"rid", "prompt", "max_new_tokens",
"temperature", "seed"}`` — because they must survive a pipe; the router
keeps the authoritative copy so a replica death never loses the request.
"""

import json
import os
import queue
import subprocess
import sys
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional

from ..monitor.runctx import (
    INCARNATION_ENV,
    ROLE_ENV,
    RUN_ID_ENV,
    ensure_run_id,
    estimate_clock_offset,
)
from .engine import EngineDrainingError

__all__ = [
    "ReplicaUnavailableError", "ThreadReplica", "SubprocessReplica",
    "build_thread_fleet", "build_subprocess_fleet",
]


class ReplicaUnavailableError(RuntimeError):
    """submit()/cancel() hit a replica that is dead, draining, or whose
    pipe is gone. The router treats this as a dispatch failure and
    retries elsewhere; it never reaches end users."""


def _submit_kwargs(spec: dict) -> dict:
    return dict(
        max_new_tokens=spec.get("max_new_tokens"),
        temperature=spec.get("temperature", 0.0),
        request_id=spec["rid"],
        seed=spec.get("seed"),
    )


class ThreadReplica:
    """In-process replica: one engine, one driver thread.

    The engine is single-threaded by design, so ALL engine calls happen
    on the driver thread; ``submit``/``cancel`` enqueue commands. Failure
    simulation mirrors the subprocess worker: ``kill()`` makes the driver
    thread exit abruptly (heartbeats stop, like a SIGKILL), and
    ``inject_stall()`` keeps it heartbeating while never stepping the
    engine (progress freezes, like a wedged accelerator).
    """

    def __init__(self, name: str, engine_factory: Callable[[], object],
                 clock: Callable[[], float] = time.monotonic,
                 poll_interval_s: float = 0.001):
        self.name = name
        self._factory = engine_factory
        self._clock = clock
        self._poll_s = poll_interval_s
        self.restarts = 0
        self.heartbeat_t = float("-inf")
        self.progress = 0
        # weight-version the engine factory builds; the router pins
        # failover retries to this so retried requests never mix
        # token streams from two published versions
        self.version: Optional[int] = None
        # live prefix-cache counters mirrored out of the engine each
        # driver tick (read-only snapshot; the bench sums these across
        # the fleet for its prefix_reuse block)
        self.reuse_stats: Dict[str, int] = {}
        # speculative-decoding counters, same mirror discipline: empty
        # when the engine runs plain decode, else rounds/drafted/
        # accepted/fallback_lanes — the bench and mixed-fleet routing
        # checks read acceptance without touching the engine thread
        self.spec_stats: Dict[str, float] = {}
        self._thread: Optional[threading.Thread] = None
        self._events: "queue.Queue[dict]" = queue.Queue()
        self._cmds: "queue.Queue[dict]" = queue.Queue()
        self._stop_evt = threading.Event()
        self._stall_evt = threading.Event()
        self._die_evt = threading.Event()
        self._ready_evt = threading.Event()
        self._draining = False
        self._lock = threading.Lock()
        self._inflight: List[str] = []

    # -- lifecycle ---------------------------------------------------

    def start(self) -> None:
        if self.alive:
            raise RuntimeError(f"replica {self.name} already running")
        self._stop_evt = threading.Event()
        self._stall_evt = threading.Event()
        self._die_evt = threading.Event()
        self._ready_evt = threading.Event()
        self._cmds = queue.Queue()
        self._draining = False
        with self._lock:
            self._inflight = []
        self.heartbeat_t = self._clock()
        self._thread = threading.Thread(
            target=self._loop, name=f"replica-{self.name}", daemon=True)
        self._thread.start()

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def wait_ready(self, timeout_s: float = 300.0) -> None:
        """Block until the driver thread has built (and, if the factory
        warms it, compiled) its engine — health timeouts shouldn't have
        to budget for XLA compile time."""
        if not self._ready_evt.wait(timeout_s):
            raise RuntimeError(
                f"replica {self.name} engine not ready within "
                f"{timeout_s}s")

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout_s)

    def kill(self) -> None:
        """Simulated SIGKILL: the driver thread exits without cleanup,
        so heartbeats stop and queued commands are dropped on the floor
        — exactly what the router's heartbeat watchdog must notice."""
        self._die_evt.set()

    def inject_stall(self) -> None:
        """Simulated wedge: heartbeats continue, tokens do not."""
        self._stall_evt.set()

    def restart(self) -> None:
        self.kill()
        self.stop(timeout_s=2.0)
        self._thread = None
        self.restarts += 1
        self.progress = 0
        self.start()
        self.wait_ready()

    def set_weights(self, weights, version: int) -> None:
        """Stage a weight push; takes effect at the next ``restart()``
        (the driver thread rebuilds its engine from the factory). For
        thread replicas ``weights`` is a replacement zero-arg engine
        factory — in-process fleets share memory, so there is nothing
        to serialize — or None to bump the version label only.

        A (target, drafter) PAIR push is a dict ``{"factory": ...,
        "drafter_params": ...}``: the target factory (optional) stages
        for the next restart as before, while the drafter weights are
        hot-swapped on the driver thread via
        ``engine.set_drafter_params`` — same drafter config, so the
        compiled draft program survives the swap."""
        if isinstance(weights, dict) and (
                "factory" in weights or "drafter_params" in weights):
            if weights.get("factory") is not None:
                self._factory = weights["factory"]
            if weights.get("drafter_params") is not None and self.alive:
                self._cmds.put({"op": "drafter",
                                "params": weights["drafter_params"]})
        elif weights is not None:
            self._factory = weights
        self.version = int(version)

    def drain(self, timeout_s: float = 30.0) -> List[str]:
        """Reject new submits, wait for in-flight work to finish.
        Returns the rids still unfinished at timeout (router requeues
        them)."""
        self._draining = True
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if not self.alive or not self.inflight_rids():
                break
            time.sleep(self._poll_s)
        return self.inflight_rids()

    # -- work --------------------------------------------------------

    def submit(self, spec: dict) -> None:
        if not self.alive:
            raise ReplicaUnavailableError(f"replica {self.name} is down")
        if self._draining:
            raise ReplicaUnavailableError(f"replica {self.name} draining")
        self._cmds.put({"op": "submit", "spec": dict(spec)})

    def cancel(self, rid: str, reason: str = "timeout") -> None:
        if self.alive:
            self._cmds.put({"op": "cancel", "rid": rid, "reason": reason})

    def poll_events(self) -> List[dict]:
        out = []
        while True:
            try:
                out.append(self._events.get_nowait())
            except queue.Empty:
                return out

    def inflight_rids(self) -> List[str]:
        with self._lock:
            return list(self._inflight)

    # -- driver thread ----------------------------------------------

    def _loop(self) -> None:
        eng = self._factory()
        self._ready_evt.set()
        tracked: List[str] = []
        first_sent: set = set()
        reported: set = set()
        while not self._stop_evt.is_set():
            if self._die_evt.is_set():
                return   # abrupt death: no final heartbeat, no cleanup
            self.heartbeat_t = self._clock()
            while True:
                try:
                    cmd = self._cmds.get_nowait()
                except queue.Empty:
                    break
                if cmd["op"] == "submit":
                    spec = cmd["spec"]
                    try:
                        eng.submit(spec["prompt"], **_submit_kwargs(spec))
                        tracked.append(spec["rid"])
                    except Exception as e:  # noqa: BLE001 - to router
                        self._events.put(
                            {"ev": "err", "rid": spec.get("rid"),
                             "error": f"{type(e).__name__}: {e}"})
                elif cmd["op"] == "cancel":
                    eng.cancel(cmd["rid"], cmd["reason"])
                elif cmd["op"] == "drafter":
                    try:
                        eng.set_drafter_params(cmd["params"])
                    except Exception as e:  # noqa: BLE001 - to router
                        self._events.put(
                            {"ev": "err", "rid": None,
                             "error": f"{type(e).__name__}: {e}"})
            if eng.has_work() and not self._stall_evt.is_set():
                eng.step()
            else:
                time.sleep(self._poll_s)
            self.progress = int(eng.metrics.total_generated)
            m = eng.metrics
            if hasattr(m, "reuse_hits"):
                self.reuse_stats = {
                    "admissions": int(m.admissions),
                    "reuse_hits": int(m.reuse_hits),
                    "prefill_tokens": int(m.prefill_tokens),
                    "tokens_saved": int(m.tokens_saved),
                    "cow_splits": int(m.cow_splits),
                    "prefill_chunks": int(m.prefill_chunks),
                }
            if getattr(m, "spec_rounds", 0):
                self.spec_stats = {
                    "rounds": int(m.spec_rounds),
                    "drafted": int(m.spec_drafted),
                    "accepted": int(m.spec_accepted),
                    "emitted": int(m.spec_emitted),
                    "fallback_lanes": int(m.spec_fallback_lanes),
                    "accept_rate": (m.spec_accepted / m.spec_drafted
                                    if m.spec_drafted else 0.0),
                }
            for rid in tracked:
                req = eng.get(rid)
                if rid not in first_sent and req.first_token_t is not None:
                    first_sent.add(rid)
                    self._events.put({"ev": "first", "rid": rid})
                if rid not in reported and req.state == "finished":
                    reported.add(rid)
                    self._events.put(
                        {"ev": "fin", "rid": rid, "tokens": req.output,
                         "reason": req.finish_reason})
            with self._lock:
                self._inflight = [r for r in tracked if r not in reported]


class SubprocessReplica:
    """Out-of-process replica: spawns ``serving.replica_worker`` and
    mirrors its stdout protocol into ``poll_events()``. ``kill()`` is a
    real SIGKILL; fault injection runs in the child via the spec's
    ``faults`` block (or the child's ``DS_TPU_FAULTS`` env)."""

    def __init__(self, name: str, spec: dict,
                 clock: Callable[[], float] = time.monotonic,
                 env: Optional[Dict[str, str]] = None,
                 ready_timeout_s: float = 300.0,
                 workdir: Optional[str] = None):
        self.name = name
        self._spec = dict(spec)
        self._clock = clock
        self._env = dict(env or {})
        self._ready_timeout_s = ready_timeout_s
        self._workdir = workdir or tempfile.mkdtemp(
            prefix=f"replica-{name}-")
        self.restarts = 0
        self.heartbeat_t = float("-inf")
        self.progress = 0
        # published WeightVersion this worker serves (spec-driven so a
        # restart rebuilds the same engine); router pins retries to it
        wv = self._spec.get("weights_version")
        self.version: Optional[int] = int(wv) if wv is not None else None
        # wall-clock skew measured by the post-ready handshake: how far
        # the child's clock runs ahead of ours (seconds); feeds the
        # trace aggregator's --offsets alignment
        self.clock_offset_s: Optional[float] = None
        self._proc: Optional[subprocess.Popen] = None
        self._reader: Optional[threading.Thread] = None
        self._events: "queue.Queue[dict]" = queue.Queue()
        self._ready_evt = threading.Event()
        self._stdin_lock = threading.Lock()
        self._hb_lock = threading.Lock()
        self._inflight: List[str] = []
        self._draining = False

    @property
    def stderr_path(self) -> str:
        return os.path.join(self._workdir, f"{self.name}.stderr.log")

    # -- lifecycle ---------------------------------------------------

    def start(self) -> None:
        if self.alive:
            raise RuntimeError(f"replica {self.name} already running")
        spec_path = os.path.join(self._workdir, f"{self.name}.spec.json")
        with open(spec_path, "w") as f:
            json.dump(self._spec, f)
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        # run-scoped observability: the child's trace lane is labeled by
        # role + incarnation, correlated to ours by the shared run id
        env[RUN_ID_ENV] = ensure_run_id()
        env[ROLE_ENV] = f"replica-{self.name}"
        env[INCARNATION_ENV] = str(self.restarts)
        env.update(self._env)
        self._ready_evt = threading.Event()
        self._draining = False
        with self._hb_lock:
            self._inflight = []
        stderr = open(self.stderr_path, "ab")
        try:
            self._proc = subprocess.Popen(
                [sys.executable, "-m",
                 "deeperspeed_tpu.serving.replica_worker",
                 "--spec", spec_path],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=stderr, env=env, text=True)
        finally:
            stderr.close()
        self._reader = threading.Thread(
            target=self._read_stdout, args=(self._proc,),
            name=f"replica-{self.name}-reader", daemon=True)
        self._reader.start()
        deadline = time.monotonic() + self._ready_timeout_s
        while not self._ready_evt.is_set():
            if self._proc.poll() is not None:
                raise RuntimeError(
                    f"replica {self.name} exited rc={self._proc.returncode} "
                    f"before ready; see {self.stderr_path}")
            if time.monotonic() > deadline:
                self._proc.kill()
                raise RuntimeError(
                    f"replica {self.name} not ready within "
                    f"{self._ready_timeout_s}s; see {self.stderr_path}")
            time.sleep(0.01)
        self.heartbeat_t = self._clock()
        # NTP-style clock handshake: t0 here, t_child there, t1 here;
        # the reply is matched in _read_stdout. Best-effort — a replica
        # that dies mid-handshake just stays unaligned.
        try:
            self._send({"op": "clock", "t0": time.time()})
        except ReplicaUnavailableError:
            pass

    @property
    def alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    def stop(self, timeout_s: float = 10.0) -> None:
        if self._proc is None:
            return
        if self.alive:
            try:
                self._send({"op": "stop"})
            except ReplicaUnavailableError:
                pass
            try:
                self._proc.wait(timeout_s)
            except subprocess.TimeoutExpired:
                self._proc.kill()
                self._proc.wait(5.0)

    def kill(self) -> None:
        """Real SIGKILL — no flushes, no goodbyes."""
        if self._proc is not None and self._proc.poll() is None:
            self._proc.kill()

    def restart(self) -> None:
        self.kill()
        if self._proc is not None:
            self._proc.wait(10.0)
        self._proc = None
        self.restarts += 1
        self.progress = 0
        self.start()

    def set_weights(self, weights: Optional[dict], version: int) -> None:
        """Stage a weight push; takes effect at the next ``restart()``
        (``start()`` rewrites spec.json from ``self._spec``). ``weights``
        is the worker's checkpoint pointer — ``{"load_dir", "tag"}``,
        plus a ``drafter_tag`` entry when the published version pairs a
        drafter with the target — or None to bump the version label
        only."""
        if weights is not None:
            self._spec["weights"] = dict(weights)
        self._spec["weights_version"] = int(version)
        self.version = int(version)

    def drain(self, timeout_s: float = 30.0) -> List[str]:
        self._draining = True
        try:
            self._send({"op": "drain"})
        except ReplicaUnavailableError:
            return self.inflight_rids()
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if not self.alive or not self.inflight_rids():
                break
            time.sleep(0.01)
        return self.inflight_rids()

    # -- work --------------------------------------------------------

    def submit(self, spec: dict) -> None:
        if self._draining:
            raise ReplicaUnavailableError(f"replica {self.name} draining")
        self._send({"op": "submit", **spec})

    def cancel(self, rid: str, reason: str = "timeout") -> None:
        try:
            self._send({"op": "cancel", "rid": rid, "reason": reason})
        except ReplicaUnavailableError:
            pass   # a dead replica has no work to cancel

    def poll_events(self) -> List[dict]:
        out = []
        while True:
            try:
                out.append(self._events.get_nowait())
            except queue.Empty:
                return out

    def inflight_rids(self) -> List[str]:
        with self._hb_lock:
            return list(self._inflight)

    # -- plumbing ----------------------------------------------------

    def _send(self, op: dict) -> None:
        if not self.alive:
            raise ReplicaUnavailableError(f"replica {self.name} is down")
        try:
            with self._stdin_lock:
                self._proc.stdin.write(json.dumps(op) + "\n")
                self._proc.stdin.flush()
        except (BrokenPipeError, OSError) as e:
            raise ReplicaUnavailableError(
                f"replica {self.name} pipe broken: {e}") from e

    def _read_stdout(self, proc: subprocess.Popen) -> None:
        for line in proc.stdout:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                continue   # not protocol (stray library print) — skip
            kind = ev.get("ev")
            if kind == "hb":
                self.heartbeat_t = self._clock()
                self.progress = int(ev.get("progress", self.progress))
                with self._hb_lock:
                    self._inflight = list(ev.get("inflight", []))
            elif kind == "ready":
                self.heartbeat_t = self._clock()
                self._ready_evt.set()
            elif kind == "clock":
                t0 = ev.get("t0")
                t_child = ev.get("t_child")
                if isinstance(t0, (int, float)) and isinstance(
                        t_child, (int, float)):
                    self.clock_offset_s = estimate_clock_offset(
                        t0, t_child, time.time())
            elif kind == "bye":
                pass
            else:
                self._events.put(ev)


def build_thread_fleet(num_replicas: int,
                       engine_factory: Callable[[], object],
                       clock: Callable[[], float] = time.monotonic,
                       poll_interval_s: float = 0.001,
                       ) -> List[ThreadReplica]:
    """N started in-process replicas over one engine factory. The
    factory must build engines with IDENTICAL weights and config, or
    failover retries will not be token-identical."""
    fleet = [ThreadReplica(f"r{i}", engine_factory, clock=clock,
                           poll_interval_s=poll_interval_s)
             for i in range(num_replicas)]
    for rep in fleet:
        rep.start()
    for rep in fleet:   # engines compile concurrently; wait for all
        rep.wait_ready()
    return fleet


def build_subprocess_fleet(num_replicas: int, spec: dict,
                           faults: Optional[Dict[int, dict]] = None,
                           env: Optional[Dict[str, str]] = None,
                           clock: Callable[[], float] = time.monotonic,
                           workdir: Optional[str] = None,
                           ) -> List[SubprocessReplica]:
    """N started subprocess replicas from one shared spec. ``faults``
    maps replica index -> fault-plan dict injected into that replica
    only (how a drill SIGKILLs replica 1 while replica 0 stays clean).
    Replicas start sequentially — each compiles the same tiny model, and
    concurrent cold starts on CPU just thrash."""
    fleet = []
    for i in range(num_replicas):
        rspec = dict(spec)
        if faults and i in faults:
            rspec["faults"] = dict(faults[i])
        rep = SubprocessReplica(f"r{i}", rspec, clock=clock, env=env,
                                workdir=workdir)
        rep.start()
        fleet.append(rep)
    return fleet
