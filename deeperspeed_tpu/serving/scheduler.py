"""Continuous-batching scheduler: admission, slot + block accounting,
eviction, preemption, backpressure.

Policy (deliberately simple and deterministic — the decode step is where
the hardware time goes, and a deterministic scheduler is what makes the
greedy-parity test meaningful):

  * FIFO admission with head-of-line blocking: the queue head is admitted
    when a slot is free AND the allocator can cover its context plus one
    decode write; otherwise admission stops (backpressure — the request
    STAYS QUEUED, nothing crashes).
  * Blocks are allocated incrementally: admission covers the prompt, and
    each time a slot's next write would cross a block boundary the
    scheduler allocates one more block. No request ever reserves
    max_seq_len worth of cache up front.
  * When the pool cannot cover a mid-decode extension, the MOST RECENTLY
    admitted slot is preempted: its blocks are freed and the request goes
    back to the FRONT of the queue carrying its generated tokens, so
    re-admission prefills prompt+generated and continues exactly where it
    left off (token-identical for greedy; sampling resumes with fresh
    keys).
  * Eviction on EOS, on exhausting max_new_tokens, and on
    request_timeout_s (queued or running; partial output is kept).
"""

import dataclasses
import itertools
import time
from collections import deque
from typing import Callable, Deque, List, Optional, Tuple

from ..monitor.tracer import trace_instant
from ..utils.logging import logger
from .config import ServingConfig
from .kv_cache import NULL_BLOCK, BlockAllocator, PrefixCache, \
    blocks_needed

QUEUED = "queued"
ACTIVE = "active"
FINISHED = "finished"

FINISH_LENGTH = "length"      # exhausted max_new_tokens
FINISH_EOS = "eos"
FINISH_TIMEOUT = "timeout"
# router-layer outcomes (serving/router.py) — kept here so every finish
# reason shares one namespace and one serving_finish_total label set
FINISH_SHED = "shed"          # rejected at admission (overload)
FINISH_RETRIED = "retried"    # attempt lost to a replica failure; requeued
FINISH_FAILED = "failed"      # retry budget exhausted


@dataclasses.dataclass
class Request:
    rid: str
    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    arrival_t: float = 0.0
    # per-request sampling seed: sampled tokens are a pure function of
    # (seed, token index), so a retried request replays its exact stream
    # on any replica; None = derive from (engine seed, rid) at submit
    seed: Optional[int] = None
    # -- runtime state --
    state: str = QUEUED
    generated: List[int] = dataclasses.field(default_factory=list)
    slot: int = -1
    cached_len: int = 0           # tokens whose KV is written to the pool
    admissions: int = 0           # 1 + number of preemption re-admissions
    # cost-ledger accounting: integral of (blocks held × seconds held),
    # accrued at every block-count change point while the request holds
    # a slot — the per-request share of the paged pool
    kv_block_s: float = 0.0
    kv_accrue_t: Optional[float] = None
    first_token_t: Optional[float] = None
    last_token_t: Optional[float] = None   # progress clock for timeouts
    finish_t: Optional[float] = None
    finish_reason: Optional[str] = None
    # prefix reuse (set per admission, cleared on preemption): tokens
    # matched in the radix cache, how many table entries are shared
    # read-only blocks, and the CoW source (block, rows) when the match
    # ends mid-block — the engine copies those rows at prefill time
    prefix_matched: int = 0
    prefix_shared_blocks: int = 0
    prefix_src: Optional[Tuple[int, int]] = None

    @property
    def context(self) -> List[int]:
        """Tokens to prefill on (re)admission: the original prompt plus
        anything generated before a preemption."""
        return self.prompt + self.generated

    @property
    def remaining(self) -> int:
        return self.max_new_tokens - len(self.generated)

    @property
    def pending_token(self) -> int:
        """The last generated token — fed to the next decode step, whose
        KV row is not yet in the pool."""
        return self.generated[-1]

    @property
    def output(self) -> List[int]:
        return list(self.generated)


class Scheduler:
    """Owns the slot array, the per-slot block lists, and the queue."""

    def __init__(self, scfg: ServingConfig, allocator: BlockAllocator,
                 clock: Callable[[], float] = time.monotonic):
        self.scfg = scfg
        self.allocator = allocator
        # radix prompt index: admissions match their longest cached
        # prefix and share those blocks read-only (refcounted)
        self.prefix_cache: Optional[PrefixCache] = (
            PrefixCache(allocator, scfg.block_size)
            if scfg.prefix_caching else None)
        self.clock = clock
        self.queue: Deque[Request] = deque()
        self.slots: List[Optional[Request]] = [None] * scfg.num_slots
        self.slot_blocks: List[List[int]] = [[] for _ in range(scfg.num_slots)]
        self._admit_seq = itertools.count()   # admission order, for victims
        self._slot_admitted_at = [-1] * scfg.num_slots
        self.finished: List[Request] = []

    # ---------------------------------------------------------------- #
    # queue / admission
    # ---------------------------------------------------------------- #

    def submit(self, req: Request) -> None:
        if not req.prompt:
            raise ValueError(f"request {req.rid}: empty prompt")
        if req.max_new_tokens < 1:
            raise ValueError(
                f"request {req.rid}: max_new_tokens must be >= 1"
            )
        ctx_cap = len(req.prompt) + req.max_new_tokens
        if ctx_cap > self.scfg.max_seq_len:
            raise ValueError(
                f"request {req.rid}: prompt ({len(req.prompt)}) + "
                f"max_new_tokens ({req.max_new_tokens}) = {ctx_cap} exceeds "
                f"max_seq_len ({self.scfg.max_seq_len})"
            )
        # worst-case footprint (full context + one decode-write of
        # headroom) must fit an EMPTY pool, else the request could never
        # admit and the engine would spin forever on backpressure
        worst = blocks_needed(ctx_cap, self.scfg.block_size)
        if worst > self.allocator.num_blocks - 1:
            raise ValueError(
                f"request {req.rid}: worst-case footprint ({worst} blocks "
                f"of {self.scfg.block_size}) exceeds the pool "
                f"({self.allocator.num_blocks - 1} usable blocks); raise "
                f"num_blocks or lower max_new_tokens"
            )
        self.queue.append(req)

    @property
    def num_active(self) -> int:
        return sum(r is not None for r in self.slots)

    @property
    def active(self) -> List[Request]:
        return [r for r in self.slots if r is not None]

    def has_work(self) -> bool:
        return bool(self.queue) or self.num_active > 0

    def _match_prefix(self, req: Request):
        """Longest cached prefix of the request's context, degraded to
        no-match when the bucket table cannot shape a suffix prefill for
        it (the engine would have to fall back to a full prefill, which
        must then own every block)."""
        if self.prefix_cache is None:
            return 0, [], None
        ctx = req.context
        matched, full, partial = self.prefix_cache.match(ctx)
        if matched and self.scfg.prefill_plan(len(ctx), matched) is None:
            return 0, [], None
        return matched, full, partial

    def pop_admissible(self):
        """(slot, request, blocks) for the queue head, or None when no
        slot is free / the pool cannot cover its context + one decode
        write (backpressure: the head stays queued).

        With prefix caching on, the head is admitted by its longest
        cached prefix: matched full blocks are ref'd and mapped into the
        table read-only (table order == logical page order), and only
        the remaining pages are allocated privately. The CoW source of a
        mid-block match is ref'd too, released by the engine (or by
        preemption/finish) once its rows are copied."""
        if not self.queue:
            return None
        try:
            slot = self.slots.index(None)
        except ValueError:
            return None
        req = self.queue[0]
        matched, full, partial = self._match_prefix(req)
        # ref shared blocks BEFORE allocating: alloc may reclaim
        # cache-only blocks, and a matched block must not be evictable
        # between the match and the table mapping
        for b in full:
            self.allocator.ref(b)
        if partial is not None:
            self.allocator.ref(partial[0])
        # +1: headroom for the first decode write, so a freshly admitted
        # request cannot be preempted before its first step
        need = blocks_needed(len(req.context) + 1, self.scfg.block_size)
        private = self.allocator.alloc(need - len(full))
        if private is None:
            if full:
                self.allocator.free(full)
            if partial is not None:
                self.allocator.free([partial[0]])
            return None
        blocks = full + private
        self.queue.popleft()
        req.state = ACTIVE
        req.slot = slot
        req.cached_len = len(req.context)
        req.admissions += 1
        req.kv_accrue_t = self.clock()
        req.prefix_matched = matched
        req.prefix_shared_blocks = len(full)
        req.prefix_src = partial
        self.slots[slot] = req
        self.slot_blocks[slot] = blocks
        self._slot_admitted_at[slot] = next(self._admit_seq)
        trace_instant("serving/admit", lane="serving", rid=req.rid,
                      slot=slot, ctx_len=req.cached_len,
                      admissions=req.admissions)
        if matched > 0:
            trace_instant("kv/reuse", lane="serving", rid=req.rid,
                          matched_tokens=matched,
                          shared_blocks=len(full),
                          ctx_len=len(req.context))
        return slot, req, blocks

    def release_prefix_src(self, req: Request) -> None:
        """Drop the admission-time ref on the CoW source block; called
        by the engine after the copy, and by preemption/finish when the
        request leaves its slot with the copy still pending."""
        if req.prefix_src is not None:
            self.allocator.free([req.prefix_src[0]])
            req.prefix_src = None

    # ---------------------------------------------------------------- #
    # decode-time capacity
    # ---------------------------------------------------------------- #

    def ensure_decode_capacity(self, tokens: int = 1) -> List[Request]:
        """Grow each active slot's block list to cover its next
        ``tokens`` writes (1 for plain decode; a speculative round asks
        for draft_k + 1, capped at the slot's table capacity); preempt
        most-recently-admitted slots when the pool runs dry. Returns the
        preempted requests (already requeued)."""
        cap = self.scfg.blocks_per_slot * self.scfg.block_size
        preempted: List[Request] = []
        for slot in range(self.scfg.num_slots):
            while True:
                req = self.slots[slot]
                if req is None:
                    break
                need = blocks_needed(min(req.cached_len + tokens, cap),
                                     self.scfg.block_size)
                short = need - len(self.slot_blocks[slot])
                if short <= 0:
                    break
                extra = self.allocator.alloc(short)
                if extra is not None:
                    self._accrue_kv(slot)
                    self.slot_blocks[slot].extend(extra)
                    break
                victim = self._preempt_victim()
                preempted.append(self._preempt(victim))
                # if we preempted THIS slot, the inner while re-checks and
                # finds it empty; otherwise retry the alloc
        return preempted

    def _preempt_victim(self) -> int:
        victims = [s for s in range(self.scfg.num_slots)
                   if self.slots[s] is not None]
        assert victims, "ensure_decode_capacity with no active slots"
        return max(victims, key=lambda s: self._slot_admitted_at[s])

    def _preempt(self, slot: int) -> Request:
        req = self.slots[slot]
        logger.info(
            "serving: preempting request %s from slot %d (%d blocks freed)",
            req.rid, slot, len(self.slot_blocks[slot]),
        )
        trace_instant("serving/preempt", lane="serving", rid=req.rid,
                      slot=slot, blocks_freed=len(self.slot_blocks[slot]))
        self._accrue_kv(slot)
        req.kv_accrue_t = None
        self.release_prefix_src(req)
        self._release_slot(slot)
        req.state = QUEUED
        req.slot = -1
        req.cached_len = 0
        req.prefix_matched = 0
        req.prefix_shared_blocks = 0
        self.queue.appendleft(req)
        return req

    # ---------------------------------------------------------------- #
    # eviction
    # ---------------------------------------------------------------- #

    def _release_slot(self, slot: int) -> None:
        self.allocator.free(self.slot_blocks[slot])
        self.slot_blocks[slot] = []
        self.slots[slot] = None
        self._slot_admitted_at[slot] = -1

    def _accrue_kv(self, slot: int) -> None:
        """Charge the slot's request for the blocks it held since the
        last change point (admission, block growth, preemption, finish).
        Block-seconds, not blocks: the cost ledger's KV-occupancy axis."""
        req = self.slots[slot]
        if req is None or req.kv_accrue_t is None:
            return
        now = self.clock()
        req.kv_block_s += ((now - req.kv_accrue_t)
                           * len(self.slot_blocks[slot]))
        req.kv_accrue_t = now

    def finish(self, req: Request, reason: str,
               now: Optional[float] = None) -> None:
        if req.state == ACTIVE:
            self._accrue_kv(req.slot)
            req.kv_accrue_t = None
            self.release_prefix_src(req)
            self._release_slot(req.slot)
        elif req.state == QUEUED:
            self.queue.remove(req)
        req.state = FINISHED
        req.slot = -1
        req.finish_reason = reason
        req.finish_t = self.clock() if now is None else now
        self.finished.append(req)
        trace_instant("serving/finish", lane="serving", rid=req.rid,
                      reason=reason, tokens=len(req.generated),
                      admissions=req.admissions,
                      kv_block_s=round(req.kv_block_s, 6))

    def check_finished(self, req: Request,
                       now: Optional[float] = None) -> bool:
        """Finish ``req`` if its last generated token ends it."""
        eos = self.scfg.eos_token_id
        if eos is not None and req.generated and req.pending_token == eos:
            self.finish(req, FINISH_EOS, now)
            return True
        if req.remaining <= 0:
            self.finish(req, FINISH_LENGTH, now)
            return True
        return False

    def expire_timeouts(self, now: float) -> List[Request]:
        """Evict requests that made no progress for request_timeout_s.

        Progress-based, not age-based: an ACTIVE request emitting tokens
        at a steady clip never expires here no matter how long it runs —
        wall-clock deadlines are the router layer's job
        (serving/router.py). A queued request never progresses, so for it
        this degenerates to time-since-arrival, which keeps the original
        stuck-in-queue eviction semantics."""
        timeout = self.scfg.request_timeout_s
        if timeout is None:
            return []
        expired = [
            r for r in list(self.queue) + self.active
            if now - (r.last_token_t if r.last_token_t is not None
                      else r.arrival_t) >= timeout
        ]
        for r in expired:
            self.finish(r, FINISH_TIMEOUT, now)
        return expired

    # ---------------------------------------------------------------- #
    # decode-step views
    # ---------------------------------------------------------------- #

    def slot_table_row(self, slot: int) -> List[int]:
        blocks = self.slot_blocks[slot]
        pad = self.scfg.blocks_per_slot - len(blocks)
        assert pad >= 0, (slot, blocks)
        return blocks + [NULL_BLOCK] * pad
