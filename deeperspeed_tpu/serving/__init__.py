"""Continuous-batching inference serving (the inference half of the
roadmap's north star).

``ServingEngine`` turns concurrent requests into efficient fixed-shape
decode batches over a slot pool backed by a paged KV cache;
``PipelineServingBridge`` exposes the same surface over
``PipelineEngine.inference_batch`` for pipelined models. On top of
single engines, the fleet layer (``FleetRouter`` over
``ThreadReplica``/``SubprocessReplica`` workers) adds admission
control, wall-clock deadlines, health-checked failover, and rolling
restarts. See docs/tutorials/serving.md for the walkthrough.
"""

from .config import RouterConfig, ServingConfig, SLOConfig
from .engine import (
    EngineDrainingError,
    PipelineServingBridge,
    ServingEngine,
    derive_request_seed,
    make_decode_step,
    request_sample_key,
)
from .fleet import (
    ReplicaUnavailableError,
    SubprocessReplica,
    ThreadReplica,
    build_subprocess_fleet,
    build_thread_fleet,
)
from .kv_cache import BlockAllocator, PagedKVCache, blocks_needed
from .metrics import FleetMetrics, ServingMetrics, SLOTracker
from .router import FleetRouter, RouterRequest, ShedError
from .scheduler import (
    FINISH_EOS,
    FINISH_FAILED,
    FINISH_LENGTH,
    FINISH_RETRIED,
    FINISH_SHED,
    FINISH_TIMEOUT,
    Request,
    Scheduler,
)

__all__ = [
    "ServingConfig",
    "RouterConfig",
    "SLOConfig",
    "SLOTracker",
    "ServingEngine",
    "PipelineServingBridge",
    "EngineDrainingError",
    "make_decode_step",
    "derive_request_seed",
    "request_sample_key",
    "BlockAllocator",
    "PagedKVCache",
    "blocks_needed",
    "ServingMetrics",
    "FleetMetrics",
    "Scheduler",
    "Request",
    "FleetRouter",
    "RouterRequest",
    "ShedError",
    "ThreadReplica",
    "SubprocessReplica",
    "ReplicaUnavailableError",
    "build_thread_fleet",
    "build_subprocess_fleet",
    "FINISH_EOS",
    "FINISH_LENGTH",
    "FINISH_TIMEOUT",
    "FINISH_SHED",
    "FINISH_RETRIED",
    "FINISH_FAILED",
]
