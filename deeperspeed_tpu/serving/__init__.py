"""Continuous-batching inference serving (the inference half of the
roadmap's north star).

``ServingEngine`` turns concurrent requests into efficient fixed-shape
decode batches over a slot pool backed by a paged KV cache;
``PipelineServingBridge`` exposes the same surface over
``PipelineEngine.inference_batch`` for pipelined models. See
docs/tutorials/serving.md for the walkthrough.
"""

from .config import ServingConfig
from .engine import PipelineServingBridge, ServingEngine, make_decode_step
from .kv_cache import BlockAllocator, PagedKVCache, blocks_needed
from .metrics import ServingMetrics
from .scheduler import (
    FINISH_EOS,
    FINISH_LENGTH,
    FINISH_TIMEOUT,
    Request,
    Scheduler,
)

__all__ = [
    "ServingConfig",
    "ServingEngine",
    "PipelineServingBridge",
    "make_decode_step",
    "BlockAllocator",
    "PagedKVCache",
    "blocks_needed",
    "ServingMetrics",
    "Scheduler",
    "Request",
    "FINISH_EOS",
    "FINISH_LENGTH",
    "FINISH_TIMEOUT",
]
