"""Serving metrics: per-request TTFT/TPOT, queue depth, slot occupancy,
tokens/s.

Collection is host-side and allocation-light (floats appended to lists);
export goes through the same surfaces the training engine uses —
``utils/timer.SynchronizedWallClockTimer`` for the prefill/decode wall
clocks and ``utils/tensorboard.TensorBoardMonitor`` for scalar series —
so serving shows up in the exact dashboards training already feeds.
"""

import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..monitor.metrics import DEFAULT_LATENCY_BUCKETS, MetricsRegistry
from ..utils.tensorboard import TensorBoardMonitor
from ..utils.timer import SynchronizedWallClockTimer

# timer names (appear in SynchronizedWallClockTimer.log output)
PREFILL_TIMER = "serving/prefill"
DECODE_TIMER = "serving/decode"


def _percentiles(xs: List[float]) -> Dict[str, float]:
    if not xs:
        return {"p50": 0.0, "p99": 0.0, "mean": 0.0, "max": 0.0}
    a = np.asarray(xs, np.float64)
    return {
        "p50": float(np.percentile(a, 50)),
        "p99": float(np.percentile(a, 99)),
        "mean": float(a.mean()),
        "max": float(a.max()),
    }


class ServingMetrics:
    def __init__(self, num_slots: int,
                 clock: Callable[[], float] = time.monotonic,
                 monitor: Optional[TensorBoardMonitor] = None,
                 registry: Optional[MetricsRegistry] = None):
        self.num_slots = num_slots
        self.clock = clock
        self.monitor = monitor
        self.registry = registry
        self.timers = SynchronizedWallClockTimer()
        self.ttft_s: List[float] = []
        self.tpot_s: List[float] = []
        self.queue_depth: List[int] = []
        self.occupancy: List[float] = []
        self.total_generated = 0
        self.decode_steps = 0
        self.prefills = 0
        self.preemptions = 0
        self.finished: Dict[str, int] = {}
        self._start_t: Optional[float] = None
        self._end_t: Optional[float] = None
        if registry is not None:
            self._c_tokens = registry.counter(
                "serving_tokens_generated_total",
                "Tokens emitted (prefill first-tokens + decode tokens).")
            self._c_prefills = registry.counter(
                "serving_prefills_total", "Prefill launches (admissions).")
            self._c_decode = registry.counter(
                "serving_decode_steps_total", "Batched decode steps.")
            self._c_preempt = registry.counter(
                "serving_preemptions_total",
                "Requests preempted back to the queue.")
            self._g_queue = registry.gauge(
                "serving_queue_depth", "Requests waiting for admission.")
            self._g_active = registry.gauge(
                "serving_active_slots", "Slots currently running a request.")
            self._g_occ = registry.gauge(
                "serving_slot_occupancy",
                "Active slots / num_slots at the last decode step.")
            self._h_ttft = registry.histogram(
                "serving_ttft_seconds", "Time to first token.",
                buckets=DEFAULT_LATENCY_BUCKETS)
            self._h_tpot = registry.histogram(
                "serving_tpot_seconds", "Time per output token (per-request "
                "mean, recorded at finish).",
                buckets=DEFAULT_LATENCY_BUCKETS)

    # ------------------------------------------------------------ #
    # recording
    # ------------------------------------------------------------ #

    def record_prefill(self, now: float,
                       ttft_s: Optional[float] = None) -> None:
        """One prefill (it emits one token). ttft_s is set only for a
        request's FIRST admission — preemption re-prefills don't re-count
        time-to-first-token."""
        if self._start_t is None:
            self._start_t = now
        self.prefills += 1
        self.total_generated += 1
        if ttft_s is not None:
            self.ttft_s.append(ttft_s)
        self._end_t = now
        if self.registry is not None:
            self._c_prefills.inc()
            self._c_tokens.inc()
            if ttft_s is not None:
                self._h_ttft.observe(ttft_s)

    def record_decode_step(self, n_active: int, queue_depth: int,
                           now: float) -> None:
        if self._start_t is None:
            self._start_t = now
        self.decode_steps += 1
        self.total_generated += n_active
        self.queue_depth.append(queue_depth)
        self.occupancy.append(n_active / self.num_slots)
        self._end_t = now
        if self.registry is not None:
            self._c_decode.inc()
            self._c_tokens.inc(n_active)
            self._g_queue.set(queue_depth)
            self._g_active.set(n_active)
            self._g_occ.set(n_active / self.num_slots)

    def record_preemption(self) -> None:
        self.preemptions += 1
        if self.registry is not None:
            self._c_preempt.inc()

    def record_finish(self, req, now: float) -> None:
        self.finished[req.finish_reason] = (
            self.finished.get(req.finish_reason, 0) + 1)
        self._end_t = now
        n = len(req.generated)
        tpot = None
        if n > 1 and req.first_token_t is not None:
            tpot = (now - req.first_token_t) / (n - 1)
            self.tpot_s.append(tpot)
        if self.registry is not None:
            self.registry.counter(
                "serving_requests_finished_total",
                "Finished requests by terminal reason.",
                labels={"reason": str(req.finish_reason)},
            ).inc()
            if tpot is not None:
                self._h_tpot.observe(tpot)

    # ------------------------------------------------------------ #
    # reporting
    # ------------------------------------------------------------ #

    @property
    def elapsed_s(self) -> float:
        if self._start_t is None or self._end_t is None:
            return 0.0
        return max(self._end_t - self._start_t, 1e-9)

    def summary(self) -> Dict:
        occ = np.asarray(self.occupancy, np.float64)
        return {
            "requests_finished": int(sum(self.finished.values())),
            "finish_reasons": dict(self.finished),
            "tokens_generated": int(self.total_generated),
            "decode_steps": int(self.decode_steps),
            "prefills": int(self.prefills),
            "preemptions": int(self.preemptions),
            "elapsed_s": self.elapsed_s,
            "tokens_per_sec": self.total_generated / self.elapsed_s
            if self.elapsed_s else 0.0,
            "ttft_s": _percentiles(self.ttft_s),
            "tpot_s": _percentiles(self.tpot_s),
            "slot_occupancy": float(occ.mean()) if occ.size else 0.0,
            "queue_depth_max": int(max(self.queue_depth, default=0)),
        }

    def export(self, step: int) -> None:
        """Push the running summary to the TensorBoard monitor (JSONL
        fallback included — see utils/tensorboard.py)."""
        if self.monitor is None:
            return
        s = self.summary()
        self.monitor.write_scalars(
            {
                "Serving/tokens_per_sec": s["tokens_per_sec"],
                "Serving/ttft_p50_s": s["ttft_s"]["p50"],
                "Serving/ttft_p99_s": s["ttft_s"]["p99"],
                "Serving/tpot_p50_s": s["tpot_s"]["p50"],
                "Serving/tpot_p99_s": s["tpot_s"]["p99"],
                "Serving/slot_occupancy": s["slot_occupancy"],
                "Serving/queue_depth": float(
                    self.queue_depth[-1] if self.queue_depth else 0),
                "Serving/preemptions": float(self.preemptions),
            },
            step,
        )
