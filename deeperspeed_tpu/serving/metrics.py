"""Serving metrics: per-request TTFT/TPOT, queue depth, slot occupancy,
tokens/s.

Collection is host-side and allocation-light (floats appended to lists);
export goes through the same surfaces the training engine uses —
``utils/timer.SynchronizedWallClockTimer`` for the prefill/decode wall
clocks and ``utils/tensorboard.TensorBoardMonitor`` for scalar series —
so serving shows up in the exact dashboards training already feeds.
"""

import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..monitor.metrics import DEFAULT_LATENCY_BUCKETS, MetricsRegistry
from ..monitor.tracer import trace_instant
from ..utils.tensorboard import TensorBoardMonitor
from ..utils.timer import SynchronizedWallClockTimer

# timer names (appear in SynchronizedWallClockTimer.log output)
PREFILL_TIMER = "serving/prefill"
DECODE_TIMER = "serving/decode"


class SLOTracker:
    """Live SLO accounting against an ``SLOConfig`` (serving/config.py).

    Each observed latency is checked against its axis target
    (``ttft``/``tpot``/``e2e`` p99 bounds in ms); a breach emits an
    ``slo/violation`` trace instant and bumps the labeled violation
    counter, and every observation refreshes the burn-rate gauge:
    ``burn_rate = violating_fraction / error_budget``. 1.0 means the
    stream violates exactly as fast as a p99 promise allows; > 1.0
    means the error budget is burning down. A None/empty config makes
    every call a no-op, so both metrics classes embed one
    unconditionally."""

    def __init__(self, slo=None,
                 registry: Optional[MetricsRegistry] = None):
        self.slo = slo
        self.registry = registry
        # axis -> [observations, violations]
        self.counts: Dict[str, List[int]] = {}

    @property
    def enabled(self) -> bool:
        return self.slo is not None and bool(self.slo.targets())

    def observe(self, axis: str, seconds: float) -> bool:
        """Record one latency on ``axis``; returns True on violation."""
        if self.slo is None:
            return False
        target_ms = self.slo.targets().get(axis)
        if target_ms is None:
            return False
        value_ms = seconds * 1e3
        n = self.counts.setdefault(axis, [0, 0])
        n[0] += 1
        violated = value_ms > target_ms
        if violated:
            n[1] += 1
            trace_instant("slo/violation", lane="serving", slo=axis,
                          value_ms=round(value_ms, 3),
                          target_ms=target_ms)
        if self.registry is not None:
            if violated:
                self.registry.counter(
                    "slo_violations_total",
                    "Latency observations over their SLO target.",
                    labels={"slo": axis}).inc()
            self.registry.gauge(
                "slo_burn_rate",
                "Violating fraction / error budget (1.0 = burning "
                "exactly at the p99 promise).",
                labels={"slo": axis}).set(self.burn_rate(axis))
        return violated

    def burn_rate(self, axis: str) -> float:
        n = self.counts.get(axis)
        if not n or not n[0] or self.slo is None:
            return 0.0
        return (n[1] / n[0]) / self.slo.error_budget

    def summary(self) -> Dict[str, Dict]:
        if self.slo is None:
            return {}
        out = {}
        for axis, target_ms in self.slo.targets().items():
            obs, viol = self.counts.get(axis, [0, 0])
            out[axis] = {
                "target_ms": target_ms,
                "observations": obs,
                "violations": viol,
                "violation_rate": viol / obs if obs else 0.0,
                "burn_rate": round(self.burn_rate(axis), 4),
            }
        return out


def record_finish_outcome(registry: Optional[MetricsRegistry],
                          reason: str) -> None:
    """Bump the labeled per-attempt outcome counter. The label space is
    the union of engine finish reasons (``length``/``eos``/``timeout``)
    and router outcomes (``shed``/``retried``/``failed``), so one
    ``serving_finish_total`` series tells the whole admission-to-finish
    story; no-op without a registry."""
    if registry is None:
        return
    registry.counter(
        "serving_finish_total",
        "Per-attempt request outcomes (engine evictions + router "
        "shed/retry/failover), labeled by reason.",
        labels={"reason": str(reason)},
    ).inc()


def _percentiles(xs: List[float]) -> Dict[str, float]:
    if not xs:
        return {"p50": 0.0, "p99": 0.0, "mean": 0.0, "max": 0.0}
    a = np.asarray(xs, np.float64)
    return {
        "p50": float(np.percentile(a, 50)),
        "p99": float(np.percentile(a, 99)),
        "mean": float(a.mean()),
        "max": float(a.max()),
    }


class ServingMetrics:
    def __init__(self, num_slots: int,
                 clock: Callable[[], float] = time.monotonic,
                 monitor: Optional[TensorBoardMonitor] = None,
                 registry: Optional[MetricsRegistry] = None,
                 slo=None):
        self.num_slots = num_slots
        self.clock = clock
        self.monitor = monitor
        self.registry = registry
        self.slo_tracker = SLOTracker(slo, registry)
        self.timers = SynchronizedWallClockTimer()
        self.ttft_s: List[float] = []
        self.tpot_s: List[float] = []
        self.queue_depth: List[int] = []
        self.occupancy: List[float] = []
        self.total_generated = 0
        self.decode_steps = 0
        self.prefills = 0
        self.preemptions = 0
        # prefix reuse / chunked prefill: admissions is every context
        # prefilled, prefill_tokens its token total; tokens_saved the
        # part served from the radix cache instead of recomputed
        self.admissions = 0
        self.prefill_tokens = 0
        self.reuse_hits = 0
        self.tokens_saved = 0
        self.cow_splits = 0
        self.prefill_chunks = 0
        self.chunk_tokens = 0
        # speculative decoding: per-round draft/accept accounting plus
        # the draft-vs-verify wall split (spec/runtime.decode_round)
        self.spec_rounds = 0
        self.spec_drafted = 0
        self.spec_accepted = 0
        self.spec_emitted = 0
        self.spec_fallback_lanes = 0
        self.spec_draft_s = 0.0
        self.spec_verify_s = 0.0
        self.spec_drafter_prefills = 0
        self.spec_drafter_prefill_tokens = 0
        self.finished: Dict[str, int] = {}
        self._start_t: Optional[float] = None
        self._end_t: Optional[float] = None
        if registry is not None:
            self._c_tokens = registry.counter(
                "serving_tokens_generated_total",
                "Tokens emitted (prefill first-tokens + decode tokens).")
            self._c_prefills = registry.counter(
                "serving_prefills_total", "Prefill launches (admissions).")
            self._c_decode = registry.counter(
                "serving_decode_steps_total", "Batched decode steps.")
            self._c_preempt = registry.counter(
                "serving_preemptions_total",
                "Requests preempted back to the queue.")
            self._g_queue = registry.gauge(
                "serving_queue_depth", "Requests waiting for admission.")
            self._g_active = registry.gauge(
                "serving_active_slots", "Slots currently running a request.")
            self._g_occ = registry.gauge(
                "serving_slot_occupancy",
                "Active slots / num_slots at the last decode step.")
            self._h_ttft = registry.histogram(
                "serving_ttft_seconds", "Time to first token.",
                buckets=DEFAULT_LATENCY_BUCKETS)
            self._h_tpot = registry.histogram(
                "serving_tpot_seconds", "Time per output token (per-request "
                "mean, recorded at finish).",
                buckets=DEFAULT_LATENCY_BUCKETS)

    # ------------------------------------------------------------ #
    # recording
    # ------------------------------------------------------------ #

    def record_prefill(self, now: float,
                       ttft_s: Optional[float] = None) -> None:
        """One prefill (it emits one token). ttft_s is set only for a
        request's FIRST admission — preemption re-prefills don't re-count
        time-to-first-token."""
        if self._start_t is None:
            self._start_t = now
        self.prefills += 1
        self.total_generated += 1
        if ttft_s is not None:
            self.ttft_s.append(ttft_s)
            self.slo_tracker.observe("ttft", ttft_s)
        self._end_t = now
        if self.registry is not None:
            self._c_prefills.inc()
            self._c_tokens.inc()
            if ttft_s is not None:
                self._h_ttft.observe(ttft_s)

    def record_reuse(self, matched: int, ctx_len: int) -> None:
        """One admission's prefix-cache outcome: ``matched`` of the
        ``ctx_len`` context tokens came out of the radix cache (0 on a
        miss — call this for EVERY admission so the saved fraction has
        its denominator)."""
        self.admissions += 1
        self.prefill_tokens += ctx_len
        if matched > 0:
            self.reuse_hits += 1
            self.tokens_saved += matched
            if self.registry is not None:
                self.registry.counter(
                    "serving_prefix_reuse_hits_total",
                    "Admissions that matched a cached prefix.").inc()
                self.registry.counter(
                    "serving_prefill_tokens_saved_total",
                    "Prompt tokens served from the prefix cache instead "
                    "of recomputed.").inc(matched)

    def record_cow_split(self) -> None:
        """A matched boundary page copied into a private block (exactly
        one per admission whose match ends mid-block)."""
        self.cow_splits += 1
        if self.registry is not None:
            self.registry.counter(
                "serving_kv_cow_splits_total",
                "Copy-on-write splits of shared boundary pages.").inc()

    def record_prefill_chunk(self, tokens: int) -> None:
        """One staged prompt-chunk forward (chunked/suffix prefill)."""
        self.prefill_chunks += 1
        self.chunk_tokens += tokens
        if self.registry is not None:
            self.registry.counter(
                "serving_prefill_chunks_total",
                "Staged prompt-chunk forwards.").inc()

    def record_decode_step(self, n_active: int, queue_depth: int,
                           now: float) -> None:
        if self._start_t is None:
            self._start_t = now
        self.decode_steps += 1
        self.total_generated += n_active
        self.queue_depth.append(queue_depth)
        self.occupancy.append(n_active / self.num_slots)
        self._end_t = now
        if self.registry is not None:
            self._c_decode.inc()
            self._c_tokens.inc(n_active)
            self._g_queue.set(queue_depth)
            self._g_active.set(n_active)
            self._g_occ.set(n_active / self.num_slots)

    def record_preemption(self) -> None:
        self.preemptions += 1
        if self.registry is not None:
            self._c_preempt.inc()

    def record_spec_round(self, n_spec: int, n_fallback: int,
                          drafted: int, accepted: int, emitted: int,
                          draft_s: float, verify_s: float) -> None:
        """One speculative decode round. ``record_decode_step`` already
        counted one token per active lane, so only the EXTRA tokens the
        round emitted beyond that (accepted drafts past the first token
        per speculating slot) are added here."""
        self.spec_rounds += 1
        self.spec_drafted += drafted
        self.spec_accepted += accepted
        self.spec_emitted += emitted
        self.spec_fallback_lanes += n_fallback
        self.spec_draft_s += draft_s
        self.spec_verify_s += verify_s
        extra = emitted - n_spec
        self.total_generated += extra
        if self.registry is not None:
            if extra > 0:
                self._c_tokens.inc(extra)
            self.registry.counter(
                "serving_spec_rounds_total",
                "Speculative draft+verify decode rounds.").inc()
            if drafted:
                self.registry.counter(
                    "serving_spec_drafted_total",
                    "Draft tokens proposed to the verify step.",
                ).inc(drafted)
            if accepted:
                self.registry.counter(
                    "serving_spec_accepted_total",
                    "Draft tokens accepted (emitted) by verification.",
                ).inc(accepted)

    def record_drafter_prefill(self, tokens: int) -> None:
        """One drafter-pool suffix prefill (spec slot sync)."""
        self.spec_drafter_prefills += 1
        self.spec_drafter_prefill_tokens += tokens
        if self.registry is not None:
            self.registry.counter(
                "serving_spec_drafter_prefills_total",
                "Drafter-cache suffix prefills (slot syncs).").inc()

    def record_finish(self, req, now: float) -> None:
        self.finished[req.finish_reason] = (
            self.finished.get(req.finish_reason, 0) + 1)
        self._end_t = now
        n = len(req.generated)
        tpot = None
        if n > 1 and req.first_token_t is not None:
            tpot = (now - req.first_token_t) / (n - 1)
            self.tpot_s.append(tpot)
            self.slo_tracker.observe("tpot", tpot)
        if req.first_token_t is not None:
            # engine-side E2E: arrival to terminal (the router tracks
            # its own accept-to-terminal E2E for fleet serving)
            self.slo_tracker.observe("e2e", now - req.arrival_t)
        if self.registry is not None:
            self.registry.counter(
                "serving_requests_finished_total",
                "Finished requests by terminal reason.",
                labels={"reason": str(req.finish_reason)},
            ).inc()
            # one label space shared with the router layer, so engine
            # evictions and router outcomes (shed/retried/failed) land
            # in the same serving_finish_total series
            record_finish_outcome(self.registry, req.finish_reason)
            if tpot is not None:
                self._h_tpot.observe(tpot)

    # ------------------------------------------------------------ #
    # reporting
    # ------------------------------------------------------------ #

    @property
    def elapsed_s(self) -> float:
        if self._start_t is None or self._end_t is None:
            return 0.0
        return max(self._end_t - self._start_t, 1e-9)

    def summary(self) -> Dict:
        occ = np.asarray(self.occupancy, np.float64)
        return {
            "requests_finished": int(sum(self.finished.values())),
            "finish_reasons": dict(self.finished),
            "tokens_generated": int(self.total_generated),
            "decode_steps": int(self.decode_steps),
            "prefills": int(self.prefills),
            "preemptions": int(self.preemptions),
            "elapsed_s": self.elapsed_s,
            "tokens_per_sec": self.total_generated / self.elapsed_s
            if self.elapsed_s else 0.0,
            "ttft_s": _percentiles(self.ttft_s),
            "tpot_s": _percentiles(self.tpot_s),
            "slot_occupancy": float(occ.mean()) if occ.size else 0.0,
            "queue_depth_max": int(max(self.queue_depth, default=0)),
            "slo": self.slo_tracker.summary(),
            "prefix_reuse": {
                "admissions": int(self.admissions),
                "reuse_hits": int(self.reuse_hits),
                "reuse_hit_rate": (self.reuse_hits / self.admissions
                                   if self.admissions else 0.0),
                "prefill_tokens": int(self.prefill_tokens),
                "tokens_saved": int(self.tokens_saved),
                "tokens_saved_frac": (self.tokens_saved
                                      / self.prefill_tokens
                                      if self.prefill_tokens else 0.0),
                "cow_splits": int(self.cow_splits),
                "prefill_chunks": int(self.prefill_chunks),
                "chunk_tokens": int(self.chunk_tokens),
            },
            "speculative": {
                "rounds": int(self.spec_rounds),
                "drafted": int(self.spec_drafted),
                "accepted": int(self.spec_accepted),
                "accept_rate": (self.spec_accepted / self.spec_drafted
                                if self.spec_drafted else 0.0),
                "emitted": int(self.spec_emitted),
                "tokens_per_round": (self.spec_emitted / self.spec_rounds
                                     if self.spec_rounds else 0.0),
                "fallback_lanes": int(self.spec_fallback_lanes),
                "draft_time_s": float(self.spec_draft_s),
                "verify_time_s": float(self.spec_verify_s),
                "drafter_prefills": int(self.spec_drafter_prefills),
                "drafter_prefill_tokens": int(
                    self.spec_drafter_prefill_tokens),
            },
        }

    def export(self, step: int) -> None:
        """Push the running summary to the TensorBoard monitor (JSONL
        fallback included — see utils/tensorboard.py)."""
        if self.monitor is None:
            return
        s = self.summary()
        self.monitor.write_scalars(
            {
                "Serving/tokens_per_sec": s["tokens_per_sec"],
                "Serving/ttft_p50_s": s["ttft_s"]["p50"],
                "Serving/ttft_p99_s": s["ttft_s"]["p99"],
                "Serving/tpot_p50_s": s["tpot_s"]["p50"],
                "Serving/tpot_p99_s": s["tpot_s"]["p99"],
                "Serving/slot_occupancy": s["slot_occupancy"],
                "Serving/queue_depth": float(
                    self.queue_depth[-1] if self.queue_depth else 0),
                "Serving/preemptions": float(self.preemptions),
            },
            step,
        )


class FleetMetrics:
    """Router-side accounting: accepted/shed/retried counts, replica
    health transitions, and router-observed TTFT/E2E latencies (clocked
    from router accept to the event arriving back at the router, so a
    retry's re-prefill time is IN the number — this is the latency a
    client actually sees under failure).

    Same split as ServingMetrics: host-side lists for ``summary()``,
    plus registry counters/gauges when a monitor/ registry is present.
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 registry: Optional[MetricsRegistry] = None,
                 slo=None):
        self.clock = clock
        self.registry = registry
        self.slo_tracker = SLOTracker(slo, registry)
        self.accepted = 0
        self.shed = 0
        self.retries = 0
        self.replica_downs: List[Dict] = []
        self.outcomes: Dict[str, int] = {}
        self.ttft_s: List[float] = []
        self.e2e_s: List[float] = []
        if registry is not None:
            self._c_accepted = registry.counter(
                "serving_router_accepted_total",
                "Requests accepted by router admission control.")
            self._c_shed = registry.counter(
                "serving_shed_total",
                "Requests rejected by admission control (overload).")
            self._c_retry = registry.counter(
                "serving_retries_total",
                "Request re-dispatches after replica failures.")
            self._h_ttft = registry.histogram(
                "serving_router_ttft_seconds",
                "Router-observed time to first token (includes retry "
                "re-prefills).", buckets=DEFAULT_LATENCY_BUCKETS)
            self._h_e2e = registry.histogram(
                "serving_router_e2e_seconds",
                "Router-observed accept-to-terminal latency.",
                buckets=DEFAULT_LATENCY_BUCKETS)

    # ------------------------------------------------------------ #

    def record_accept(self) -> None:
        self.accepted += 1
        if self.registry is not None:
            self._c_accepted.inc()

    def record_shed(self) -> None:
        self.shed += 1
        if self.registry is not None:
            self._c_shed.inc()
        record_finish_outcome(self.registry, "shed")

    def record_retry(self) -> None:
        self.retries += 1
        if self.registry is not None:
            self._c_retry.inc()
        record_finish_outcome(self.registry, "retried")

    def record_replica_down(self, name: str, cause: str,
                            inflight: int) -> None:
        self.replica_downs.append(
            {"replica": name, "cause": cause, "inflight": inflight,
             "t": self.clock()})
        if self.registry is not None:
            self.registry.counter(
                "serving_replica_down_total",
                "Replicas marked unhealthy, by cause.",
                labels={"replica": name, "cause": cause},
            ).inc()

    def record_ttft(self, ttft: float) -> None:
        self.ttft_s.append(ttft)
        self.slo_tracker.observe("ttft", ttft)
        if self.registry is not None:
            self._h_ttft.observe(ttft)

    def record_outcome(self, reason: str,
                       e2e_s: Optional[float] = None) -> None:
        """Terminal outcome for an ACCEPTED request (finish reasons plus
        router-level timeout/failed); shed requests were never accepted
        and are counted by record_shed."""
        self.outcomes[reason] = self.outcomes.get(reason, 0) + 1
        if e2e_s is not None:
            self.e2e_s.append(e2e_s)
            self.slo_tracker.observe("e2e", e2e_s)
            if self.registry is not None:
                self._h_e2e.observe(e2e_s)
        record_finish_outcome(self.registry, reason)

    def set_replica_gauges(self, name: str, healthy: bool,
                           inflight: int) -> None:
        if self.registry is None:
            return
        self.registry.gauge(
            "serving_replica_healthy",
            "1 while the replica passes both watchdogs, else 0.",
            labels={"replica": name}).set(1.0 if healthy else 0.0)
        self.registry.gauge(
            "serving_replica_inflight",
            "Requests currently dispatched to the replica.",
            labels={"replica": name}).set(float(inflight))

    def set_load_gauges(self, queue_depth: int,
                        inflight_tokens: int) -> None:
        if self.registry is None:
            return
        self.registry.gauge(
            "serving_fleet_queue_depth",
            "Accepted-but-unfinished requests at the router.",
        ).set(float(queue_depth))
        self.registry.gauge(
            "serving_fleet_inflight_tokens",
            "Token budget in flight (sum of prompt + max_new_tokens).",
        ).set(float(inflight_tokens))

    # ------------------------------------------------------------ #

    def summary(self) -> Dict:
        offered = self.accepted + self.shed
        return {
            "accepted": self.accepted,
            "shed": self.shed,
            "shed_rate": self.shed / offered if offered else 0.0,
            "retries": self.retries,
            "replica_downs": list(self.replica_downs),
            "outcomes": dict(self.outcomes),
            "router_ttft_s": _percentiles(self.ttft_s),
            "router_e2e_s": _percentiles(self.e2e_s),
            "slo": self.slo_tracker.summary(),
        }
