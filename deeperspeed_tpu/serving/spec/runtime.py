"""SpecRuntime: the host side of drafter-backed speculative decoding.

Owns the drafter — config, params, and a small paged KV pool that rides
the same ``BlockAllocator`` refcount/reclaim machinery (and, when prefix
caching is on, its own ``PrefixCache`` radix index) as the target pool —
plus the two compiled programs from ``spec/steps.py``. The engine hands
it the decode phase each step (``decode_round``); everything else
(admission, prefill, scheduling, eviction) stays the engine's.

Drafter state is synced LAZILY per slot: the runtime tracks {rid, cached
rows} per slot and, whenever a slot's occupant or length disagrees,
rebuilds the drafter cache for that slot by prefilling the same suffix
the target prefilled — longest radix-cached prefix mapped read-only,
remainder forwarded through a staged gather → one-shot suffix forward →
scatter (the engine's own prefix-reuse machinery, against the drafter
pool). One sync path uniformly covers fresh admissions, chunked-prefill
completions, preemption re-admissions, failover re-submissions, and
rounds a slot spent on the fallback program.

A slot speculates only when (a) its table can hold ``draft_k + 1`` more
rows, (b) it has more than one token left to emit, and (c) the drafter
sync and block allocation succeed; otherwise it decodes on the engine's
fallback plain program the same step. Both programs always run the full
slot array, so mixed eligibility never changes compiled shapes.
"""

import dataclasses
import time
from typing import List, Optional

import jax
import numpy as np

from ...models.generation import apply_with_cache
from ...models.gpt import GPTConfig
from ...utils.logging import logger
from ..config import ServingConfig, SpeculativeConfig
from ..kv_cache import NULL_BLOCK, PagedKVCache, PrefixCache, \
    blocks_needed
from ..metrics import DECODE_TIMER
from ...monitor.tracer import trace_instant, trace_span
from .steps import make_draft_step, make_verify_step


def truncated_drafter(cfg: GPTConfig, params, n_layer: int):
    """Derive a layer-truncated drafter from the target model: share the
    embedding, final layer norm, and head; keep the first ``n_layer``
    stacked decoder layers. Returns (drafter_cfg, drafter_params) with
    the params VIEWING the target's arrays (no copy) — a checkpointed or
    distilled drafter replaces this wholesale via ``drafter_params``."""
    if not (1 <= n_layer <= cfg.n_layer):
        raise ValueError(
            f"drafter n_layer must be in [1, {cfg.n_layer}], got {n_layer}")
    dcfg = dataclasses.replace(cfg, n_layer=int(n_layer))
    dparams = dict(params)
    dparams["layers"] = jax.tree.map(lambda x: x[:n_layer],
                                     params["layers"])
    return dcfg, dparams


class SpecRuntime:
    """Drafter engine + speculative decode round for a ServingEngine."""

    def __init__(self, engine, spec_cfg: SpeculativeConfig,
                 drafter_params=None):
        self.eng = engine
        self.spec_cfg = spec_cfg
        self.K = spec_cfg.draft_k
        cfg: GPTConfig = engine.cfg
        scfg: ServingConfig = engine.scfg
        if drafter_params is not None:
            if spec_cfg.drafter is None:
                raise ValueError(
                    "speculative.drafter (a GPTConfig dict) is required "
                    "when passing drafter_params")
            self.dcfg = GPTConfig(**spec_cfg.drafter)
            self.dparams = drafter_params
        elif spec_cfg.drafter_checkpoint is not None:
            raise ValueError(
                "speculative.drafter_checkpoint requires the caller to "
                "load the checkpoint and pass drafter_params (the "
                "lifecycle rollout path ships (target, drafter) weight "
                "pairs through set_weights)")
        else:
            # no drafter given: derive a layer-truncated one from the
            # target (cheap, deterministic, surprisingly strong when the
            # target's upper layers refine rather than overturn)
            n = max(1, cfg.n_layer // 4)
            if spec_cfg.drafter:
                d = dict(spec_cfg.drafter)
                n = int(d.pop("n_layer", n))
                for key, val in d.items():
                    if getattr(cfg, key, None) != val:
                        raise ValueError(
                            f"derived (layer-truncated) drafter can only "
                            f"override n_layer; {key}={val!r} differs "
                            f"from the target's {getattr(cfg, key, None)!r}"
                            f" — pass drafter_params for a real drafter")
            self.dcfg, self.dparams = truncated_drafter(cfg,
                                                        engine.params, n)
        if self.dcfg.vocab_size != cfg.vocab_size:
            raise ValueError(
                f"drafter vocab_size ({self.dcfg.vocab_size}) must match "
                f"the target's ({cfg.vocab_size}): draft tokens are "
                f"verified by identity in the target's vocabulary")
        # drafter pool: target geometry (block_size, table width), its
        # own block count and allocator/radix instances
        nb = (scfg.num_blocks if spec_cfg.num_blocks is None
              else spec_cfg.num_blocks)
        self.kv = PagedKVCache(self.dcfg, scfg, num_blocks=nb)
        self.prefix: Optional[PrefixCache] = (
            PrefixCache(self.kv.allocator, scfg.block_size)
            if scfg.prefix_caching else None)
        # per-slot drafter mirror: which rid's context the drafter pool
        # holds for the slot, how many rows of it, in which blocks
        n_slots = scfg.num_slots
        self.slot_rid: List[Optional[str]] = [None] * n_slots
        self.slot_len: List[int] = [0] * n_slots
        self.slot_blocks: List[List[int]] = [[] for _ in range(n_slots)]
        self._draft_step = make_draft_step(self.dcfg, scfg, self.K)
        self._verify_step = make_verify_step(cfg, scfg, self.K)
        self._suffix = jax.jit(
            lambda p, toks, kc, vc, off: apply_with_cache(
                self.dcfg, p, toks, {"k": kc, "v": vc}, off),
            donate_argnums=(2, 3))
        if engine.telemetry is not None:
            # all three decode-path programs are watched; draft/verify
            # compile once each (static shapes over the full slot array)
            engine.telemetry.watchdog.watch("serving/draft_step",
                                            self._draft_step)
            engine.telemetry.watchdog.watch("serving/verify_step",
                                            self._verify_step)

    # -- compile counters (tests assert one compile each) -------------- #

    @property
    def draft_compile_count(self) -> int:
        return getattr(self._draft_step, "_cache_size", lambda: -1)()

    @property
    def verify_compile_count(self) -> int:
        return getattr(self._verify_step, "_cache_size", lambda: -1)()

    def set_drafter_params(self, drafter_params) -> None:
        """Swap drafter weights in place (lifecycle rollout of a
        (target, drafter) version pair). Cached drafter KV becomes stale
        for the NEW weights, so every slot's mirror is dropped and
        resyncs lazily — exactly the failover path."""
        self.dparams = drafter_params
        for s in range(len(self.slot_rid)):
            self._release(s)

    # -- drafter slot sync --------------------------------------------- #

    def _release(self, slot: int) -> None:
        if self.slot_blocks[slot]:
            self.kv.allocator.free(self.slot_blocks[slot])
        self.slot_blocks[slot] = []
        self.slot_rid[slot] = None
        self.slot_len[slot] = 0

    def _sweep(self) -> None:
        """Release drafter state whose slot now runs a different rid.
        An EMPTY slot keeps its state: a preempted request re-admitted
        to the same slot resumes from its still-valid drafter prefix."""
        for s, occ in enumerate(self.eng.sched.slots):
            if self.slot_rid[s] is not None and occ is not None \
                    and occ.rid != self.slot_rid[s]:
                self._release(s)

    def _ensure_blocks(self, slot: int, want_tokens: int) -> bool:
        need = blocks_needed(want_tokens, self.eng.scfg.block_size) \
            - len(self.slot_blocks[slot])
        if need <= 0:
            return True
        got = self.kv.allocator.alloc(need)
        if got is None:
            return False
        self.slot_blocks[slot].extend(got)
        return True

    def _sync_slot(self, slot: int, req) -> bool:
        """Bring the drafter's cache for ``slot`` up to the target's
        ``req.cached_len`` rows; returns False (slot falls back to plain
        decode this round) when the drafter pool cannot cover it."""
        c = req.cached_len
        if self.slot_rid[slot] != req.rid:
            self._release(slot)
            self.slot_rid[slot] = req.rid
        if self.slot_len[slot] < c:
            if not self._prefill_suffix(slot, req, c):
                self._release(slot)
                return False
        # headroom for this round's K+1 drafter writes (rows c..c+K)
        return self._ensure_blocks(slot, c + self.K + 1)

    def _prefill_suffix(self, slot: int, req, c: int) -> bool:
        """Forward ``req.context[start:c]`` into the drafter pool for
        this slot (start = rows already held). Fresh slots first map the
        longest radix-cached prefix read-only — whole blocks only, the
        drafter skips the boundary CoW copy — then the remainder runs as
        ONE staged suffix forward (gather shared/held pages, forward at
        the traced offset, scatter private pages back)."""
        eng = self.eng
        scfg = eng.scfg
        bs = scfg.block_size
        start = self.slot_len[slot]
        ctx = req.context[:c]
        if start == 0 and not self.slot_blocks[slot] \
                and self.prefix is not None:
            matched, full, _partial = self.prefix.match(ctx)
            m = min(matched, c - 1) // bs * bs   # whole blocks only
            full = full[:m // bs]
            for b in full:
                self.kv.allocator.ref(b)
            self.slot_blocks[slot] = list(full)
            start = m
        if not self._ensure_blocks(slot, c):
            return False
        n_pages = blocks_needed(c, bs)
        if start < c:
            suf = ctx[start:c]
            pad = scfg.bucket_for(len(suf))
            cache_len = scfg.bucket_for(max(c, start + pad))
            pages = cache_len // bs
            gather_map = [NULL_BLOCK] * pages
            for p in range(n_pages):
                gather_map[p] = self.slot_blocks[slot][p]
            k_stage, v_stage = self.kv.gather_pages(gather_map)
            toks = np.zeros((1, pad), np.int32)
            toks[0, :len(suf)] = suf
            _, cache = self._suffix(self.dparams, jax.numpy.asarray(toks),
                                    k_stage, v_stage, start)
            scatter_map = [NULL_BLOCK] * pages
            for p in range(start // bs, n_pages):
                scatter_map[p] = self.slot_blocks[slot][p]
            self.kv.write_pages(cache["k"], cache["v"], scatter_map)
            eng.metrics.record_drafter_prefill(len(suf))
        self.slot_len[slot] = c
        if self.prefix is not None:
            aligned = len(req.prompt) // bs * bs
            if aligned > 0 and c >= aligned:
                self.prefix.insert(req.prompt[:aligned],
                                   self.slot_blocks[slot][:aligned // bs])
        logger.debug("spec: drafter slot %d synced to %d rows for %s",
                     slot, c, req.rid)
        return True

    # -- the speculative decode round ---------------------------------- #

    def _lane_arrays(self, lanes):
        """The decode step's per-slot input arrays for ``lanes``, other
        lanes idle (token 0 / length 0 / null tables — the shared static
        -shape contract)."""
        scfg = self.eng.scfg
        N = scfg.num_slots
        lengths = np.zeros(N, np.int32)
        tokens = np.zeros(N, np.int32)
        temps = np.zeros(N, np.float32)
        seeds = np.zeros(N, np.int32)
        counts = np.zeros(N, np.int32)
        for s, req in lanes:
            lengths[s] = req.cached_len
            tokens[s] = req.pending_token
            temps[s] = req.temperature
            seeds[s] = req.seed
            counts[s] = len(req.generated)
        return lengths, tokens, temps, seeds, counts

    def _dispatch_draft(self, spec_lanes) -> np.ndarray:
        eng = self.eng
        scfg = eng.scfg
        N = scfg.num_slots
        tables = np.zeros((N, scfg.blocks_per_slot), np.int32)
        for s, _req in spec_lanes:
            row = self.slot_blocks[s]
            tables[s, :len(row)] = row
        lengths, tokens, temps, seeds, counts = \
            self._lane_arrays(spec_lanes)
        _place = eng._place_slot_array
        args = (self.dparams, self.kv.k, self.kv.v, _place(tables),
                _place(lengths), _place(tokens), _place(temps),
                _place(seeds), _place(counts))
        drafts, self.kv.k, self.kv.v = self._draft_step(*args)
        drafts = np.asarray(drafts)                     # device sync
        tel = eng.telemetry
        if tel is not None and tel.cost_index is not None:
            tel.cost_index.observe("serving/draft_step",
                                   self._draft_step, args)
        return drafts

    def _dispatch_verify(self, spec_lanes, drafts):
        eng = self.eng
        scfg = eng.scfg
        N = scfg.num_slots
        tables = np.zeros((N, scfg.blocks_per_slot), np.int32)
        vtokens = np.zeros((N, self.K + 1), np.int32)
        for s, req in spec_lanes:
            tables[s] = eng.sched.slot_table_row(s)
            vtokens[s, 0] = req.pending_token
            vtokens[s, 1:] = drafts[s]
        lengths, _tokens, temps, seeds, counts = \
            self._lane_arrays(spec_lanes)
        _place = eng._place_slot_array
        args = (eng.params, eng.kv.k, eng.kv.v, _place(tables),
                _place(lengths), _place(vtokens), _place(temps),
                _place(seeds), _place(counts))
        n_acc, bonus, eng.kv.k, eng.kv.v = self._verify_step(*args)
        n_acc = np.asarray(n_acc)                       # device sync
        bonus = np.asarray(bonus)
        tel = eng.telemetry
        if tel is not None and tel.cost_index is not None:
            tel.cost_index.observe("serving/verify_step",
                                   self._verify_step, args)
        return n_acc, bonus

    def decode_round(self) -> None:
        """The engine's decode phase with speculation: draft + verify
        for eligible slots, the fallback plain program for the rest —
        all inside ONE serving/decode span so the request ledger's
        decode attribution joins exactly as before."""
        eng = self.eng
        K = self.K
        scfg = eng.scfg
        bs = scfg.block_size
        cap = scfg.blocks_per_slot * bs
        active = eng._active_decodable()
        if not active:
            return
        self._sweep()
        spec_lanes, fallback = [], []
        for s, req in active:
            if (req.cached_len + K + 1 <= cap
                    and req.remaining > 1
                    and len(eng.sched.slot_blocks[s])
                    >= blocks_needed(req.cached_len + K + 1, bs)
                    and self._sync_slot(s, req)):
                spec_lanes.append((s, req))
            else:
                fallback.append((s, req))
        drafts = n_acc = bonus = nxt = None
        draft_s = verify_s = 0.0
        with trace_span("serving/decode", lane="serving",
                        n_active=len(active),
                        rids=",".join(r.rid for _, r in active)) as _sp:
            timer = eng.metrics.timers(DECODE_TIMER)
            timer.safe_start()
            if spec_lanes:
                _t0 = time.perf_counter()
                drafts = self._dispatch_draft(spec_lanes)
                _t1 = time.perf_counter()
                draft_s = _t1 - _t0
                trace_instant("spec/draft", lane="serving",
                              n_active=len(spec_lanes), k=K,
                              dur_us=round(draft_s * 1e6, 1))
                n_acc, bonus = self._dispatch_verify(spec_lanes, drafts)
                verify_s = time.perf_counter() - _t1
                trace_instant("spec/verify", lane="serving",
                              n_active=len(spec_lanes), k=K,
                              dur_us=round(verify_s * 1e6, 1))
            if fallback:
                nxt = eng._dispatch_plain(fallback)
            timer.stop()
            tel = eng.telemetry
            if tel is not None and tel.memwatch is not None:
                tel.memwatch.annotate(_sp, "decode")
        tel = eng.telemetry
        if tel is not None:
            if spec_lanes:
                tel.watchdog.observe("serving/draft_step",
                                     step=eng._step_i)
                tel.watchdog.observe("serving/verify_step",
                                     step=eng._step_i)
            if fallback:
                tel.watchdog.observe("serving/decode_step",
                                     step=eng._step_i)
        eng.metrics.record_decode_step(len(active),
                                       len(eng.sched.queue), eng.clock())
        emitted = 0
        accepted = 0
        eos = scfg.eos_token_id
        for s, req in spec_lanes:
            n = int(n_acc[s])
            toks = [int(drafts[s, j]) for j in range(n)] + [int(bonus[s])]
            # truncate exactly where plain decode would have stopped:
            # at the request's token budget, and at the first EOS
            toks = toks[:req.remaining]
            if eos is not None and eos in toks:
                toks = toks[:toks.index(eos) + 1]
            acc = min(n, len(toks))
            req.cached_len += len(toks)
            req.generated.extend(toks)
            self.slot_len[s] = req.cached_len
            emitted += len(toks)
            accepted += acc
            trace_instant("spec/accept", lane="serving", rid=req.rid,
                          accepted=acc, k=K, emitted=len(toks))
            eng._record_emitted(req, prefill=False)
        for s, req in fallback:
            req.cached_len += 1
            req.generated.append(int(nxt[s]))
            eng._record_emitted(req, prefill=False)
        eng.metrics.record_spec_round(
            n_spec=len(spec_lanes), n_fallback=len(fallback),
            drafted=K * len(spec_lanes), accepted=accepted,
            emitted=emitted, draft_s=draft_s, verify_s=verify_s)

    def stats(self) -> dict:
        """Drafter-pool counters for fleet mirrors and benches (the
        acceptance counters live in ServingMetrics.summary())."""
        out = {
            "draft_k": self.K,
            "drafter_layers": self.dcfg.n_layer,
            "drafter_blocks_free": self.kv.allocator.num_free,
            "drafter_blocks_allocated": self.kv.allocator.num_allocated,
        }
        if self.prefix is not None:
            out["drafter_prefix"] = self.prefix.stats()
        return out
