"""Drafter-backed speculative decoding for the serving engine.

Enabled by the ``"speculative"`` sub-block of the serving config (see
serving/config.SpeculativeConfig; off by default). The engine keeps
exactly three compiled decode-path programs — drafter decode, target
verify, fallback plain decode — and the emitted token stream is, by
construction, identical to what plain decode would produce: greedy
bit-identical, sampled a pure function of (per-rid seed, token index).
docs/tutorials/serving.md covers drafter sizing, k tuning, and the
determinism contract.
"""

from .runtime import SpecRuntime, truncated_drafter
from .steps import make_draft_step, make_verify_step

__all__ = [
    "SpecRuntime",
    "truncated_drafter",
    "make_draft_step",
    "make_verify_step",
]
