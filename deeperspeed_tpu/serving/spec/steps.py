"""The speculative decode path's two compiled programs.

``make_draft_step`` runs the DRAFTER: one jitted program containing a
``lax.scan`` over ``draft_k + 1`` single-token paged decodes (the same
per-layer math as the engine's decode step, against the drafter's own
paged pool), proposing ``draft_k`` tokens per slot. The scan runs one
extra iteration so the last proposal's KV row is already in the drafter
pool when every draft is accepted — a full-accept round never needs a
host-side drafter resync.

``make_verify_step`` runs the TARGET over the ``draft_k + 1`` window
``[pending, d_1..d_K]`` in one forward (``paged_attend_multi``), picks
the target's own next-token choice at every position with EXACTLY the
decode step's selection math (argmax when temperature <= 0, else a
top-k-filtered categorical keyed by ``request_sample_key(seed, token
index)``), and accepts the longest draft prefix that MATCHES those
choices. Because the emitted stream — accepted drafts plus the target's
choice at the first mismatch — is by construction the token stream the
plain decode step would have produced, greedy speculative output is
bit-identical to plain greedy decode, and sampled accept/reject is a
pure function of (per-rid seed, token index): a failover retry or a
spec-off replica replays the identical stream. (This is common-random-
numbers coupling: drafter and target sample with the SAME key per token
index, so close distributions agree often — that agreement rate IS the
acceptance rate.)

Both programs are static-shape over the full slot array (idle lanes:
token 0 / length 0 / null tables) and donate their pools, so together
with the engine's fallback plain decode the decode path holds exactly
three compiled programs, each watched by the recompile watchdog.

KV rows written for rejected drafts are stale-but-invisible: the next
round's length-derived masks hide them until overwritten (the same
rollback-free contract as models/speculative.py).
"""

from functools import partial

import jax
import jax.numpy as jnp

from ...models.gpt import GPTConfig, layer_norm
from ..config import ServingConfig
from ..engine import _paged_block, request_sample_key
from ..kv_cache import paged_attend_multi


def _choose(logits, temps, seeds, idx, top_k):
    """The decode step's next-token selection over (N, V) logits —
    replicated operation-for-operation (engine.make_decode_step) so the
    verify step's per-position choices are bit-identical to what the
    plain decode program would pick at the same position."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    l32 = logits.astype(jnp.float32) / jnp.maximum(temps, 1e-6)[:, None]
    if top_k is not None:
        kth = jax.lax.top_k(l32, top_k)[0][..., -1:]
        l32 = jnp.where(l32 < kth, -1e30, l32)
    keys = jax.vmap(request_sample_key)(seeds, idx)
    sampled = jax.vmap(
        lambda k, row: jax.random.categorical(k, row)
    )(keys, l32).astype(jnp.int32)
    return jnp.where(temps > 0.0, sampled, greedy)


def _resolve_top_k(cfg: GPTConfig, scfg: ServingConfig):
    top_k = scfg.top_k
    if top_k is not None and top_k >= cfg.vocab_size:
        return None  # full-vocab top-k is a no-op filter
    return top_k


def _unembed(cfg: GPTConfig, params, x):
    cdt = cfg.dtype
    x = layer_norm(x, params["final_ln"]["scale"],
                   params["final_ln"]["bias"], cfg.layernorm_eps)
    if cfg.tie_embeddings:
        return x @ params["embed"]["wte"].astype(cdt).T
    return x @ params["lm_head"].astype(cdt)


def make_draft_step(cfg: GPTConfig, scfg: ServingConfig, draft_k: int):
    """Build the jitted drafter program.

    draft_step(params, k_pool, v_pool, tables, lengths, tokens, temps,
    seeds, counts) -> (drafts (N, K) int32, k_pool', v_pool'). ``cfg``
    is the DRAFTER config; pools are the drafter's paged pool (donated).
    Scan iteration j feeds the running token (the slot's pending token
    at j=0), writes its KV at row ``lengths + j``, and proposes the
    token for emitted index ``counts + j`` with the engine's selection
    math keyed at that index.
    """
    top_k = _resolve_top_k(cfg, scfg)

    @partial(jax.jit, donate_argnums=(1, 2))
    def draft_step(params, k_pool, v_pool, tables, lengths, tokens,
                   temps, seeds, counts):
        cdt = cfg.dtype
        N = tokens.shape[0]
        wte = params["embed"]["wte"].astype(cdt)

        def one(carry, j):
            tok, k_pool, v_pool = carry
            pos = lengths + j
            x = jnp.take(wte, tok, axis=0)[:, None, :]      # (N, 1, D)
            positions = pos[:, None]
            if not cfg.rotary:
                x = x + jnp.take(params["embed"]["wpe"], positions,
                                 axis=0).astype(cdt)
            wblk = tables[jnp.arange(N), pos // scfg.block_size]
            woff = pos % scfg.block_size

            def scan_body(h, xs):
                layer_params, k_l, v_l = xs
                h, k_l, v_l = _paged_block(cfg, h, layer_params, k_l,
                                           v_l, tables, pos, wblk, woff,
                                           positions)
                return h, (k_l, v_l)

            x, (k_pool, v_pool) = jax.lax.scan(
                scan_body, x, (params["layers"], k_pool, v_pool))
            logits = _unembed(cfg, params, x)[:, 0]
            nxt = _choose(logits, temps, seeds, counts + j, top_k)
            return (nxt, k_pool, v_pool), nxt

        # K+1 iterations: the extra one writes d_K's KV row (and its
        # proposal is discarded), keeping the drafter cache complete
        # even when the verify step accepts every draft
        (_, k_pool, v_pool), drafts = jax.lax.scan(
            one, (tokens, k_pool, v_pool),
            jnp.arange(draft_k + 1, dtype=jnp.int32))
        return drafts[:draft_k].T, k_pool, v_pool

    return draft_step


def _paged_block_multi(cfg: GPTConfig, x, layer_params, k_l, v_l,
                       tables, lengths, wblk, woff, positions):
    """One decoder layer over all slots' T-token windows — the multi-
    token twin of engine._paged_block (same decoder_block math, the
    attention core swapped for paged_attend_multi)."""
    from ...models.gpt import decoder_block

    def attend(q, k, v):
        ctx, k2, v2 = paged_attend_multi(k_l, v_l, q, k, v, tables,
                                         lengths, wblk, woff)
        return ctx, (k2, v2)

    moe_cfg = cfg.moe
    if moe_cfg is not None:
        from ...models.moe import moe_ffn

        def mlp_fn(mlp_in):
            return moe_ffn(layer_params["moe"], mlp_in, moe_cfg)

        x, ((k_l, v_l), _) = decoder_block(
            cfg, None, x, layer_params, positions, attend, mlp_fn=mlp_fn
        )
    else:
        x, (k_l, v_l) = decoder_block(cfg, None, x, layer_params,
                                      positions, attend)
    return x, k_l, v_l


def make_verify_step(cfg: GPTConfig, scfg: ServingConfig, draft_k: int):
    """Build the jitted target verify program.

    verify_step(params, k_pool, v_pool, tables, lengths, tokens (N, K+1),
    temps, seeds, counts) -> (n_acc (N,), bonus (N,), k_pool', v_pool').
    ``tokens`` is ``[pending, d_1..d_K]`` per slot; ``cfg``/pools are
    the TARGET's. n_acc is the length of the longest draft prefix
    matching the target's own per-position choices; bonus is the
    target's choice at the first mismatch (== position n_acc) — the
    host emits ``drafts[:n_acc] + [bonus]``.
    """
    T = draft_k + 1
    top_k = _resolve_top_k(cfg, scfg)

    @partial(jax.jit, donate_argnums=(1, 2))
    def verify_step(params, k_pool, v_pool, tables, lengths, tokens,
                    temps, seeds, counts):
        cdt = cfg.dtype
        N = tokens.shape[0]
        wte = params["embed"]["wte"].astype(cdt)
        x = jnp.take(wte, tokens, axis=0)                   # (N, T, D)
        positions = lengths[:, None] + jnp.arange(T, dtype=jnp.int32)
        if not cfg.rotary:
            x = x + jnp.take(params["embed"]["wpe"], positions,
                             axis=0).astype(cdt)
        wblk = jnp.take_along_axis(tables,
                                   positions // scfg.block_size, axis=1)
        woff = positions % scfg.block_size

        def scan_body(h, xs):
            layer_params, k_l, v_l = xs
            h, k_l, v_l = _paged_block_multi(cfg, h, layer_params, k_l,
                                             v_l, tables, lengths, wblk,
                                             woff, positions)
            return h, (k_l, v_l)

        x, (k_pool, v_pool) = jax.lax.scan(
            scan_body, x, (params["layers"], k_pool, v_pool))
        logits = _unembed(cfg, params, x)                   # (N, T, V)
        # target's own choice at every window position, one static
        # unroll per position (T is small) so the selection math stays
        # the decode step's, operation for operation
        choice = jnp.stack(
            [_choose(logits[:, t], temps, seeds, counts + t, top_k)
             for t in range(T)], axis=1)                    # (N, T)
        drafts = tokens[:, 1:]                              # (N, K)
        matches = (drafts == choice[:, :draft_k]).astype(jnp.int32)
        n_acc = jnp.sum(jnp.cumprod(matches, axis=1), axis=1)
        bonus = jnp.take_along_axis(choice, n_acc[:, None], axis=1)[:, 0]
        return (n_acc.astype(jnp.int32), bonus.astype(jnp.int32),
                k_pool, v_pool)

    return verify_step
