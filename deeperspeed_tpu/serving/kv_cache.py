"""Slot-based paged KV cache: block pool, allocator, and the paged
attention/cache-write math for the serving decode step.

Layout: one pool per cache side, stacked over layers —

    k, v: (n_layer, num_blocks, block_size, n_kv_head, head_dim)

A request's cache lives in whichever blocks the allocator hands it; the
per-slot BLOCK TABLE (``(num_slots, blocks_per_slot)`` int32) maps the
request's logical block ``i`` to its physical block. Block 0 is the
reserved NULL block: idle slots' tables and padded table entries point at
it, so the fully static decode step can scatter/gather unconditionally —
garbage lands in (or comes from) block 0 and is masked out by the
per-slot length.

Writes are static-shape updates into slot pages: prefill scatters whole
``block_size`` pages (the dense prefill cache reshaped to pages, indexed
by the allocated block list), decode scatters each slot's single new
(K, V) row at ``(block_table[len // bs], len % bs)``. Reads gather the
slot's pages back into a contiguous ``blocks_per_slot * block_size``
view per layer — the XLA-gather formulation of paged attention; a Pallas
kernel that walks the table in HBM without materializing the view is the
planned TPU fast path (see docs/tutorials/serving.md).
"""

import math
from typing import List, Optional

import jax
import jax.numpy as jnp

from ..models.gpt import GPTConfig
from .config import ServingConfig

NULL_BLOCK = 0


class OutOfBlocks(Exception):
    """Raised only for internal invariant violations — normal exhaustion
    returns None from alloc() (backpressure, not an error)."""


class BlockAllocator:
    """Free-list allocator over the physical blocks of the KV pool.

    Block 0 (NULL_BLOCK) is never handed out. alloc() is all-or-nothing:
    a request that cannot get every block it asked for gets none, and the
    caller leaves it queued (backpressure) or preempts a victim.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is reserved)")
        self.num_blocks = num_blocks
        # LIFO free list: recently freed (cache-warm) blocks reused first
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._allocated = set()

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_allocated(self) -> int:
        return len(self._allocated)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """n blocks, or None when the pool cannot satisfy the request."""
        if n < 0:
            raise ValueError(f"cannot alloc {n} blocks")
        if n > len(self._free):
            return None
        blocks = [self._free.pop() for _ in range(n)]
        self._allocated.update(blocks)
        return blocks

    def free(self, blocks: List[int]) -> None:
        for b in blocks:
            if b not in self._allocated:
                raise OutOfBlocks(
                    f"double free / foreign free of block {b} "
                    f"(allocated={sorted(self._allocated)})"
                )
            self._allocated.remove(b)
            self._free.append(b)


def blocks_needed(n_tokens: int, block_size: int) -> int:
    return math.ceil(n_tokens / block_size) if n_tokens > 0 else 0


class PagedKVCache:
    """The device-side block pool plus its host-side allocator.

    ``k``/``v`` are replaced wholesale by the jitted prefill-write and
    decode steps (which donate the old pools); this object owns the
    handles and the block accounting.
    """

    def __init__(self, cfg: GPTConfig, scfg: ServingConfig):
        self.cfg = cfg
        self.scfg = scfg
        shape = (cfg.n_layer, scfg.num_blocks, scfg.block_size,
                 cfg.kv_heads, cfg.head_dim)
        self.k = jnp.zeros(shape, cfg.dtype)
        self.v = jnp.zeros(shape, cfg.dtype)
        self.allocator = BlockAllocator(scfg.num_blocks)
        self._write_prefill = jax.jit(_scatter_prefill_pages,
                                      donate_argnums=(0, 1))

    def write_prefill(self, k_dense, v_dense, blocks: List[int],
                      length: int) -> None:
        """Scatter a dense prefill cache (L, 1, bucket, Hkv, Dh) into the
        allocated ``blocks``. ``bucket`` is a multiple of block_size;
        pages beyond ``blocks`` (prompt padding) go to the null block."""
        bs = self.scfg.block_size
        bucket = k_dense.shape[2]
        assert bucket % bs == 0, (bucket, bs)
        n_pages = bucket // bs
        assert len(blocks) == blocks_needed(length, bs), (blocks, length)
        idx = jnp.asarray(
            list(blocks) + [NULL_BLOCK] * (n_pages - len(blocks)),
            jnp.int32,
        )
        self.k, self.v = self._write_prefill(self.k, self.v, k_dense,
                                             v_dense, idx)


def _scatter_prefill_pages(k_pool, v_pool, k_dense, v_dense, idx):
    """(L, 1, bucket, Hkv, Dh) dense prefill cache -> pool pages at idx."""
    L, _, bucket, Hkv, Dh = k_dense.shape
    bs = k_pool.shape[2]
    pages_k = k_dense.reshape(L, bucket // bs, bs, Hkv, Dh)
    pages_v = v_dense.reshape(L, bucket // bs, bs, Hkv, Dh)
    # duplicate null-block targets (padding pages) may race; block 0's
    # content is never read unmasked, so last-writer-wins is fine
    return (k_pool.at[:, idx].set(pages_k.astype(k_pool.dtype)),
            v_pool.at[:, idx].set(pages_v.astype(v_pool.dtype)))


def paged_attend(k_pool_l, v_pool_l, q, k_new, v_new, tables, lengths,
                 write_block, write_off):
    """One layer of single-token paged-cache attention for all slots.

    k_pool_l/v_pool_l: (num_blocks, bs, Hkv, Dh) — this layer's pool.
    q: (N, 1, H, Dh); k_new/v_new: (N, 1, Hkv, Dh) — the new token's
    projections per slot. tables: (N, blocks_per_slot) int32; lengths:
    (N,) tokens already cached per slot; write_block/write_off: (N,)
    physical block + in-block offset for the new row.

    Returns (ctx (N, 1, H, Dh), k_pool_l', v_pool_l'). Mirrors
    models/generation._cached_block's grouped-einsum math (GQA reads at
    the small Hkv width) so greedy serving outputs are token-identical to
    make_generator's.
    """
    N = q.shape[0]
    Hq, Dh = q.shape[2], q.shape[3]
    cdt = k_pool_l.dtype
    # write the new row: idle slots target (null block, 0) by construction
    k_pool_l = k_pool_l.at[write_block, write_off].set(
        k_new[:, 0].astype(cdt))
    v_pool_l = v_pool_l.at[write_block, write_off].set(
        v_new[:, 0].astype(cdt))
    # gather each slot's pages into a contiguous logical view
    bs = k_pool_l.shape[1]
    view = tables.shape[1] * bs
    k_c = k_pool_l[tables].reshape(N, view, k_pool_l.shape[2], Dh)
    v_c = v_pool_l[tables].reshape(N, view, v_pool_l.shape[2], Dh)
    Hkv = k_c.shape[2]
    rep = Hq // Hkv
    qg = q.reshape(N, 1, Hkv, rep, Dh)
    scores = jnp.einsum("bqhrd,bkhd->bhrqk", qg, k_c,
                        preferred_element_type=jnp.float32)
    scores = scores / math.sqrt(Dh)
    # valid keys: logical positions 0..length inclusive (the row written
    # above sits at position == length)
    key_pos = jnp.arange(view, dtype=jnp.int32)
    valid = key_pos[None, :] <= lengths[:, None]          # (N, view)
    scores = jnp.where(valid[:, None, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    ctx = jnp.einsum("bhrqk,bkhd->bqhrd", probs, v_c)
    return ctx.reshape(N, 1, Hq, Dh), k_pool_l, v_pool_l
